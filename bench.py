"""Benchmark: the BASELINE.md headline on real TPU hardware.

Phase 1 — BASELINE.json configs through the real control plane with a
real process-launching agent:
  #1 frameworks/helloworld simple.yml single-pod deploy
  #2 frameworks/helloworld max_per_host.yml (constraint respected)
  #3 frameworks/jax svc_mnist.yml — a REAL JAX training subprocess on
     the TPU; install -> plan COMPLETE wall-clock is the headline.
The reference publishes no numbers (BASELINE.md), so vs_baseline is
measured against the 60 s target budget recorded there (>1.0 = faster
than budget).

Phase 2 (extras) — flagship transformer train-step throughput on the
chip (tokens/s + model FLOPs utilisation), the forward-looking perf
number the multi-host pod scales from.

Prints exactly ONE JSON line.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

DEPLOY_BUDGET_S = 60.0


def flagship_config():
    """The one flagship TransformerConfig both bench_transformer and
    bench_profile measure — chip-scale (v5e, 16 GB): 872M params fills
    the MXU; FA2 backward kernels + 512/512 attention tiles measured
    best in the round-3 sweeps.  The r5 (batch, no_remat_layers)
    frontier sweep (bench_mfu_frontier) found the remat frontier
    optimum at batch 12 with ONE stored-activation layer: 20.3k tok/s
    / MFU 0.540 vs 19.9k / 0.530 at batch 16 full-remat — trading 25%
    batch for one layer of recompute is tokens/s-POSITIVE; b12/nr2 and
    b16/nr1 sit past the HBM boundary (compile-time OOM)."""
    import jax.numpy as jnp

    from dcos_commons_tpu.models import TransformerConfig

    return TransformerConfig(
        vocab=32768,
        d_model=2048,
        n_layers=12,
        n_heads=16,
        n_kv_heads=16,
        d_ff=8192,
        max_seq=2048,
        dtype=jnp.bfloat16,
        remat=True,
        no_remat_layers=int(os.environ.get("BENCH_NO_REMAT_LAYERS", "1")),
        attn_block_q=512,
        attn_block_k=512,
    )


def _run_deploy(yaml_path: str, env: dict, hosts, budget_s: float = 600.0):
    """Deploy one service YAML through the full control plane with a
    real process-launching agent; returns (elapsed, completed,
    scheduler, agent, workdir)."""
    import tempfile

    from dcos_commons_tpu.agent import LocalProcessAgent
    from dcos_commons_tpu.offer.inventory import SliceInventory
    from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
    from dcos_commons_tpu.storage import FileWalPersister

    workdir = tempfile.mkdtemp(prefix="bench-")
    from dcos_commons_tpu.specification import from_yaml_file

    spec = from_yaml_file(yaml_path, env)
    builder = SchedulerBuilder(
        spec,
        SchedulerConfig(
            sandbox_root=os.path.join(workdir, "sandboxes"),
            backoff_enabled=False,
        ),
        FileWalPersister(os.path.join(workdir, "state"), fsync=False),
    )
    builder.set_inventory(SliceInventory(list(hosts)))
    agent = LocalProcessAgent(os.path.join(workdir, "sandboxes"))
    builder.set_agent(agent)
    scheduler = builder.build()

    t0 = time.monotonic()
    deadline = t0 + budget_s
    completed = False
    while time.monotonic() < deadline:
        scheduler.run_cycle()
        if scheduler.deploy_manager.get_plan().is_complete:
            completed = True
            break
        time.sleep(0.1)
    elapsed = time.monotonic() - t0
    return elapsed, completed, scheduler, agent, workdir


def _cpu_hosts(n: int):
    from dcos_commons_tpu.offer.inventory import TpuHost

    return [
        TpuHost(host_id=f"host-{i}", cpus=8.0, memory_mb=16384)
        for i in range(n)
    ]


def bench_helloworld() -> dict:
    """BASELINE configs #1 and #2: helloworld CPU deploys through the
    control plane (reference: frameworks/helloworld simple +
    MAX_PER_HOST scenarios)."""
    import shutil

    results = {}
    # config 1: single-pod deploy
    elapsed, completed, scheduler, agent, workdir = _run_deploy(
        os.path.join(REPO, "frameworks/helloworld/simple.yml"),
        {"SLEEP_DURATION": "1000"},
        _cpu_hosts(1),
        budget_s=60.0,
    )
    results["helloworld_simple_deploy_s"] = round(elapsed, 3)
    results["helloworld_simple_completed"] = completed
    agent.shutdown()
    shutil.rmtree(workdir, ignore_errors=True)

    # config 2: 3 instances, max-per-host:1 over 3 hosts
    elapsed, completed, scheduler, agent, workdir = _run_deploy(
        os.path.join(REPO, "frameworks/helloworld/max_per_host.yml"),
        {"SLEEP_DURATION": "1000"},
        _cpu_hosts(3),
        budget_s=60.0,
    )
    placed_hosts = set()
    for info in scheduler.state_store.fetch_tasks():
        placed_hosts.add(info.labels.get("offer_hostname", info.agent_id))
    results["helloworld_max_per_host_deploy_s"] = round(elapsed, 3)
    results["helloworld_max_per_host_completed"] = completed
    results["helloworld_max_per_host_distinct_hosts"] = len(placed_hosts)
    agent.shutdown()
    shutil.rmtree(workdir, ignore_errors=True)
    return results


def bench_mfu_frontier() -> dict:
    """Dense-flagship (batch, no_remat_layers) frontier at S=2048
    (VERDICT r4 #5): either a point beats the remat-full batch-24
    tokens/s, or this records the measured proof that trading batch
    for less recompute is tokens/s-worse.  Points that OOM report as
    OOM — the frontier INCLUDES the infeasible region's boundary."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from dcos_commons_tpu.models import init_params, make_train_step
    from dcos_commons_tpu.utils import param_count, synthetic_tokens

    steps = int(os.environ.get("BENCH_FRONTIER_STEPS", "6"))
    base = flagship_config()
    peak = _peak_bf16_tflops(jax.devices()[0]) * 1e12
    points = [
        # (batch, no_remat_layers): the r5 full sweep measured
        # b12/nr1 0.540 > b16/nr0 0.530 > b8/nr2 0.528 > b14/nr1
        # 0.515, with b16/nr1, b12/nr2, b8/nr4, b4/nr12 and b24 past
        # the HBM boundary.  The recurring bench re-verifies the
        # three live frontier points (each is a fresh ~2-4 min
        # compile, so the full boundary scan is not re-paid per run);
        # override with BENCH_FRONTIER_POINTS="b:k,b:k,..." to rescan.
        (16, 0), (12, 1), (8, 2),
    ]
    env_points = os.environ.get("BENCH_FRONTIER_POINTS", "")
    if env_points:
        points = [
            tuple(int(v) for v in p.split(":"))
            for p in env_points.split(",")
        ]
    out = {}
    frontier = []
    for batch, k in points:
        tag = f"b{batch}_nr{k}"
        cfg = dataclasses.replace(
            base, no_remat_layers=k, remat=k < base.n_layers,
        )
        try:
            params = init_params(cfg, jax.random.key(0))
            optimizer = optax.adamw(3e-4)
            opt_state = optimizer.init(params)
            step_fn = make_train_step(cfg, optimizer, donate=True)
            tokens, targets = synthetic_tokens(
                jax.random.key(1), batch, cfg.max_seq, cfg.vocab
            )
            params, opt_state, loss = step_fn(
                params, opt_state, tokens, targets
            )
            float(jax.device_get(jnp.sum(loss)))
            for _ in range(2):  # relay: first post-compile exec is slow
                params, opt_state, loss = step_fn(
                    params, opt_state, tokens, targets
                )
            float(jax.device_get(jnp.sum(loss)))
            t0 = time.monotonic()
            for _ in range(steps):
                params, opt_state, loss = step_fn(
                    params, opt_state, tokens, targets
                )
            float(jax.device_get(jnp.sum(loss)))
            dt = time.monotonic() - t0
            toks = batch * cfg.max_seq * steps / dt
            mfu = toks * 6 * param_count(params) / peak if peak else 0.0
            frontier.append(f"{tag}: {round(toks)} tok/s mfu {mfu:.3f}")
            out[f"frontier_{tag}_tokens_per_s"] = round(toks)
            out[f"frontier_{tag}_mfu"] = round(mfu, 3)
            del params, opt_state
        except Exception as e:  # OOM boundary is a RESULT here
            frontier.append(f"{tag}: infeasible ({repr(e)[:60]})")
            out[f"frontier_{tag}_tokens_per_s"] = 0
    out["frontier_notes"] = "; ".join(frontier)
    return out


def bench_scheduler_scale() -> dict:
    """Scheduler-loop latency at FLEET scale: a 100-pod service over a
    64-host inventory with a placement constraint, through the full
    offer-evaluation pipeline (fake agent — this measures the
    SCHEDULER, not process spawns).  The regression fence for an
    accidental O(n^2) in offer/evaluate.py — the reference's whole
    reason for decline/suppress machinery
    (framework/OfferProcessor.java:133,142)."""
    import statistics

    from dcos_commons_tpu.common import TaskState, TaskStatus
    from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
    from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
    from dcos_commons_tpu.specification import from_yaml
    from dcos_commons_tpu.storage import MemPersister
    from dcos_commons_tpu.testing import FakeAgent

    n_hosts, n_pods = 64, 100
    spec = from_yaml(
        "name: scalesvc\n"
        "pods:\n"
        "  app:\n"
        f"    count: {n_pods}\n"
        "    placement: 'max-per-host:2'\n"
        "    tasks:\n"
        "      server:\n"
        "        goal: RUNNING\n"
        "        cmd: sleep 1000\n"
        "        cpus: 4\n"
        "        memory: 1024\n"
        "plans:\n"
        "  deploy:\n"
        "    strategy: serial\n"
        "    phases:\n"
        "      app:\n"
        "        strategy: parallel\n"
        "        pod: app\n"
    )
    hosts = [
        TpuHost(host_id=f"h{i:03d}", cpus=16.0, memory_mb=65536)
        for i in range(n_hosts)
    ]
    builder = SchedulerBuilder(
        spec,
        SchedulerConfig(backoff_enabled=False, revive_capacity=10**9),
        MemPersister(),
    )
    builder.set_inventory(SliceInventory(hosts))
    agent = FakeAgent()
    builder.set_agent(agent)
    scheduler = builder.build()

    cycle_ms = []
    acked = set()
    t0 = time.monotonic()
    deadline = t0 + 300.0
    completed = False
    while time.monotonic() < deadline:
        c0 = time.monotonic()
        scheduler.run_cycle()
        cycle_ms.append((time.monotonic() - c0) * 1e3)
        # ack every newly launched task as RUNNING (the fleet's agents
        # answering; launch->RUNNING latency is not the scheduler's)
        for info in agent.launched:
            if info.task_id not in acked:
                acked.add(info.task_id)
                agent.send(TaskStatus(
                    task_id=info.task_id, state=TaskState.RUNNING,
                    ready=True,
                ))
        if scheduler.deploy_manager.get_plan().is_complete:
            completed = True
            break
    deploy_s = time.monotonic() - t0
    # steady state: every pod RUNNING, nothing to place — the
    # decline/suppress path the fleet idles on
    idle_ms = []
    for _ in range(50):
        c0 = time.monotonic()
        scheduler.run_cycle()
        idle_ms.append((time.monotonic() - c0) * 1e3)
    quantiles = statistics.quantiles(cycle_ms, n=100)
    return {
        "sched_scale_hosts": n_hosts,
        "sched_scale_pods": n_pods,
        "sched_scale_completed": completed,
        "sched_scale_deploy_s": round(deploy_s, 3),
        "sched_scale_cycles": len(cycle_ms),
        "sched_scale_cycle_p50_ms": round(quantiles[49], 2),
        "sched_scale_cycle_p99_ms": round(quantiles[98], 2),
        "sched_scale_idle_cycle_ms": round(
            statistics.median(idle_ms), 2
        ),
    }


def bench_offer_cycle() -> dict:
    """Offer-cycle fast path microbench (ISSUE 1): a 16-step serial
    deploy over a 64-host TPU fleet through run_forever with the
    production 0.5 s fallback interval.  Two numbers are fenced:

    * snapshot rebuild reduction — the generation-stamped cache must
      cut per-host snapshot rebuilds >= 5x vs the rebuild-every-
      request baseline (requests / misses);
    * event-driven wall-clock — statuses nudge the loop, so the
      deploy must complete in well under steps x interval_s (the old
      loop paid >= one 0.5 s sleep per step)."""
    import threading

    from dcos_commons_tpu.common import TaskState, TaskStatus
    from dcos_commons_tpu.offer.inventory import (
        SliceInventory,
        make_test_fleet,
    )
    from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
    from dcos_commons_tpu.specification import from_yaml
    from dcos_commons_tpu.storage import MemPersister
    from dcos_commons_tpu.testing import FakeAgent

    n_steps, interval_s = 16, 0.5
    hosts = []
    for s in range(4):  # 4 slices x 16 hosts = 64 TPU hosts
        hosts.extend(make_test_fleet(
            slice_id=f"pod-{s}", host_grid=(4, 4), chip_block=(2, 2),
            cpus=32.0, memory_mb=131072,
        ))
    spec = from_yaml(
        "name: offercycle\n"
        "pods:\n"
        "  app:\n"
        f"    count: {n_steps}\n"
        "    placement: 'max-per-host:1'\n"
        "    tasks:\n"
        "      server:\n"
        "        goal: RUNNING\n"
        "        cmd: sleep 1000\n"
        "        cpus: 2\n"
        "        memory: 1024\n"
        "plans:\n"
        "  deploy:\n"
        "    strategy: serial\n"
        "    phases:\n"
        "      app:\n"
        "        strategy: serial\n"
        "        pod: app\n"
    )
    builder = SchedulerBuilder(
        spec,
        SchedulerConfig(backoff_enabled=False, revive_capacity=10**9),
        MemPersister(),
    )
    inventory = SliceInventory(hosts)
    builder.set_inventory(inventory)
    agent = FakeAgent()
    builder.set_agent(agent)
    scheduler = builder.build()

    acked = set()
    stop = threading.Event()

    def responder():  # the fleet's agents acking RUNNING
        while not stop.is_set():
            for info in list(agent.launched):
                if info.task_id not in acked:
                    acked.add(info.task_id)
                    agent.send(TaskStatus(
                        task_id=info.task_id, state=TaskState.RUNNING,
                        ready=True, agent_id=info.agent_id,
                    ))
            time.sleep(0.002)

    responder_thread = threading.Thread(target=responder, daemon=True)
    responder_thread.start()
    t0 = time.monotonic()
    loop_thread = scheduler.run_forever(interval_s=interval_s)
    deadline = t0 + 60.0
    completed = False
    while time.monotonic() < deadline:
        if scheduler.deploy_manager.get_plan().is_complete:
            completed = True
            break
        time.sleep(0.01)
    elapsed = time.monotonic() - t0
    scheduler.stop()
    loop_thread.join(timeout=5)
    stop.set()
    responder_thread.join(timeout=5)
    requests = inventory.cache_hits + inventory.cache_misses
    rebuild_reduction = requests / max(1, inventory.cache_misses)
    return {
        "offer_cycle_hosts": len(hosts),
        "offer_cycle_steps": n_steps,
        "offer_cycle_completed": completed,
        "offer_cycle_deploy_s": round(elapsed, 3),
        "offer_cycle_serial_budget_s": round(n_steps * interval_s, 1),
        "offer_cycle_snapshot_requests": requests,
        "offer_cycle_snapshot_rebuilds": inventory.cache_misses,
        "offer_cycle_rebuild_reduction_x": round(rebuild_reduction, 1),
        "offer_cycle_nudges": int(
            scheduler.metrics.counters().get("cycle.nudges", 0)
        ),
    }


def bench_fleet_scale() -> dict:
    """Fleet-scale offer cycle (ISSUE 9): dirty-host incremental
    snapshot sync + indexed placement pre-filtering + requirement
    memo vs the PR-1 full-copy path, at 1k and 10k simulated hosts.

    Scenario per fleet size: a 32-pod TPU deploy (parallel phase),
    then 50 steady-state IDLE cycles, then 6 CHURN rounds (restart one
    pod -> drive to recovered).  Fences, at 10k hosts:

    * steady-state (idle / single-status churn) cycle must be >= 10x
      faster than the full-rebuild path (median per-round);
    * the fast path stays inside absolute budgets (idle cycle and
      churn round) so a regression cannot hide behind the baseline
      getting slower too;
    * idle cycles report dirty_hosts == 0 — cycle cost scales with
      dirty hosts, not fleet size.
    """
    import statistics

    from dcos_commons_tpu.common import TaskState, TaskStatus
    from dcos_commons_tpu.offer.inventory import (
        SliceInventory,
        make_test_fleet,
    )
    from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
    from dcos_commons_tpu.specification import from_yaml
    from dcos_commons_tpu.storage import MemPersister
    from dcos_commons_tpu.testing import FakeAgent

    n_pods, idle_cycles, churn_rounds = 32, 50, 6

    def build_world(n_hosts, fast):
        hosts = []
        n_slices = n_hosts // 16
        for s in range(n_slices):
            hosts.extend(make_test_fleet(
                slice_id=f"pod-{s:04d}", host_grid=(4, 4),
                chip_block=(2, 2), cpus=32.0, memory_mb=131072,
            ))
        spec = from_yaml(
            "name: fleetscale\n"
            "pods:\n"
            "  app:\n"
            f"    count: {n_pods}\n"
            "    placement: 'max-per-host:1'\n"
            "    tpu:\n"
            "      generation: v5e\n"
            "      chips-per-host: 4\n"
            "    tasks:\n"
            "      worker:\n"
            "        goal: RUNNING\n"
            "        cmd: sleep 1000\n"
            "        cpus: 2\n"
            "        memory: 1024\n"
            "plans:\n"
            "  deploy:\n"
            "    strategy: serial\n"
            "    phases:\n"
            "      app:\n"
            "        strategy: parallel\n"
            "        pod: app\n"
        )
        builder = SchedulerBuilder(
            spec,
            SchedulerConfig(backoff_enabled=False, revive_capacity=10**9),
            MemPersister(),
        )
        inventory = SliceInventory(hosts)
        builder.set_inventory(inventory)
        agent = FakeAgent()
        builder.set_agent(agent)
        scheduler = builder.build()
        scheduler.evaluator.fast_path = fast
        return scheduler, agent, inventory

    def drive(scheduler, agent, acked, deadline_s=120.0):
        """run_cycle + inline RUNNING acks until no work pending."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline_s:
            scheduler.run_cycle()
            for info in list(agent.launched):
                if info.task_id not in acked:
                    acked.add(info.task_id)
                    agent.send(TaskStatus(
                        task_id=info.task_id, state=TaskState.RUNNING,
                        ready=True, agent_id=info.agent_id,
                    ))
            if not scheduler.work_pending():
                return True
        return False

    out = {}
    ratios = {}
    for n_hosts in (1024, 10240):
        tag = f"{n_hosts // 1024}k" if n_hosts < 10000 else "10k"
        for fast in (True, False):
            mode = "fast" if fast else "rebuild"
            scheduler, agent, inventory = build_world(n_hosts, fast)
            acked = set()
            t0 = time.monotonic()
            completed = drive(scheduler, agent, acked)
            deploy_s = time.monotonic() - t0
            assert completed and \
                scheduler.deploy_manager.get_plan().is_complete, (
                    f"{mode}@{tag}: 32-pod deploy did not complete"
                )
            idle_ms = []
            idle_misses_before = inventory.cache_misses
            for _ in range(idle_cycles):
                c0 = time.monotonic()
                scheduler.run_cycle()
                idle_ms.append((time.monotonic() - c0) * 1e3)
            idle_rebuilds = inventory.cache_misses - idle_misses_before
            churn_s = []
            # churn-phase evaluation cost: the steady-state
            # "single-status cycle" number the 10x fence compares —
            # cycle.evaluate spans snapshot sync + placement for one
            # requirement
            eval_n0 = scheduler.metrics.timer_count("cycle.evaluate")
            for round_i in range(churn_rounds):
                c0 = time.monotonic()
                scheduler.restart_pod("app", round_i % n_pods)
                recovered = drive(scheduler, agent, acked)
                churn_s.append(time.monotonic() - c0)
                assert recovered, f"{mode}@{tag}: churn round wedged"
            eval_samples = scheduler.metrics.timer_samples(
                "cycle.evaluate", since_count=eval_n0
            )
            # fail LOUDLY on an empty window: a renamed/relocated
            # cycle.evaluate timer would otherwise make the 10x fence
            # vacuous (0.0 fast -> huge ratio) or spuriously fail it
            assert eval_samples, (
                f"{mode}@{tag}: no cycle.evaluate samples in the "
                "churn window — timer renamed or churn did not evaluate?"
            )
            churn_eval_ms = statistics.median(eval_samples) * 1e3
            out[f"fleet_scale_{tag}_{mode}_deploy_s"] = round(deploy_s, 3)
            out[f"fleet_scale_{tag}_{mode}_idle_cycle_ms"] = round(
                statistics.median(idle_ms), 3
            )
            out[f"fleet_scale_{tag}_{mode}_churn_round_ms"] = round(
                statistics.median(churn_s) * 1e3, 2
            )
            out[f"fleet_scale_{tag}_{mode}_churn_eval_ms"] = round(
                churn_eval_ms, 3
            )
            if fast:
                out[f"fleet_scale_{tag}_idle_rebuilds"] = idle_rebuilds
                out[f"fleet_scale_{tag}_shortcircuits"] = int(
                    scheduler.metrics.counters().get(
                        "offers.eval.shortcircuit", 0
                    )
                )
                out[f"fleet_scale_{tag}_index_hits"] = int(
                    scheduler.metrics.counters().get("offers.index.hit", 0)
                )
        for dim in ("idle_cycle_ms", "churn_round_ms", "churn_eval_ms",
                    "deploy_s"):
            fast_v = out[f"fleet_scale_{tag}_fast_{dim}"]
            slow_v = out[f"fleet_scale_{tag}_rebuild_{dim}"]
            ratios[f"fleet_scale_{tag}_{dim}_speedup_x"] = round(
                slow_v / max(fast_v, 1e-6), 1
            )
    out.update(ratios)
    # fences (10k): steady-state >= 10x vs full rebuild, inside
    # absolute budgets, and idle cycles touch zero hosts
    assert out["fleet_scale_10k_idle_rebuilds"] == 0, \
        "idle cycles re-synthesized host snapshots — dirty tracking broken"
    eval_speedup = ratios["fleet_scale_10k_churn_eval_ms_speedup_x"]
    assert eval_speedup >= 10.0, (
        f"steady-state evaluated-cycle speedup at 10k is "
        f"{eval_speedup}x (< 10x): the incremental path is not "
        "sublinear in fleet size"
    )
    # generous absolute budgets for shared CI boxes (measured: idle
    # well under 1 ms, churn rounds tens of ms)
    assert out["fleet_scale_10k_fast_idle_cycle_ms"] < 50.0, \
        f"10k-host idle cycle {out['fleet_scale_10k_fast_idle_cycle_ms']}ms"
    assert out["fleet_scale_10k_fast_churn_round_ms"] < 2000.0, \
        f"10k-host churn round {out['fleet_scale_10k_fast_churn_round_ms']}ms"
    return out


def bench_trace_overhead() -> dict:
    """traceview recorder overhead bound (ISSUE 5): the PR 1 offer-
    cycle scenario (serial deploy over 64 TPU hosts) driven
    synchronously — run_cycle until complete, FakeAgent acking RUNNING
    inline — with the flight recorder DISABLED (trace_capacity=0) and
    ENABLED in LOCKSTEP: two identical worlds alternate cycles, each
    cycle timed individually, and the overhead is the median of the
    per-cycle-index enabled/disabled ratios.  Pairing at ~1ms cycle
    granularity cancels host drift, and the median rejects preemption
    spikes — a shared CI box cannot fake a systematic ratio.  The
    assertion enforces the tentpole's bound: per-event spans must cost
    <5% of the offer-cycle figure."""
    from dcos_commons_tpu.common import TaskState, TaskStatus
    from dcos_commons_tpu.offer.inventory import (
        SliceInventory,
        make_test_fleet,
    )
    from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
    from dcos_commons_tpu.specification import from_yaml
    from dcos_commons_tpu.storage import MemPersister
    from dcos_commons_tpu.testing import FakeAgent

    # 32 serial steps (2x the PR 1 scenario): ~70 busy cycles per
    # deploy = enough paired samples for a stable median
    n_steps = 32
    yaml_text = (
        "name: traceoverhead\n"
        "pods:\n"
        "  app:\n"
        f"    count: {n_steps}\n"
        "    placement: 'max-per-host:1'\n"
        "    tasks:\n"
        "      server:\n"
        "        goal: RUNNING\n"
        "        cmd: sleep 1000\n"
        "        cpus: 2\n"
        "        memory: 1024\n"
        "plans:\n"
        "  deploy:\n"
        "    strategy: serial\n"
        "    phases:\n"
        "      app:\n"
        "        strategy: serial\n"
        "        pod: app\n"
    )

    def build_world(trace_capacity: int):
        hosts = []
        for s in range(4):
            hosts.extend(make_test_fleet(
                slice_id=f"pod-{s}", host_grid=(4, 4), chip_block=(2, 2),
                cpus=32.0, memory_mb=131072,
            ))
        builder = SchedulerBuilder(
            from_yaml(yaml_text),
            SchedulerConfig(
                backoff_enabled=False, revive_capacity=10**9,
                trace_capacity=trace_capacity,
            ),
            MemPersister(),
        )
        builder.set_inventory(SliceInventory(hosts))
        agent = FakeAgent()
        builder.set_agent(agent)
        return builder.build(), agent, set()

    def tick(scheduler, agent, acked):
        """One timed cycle + inline RUNNING acks; returns seconds."""
        t0 = time.monotonic()
        scheduler.run_cycle()
        elapsed = time.monotonic() - t0
        for info in list(agent.launched):
            if info.task_id not in acked:
                acked.add(info.task_id)
                agent.send(TaskStatus(
                    task_id=info.task_id, state=TaskState.RUNNING,
                    ready=True, agent_id=info.agent_id,
                ))
        return elapsed

    import gc

    # warm both code paths, then run the two worlds in lockstep: the
    # same cycle index does the same work in both, so per-index
    # ratios pair ~1ms regions executed back to back.  GC is parked
    # so a collection landing in one world's cycle doesn't masquerade
    # as recorder overhead.
    for warm_capacity in (0, 2048):
        scheduler, agent, acked = build_world(warm_capacity)
        for _ in range(10 * n_steps):
            tick(scheduler, agent, acked)
            if scheduler.deploy_manager.get_plan().is_complete:
                break
    sched_off, agent_off, acked_off = build_world(0)
    sched_on, agent_on, acked_on = build_world(2048)
    off_times, on_times = [], []
    gc.collect()
    gc.disable()
    try:
        for _ in range(10 * n_steps):
            off_times.append(tick(sched_off, agent_off, acked_off))
            on_times.append(tick(sched_on, agent_on, acked_on))
            if sched_off.deploy_manager.get_plan().is_complete and \
                    sched_on.deploy_manager.get_plan().is_complete:
                break
    finally:
        gc.enable()
    assert sched_off.deploy_manager.get_plan().is_complete
    assert sched_on.deploy_manager.get_plan().is_complete
    ratios = sorted(
        on / max(off, 1e-9) for off, on in zip(off_times, on_times)
    )
    overhead = ratios[len(ratios) // 2] - 1.0
    # the tentpole's bound: tracing must cost <5% of the offer-cycle
    # figure
    assert overhead < 0.05, (
        f"trace recorder overhead {overhead * 100:.1f}% exceeds the 5% "
        f"bound (median per-cycle ratio over {len(ratios)} lockstep "
        f"cycles; totals {sum(on_times):.4f}s traced vs "
        f"{sum(off_times):.4f}s)"
    )
    return {
        "trace_overhead_deploy_s_disabled": round(sum(off_times), 4),
        "trace_overhead_deploy_s_enabled": round(sum(on_times), 4),
        "trace_overhead_pct": round(overhead * 100, 2),
        "trace_overhead_cycles": len(ratios),
        "trace_overhead_spans": len(sched_on.tracer.snapshot()),
        "trace_overhead_dropped": sched_on.tracer.dropped,
    }


def bench_health_overhead() -> dict:
    """Fleet health plane overhead bound (ISSUE 10): the trace-bench
    scenario (serial deploy over 64 TPU hosts, 32 steps = 2x the issue
    scenario for stable medians) with the health plane DISABLED
    (health_enabled=False -> NullHealthMonitor) vs ENABLED in
    LOCKSTEP — same pairing/median discipline as bench_trace_overhead.
    The enabled arm pays the full per-cycle bill: detector pass every
    cycle (straggler median-ratio over a seeded 64-host steplog fan-in,
    SLO watch, lease-churn watch), plan-transition journaling with
    per-dirty-cycle flushes through the store, and metric-history
    sampling at the production 1s cadence.  Tracing is OFF in both
    arms so the ratio isolates the health plane.  The assertion
    enforces the acceptance criterion: detectors + journal must cost
    <5% of the offer-cycle figure."""
    from dcos_commons_tpu.common import TaskState, TaskStatus
    from dcos_commons_tpu.offer.inventory import (
        SliceInventory,
        make_test_fleet,
    )
    from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
    from dcos_commons_tpu.specification import from_yaml
    from dcos_commons_tpu.storage import MemPersister
    from dcos_commons_tpu.testing import FakeAgent

    n_steps = 32
    yaml_text = (
        "name: healthoverhead\n"
        "pods:\n"
        "  app:\n"
        f"    count: {n_steps}\n"
        "    placement: 'max-per-host:1'\n"
        "    tasks:\n"
        "      server:\n"
        "        goal: RUNNING\n"
        "        cmd: sleep 1000\n"
        "        cpus: 2\n"
        "        memory: 1024\n"
        "plans:\n"
        "  deploy:\n"
        "    strategy: serial\n"
        "    phases:\n"
        "      app:\n"
        "        strategy: serial\n"
        "        pod: app\n"
    )

    def steplog_of(task_name, agent_id=None):
        # the shape a real gang-skew steplog has: 8 trailing records
        # per task, one implicit straggler (app-7's host shows 10x own
        # time), so the enabled arm's detector does real scoring work
        own = 1.0 if task_name.startswith("app-7-") else 0.1
        return [
            {"step": i, "t": 100.0 + i, "wall_s": 1.0,
             "blocked_s": round(1.0 - own, 3), "tokens": 4096}
            for i in range(8)
        ]

    def build_world(enabled: bool):
        hosts = []
        for s in range(4):
            hosts.extend(make_test_fleet(
                slice_id=f"pod-{s}", host_grid=(4, 4), chip_block=(2, 2),
                cpus=32.0, memory_mb=131072,
            ))
        builder = SchedulerBuilder(
            from_yaml(yaml_text),
            SchedulerConfig(
                backoff_enabled=False, revive_capacity=10**9,
                trace_capacity=0, health_enabled=enabled,
            ),
            MemPersister(),
        )
        builder.set_inventory(SliceInventory(hosts))
        agent = FakeAgent()
        agent.steplog_of = steplog_of
        builder.set_agent(agent)
        scheduler = builder.build()
        # charge steplog fan-in + detector scoring at 20 Hz — 100x
        # the production 5s cadence (sub-ms sim cycles would otherwise
        # outrun the throttle and never exercise the detectors): the
        # measured ratio upper-bounds what an operator pays
        if enabled:
            scheduler.health.telemetry_interval_s = 0.05
        return scheduler, agent, set()

    def tick(scheduler, agent, acked):
        t0 = time.monotonic()
        scheduler.run_cycle()
        elapsed = time.monotonic() - t0
        for info in list(agent.launched):
            if info.task_id not in acked:
                acked.add(info.task_id)
                agent.send(TaskStatus(
                    task_id=info.task_id, state=TaskState.RUNNING,
                    ready=True, agent_id=info.agent_id,
                ))
        return elapsed

    import gc

    for warm_enabled in (False, True):
        scheduler, agent, acked = build_world(warm_enabled)
        for _ in range(10 * n_steps):
            tick(scheduler, agent, acked)
            if scheduler.deploy_manager.get_plan().is_complete:
                break
    sched_off, agent_off, acked_off = build_world(False)
    sched_on, agent_on, acked_on = build_world(True)
    off_times, on_times = [], []
    gc.collect()
    gc.disable()
    try:
        for _ in range(10 * n_steps):
            off_times.append(tick(sched_off, agent_off, acked_off))
            on_times.append(tick(sched_on, agent_on, acked_on))
            if sched_off.deploy_manager.get_plan().is_complete and \
                    sched_on.deploy_manager.get_plan().is_complete:
                break
    finally:
        gc.enable()
    assert sched_off.deploy_manager.get_plan().is_complete
    assert sched_on.deploy_manager.get_plan().is_complete
    # sanity: the enabled arm actually did health work (journal
    # carries the deploy's plan transitions; a vacuous arm would make
    # the 5% bound meaningless)
    journaled = sched_on.journal.last_seq
    assert journaled >= n_steps, f"journal only reached seq {journaled}"
    assert not sched_off.journal.enabled
    # ...and the detectors actually scored the seeded straggler
    assert sched_on.health.straggler.suspects, "straggler never scored"
    ratios = sorted(
        on / max(off, 1e-9) for off, on in zip(off_times, on_times)
    )
    overhead = ratios[len(ratios) // 2] - 1.0
    assert overhead < 0.05, (
        f"health plane overhead {overhead * 100:.1f}% exceeds the 5% "
        f"bound (median per-cycle ratio over {len(ratios)} lockstep "
        f"cycles; totals {sum(on_times):.4f}s enabled vs "
        f"{sum(off_times):.4f}s)"
    )
    return {
        "health_overhead_deploy_s_disabled": round(sum(off_times), 4),
        "health_overhead_deploy_s_enabled": round(sum(on_times), 4),
        "health_overhead_pct": round(overhead * 100, 2),
        "health_overhead_cycles": len(ratios),
        "health_overhead_journal_seq": journaled,
        "health_overhead_suspects": len(sched_on.health.straggler.suspects),
    }


def bench_failover() -> dict:
    """HA failover latency (ISSUE 8): a 64-host/32-pod deploy is
    driven halfway by leader scheduler A, which is then hard-killed
    (renewals simply stop — the SIGKILL analogue).  A hot standby
    candidates for the lease; the measured numbers are the phases an
    operator actually waits through:

      failover_lease_wait_s   kill -> standby holds the lease (bounded
                              by TTL + one candidate poll)
      failover_rebuild_s      lease -> scheduler rebuilt over the
                              shared store (config/plan/ledger load)
      failover_first_cycle_s  rebuild -> first working cycle DONE
                              (includes the rehydrate.replay pass)
      failover_total_s        kill -> first new working cycle
      failover_resume_s       kill -> the interrupted deploy COMPLETE

    The takeover must adopt every in-flight launch (no re-issue storm:
    failover_reissued == 0 here — A died between cycles, not inside
    one) and finish the rollout without restarting completed pods."""
    from dcos_commons_tpu.common import TaskState, TaskStatus
    from dcos_commons_tpu.ha.election import LeaderLease
    from dcos_commons_tpu.offer.inventory import (
        SliceInventory,
        make_test_fleet,
    )
    from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
    from dcos_commons_tpu.specification import from_yaml
    from dcos_commons_tpu.storage import MemPersister
    from dcos_commons_tpu.testing import FakeAgent

    n_pods, ttl_s = 32, 0.6
    hosts = []
    for s in range(4):  # 64 TPU hosts
        hosts.extend(make_test_fleet(
            slice_id=f"pod-{s}", host_grid=(4, 4), chip_block=(2, 2),
            cpus=32.0, memory_mb=131072,
        ))
    yaml_text = (
        "name: failover\n"
        "pods:\n"
        "  app:\n"
        f"    count: {n_pods}\n"
        "    placement: 'max-per-host:1'\n"
        "    tasks:\n"
        "      server:\n"
        "        goal: RUNNING\n"
        "        cmd: sleep 1000\n"
        "        cpus: 2\n"
        "        memory: 1024\n"
        "plans:\n"
        "  deploy:\n"
        "    strategy: serial\n"
        "    phases:\n"
        "      app:\n"
        "        strategy: serial\n"
        "        pod: app\n"
    )
    persister = MemPersister()
    agent = FakeAgent()
    acked = set()

    def build(lease):
        builder = SchedulerBuilder(
            from_yaml(yaml_text),
            SchedulerConfig(backoff_enabled=False, revive_capacity=10**9),
            persister,
        )
        builder.set_inventory(SliceInventory(hosts))
        builder.set_agent(agent)
        builder.set_leader_lease(lease)
        return builder.build()

    def ack():
        for info in list(agent.launched):
            if info.task_id not in acked:
                acked.add(info.task_id)
                agent.send(TaskStatus(
                    task_id=info.task_id, state=TaskState.RUNNING,
                    ready=True, agent_id=info.agent_id,
                ))

    lease_a = LeaderLease(persister, "failover", "sched-a", ttl_s=ttl_s)
    assert lease_a.try_acquire()
    sched_a = build(lease_a)
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        sched_a.run_cycle()
        ack()
        lease_a.renew()
        if len(agent.launched) >= n_pods // 2:
            break
    launched_at_kill = len(agent.launched)
    running_ids = {
        info.name: info.task_id
        for info in sched_a.state_store.fetch_tasks()
    }

    t_kill = time.monotonic()  # A is gone: no more cycles, no renewals
    lease_b = LeaderLease(persister, "failover", "sched-b", ttl_s=ttl_s)
    while not lease_b.try_acquire():
        time.sleep(ttl_s / 3.0)  # the candidate poll cadence
    t_lease = time.monotonic()
    sched_b = build(lease_b)
    t_built = time.monotonic()
    sched_b.run_cycle()  # rehydrate.replay + first working cycle
    t_first_cycle = time.monotonic()
    completed = False
    deadline = time.monotonic() + 120.0
    while time.monotonic() < deadline:
        ack()
        sched_b.run_cycle()
        lease_b.renew()
        if sched_b.deploy_manager.get_plan().is_complete:
            completed = True
            break
    rehydration = sched_b.last_rehydration or {}
    # the takeover adopted the running fleet instead of relaunching it
    survivors = {
        info.name: info.task_id
        for info in sched_b.state_store.fetch_tasks()
        if info.name in running_ids
    }
    adoption_clean = all(
        survivors.get(name) == task_id
        for name, task_id in running_ids.items()
    )
    return {
        "failover_hosts": len(hosts),
        "failover_pods": n_pods,
        "failover_lease_ttl_s": ttl_s,
        "failover_launched_at_kill": launched_at_kill,
        "failover_lease_wait_s": round(t_lease - t_kill, 3),
        "failover_rebuild_s": round(t_built - t_lease, 3),
        "failover_first_cycle_s": round(t_first_cycle - t_built, 3),
        "failover_total_s": round(t_first_cycle - t_kill, 3),
        "failover_resume_s": round(time.monotonic() - t_kill, 3),
        "failover_completed": completed,
        "failover_epoch": lease_b.epoch,
        "failover_adopted": rehydration.get("adopted", 0),
        "failover_reissued": rehydration.get("reissued", 0),
        "failover_adoption_clean": adoption_clean,
    }


def bench_slo_recovery() -> dict:
    """Closed health->action loop latency (ISSUE 15): seeded serving
    SLO breach under open-loop load -> time to the scale-out plan and
    time to recovered SLO, then a quiet period -> scale-in with the
    pre-kill drain, zero flap asserted over the whole run.

    The load model is open-loop at the control-plane boundary: each
    serving pod mirrors ``queue_depth = offered / live_pods`` — the
    gauge every pod already exports — so the breach clears exactly
    when the scale-out's new instances reach RUNNING and take their
    share.  Offered load 48 vs a queue-depth SLO of 16: one pod
    breaches 3x (severity 3 -> a 2-instance step), three pods sit at
    the threshold (recovered).  FakeAgent: this measures the
    scheduler loop — detection latency, plan synthesis, deploy-through
    -offer-cycle — not model serving.

      slo_recovery_scale_plan_s   breach injected -> scale-out plan
                                  journaled (detection + hysteresis
                                  hold + governor)
      slo_recovery_recovered_s    breach injected -> SLO clear event
                                  (new pods RUNNING, load spread)
      slo_recovery_scale_in_s     quiet injected -> scale-in plan
                                  complete (incl. the router drain
                                  grace before the kill)
      slo_recovery_zero_flap      1 = exactly one scale-out and one
                                  scale-in, in that order, no
                                  opposite-direction overlap

    Tracked like failover_*: regressions here mean the loop got
    slower to react or started flapping."""
    from dcos_commons_tpu.common import TaskState, TaskStatus
    from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
    from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
    from dcos_commons_tpu.specification import from_yaml
    from dcos_commons_tpu.storage import MemPersister
    from dcos_commons_tpu.testing import FakeAgent

    yaml_text = (
        "name: slo\n"
        "pods:\n"
        "  serve:\n"
        "    count: 1\n"
        "    tasks:\n"
        "      server:\n"
        "        goal: RUNNING\n"
        "        cmd: serve\n"
        "        cpus: 1\n"
        "        memory: 512\n"
    )
    config = SchedulerConfig(
        backoff_enabled=False,
        revive_capacity=10**9,
        health_autoscale=True,
        health_queue_depth_slo=16.0,
        autoscale_max_instances=4,
        autoscale_breach_hold_s=0.05,
        autoscale_quiet_hold_s=0.05,
        autoscale_cooldown_out_s=0.5,
        autoscale_cooldown_in_s=0.5,
        autoscale_drain_grace_s=0.1,
    )
    hosts = [TpuHost(host_id=f"host-{i}", cpus=8.0, memory_mb=8192)
             for i in range(4)]
    agent = FakeAgent()
    builder = SchedulerBuilder(
        from_yaml(yaml_text), config, MemPersister()
    )
    builder.set_inventory(SliceInventory(hosts))
    builder.set_agent(agent)
    scheduler = builder.build()
    monitor = scheduler.health
    # the bench injects gauges directly (the sandbox/wire fan-in is
    # bench_health_overhead's subject): park collection
    monitor.telemetry_interval_s = 1e9
    monitor._last_telemetry = 1e18
    acked = set()

    def ack():
        for info in list(agent.launched):
            if info.task_id not in acked:
                acked.add(info.task_id)
                agent.send(TaskStatus(
                    task_id=info.task_id, state=TaskState.RUNNING,
                    ready=True, agent_id=info.agent_id,
                ))

    def running_serve_tasks():
        out = []
        for name, status in scheduler.state_store.fetch_statuses().items():
            if status.state is TaskState.RUNNING and \
                    name.startswith("serve-"):
                out.append(name)
        return out

    def inject(offered: float):
        live = running_serve_tasks()
        depth = offered / max(1, len(live))
        monitor._serving_stats = {
            name: {"queue_depth": depth} for name in live
        }
        monitor._serving_env = {name: {} for name in live}
        monitor._telemetry_seq += 1

    def health_events():
        return scheduler.journal.events(kinds=("health",))

    def spin(offered: float, until, timeout_s: float, label: str):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            inject(offered)
            scheduler.run_cycle()
            ack()
            if until():
                return
        raise RuntimeError(f"slo bench: {label} not reached "
                           f"in {timeout_s}s")

    # deploy the single pod
    spin(0.0, lambda: scheduler.deploy_manager.get_plan().is_complete,
         30.0, "initial deploy")

    # phase 1: the breach
    t_breach = time.monotonic()
    spin(
        48.0,
        lambda: any(e.get("stage") == "start" for e in health_events()),
        30.0, "scale-out plan",
    )
    t_plan = time.monotonic()
    spin(
        48.0,
        lambda: any(
            e.get("detector") == "slo" and e.get("cleared")
            for e in scheduler.journal.events(kinds=("alert",))
        ) and scheduler.actions.manager.phase_for("serve") is None,
        60.0, "recovered SLO",
    )
    t_recovered = time.monotonic()
    count_after_out = scheduler.spec.pod("serve").count

    # phase 2: the quiet period
    t_quiet = time.monotonic()
    spin(
        0.5,
        lambda: any(
            e.get("verb") == "scale-in" and e.get("stage") == "complete"
            for e in health_events()
        ),
        60.0, "scale-in complete",
    )
    t_scaled_in = time.monotonic()

    stages = [
        (e["verb"], e["stage"]) for e in health_events()
        if e.get("stage") in ("start", "complete")
    ]
    outs = [s for s in stages if s[0] == "scale-out"]
    ins = [s for s in stages if s[0] == "scale-in"]
    # zero flap: one scale-out episode, then scale-in(s) — never an
    # out after an in, never overlapping opposite directions (starts
    # strictly alternate with their completes)
    first_in = stages.index(("scale-in", "start")) if ins else len(stages)
    zero_flap = (
        outs == [("scale-out", "start"), ("scale-out", "complete")]
        and all(s[0] == "scale-in" for s in stages[first_in:])
        and stages[:2] == outs
    )
    assert zero_flap, stages
    assert count_after_out == 3, count_after_out
    scale_plan_s = t_plan - t_breach
    recovered_s = t_recovered - t_breach
    scale_in_s = t_scaled_in - t_quiet
    assert scale_plan_s < 10.0, scale_plan_s
    assert recovered_s < 30.0, recovered_s
    assert scale_in_s < 30.0, scale_in_s
    return {
        "slo_recovery_scale_plan_s": round(scale_plan_s, 3),
        "slo_recovery_recovered_s": round(recovered_s, 3),
        "slo_recovery_scale_in_s": round(scale_in_s, 3),
        "slo_recovery_count_after_out": count_after_out,
        "slo_recovery_count_final": scheduler.spec.pod("serve").count,
        "slo_recovery_zero_flap": 1 if zero_flap else 0,
        "slo_recovery_events": len(stages),
    }


def bench_preemption_recovery() -> dict:
    """Preemption -> gang recovery latency (ISSUE 13) at 64 hosts.

    Two scenarios over a 16-slice/64-host fleet with one 4-host
    tpu-gang trainer (FakeAgent — control-plane latency, no jax):

      preemption_resume_s       single gang-host kill -> the WHOLE
                                gang relaunched and RUNNING again
                                (kill survivors, unreserve the broken
                                sub-slice, re-place honoring torus
                                adjacency on a spare slice, statuses
                                acked) — "time to training resumed"
                                at the scheduler's granularity
      preemption_storm_s        a 4-kill storm (2 at once, a third
                                mid-recovery, a fourth at a plan-
                                transition boundary) -> converged
                                with the storm invariants held (zero
                                double-reservations, zero orphaned
                                reservations on preempted hosts,
                                exactly one gang incarnation running)

    Wall budgets are generous CI fences (shared boxes swing), not
    perf claims: the point is that recovery converges in control-
    plane time, not operator time."""
    from dcos_commons_tpu.offer.inventory import make_test_fleet
    from dcos_commons_tpu.testing.chaos import (
        RECOVERY_ACTIVE,
        STORM_START,
        PreemptSpec,
        PreemptionStorm,
    )

    def fleet():
        hosts = []
        for s in range(16):  # 64 TPU hosts, 16 placeable slices
            hosts.extend(make_test_fleet(
                slice_id=f"pod-{s}", host_grid=(2, 2), chip_block=(2, 2),
                cpus=16.0, memory_mb=65536,
            ))
        return hosts

    # single gang-host preemption
    storm = PreemptionStorm(
        [PreemptSpec(at=STORM_START, hosts=1)], hosts=fleet(),
    )
    t0 = time.monotonic()
    report = storm.run(timeout_s=60.0)
    single_s = time.monotonic() - t0
    single_cycles = report.cycles
    storm.shutdown()

    # 4-kill storm: 2 simultaneous, 1 mid-recovery, 1 at a span
    # boundary the recovery work itself causes
    storm = PreemptionStorm(
        [
            PreemptSpec(at=STORM_START, hosts=2),
            PreemptSpec(at=RECOVERY_ACTIVE, occurrence=1, hosts=1),
            PreemptSpec(at="mid-plan-transition", occurrence=2, hosts=1),
        ],
        hosts=fleet(),
    )
    t0 = time.monotonic()
    storm_report = storm.run(timeout_s=120.0)
    storm_s = time.monotonic() - t0
    storm.shutdown()

    assert report.converged and storm_report.converged
    assert single_s < 10.0, f"single-kill resume took {single_s:.1f}s"
    assert storm_s < 30.0, f"4-kill storm took {storm_s:.1f}s"
    return {
        "preemption_hosts": 64,
        "preemption_resume_s": round(single_s, 3),
        "preemption_resume_cycles": single_cycles,
        "preemption_storm_kills": len(storm_report.preempted),
        "preemption_storm_s": round(storm_s, 3),
        "preemption_storm_cycles": storm_report.cycles,
        "preemption_storm_converged": storm_report.converged,
    }


def bench_multislice() -> dict:
    """Multi-slice gang lifecycle (ISSUE 20) on a 10,000-host world.

    One 2-slice x 4x4 elastic trainer gang (8 hosts over DCN) on a
    fleet of 2,500 slices where exactly TWO slices match the gang's
    generation — so a whole-slice preemption cannot re-place at full
    width and MUST take the elastic whole-slice shrink path
    (FakeAgent — control-plane latency, no jax):

      multislice_deploy_s         spec PUT -> 8 workers RUNNING with
                                  the cross-slice coordinator contract
                                  (TPU_SLICE_COORDS et al) claimed —
                                  slice-set placement over 10k hosts
      multislice_shrink_resume_s  one whole slice preempted,
                                  physically, statuses never arrive ->
                                  converged at 1 slice (kill
                                  survivors, unreserve, re-place
                                  shrunken, trim) — "time to training
                                  resumed at reduced width"
      multislice_regrow_s         the dead slice's hosts return ->
                                  converged back at declared width
                                  (the manager's elastic-regrow
                                  choreography)

    Wall budgets are generous CI fences (shared boxes swing), not
    perf claims: the point is that whole-slice elasticity converges
    in control-plane time even on a 10k-host world."""
    from dcos_commons_tpu.offer.inventory import make_test_fleet
    from dcos_commons_tpu.testing.chaos import (
        CHAOS_MULTISLICE_YAML,
        PreemptSpec,
        PreemptionStorm,
        STORM_START,
    )

    def fleet():
        hosts = []
        for s in range(2):  # the only slices matching the gang
            hosts.extend(make_test_fleet(
                slice_id=f"gang-{s}", host_grid=(2, 2),
                chip_block=(2, 2), generation="v5p",
                cpus=16.0, memory_mb=65536,
            ))
        for s in range(2498):  # 9,992 filler hosts, wrong generation
            hosts.extend(make_test_fleet(
                slice_id=f"filler-{s}", host_grid=(2, 2),
                chip_block=(2, 2), generation="v5e",
                cpus=16.0, memory_mb=65536,
            ))
        return hosts

    storm = PreemptionStorm(
        [PreemptSpec(at=STORM_START, hosts=1, whole_slice=True)],
        yaml_text=CHAOS_MULTISLICE_YAML.replace(
            "generation: v5e", "generation: v5p"
        ),
        hosts=fleet(),
    )
    scheduler = storm.harness.build_scheduler()
    storm.scheduler = scheduler
    n_hosts = len(storm.harness.hosts)

    # phase 1: the 2-slice deploy
    t0 = time.monotonic()
    deadline = t0 + 300.0
    while time.monotonic() < deadline:
        scheduler.run_cycle()
        storm._ack_staging(scheduler)
        if scheduler.deploy_manager.get_plan().is_complete:
            break
    deploy_s = time.monotonic() - t0
    assert scheduler.deploy_manager.get_plan().is_complete, \
        "2-slice deploy never completed"

    # phase 2: one whole slice preempted mid-training -> shrink
    t0 = time.monotonic()
    storm.preempt_now(1, whole_slice=True)
    shrink_cycles = 0
    while time.monotonic() < deadline:
        scheduler.run_cycle()
        shrink_cycles += 1
        for host_id in sorted(storm._unnotified):
            scheduler.note_host_preempted(host_id)
            storm._unnotified.discard(host_id)
        storm._ack_staging(scheduler)
        if storm._gang_converged(scheduler):
            break
    shrink_s = time.monotonic() - t0
    stored = [
        info for info in scheduler.state_store.fetch_tasks()
        if info.pod_type == "trainer"
    ]
    assert len(stored) == 4, \
        f"expected a 1-slice shrunken gang, got {len(stored)} workers"
    verbs = [
        e.get("verb")
        for e in scheduler.journal.events(kinds=("recovery",))
    ]
    assert "elastic-shrink" in verbs, verbs

    # phase 3: the dead slice returns -> regrow to declared width
    for host_id in list(storm.report.preempted):
        scheduler.inventory.mark_up(host_id)
    t0 = time.monotonic()
    regrow_cycles = 0
    regrown = False
    while time.monotonic() < deadline:
        scheduler.run_cycle()
        regrow_cycles += 1
        storm._ack_staging(scheduler)
        stored = [
            info for info in scheduler.state_store.fetch_tasks()
            if info.pod_type == "trainer"
        ]
        if len(stored) == 8 and storm._gang_converged(scheduler):
            regrown = True
            break
    regrow_s = time.monotonic() - t0
    assert regrown, "gang never regrew to declared width"
    verbs = [
        e.get("verb")
        for e in scheduler.journal.events(kinds=("recovery",))
    ]
    assert "elastic-regrow" in verbs, verbs
    storm.shutdown()

    assert deploy_s < 120.0, f"2-slice deploy took {deploy_s:.1f}s"
    assert shrink_s < 60.0, f"shrink-resume took {shrink_s:.1f}s"
    assert regrow_s < 60.0, f"regrow took {regrow_s:.1f}s"
    return {
        "multislice_hosts": n_hosts,
        "multislice_deploy_s": round(deploy_s, 3),
        "multislice_shrink_resume_s": round(shrink_s, 3),
        "multislice_shrink_cycles": shrink_cycles,
        "multislice_regrow_s": round(regrow_s, 3),
        "multislice_regrow_cycles": regrow_cycles,
    }


def bench_continuous_serve() -> dict:
    """Continuous batching vs dispatch-per-group serving (ISSUE 6),
    CPU-runnable: the SAME open-loop load — staggered arrivals, mixed
    generation lengths — driven through (a) the slot-pool engine
    (serve/engine.py + serve/pool.py: admit at every decode step,
    retire per-row) and (b) the dispatch-per-group baseline this PR
    replaced (MicroBatcher + one whole jitted generate per group,
    every row padded to MAX_NEW steps).  Three numbers are fenced:

    * GREEDY EQUALITY — both paths must produce token-identical
      continuations per request (correctness before speed);
    * tokens/s — useful tokens / makespan must IMPROVE: the baseline
      burns MAX_NEW steps per dispatch while the mean request wants
      ~half that (the mean-to-max ratio IS the headroom), and a
      request arriving mid-dispatch serializes behind it;
    * p95 TTFT — time to first token must DROP from O(a whole
      preceding generation) to O(one decode tick + own prefill).

    Open-loop: arrival times come from a fixed seeded schedule, never
    from completions — a saturating server cannot slow the offered
    load, exactly like production traffic."""
    import random
    import statistics
    import threading

    import numpy as np

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp

    from dcos_commons_tpu.models import (
        TransformerConfig,
        generate,
        init_params,
    )
    from dcos_commons_tpu.serve.engine import SlotEngine
    from dcos_commons_tpu.serve.pool import PoolModel
    from dcos_commons_tpu.utils.microbatch import (
        MicroBatcher,
        WorkItem,
        pack_mixed_rows,
        unpack_results,
    )

    # big enough that per-step compute dominates dispatch overhead on
    # CPU even in a CONTENDED window (the continuous path pays one
    # dispatch per TOKEN where the baseline scans inside one jit, so
    # inflated dispatch costs hit it ~5x harder — r6 tuning found
    # d256 bimodal on a shared box), small enough to compile fast
    config = TransformerConfig(
        vocab=512, d_model=512, n_layers=4, n_heads=8, n_kv_heads=8,
        d_ff=1376, max_seq=128, dtype=jnp.float32, remat=False,
    )
    params = init_params(config, jax.random.key(0))
    # a short prompt region keeps the per-request prefill ~one decode
    # tick: the bench isolates the SCHEDULING difference (per-step
    # admission + early retirement), which is what this PR changed —
    # chunked/batched prefill is its own future lever
    slots, max_new, max_len = 8, 32, 48
    prompt_len = max_len - max_new
    n_requests = 24

    # the offered load, shared by both paths: mixed generation
    # lengths (mean ~= half of max: the baseline's padding waste) and
    # staggered open-loop arrivals at roughly the continuous path's
    # service rate (the baseline saturates and queues)
    rng = random.Random(0)
    requests = []
    for i in range(n_requests):
        plen = rng.randint(3, 10)
        requests.append({
            "prompt": [rng.randrange(config.vocab) for _ in range(plen)],
            # mean 13.25 vs max 32: the mean-to-max ratio is the
            # baseline's padding waste (it decodes 32 steps per
            # dispatch no matter what its rows asked for)
            "n": [3, 6, 12, max_new][i % 4],
        })

    def run_load(submit, reqs=None, arrivals=None):
        """Drive an open-loop schedule; returns (per-request results,
        per-request completion latencies, makespan).  Defaults to the
        legacy round's request set and arrival schedule."""
        if reqs is None:
            reqs = requests
        if arrivals is None:
            arrivals = []
            t = 0.0
            for i in range(len(reqs)):
                arrivals.append(t)
                t += rng_arrival[i]
        results = [None] * len(reqs)
        done_s = [0.0] * len(reqs)
        errors = []
        t0 = time.monotonic()

        def client(i):
            delay = arrivals[i] - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            try:
                results[i] = submit(
                    reqs[i]["prompt"], reqs[i]["n"]
                )
                done_s[i] = (time.monotonic() - t0) - arrivals[i]
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(len(reqs))
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)
        assert not errors, errors
        makespan = time.monotonic() - t0
        return results, done_s, makespan

    # calibrate one decode-step's cost to set the arrival cadence
    # (absolute wall clocks vary 10x across hosts; the SCHEDULE must
    # stress both paths identically relative to the chip's speed)
    pool = PoolModel(config, params, slots, max_len)
    pool.warm(prompt_len)
    t0 = time.monotonic()
    for _ in range(5):
        pool.decode(
            np.zeros(slots, np.int32),
            np.full(slots, prompt_len, np.int32),
            np.zeros(slots, np.float32), np.zeros(slots, np.int32),
        )
    step_s = (time.monotonic() - t0) / 5
    # ~1 tick between arrivals SATURATES both servers: the makespan
    # then measures each scheduler's sustained service rate, not the
    # shared arrival window — and the baseline's head-of-line wait
    # (a whole dispatch) shows up undiluted in its TTFT
    rng_arrival = [rng.expovariate(1.0 / step_s)
                   for _ in range(n_requests)]

    # -- the two servers ------------------------------------------
    ticks = [0, 0]  # (decode ticks, active-row steps) across rounds

    def counted_decode(tok, pos, temps, seeds, n_active):
        ticks[0] += 1
        ticks[1] += n_active
        return pool.decode(tok, pos, temps, seeds)

    gen = jax.jit(lambda p, t, n: generate(
        config, p, t, max_new_tokens=max_new, max_len=max_len,
        true_len=n,
    ))
    lock = threading.Lock()

    def run_group(items):
        padded, lens, _used = pack_mixed_rows(items, slots, prompt_len)
        with lock:
            out = gen(params, jnp.asarray(padded), jnp.asarray(lens))
        unpack_results(items, np.asarray(jax.device_get(out)))

    # warm the baseline compile outside the measured windows too
    run_group([WorkItem([[0] * prompt_len], max_new, 0.0)])
    useful_tokens = sum(r["n"] for r in requests)

    from dcos_commons_tpu.metrics.registry import (
        percentile as _nearest_rank,
    )

    def percentile(samples, q):
        # the one shared nearest-rank convention (metrics/registry.py)
        return _nearest_rank(sorted(samples), q)

    def measure_continuous():
        engine = SlotEngine(
            pool.prefill, counted_decode, slots, max_len, prompt_len,
            queue_timeout_s=600,
        )
        try:
            results, done, makespan = run_load(
                lambda prompt, n: engine.submit([prompt], n)[0]
            )
            stats = engine.stats()
        finally:
            engine.stop()
        return results, {
            "tps": useful_tokens / makespan,
            "p50": stats["ttft_p50_s"], "p95": stats["ttft_p95_s"],
            "mean": statistics.mean(done),
        }

    def measure_baseline():
        batcher = MicroBatcher(
            run_group, capacity=slots, window_s=0.0,
            queue_timeout_s=600,
        )
        results, done, makespan = run_load(
            lambda prompt, n: batcher.submit(
                WorkItem([prompt], n, 0.0)
            )[0]
        )
        # baseline TTFT = completion: dispatch-per-group cannot
        # stream a first token before its whole generate finishes
        return results, {
            "tps": useful_tokens / makespan,
            "p50": percentile(done, 50), "p95": percentile(done, 95),
            "mean": statistics.mean(done),
        }

    # ALTERNATING adjacent pairs, fenced on the MEDIAN per-pair ratio
    # (the PR 5 lesson: this host's CPU availability swings 2-3x
    # between windows; a continuous-then-baseline pair runs ~seconds
    # apart, so the ratio inside a pair mostly cancels the swing and
    # the median rejects the pair a preemption spike lands in — a
    # noisy box cannot fake a systematic win, only hide one)
    cont_rounds, base_rounds = [], []
    for _round in range(3):
        cont_results, cont_m = measure_continuous()
        base_results, base_m = measure_baseline()
        # correctness first, EVERY round: token-identical greedy
        # continuations or the perf numbers mean nothing
        assert cont_results == base_results, (
            "continuous batching changed a greedy continuation"
        )
        cont_rounds.append(cont_m)
        base_rounds.append(base_m)
    speedup = statistics.median(
        c["tps"] / b["tps"] for c, b in zip(cont_rounds, base_rounds)
    )
    ttft_improvement = statistics.median(
        b["p95"] / max(c["p95"], 1e-9)
        for c, b in zip(cont_rounds, base_rounds)
    )
    # absolutes reported from each path's best window
    cont_tps = max(m["tps"] for m in cont_rounds)
    base_tps = max(m["tps"] for m in base_rounds)
    cont_p50 = min(m["p50"] for m in cont_rounds)
    cont_p95 = min(m["p95"] for m in cont_rounds)
    base_p50 = min(m["p50"] for m in base_rounds)
    base_p95 = min(m["p95"] for m in base_rounds)
    utilization = ticks[1] / float(max(1, ticks[0]) * slots)

    # ---- ISSUE 11: paged arena vs slot pool at the SAME HBM budget
    # geometry: slot pool 8 rows x 64 positions == paged 64 pages x 8
    # tokens (byte-identical KV bytes); the paged arm runs 2x the
    # decode rows over that budget — the capacity multiplier block-
    # granular allocation buys when most requests use a fraction of a
    # MAX_LEN row.  Load: one LONG-prompt request followed hot by
    # n=1 short probes (a probe's completion time IS its TTFT — the
    # head-of-line scenario chunked prefill exists to fix), then a
    # saturating mixed tail, part of it sharing an 8-token system
    # prefix (the prefix-cache traffic shape).  Three fences:
    # greedy token-equality (every round), >= 1.3x peak concurrent
    # requests sustained, and no p95 TTFT regression for the short
    # probes behind the long prefill (median of adjacent pairs, same
    # methodology as above).
    from dcos_commons_tpu.serve.engine import PagedEngine
    from dcos_commons_tpu.serve.pool import PagedPoolModel

    p_tok = 8
    chunk_p = 8
    max_len_p = 64
    prompt_len_p = max_len_p - max_new           # 32: 4 chunks
    pages_p = slots * max_len_p // p_tok         # 64 pages: same bytes
    slots_p = slots * 2                          # 16 decode rows
    sys_prefix = [rng.randrange(config.vocab) for _ in range(8)]
    long_prompt = [
        rng.randrange(config.vocab) for _ in range(prompt_len_p)
    ]
    paged_reqs = [{"prompt": long_prompt, "n": max_new}]
    short_idx = []
    for i in range(8):
        short_idx.append(len(paged_reqs))
        paged_reqs.append({
            "prompt": [rng.randrange(config.vocab)
                       for _ in range(3 + i % 2)],
            "n": 1,
        })
    for i in range(21):
        if i % 2:
            prompt = sys_prefix + [
                rng.randrange(config.vocab) for _ in range(2 + i % 5)
            ]
        else:
            prompt = [
                rng.randrange(config.vocab) for _ in range(3 + i % 8)
            ]
        paged_reqs.append({
            "prompt": prompt, "n": [max_new, 6, max_new, 12][i % 4],
        })
    # the long at t=0, probes hot on its heels, the tail at a
    # saturating ~half-step cadence
    arrivals_p = [0.0] + [0.02 * (i + 1) * step_s for i in range(8)]
    t_arr = arrivals_p[-1]
    for _ in range(21):
        t_arr += 0.5 * step_s
        arrivals_p.append(t_arr)
    useful_p = sum(r["n"] for r in paged_reqs)

    slot_pool_p = PoolModel(config, params, slots, max_len_p)
    slot_pool_p.warm(prompt_len_p)
    paged_pool = PagedPoolModel(
        config, params, slots_p, max_len_p, p_tok, pages_p, chunk_p
    )
    paged_pool.warm()

    def measure_slot_arm():
        peak = [0]

        def decode(tok, pos, temps, seeds, n_active):
            peak[0] = max(peak[0], n_active)
            return slot_pool_p.decode(tok, pos, temps, seeds)

        engine = SlotEngine(
            slot_pool_p.prefill, decode, slots, max_len_p,
            prompt_len_p, queue_timeout_s=600,
        )
        try:
            results, done, makespan = run_load(
                lambda prompt, n: engine.submit([prompt], n)[0],
                paged_reqs, list(arrivals_p),
            )
        finally:
            engine.stop()
        return results, {
            "tps": useful_p / makespan,
            "peak": peak[0],
            "short_p95": percentile(
                [done[i] for i in short_idx], 95
            ),
        }

    def measure_paged_arm():
        peak = [0]

        def decode(tok, pos, temps, seeds, tables, n_active):
            peak[0] = max(peak[0], n_active)
            return paged_pool.decode(tok, pos, temps, seeds, tables)

        engine = PagedEngine(
            paged_pool.prefill_chunk, decode, slots_p, max_len_p,
            prompt_len_p, page_tokens=p_tok, pages=pages_p,
            chunk_tokens=chunk_p, queue_timeout_s=600,
        )
        try:
            results, done, makespan = run_load(
                lambda prompt, n: engine.submit([prompt], n)[0],
                paged_reqs, list(arrivals_p),
            )
            stats = engine.stats()
        finally:
            engine.stop()
        return results, {
            "tps": useful_p / makespan,
            "peak": peak[0],
            "short_p95": percentile(
                [done[i] for i in short_idx], 95
            ),
            "prefix_hit_rate": stats["prefix_cache_hit_rate"],
        }

    paged_rounds, slotp_rounds = [], []
    for _round in range(3):
        p_res, p_m = measure_paged_arm()
        s_res, s_m = measure_slot_arm()
        # correctness first, EVERY round: the paged arena must not
        # change a single greedy token vs the slot pool
        assert p_res == s_res, (
            "paged arena changed a greedy continuation"
        )
        paged_rounds.append(p_m)
        slotp_rounds.append(s_m)
    paged_peak = max(m["peak"] for m in paged_rounds)
    slotp_peak = max(m["peak"] for m in slotp_rounds)
    paged_tps_x = statistics.median(
        p["tps"] / s["tps"]
        for p, s in zip(paged_rounds, slotp_rounds)
    )
    paged_short_ttft_ratio = statistics.median(
        p["short_p95"] / max(s["short_p95"], 1e-9)
        for p, s in zip(paged_rounds, slotp_rounds)
    )

    out = {
        "continuous_serve_requests": n_requests,
        "continuous_serve_slots": slots,
        "continuous_serve_rounds": len(cont_rounds),
        "continuous_serve_step_s": round(step_s, 5),
        "continuous_serve_tokens_per_s": round(cont_tps, 1),
        "continuous_serve_baseline_tokens_per_s": round(base_tps, 1),
        "continuous_serve_speedup_x": round(speedup, 2),
        "continuous_serve_ttft_p50_s": round(cont_p50, 4),
        "continuous_serve_ttft_p95_s": round(cont_p95, 4),
        "continuous_serve_baseline_ttft_p50_s": round(base_p50, 4),
        "continuous_serve_baseline_ttft_p95_s": round(base_p95, 4),
        "continuous_serve_ttft_p95_improvement_x": round(
            ttft_improvement, 2
        ),
        "continuous_serve_slot_utilization": round(utilization, 3),
        "continuous_serve_mean_latency_s": round(
            min(m["mean"] for m in cont_rounds), 4
        ),
        "continuous_serve_baseline_mean_latency_s": round(
            min(m["mean"] for m in base_rounds), 4
        ),
        # paged arena vs slot pool at the SAME HBM budget (ISSUE 11)
        "continuous_serve_paged_pages": pages_p,
        "continuous_serve_paged_page_tokens": p_tok,
        "continuous_serve_paged_rows": slots_p,
        "continuous_serve_paged_chunk_tokens": chunk_p,
        "continuous_serve_paged_requests": len(paged_reqs),
        "continuous_serve_paged_peak_concurrent": paged_peak,
        "continuous_serve_paged_slot_peak_concurrent": slotp_peak,
        "continuous_serve_paged_concurrency_x": round(
            paged_peak / max(slotp_peak, 1), 2
        ),
        "continuous_serve_paged_tokens_per_s": round(
            max(m["tps"] for m in paged_rounds), 1
        ),
        "continuous_serve_paged_slot_tokens_per_s": round(
            max(m["tps"] for m in slotp_rounds), 1
        ),
        "continuous_serve_paged_tps_x": round(paged_tps_x, 2),
        "continuous_serve_paged_short_ttft_p95_s": round(
            min(m["short_p95"] for m in paged_rounds), 4
        ),
        "continuous_serve_paged_slot_short_ttft_p95_s": round(
            min(m["short_p95"] for m in slotp_rounds), 4
        ),
        "continuous_serve_paged_short_ttft_ratio": round(
            paged_short_ttft_ratio, 3
        ),
        "continuous_serve_paged_prefix_hit_rate": round(
            max(m["prefix_hit_rate"] for m in paged_rounds), 4
        ),
    }
    print(  # the human summary (stderr: stdout carries bench JSON)
        f"[continuous-serve] tokens/s {base_tps:.1f} -> {cont_tps:.1f} "
        f"(median pairwise {speedup:.2f}x), p95 TTFT "
        f"{base_p95:.3f}s -> {cont_p95:.3f}s "
        f"(median pairwise {ttft_improvement:.2f}x), "
        f"slot utilization {utilization:.0%}",
        file=sys.stderr, flush=True,
    )
    print(
        f"[continuous-serve/paged] same {pages_p * p_tok}-token KV "
        f"budget: peak concurrent {slotp_peak} -> {paged_peak} "
        f"({paged_peak / max(slotp_peak, 1):.2f}x), tokens/s median "
        f"pairwise {paged_tps_x:.2f}x, short-probe p95 TTFT ratio "
        f"{paged_short_ttft_ratio:.2f} (<1 = paged faster), prefix "
        f"hit rate "
        f"{max(m['prefix_hit_rate'] for m in paged_rounds):.0%}",
        file=sys.stderr, flush=True,
    )
    # the tentpole's bound, asserted: continuous batching must beat
    # dispatch-per-group on BOTH throughput and p95 TTFT under the
    # same open-loop load (median of adjacent-pair ratios)
    assert speedup > 1.0, (
        f"continuous batching tokens/s did not beat dispatch-per-"
        f"group: median pairwise ratio {speedup:.2f}"
    )
    assert ttft_improvement > 1.0, (
        f"continuous batching p95 TTFT did not beat dispatch-per-"
        f"group: median pairwise ratio {ttft_improvement:.2f}"
    )
    # ISSUE 11 fences: at the SAME HBM budget the paged arm must
    # sustain >= 1.3x the slot pool's concurrent requests, and the
    # short probes admitted behind the long prefill must show no p95
    # TTFT regression (small collar for pairwise residual noise —
    # chunked prefill should WIN here, and the reported ratio tracks
    # by how much)
    assert paged_peak >= 1.3 * slotp_peak, (
        f"paged arena sustained {paged_peak} concurrent vs the slot "
        f"pool's {slotp_peak} at the same KV budget (< 1.3x)"
    )
    assert paged_short_ttft_ratio <= 1.1, (
        f"short requests behind a long prefill regressed: paged/slot "
        f"p95 TTFT ratio {paged_short_ttft_ratio:.2f}"
    )
    return out


def bench_router_scale() -> dict:
    """Serving front door (ISSUE 12), CPU-runnable and jax-free: an
    open-loop load sweep through the multi-pod RequestRouter over 1,
    2 and 4 in-process "pods" — each a REAL PagedEngine (page-
    budgeted admission, chunked prefill, refcounted prefix cache)
    over a deterministic chain model whose decode tick costs a fixed
    calibrated sleep, so pod service time is held constant and the
    sweep measures the ROUTING layer: placement quality, affinity,
    drain/failover.  (In production each pod is its own host; the
    sleep stands in for the chip tick.)  Four fences:

    * GREEDY EQUALITY, every round — continuations through the
      router are token-identical to direct-to-pod (the chain
      oracle), including through prefix-cache hits and mid-sweep
      failover: the router must never corrupt or duplicate a reply;
    * NEAR-LINEAR SCALING — aggregate tokens/s at 4 pods >= 3x the
      single-pod run under proportionally-scaled offered load;
    * AFFINITY BEATS SPRAY — under a shared-system-prompt session
      workload, prefix-affinity routing must beat round-robin on the
      pods' aggregate prefix_cache_hit_rate (random spray makes
      every pod re-prefill every session: the 1/N dilution);
    * BOUNDED DRAIN — a mid-sweep drain + kill of one pod loses no
      request (in-flight fails over within the retry budget, the
      drained pod takes zero new admissions) and p95 completion
      latency stays within a fenced ratio of the steady-state round.

    Open-loop throughout: arrivals ride a fixed schedule, never
    completions — a saturating tier cannot slow its offered load.
    """
    import random
    import statistics
    import threading

    import numpy as np

    from dcos_commons_tpu.router import PodTransportError, RequestRouter
    from dcos_commons_tpu.serve.engine import PagedEngine

    _V = 997

    def _chain_first(prompt):
        return (sum(prompt) * 31 + len(prompt)) % _V

    def _chain_next(tok, pos):
        return (tok * 7 + pos * 3 + 1) % _V

    def _oracle(prompt, n):
        out = [_chain_first(prompt)]
        pos = len(prompt)
        while len(out) < n:
            out.append(_chain_next(out[-1], pos))
            pos += 1
        return out

    # pod geometry: pages of 4 so an 8-token session prefix is two
    # cacheable full pages; the decode tick's sleep is the modeled
    # chip time (dominates the host bookkeeping by ~100x)
    P_TOK, CHUNK, MAX_LEN, PROMPT_LEN = 4, 8, 32, 24
    SLOTS, STEP_S = 8, 0.01
    PAGES = SLOTS * (MAX_LEN // P_TOK)
    MAX_NEW = 8

    class ChainArena:
        """The fake device half of a paged pod: every prefilled
        token is written into its (page, offset) cell, so a prefix-
        cache-served prefix is RECONSTRUCTED from the arena exactly
        like real attention would gather it — first tokens depend on
        the full prompt regardless of how much the cache served, and
        greedy equality survives any hit depth."""

        def __init__(self):
            self.cells = {}  # page -> {offset: token}
            self.lock = threading.Lock()

        def prefill_chunk(self, padded, slot, table, start, true_len,
                          temp, seed):
            time.sleep(STEP_S * 0.5)  # the modeled prefill dispatch
            with self.lock:
                buf = [
                    self.cells[int(table[pos // P_TOK])][pos % P_TOK]
                    for pos in range(start)
                ]
                for i in range(true_len):
                    pos = start + i
                    page = int(table[pos // P_TOK])
                    tok = int(padded[0, i])
                    self.cells.setdefault(page, {})[pos % P_TOK] = tok
                    buf.append(tok)
            return _chain_first(buf)

        def decode(self, tok, pos, temps, seeds, tables, n_active):
            time.sleep(STEP_S)  # the modeled decode tick
            return np.asarray(
                [_chain_next(int(t), int(q))
                 for t, q in zip(tok, pos)],
                np.int32,
            )

    class BenchPod:
        def __init__(self, name):
            self.name = name
            self.arena = ChainArena()
            self.engine = PagedEngine(
                self.arena.prefill_chunk, self.arena.decode, SLOTS,
                MAX_LEN, PROMPT_LEN, page_tokens=P_TOK, pages=PAGES,
                chunk_tokens=CHUNK, prefix_cache=True,
                queue_timeout_s=600,
            )
            self.killed = threading.Event()
            self.admitted = 0

        def send(self, request):
            if self.killed.is_set():
                raise PodTransportError(f"{self.name} is dead")
            self.admitted += 1
            out = self.engine.submit(
                request["tokens"], request["max_new_tokens"],
            )
            if self.killed.is_set():
                # the reply died on the wire: the failover trigger
                raise PodTransportError(f"{self.name} died mid-reply")
            return out

        def stop(self):
            self.engine.stop()

    def build_workload(n_pods, rng):
        """Per-pod-scaled session traffic: 6-request sessions sharing
        an 8-token (two-full-page) prefix, plus unshared one-offs —
        arrivals saturate the tier at ~1.3x its service rate so the
        makespan measures sustained routing throughput."""
        n_sessions = 10 * n_pods
        reqs = []
        for s in range(n_sessions):
            prefix = [rng.randrange(_V) for _ in range(8)]
            for i in range(6):
                reqs.append({
                    "prompt": prefix + [
                        rng.randrange(_V) for _ in range(1 + i % 4)
                    ],
                    "n": [2, 4, MAX_NEW, MAX_NEW, 4, 6][i % 6],
                })
        for _ in range(12 * n_pods):
            reqs.append({
                "prompt": [rng.randrange(_V)
                           for _ in range(2 + rng.randrange(8))],
                "n": [2, 4, MAX_NEW][rng.randrange(3)],
            })
        rng.shuffle(reqs)
        useful = sum(r["n"] for r in reqs)
        # offered rate = 1.5x the tier's token service rate: deep
        # enough saturation that every pod's decode rows stay full
        capacity_tps = n_pods * SLOTS / STEP_S
        span = useful / (1.5 * capacity_tps)
        arrivals = sorted(rng.uniform(0.0, span) for _ in reqs)
        return reqs, arrivals, useful

    def run_round(n_pods, policy, rng, drain_script=None):
        """One open-loop load through a fresh router + fresh pods.
        Returns (metrics dict, pods) — pods still warm for gauge
        reads; caller stops them."""
        pods = {f"p{i}": BenchPod(f"p{i}") for i in range(n_pods)}
        router = RequestRouter(
            lambda name, addr, req: pods[name].send(req),
            page_tokens=P_TOK, policy=policy, stale_after_s=5.0,
            retry_budget=2,
            # a tight slack keeps session pinning from imbalancing
            # the tier: a hot pod sheds affinity traffic early
            affinity_slack=2.0,
        )
        router.update_pods(
            {n: {"address": f"{n}:0"} for n in pods}, generation="g1"
        )
        stop_poll = threading.Event()

        def poller():
            while not stop_poll.is_set():
                for name, pod in pods.items():
                    if not pod.killed.is_set():
                        router.observe_stats(name, pod.engine.stats())
                stop_poll.wait(0.025)

        reqs, arrivals, useful = build_workload(n_pods, rng)
        results = [None] * len(reqs)
        done_s = [0.0] * len(reqs)
        errors = []
        t0 = time.monotonic()

        def client(i):
            delay = arrivals[i] - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            t_req = time.monotonic()
            try:
                results[i] = router.submit(
                    reqs[i]["prompt"], reqs[i]["n"]
                )
                done_s[i] = time.monotonic() - t_req
            except Exception as e:  # noqa: BLE001
                errors.append((i, e))

        poll_thread = threading.Thread(target=poller, daemon=True)
        poll_thread.start()
        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(len(reqs))
        ]
        span = arrivals[-1] if arrivals else 0.0
        script_thread = None
        if drain_script is not None:
            script_thread = threading.Thread(
                target=drain_script, args=(router, pods, t0, span),
                daemon=True,
            )
            script_thread.start()
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)
        makespan = time.monotonic() - t0
        stop_poll.set()
        poll_thread.join(timeout=5)
        if script_thread is not None:
            script_thread.join(timeout=5)
        assert not errors, errors[:3]
        # correctness before speed, EVERY round: token-identical to
        # direct-to-pod, through cache hits and failovers alike
        for req, result in zip(reqs, results):
            assert result == _oracle(req["prompt"], req["n"]), (
                "router changed a greedy continuation"
            )
        hits = lookups = 0
        for pod in pods.values():
            s = pod.engine.stats()
            hits += s["prefix_cache_hits"]
            lookups += s["prefix_cache_lookups"]
        metrics = {
            "tps": useful / makespan,
            "p95": statistics.quantiles(done_s, n=20)[-1]
            if len(done_s) >= 2 else done_s[0],
            "hit_rate": hits / lookups if lookups else 0.0,
            "router": router.stats(),
        }
        return metrics, pods

    out = {
        "router_scale_step_s": STEP_S,
        "router_scale_slots": SLOTS,
        "router_scale_page_tokens": P_TOK,
    }

    # ---- the 1 -> 2 -> 4 pod sweep (affinity policy, the default)
    sweep = {}
    for n_pods in (1, 2, 4):
        m, pods = run_round(n_pods, "affinity", random.Random(n_pods))
        for pod in pods.values():
            pod.stop()
        sweep[n_pods] = m
        out[f"router_scale_tokens_per_s_{n_pods}p"] = round(m["tps"], 1)
        out[f"router_scale_p95_s_{n_pods}p"] = round(m["p95"], 4)
    scale_x = sweep[4]["tps"] / sweep[1]["tps"]
    out["router_scale_x_4p"] = round(scale_x, 2)

    # ---- prefix affinity vs round-robin spray (4 pods, same seed:
    # identical session workload, only the placement policy differs)
    aff, aff_pods = run_round(4, "affinity", random.Random(99))
    for pod in aff_pods.values():
        pod.stop()
    rr, rr_pods = run_round(4, "round-robin", random.Random(99))
    for pod in rr_pods.values():
        pod.stop()
    out["router_affinity_prefix_hit_rate"] = round(aff["hit_rate"], 4)
    out["router_roundrobin_prefix_hit_rate"] = round(rr["hit_rate"], 4)
    out["router_affinity_tokens_per_s"] = round(aff["tps"], 1)
    out["router_roundrobin_tokens_per_s"] = round(rr["tps"], 1)
    out["router_affinity_hit_rate_gain"] = round(
        aff["hit_rate"] - rr["hit_rate"], 4
    )

    # ---- mid-sweep drain + kill: graceful drain at 40% of the
    # arrival span, hard kill at 70% — in-flight work fails over
    def drain_script(router, pods, t0, span):
        deadline = t0 + 0.4 * span
        wait = deadline - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        router.drain("p3")
        wait = t0 + 0.7 * span - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        pods["p3"].killed.set()

    drain, drain_pods = run_round(
        4, "affinity", random.Random(7), drain_script=drain_script
    )
    drained_admitted = drain_pods["p3"].admitted
    for pod in drain_pods.values():
        pod.stop()
    out["router_drain_p95_s"] = round(drain["p95"], 4)
    drain_ratio = drain["p95"] / max(sweep[4]["p95"], 1e-9)
    out["router_drain_p95_ratio"] = round(drain_ratio, 2)
    out["router_drain_failovers"] = drain["router"]["router_failovers"]
    out["router_drain_completed"] = drain["router"]["requests_completed"]

    print(
        f"[router-scale] tokens/s 1p {sweep[1]['tps']:.0f} -> 2p "
        f"{sweep[2]['tps']:.0f} -> 4p {sweep[4]['tps']:.0f} "
        f"({scale_x:.2f}x), prefix hit rate affinity "
        f"{aff['hit_rate']:.0%} vs round-robin {rr['hit_rate']:.0%}, "
        f"drain p95 ratio {drain_ratio:.2f} "
        f"({drain['router']['router_failovers']} failover(s))",
        file=sys.stderr, flush=True,
    )
    # the headline fences
    assert scale_x >= 3.0, (
        f"aggregate tokens/s at 4 pods only {scale_x:.2f}x one pod "
        "(near-linear fence is 3.0x)"
    )
    assert aff["hit_rate"] > rr["hit_rate"], (
        f"prefix affinity ({aff['hit_rate']:.2%}) did not beat "
        f"round-robin spray ({rr['hit_rate']:.2%}) on prefix cache "
        "hit rate"
    )
    # every drain-round request completed (none lost) and the drained
    # pod took zero admissions after its drain point is implied by
    # the equality + failed-send accounting; the p95 collar bounds
    # the failover detour
    assert drain_ratio <= 4.0, (
        f"p95 completion latency through a pod drain blew out "
        f"{drain_ratio:.1f}x vs steady state (fence 4.0x)"
    )
    assert drained_admitted < drain["router"]["requests_admitted"], (
        "drain round routed every request at the drained pod"
    )
    return out


def bench_disagg() -> dict:
    """Disaggregated prefill/decode + live KV page migration
    (ISSUE 16), CPU-runnable and jax-free: the same calibrated-sleep
    chain pods as ``bench_router_scale``, arranged two ways under an
    identical long-prefill-heavy mix —

    * UNIFIED: two unified pods; long prompts' chunked prefill
      interleaves with every pod's decode ticks, so short requests
      pay head-of-line TTFT behind long prefills;
    * DISAGGREGATED: one prefill-role pod + one decode-role pod; the
      router sends long prompts to prefill capacity, the prefill pod
      streams finished pages to the decode pool (the migration
      protocol), and short requests land on a pod that never runs a
      long prefill.

    Fences: greedy equality on EVERY request in both topologies
    (zero token loss through handoff + collect-follow), disaggregated
    short-request p95 TTFT strictly better than unified, and
    drain-with-migration strictly faster than waiting out the
    generations.  Also reported, unfenced: decode tick jitter per
    topology and the bytes/duration of one mid-generation move over
    the simulated DCN transport.
    """
    import random
    import statistics
    import threading

    import numpy as np

    from dcos_commons_tpu.router import RequestRouter
    from dcos_commons_tpu.serve.engine import PagedEngine
    from dcos_commons_tpu.serve.migration import (
        PrefillHandoff,
        SessionMigratedError,
        SimulatedDcnTransport,
        drain_sessions,
        migrate_session,
    )

    _V = 997

    def _chain_first(prompt):
        return (sum(prompt) * 31 + len(prompt)) % _V

    def _chain_next(tok, pos):
        return (tok * 7 + pos * 3 + 1) % _V

    def _oracle(prompt, n):
        out = [_chain_first(prompt)]
        pos = len(prompt)
        while len(out) < n:
            out.append(_chain_next(out[-1], pos))
            pos += 1
        return out

    P_TOK, CHUNK, MAX_LEN, PROMPT_LEN = 4, 8, 64, 48
    SLOTS, STEP_S, PAGES = 8, 0.01, 160
    LONG = 40  # >= the router's 4*page_tokens prefill-route floor

    class ChainArena:
        """Content-faithful fake device (the test_migration arena):
        every token lands in its (page, offset) cell so a migrated
        page's payload is the real export/import contract, and
        prefill resume after a move reads the spliced cells."""

        def __init__(self):
            self.cells = {}
            self.lock = threading.Lock()
            self.ticks = []  # decode dispatch timestamps (jitter)

        def prefill_chunk(self, padded, slot, table, start, true_len,
                          temp, seed):
            # a full-width chunk costs about a decode tick on real
            # chips; the 5-chunk long prompts are the head-of-line
            # hazard this bench measures
            time.sleep(STEP_S)
            with self.lock:
                buf = [
                    self.cells[int(table[pos // P_TOK])][pos % P_TOK]
                    for pos in range(start)
                ]
                for i in range(true_len):
                    pos = start + i
                    page = int(table[pos // P_TOK])
                    tok = int(padded[0, i])
                    self.cells.setdefault(page, {})[pos % P_TOK] = tok
                    buf.append(tok)
            return _chain_first(buf)

        def decode(self, tok, pos, temps, seeds, tables, n_active):
            time.sleep(STEP_S)  # the modeled decode tick
            with self.lock:
                self.ticks.append(time.monotonic())
                for s in range(len(tok)):
                    if int(pos[s]) > 0:
                        page = int(tables[s][int(pos[s]) // P_TOK])
                        if page != 0:
                            self.cells.setdefault(page, {})[
                                int(pos[s]) % P_TOK
                            ] = int(tok[s])
            return np.asarray(
                [_chain_next(int(t), int(q))
                 for t, q in zip(tok, pos)],
                np.int32,
            )

        def read_page(self, page):
            with self.lock:
                return dict(self.cells.get(page, {}))

        def write_page(self, page, payload):
            with self.lock:
                self.cells[page] = dict(payload)

    class BenchPod:
        def __init__(self, name, role="unified", handoff=None):
            self.name = name
            self.arena = ChainArena()
            self.engine = PagedEngine(
                self.arena.prefill_chunk, self.arena.decode, SLOTS,
                MAX_LEN, PROMPT_LEN, page_tokens=P_TOK, pages=PAGES,
                chunk_tokens=CHUNK, prefix_cache=True, role=role,
                read_page=self.arena.read_page,
                write_page=self.arena.write_page, handoff=handoff,
                queue_timeout_s=600,
            )

        def send(self, request):
            if "collect" in request:
                # the router following a migrated session
                return [self.engine.collect(
                    int(request["collect"]), timeout=120
                )]
            return self.engine.submit(
                request["tokens"], request["max_new_tokens"]
            )

        def stop(self):
            self.engine.stop()

    def build_mix(rng):
        """Long-prefill-heavy: 36 long prompts (5 prefill chunks
        each), 48 decode-load shorts, and 24 one-token PROBES whose
        client-side completion time IS their TTFT (queue + prefill +
        first sample; no decode tail to blur it)."""
        reqs = []
        for _ in range(36):
            reqs.append({
                "prompt": [rng.randrange(_V) for _ in range(LONG)],
                "n": 4, "probe": False,
            })
        for _ in range(44):
            reqs.append({
                "prompt": [rng.randrange(_V)
                           for _ in range(4 + rng.randrange(8))],
                "n": 6, "probe": False,
            })
        for _ in range(32):
            reqs.append({
                "prompt": [rng.randrange(_V)
                           for _ in range(4 + rng.randrange(4))],
                "n": 1, "probe": True,
            })
        rng.shuffle(reqs)
        arrivals = sorted(rng.uniform(0.0, 2.4) for _ in reqs)
        return reqs, arrivals

    def run_topology(disagg):
        """One open-loop mix through a fresh router + fresh pods;
        identical workload seed either way, only the topology
        differs.  Returns (probe p95 TTFT, decode tick jitter ms,
        handoff counters)."""
        handoff = None
        if disagg:
            pods = {}
            pods["dc0"] = BenchPod("dc0", role="decode")
            handoff = PrefillHandoff(
                lambda: {"dc0": pods["dc0"].engine}
            )
            pods["pf0"] = BenchPod(
                "pf0", role="prefill", handoff=handoff
            )
            entries = {
                "pf0": {"address": "pf0:0", "role": "prefill"},
                "dc0": {"address": "dc0:0", "role": "decode"},
            }
            decode_arenas = [pods["dc0"].arena]
        else:
            pods = {n: BenchPod(n) for n in ("u0", "u1")}
            entries = {n: {"address": f"{n}:0"} for n in pods}
            decode_arenas = [p.arena for p in pods.values()]
        router = RequestRouter(
            lambda name, addr, req: pods[name].send(req),
            page_tokens=P_TOK, policy="affinity",
            stale_after_s=5.0, retry_budget=2,
        )
        router.update_pods(entries, generation="g1")
        stop_poll = threading.Event()

        def poller():
            while not stop_poll.is_set():
                for name, pod in pods.items():
                    router.observe_stats(name, pod.engine.stats())
                stop_poll.wait(0.025)

        rng = random.Random(16)
        reqs, arrivals = build_mix(rng)
        results = [None] * len(reqs)
        done_s = [0.0] * len(reqs)
        errors = []
        t0 = time.monotonic()

        def client(i):
            delay = arrivals[i] - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            t_req = time.monotonic()
            try:
                results[i] = router.submit(
                    reqs[i]["prompt"], reqs[i]["n"]
                )
                done_s[i] = time.monotonic() - t_req
            except Exception as e:  # noqa: BLE001
                errors.append((i, e))

        poll_thread = threading.Thread(target=poller, daemon=True)
        poll_thread.start()
        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(len(reqs))
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)
        stop_poll.set()
        poll_thread.join(timeout=5)
        assert not errors, errors[:3]
        # zero token loss, EVERY request: identical to direct-to-pod,
        # through prefill handoff + collect-follow included
        for req, result in zip(reqs, results):
            assert result == _oracle(req["prompt"], req["n"]), (
                "topology changed a greedy continuation"
            )
        probes = [d for r, d in zip(reqs, done_s) if r["probe"]]
        p95 = statistics.quantiles(probes, n=20)[-1]
        gaps = []
        for arena in decode_arenas:
            with arena.lock:
                ticks = list(arena.ticks)
            gaps.extend(
                b - a for a, b in zip(ticks, ticks[1:])
                if b - a <= 10 * STEP_S  # drop idle-loop stretches
            )
        jitter_ms = (
            statistics.pstdev(gaps) * 1e3 if len(gaps) >= 2 else 0.0
        )
        counters = (
            (handoff.handoffs, handoff.fallbacks) if handoff
            else (0, 0)
        )
        for pod in pods.values():
            pod.stop()
        return p95, jitter_ms, counters

    out = {
        "disagg_step_s": STEP_S,
        "disagg_long_prompt_tokens": LONG,
    }

    # ---- unified vs disaggregated under the same mix
    uni_p95, uni_jit, _ = run_topology(disagg=False)
    dis_p95, dis_jit, (handoffs, fallbacks) = run_topology(
        disagg=True
    )
    out["disagg_unified_ttft_p95_s"] = round(uni_p95, 4)
    out["disagg_split_ttft_p95_s"] = round(dis_p95, 4)
    out["disagg_ttft_gain_x"] = round(uni_p95 / max(dis_p95, 1e-9), 2)
    out["disagg_unified_tick_jitter_ms"] = round(uni_jit, 3)
    out["disagg_decode_tick_jitter_ms"] = round(dis_jit, 3)
    out["disagg_handoffs"] = handoffs
    out["disagg_handoff_fallbacks"] = fallbacks

    # ---- drain a loaded pod: wait out the generations vs migrate
    def load_sessions(src):
        """Six mid-generation sessions on ``src``; returns (threads,
        results, prompts, n) once every session is decoding."""
        rng = random.Random(7)
        prompts = [
            [rng.randrange(_V) for _ in range(8)] for _ in range(6)
        ]
        n = 48
        results = [None] * len(prompts)

        def run(i):
            try:
                results[i] = src.engine.submit([prompts[i]], n)[0]
            except SessionMigratedError as e:
                results[i] = e
        threads = [
            threading.Thread(target=run, args=(i,), daemon=True)
            for i in range(len(prompts))
        ]
        for th in threads:
            th.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            sess = src.engine.sessions()
            if (len(sess) == len(prompts)
                    and all(s["state"] == "decode" for s in sess)
                    and src.engine.stats()["tokens_out"]
                    >= 4 * len(prompts)):
                break
            time.sleep(0.005)
        else:
            raise AssertionError("sessions never reached mid-decode")
        return threads, results, prompts, n

    # without migration: drain = stop admitting, wait for the tail
    src = BenchPod("src")
    threads, results, prompts, n = load_sessions(src)
    t0 = time.monotonic()
    for th in threads:
        th.join(timeout=120)
    legacy_s = time.monotonic() - t0
    for got, prompt in zip(results, prompts):
        assert got == _oracle(prompt, n)
    src.stop()

    # with migration: the same tail moves to a peer in one pass
    src, dst = BenchPod("src"), BenchPod("dst")
    threads, results, prompts, n = load_sessions(src)
    t0 = time.monotonic()
    report = drain_sessions(src.engine, {"dst": dst.engine})
    migrate_s = time.monotonic() - t0
    assert all(row["ok"] for row in report), report
    assert src.engine.sessions() == []
    for th in threads:
        th.join(timeout=120)
    for got, prompt in zip(results, prompts):
        assert isinstance(got, SessionMigratedError), got
        assert dst.engine.collect(got.dest_rid, timeout=120) \
            == _oracle(prompt, n), "migration lost or doubled tokens"
    out["disagg_drain_legacy_s"] = round(legacy_s, 3)
    out["disagg_drain_migrate_s"] = round(migrate_s, 3)
    out["disagg_drain_speedup_x"] = round(
        legacy_s / max(migrate_s, 1e-9), 1
    )
    src.stop()
    dst.stop()

    # ---- one forced mid-generation move over the modeled DCN
    src, dst = BenchPod("src"), BenchPod("dst")
    rng = random.Random(11)
    prompt = [rng.randrange(_V) for _ in range(16)]
    n = 24
    moved = {}

    def mover():
        try:
            moved["r"] = src.engine.submit([prompt], n)[0]
        except SessionMigratedError as e:
            moved["r"] = e
    th = threading.Thread(target=mover, daemon=True)
    th.start()
    deadline = time.monotonic() + 30
    rid = None
    while time.monotonic() < deadline:
        sess = src.engine.sessions()
        if (sess and sess[0]["state"] == "decode"
                and src.engine.stats()["tokens_out"] >= 8):
            rid = sess[0]["rid"]
            break
        time.sleep(0.005)
    assert rid is not None, "session never reached mid-decode"
    record = migrate_session(
        src.engine, dst.engine, rid, dest_name="dst",
        transport=SimulatedDcnTransport(),
    )
    th.join(timeout=120)
    err = moved["r"]
    assert isinstance(err, SessionMigratedError), err
    assert dst.engine.collect(err.dest_rid, timeout=120) \
        == _oracle(prompt, n), "mid-generation move lost tokens"
    assert src.engine.stats()["migrations_out"] == 1
    assert dst.engine.stats()["migrations_in"] == 1
    out["disagg_migration_kbytes"] = round(record.bytes / 1024, 1)
    out["disagg_migration_ms"] = round(record.duration_s * 1e3, 1)
    out["disagg_migration_pages"] = record.pages
    out["disagg_migration_greedy_equal"] = 1
    src.stop()
    dst.stop()

    print(
        f"[disagg] probe TTFT p95 unified {uni_p95 * 1e3:.0f}ms -> "
        f"split {dis_p95 * 1e3:.0f}ms "
        f"({out['disagg_ttft_gain_x']:.2f}x), tick jitter "
        f"{uni_jit:.2f} -> {dis_jit:.2f}ms, drain {legacy_s:.2f}s -> "
        f"{migrate_s:.2f}s ({out['disagg_drain_speedup_x']:.0f}x), "
        f"{handoffs} handoff(s) / {fallbacks} fallback(s)",
        file=sys.stderr, flush=True,
    )
    # the headline fences
    assert dis_p95 < uni_p95, (
        f"disaggregation did not improve short-request p95 TTFT "
        f"({dis_p95 * 1e3:.0f}ms vs unified {uni_p95 * 1e3:.0f}ms)"
    )
    assert handoffs >= 1, (
        "the prefill pod never handed a session to the decode pool"
    )
    assert migrate_s < legacy_s, (
        f"drain-with-migration ({migrate_s:.2f}s) was not faster "
        f"than waiting out the generations ({legacy_s:.2f}s)"
    )
    return out


def bench_train_step() -> dict:
    """The worker step-time fast path vs the loop it replaced
    (ISSUE 7), CPU-runnable.  Two loops over identical data from an
    identical init, same checkpoint cadence:

    * LEGACY — the pre-PR worker verbatim: donate=False step, block
      on every step's loss, stop-the-world save_checkpoint on the
      save steps;
    * FAST — the new worker defaults: donated buffers, bounded
      in-flight dispatch window (trace/steplog.py InflightWindow),
      AsyncCheckpointer saves (async device-side snapshot + background
      writer), with the writer drained INSIDE the measured makespan
      (the tail write is the async path's only serial cost).

    Fences, in order of importance: (1) LOSS EQUIVALENCE — the fast
    loop must reproduce the legacy loop's loss sequence EXACTLY under
    this deterministic config (donation, dispatch order, and snapshot
    copies may move buffers, never values — PR 6's token-equality
    discipline); (2) the fast loop must WIN the median of alternating
    legacy/fast pairs (bench_continuous_serve methodology: ratios
    inside an adjacent pair mostly cancel this host's 2-3x load
    swings); (3) the COST-MODEL GATE — shardcheck.stepcompare holds
    the fast loop's measured p50 step time (records from the SAVE
    rounds) against the calibrated no-save device floor + wire model
    (0 wire on one chip): a save that stopped the world, or any step
    regression past TRAIN_STEP_GATE_PCT (default 50%%), trips it;
    (4) the async path's last checkpoint must restore bit-identically
    to the run's true final params (a snapshot aliasing a donated
    buffer would have been overwritten while the writer drained).

    Honesty note: this container's CPU backend executes jit
    computations INLINE at dispatch (measured: dispatch carries the
    whole step, block_until_ready returns in ~50us), so the dispatch
    window cannot hide host work HERE and the measured win comes from
    the non-blocking checkpoint path + donation.  On accelerator
    backends with real async dispatch the same loop structure also
    overlaps per-step host work with device compute; the window's
    accounting contract is fenced by tests/test_train_overlap.py
    either way."""
    import statistics
    import tempfile

    import numpy as np

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import optax

    from dcos_commons_tpu.analysis.shardcheck import stepcompare
    from dcos_commons_tpu.models import (
        TransformerConfig,
        init_params,
        make_train_step,
    )
    from dcos_commons_tpu.trace.steplog import InflightWindow
    from dcos_commons_tpu.utils import (
        AsyncCheckpointer,
        restore_checkpoint,
        save_checkpoint,
    )

    # big enough that a step clears timer noise and a checkpoint is a
    # real file (~12 MB: params + adam moments), small enough that the
    # section fits a CI window
    config = TransformerConfig(
        vocab=256, d_model=128, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=352, max_seq=64, dtype=jnp.float32, remat=False,
    )
    optimizer = optax.adamw(3e-4)
    # save_every=2 makes the save path the dominant structural term:
    # twelve ~35ms stop-the-world saves on a ~20ms step give the
    # legacy arm a handicap the fast arm genuinely does not pay
    # (measured 1.4x median pairwise on the 2-core CI box, every
    # round >1.2) — far above this host's pairwise-residual noise
    steps, batch, inflight, save_every = 24, 4, 2, 2
    gate_pct = float(os.environ.get("TRAIN_STEP_GATE_PCT", "50"))
    legacy_fn = make_train_step(config, optimizer, donate=False)
    fast_fn = make_train_step(config, optimizer, donate=True)

    # deterministic per-step host batches, shared by both arms
    corpus = np.random.RandomState(0).randint(
        0, config.vocab, size=(steps, batch, config.max_seq + 1),
        dtype=np.int32,
    )

    def init_state():
        params = init_params(config, jax.random.key(0))
        return params, optimizer.init(params)

    class _Recorder:
        def __init__(self):
            self.records = []

        def record(self, step, **fields):
            self.records.append(dict(step=step, **fields))

    def run_loop(fast, ckpt_dir=None, staged=None):
        """One measured loop.  ``fast`` picks the whole arm: step fn,
        window size, save path.  Returns (losses by step, steplog
        records, makespan s, final params)."""
        params, opt_state = init_state()
        jax.block_until_ready(params)
        recorder = _Recorder()
        window = InflightWindow(recorder, inflight if fast else 0)
        checkpointer = None
        if fast and ckpt_dir is not None:
            checkpointer = AsyncCheckpointer(
                ckpt_dir, keep=2, max_pending=2
            )
        step_fn = fast_fn if fast else legacy_fn
        losses = {}
        t_start = time.monotonic()
        for i in range(steps):
            t0 = time.time()
            if staged is not None:
                tokens, targets = staged
            else:
                tokens = jnp.asarray(corpus[i, :, :-1])
                targets = jnp.asarray(corpus[i, :, 1:])
            params, opt_state, loss = step_fn(
                params, opt_state, tokens, targets
            )
            if ckpt_dir is not None and (i + 1) % save_every == 0:
                state = {"params": params, "opt_state": opt_state}
                if checkpointer is not None:
                    # async device-side snapshot, enqueued before the
                    # next dispatch donates these buffers
                    checkpointer.save(i + 1, state)
                else:
                    save_checkpoint(ckpt_dir, i + 1, state, keep=2)
            for s, ready in window.push(i, loss, t0):
                losses[s] = float(ready)
        for s, ready in window.drain():
            losses[s] = float(ready)
        if checkpointer is not None:
            # drain the writer INSIDE the makespan: the async arm
            # only wins by what it genuinely overlapped
            errors = checkpointer.close()
            assert not errors, f"async checkpoint errors: {errors}"
        makespan = time.monotonic() - t_start
        return losses, recorder.records, makespan, params

    # compile + warm both arms END TO END outside every measured
    # window — including the save paths (the fused snapshot copy and
    # the legacy save have first-call compile/alloc costs that must
    # not land in round 1)
    run_loop(False, ckpt_dir=tempfile.mkdtemp(prefix="bench-ckpt-warm-"))
    run_loop(True, ckpt_dir=tempfile.mkdtemp(prefix="bench-ckpt-warm-"))

    import gc

    gc.disable()  # the PR 5 lesson: a GC pause inside one arm of a
    try:          # pair fakes (or hides) a 10%-class effect
        # device floor for the gate: the fast loop, data pre-staged on
        # device, no saves — what the chip says a bare step costs
        staged = (
            jnp.asarray(corpus[0, :, :-1]), jnp.asarray(corpus[0, :, 1:])
        )
        # mean, not p50: the window bills ready-to-ready so TOTAL wall
        # is conserved; inline CPU dispatch clusters ready events,
        # which skews individual records but never their sum.  Two
        # calibrations, keep the LARGER mean: a floor measured in a
        # lucky-fast window would false-trip the gate, a lenient floor
        # still catches the 2x-class stop-the-world regressions the
        # gate exists for
        floor_us = 0.0
        for _cal in range(2):
            _l, floor_records, _m, _p = run_loop(True, staged=staged)
            floor_walls = [r["wall_s"] for r in floor_records]
            floor_us = max(
                floor_us, sum(floor_walls) / len(floor_walls) * 1e6
            )

        # measured side of the cost-model gate: the overlapped loop
        # doing its real per-step host work (slice + device_put), no
        # saves — save-stall detection belongs to the legacy/fast
        # speedup fence below, where both arms save
        _l, fast_records, _m, _p = run_loop(True)
        comparison = stepcompare(
            None, fast_records, floor_us=floor_us,
            slack=gate_pct / 100.0,
        )

        # alternating legacy/fast pairs, median ratio; every round
        # also fences loss equivalence
        legacy_rounds, fast_rounds = [], []
        final_params = None
        async_dir = None
        for _round in range(5):
            legacy_losses, _r, legacy_s, _p = run_loop(
                False,
                ckpt_dir=tempfile.mkdtemp(prefix="bench-ckpt-legacy-"),
            )
            async_dir = tempfile.mkdtemp(prefix="bench-ckpt-fast-")
            fast_losses, _r, fast_s, final_params = run_loop(
                True, ckpt_dir=async_dir
            )
            assert legacy_losses == fast_losses, (
                "fast loop changed the loss sequence"
            )
            legacy_rounds.append(legacy_s)
            fast_rounds.append(fast_s)
    finally:
        gc.enable()
    speedup = statistics.median(
        l / max(f, 1e-9) for l, f in zip(legacy_rounds, fast_rounds)
    )

    # snapshot-vs-donation correctness: the async arm's last save
    # (step 24) must restore to the state the loop actually reached
    params, opt_state = init_state()
    restored, restored_step = restore_checkpoint(
        async_dir, {"params": params, "opt_state": opt_state}
    )
    assert restored_step == steps, (
        f"async checkpoint stamped {restored_step}, wanted {steps}"
    )
    for want, got in zip(
        jax.tree.leaves(final_params),
        jax.tree.leaves(restored["params"]),
    ):
        np.testing.assert_array_equal(
            np.asarray(want), np.asarray(got),
            err_msg="async snapshot diverged from the run's final state",
        )

    out = {
        "train_step_steps": steps,
        "train_step_inflight": inflight,
        "train_step_saves_per_run": steps // save_every,
        "train_step_legacy_s": round(min(legacy_rounds), 4),
        "train_step_fast_s": round(min(fast_rounds), 4),
        "train_step_speedup_x": round(speedup, 3),
        "train_step_equivalent": True,  # asserted every round above
        "train_step_floor_us": round(floor_us, 1),
        "train_step_mean_us": comparison["measured_mean_us"],
        "train_step_p50_us": comparison["measured_p50_us"],
        "train_step_p95_us": comparison["measured_p95_us"],
        "train_step_over_floor_x": comparison["measured_over_floor_x"],
        "train_step_gate_pct": gate_pct,
        "train_step_gate_regression": comparison["regression"],
    }
    print(
        f"[train-step] legacy {min(legacy_rounds):.3f}s -> fast "
        f"{min(fast_rounds):.3f}s (median pairwise {speedup:.2f}x), "
        f"step mean {comparison['measured_mean_us']:.0f}us vs floor "
        f"{floor_us:.0f}us "
        f"({comparison['measured_over_floor_x']}x, gate "
        f"{gate_pct:.0f}%), losses step-equivalent",
        file=sys.stderr, flush=True,
    )
    # the tentpole's bounds, asserted (acceptance criteria):
    assert speedup > 1.0, (
        f"fast loop did not beat the legacy loop: median pairwise "
        f"ratio {speedup:.3f}"
    )
    assert comparison["regression"] is False, (
        f"measured step time regressed past the cost-model floor "
        f"(a save stopped the world, or the step slowed): {comparison}"
    )
    return out


def bench_deploy() -> dict:
    """Control-plane deploy of the single-chip MNIST service."""
    import shutil

    from dcos_commons_tpu.offer.inventory import TpuHost

    host = TpuHost(
        host_id="tpu-host-0",
        slice_id="bench-slice",
        generation="v5e",
        grid=(0, 0),
        chip_block=(1, 1),
        cpus=8.0,
        memory_mb=32768,
    )
    elapsed, completed, scheduler, agent, workdir = _run_deploy(
        os.path.join(REPO, "frameworks/jax/svc_mnist.yml"),
        {
            "JAX_FRAMEWORK_DIR": os.path.join(REPO, "frameworks/jax"),
            "TRAIN_STEPS": os.environ.get("BENCH_MNIST_STEPS", "30"),
        },
        [host],
    )
    status = scheduler.state_store.fetch_status("mnist-0-train")
    agent.shutdown()
    result = {
        "deploy_wall_clock_s": round(elapsed, 3),
        "deploy_completed": completed,
        "task_state": status.state.value if status else None,
    }
    stdout = os.path.join(workdir, "sandboxes", "mnist-0-train", "stdout")
    if os.path.exists(stdout):
        with open(stdout) as f:
            lines = f.read().strip().splitlines()
        if lines:
            result["task_log_tail"] = lines[-1]
    shutil.rmtree(workdir, ignore_errors=True)
    return result


def bench_transformer() -> dict:
    """Flagship train-step throughput on the attached chip."""
    import jax
    import jax.numpy as jnp
    import optax

    from dcos_commons_tpu.models import init_params, make_train_step
    from dcos_commons_tpu.utils import param_count, synthetic_tokens

    config = flagship_config()
    # r5 frontier optimum: batch 12 + no_remat_layers 1 (see
    # flagship_config docstring); batch 16 needs full remat
    batch = int(os.environ.get("BENCH_BATCH", "12"))
    steps = int(os.environ.get("BENCH_STEPS", "30"))
    params = init_params(config, jax.random.key(0))
    optimizer = optax.adamw(3e-4)
    opt_state = optimizer.init(params)
    step_fn = make_train_step(config, optimizer, donate=True)
    tokens, targets = synthetic_tokens(
        jax.random.key(1), batch, config.max_seq, config.vocab
    )
    t0 = time.monotonic()
    params, opt_state, loss = step_fn(params, opt_state, tokens, targets)
    float(jax.device_get(jnp.sum(loss)))  # relay: block_until_ready lies
    compile_s = time.monotonic() - t0
    # warm TWICE before the window: the first post-compile executions
    # run far below steady state on the axon relay (the r4 decode
    # lesson, _timed_median_steps) — without this the 30-step window
    # under-reports steady-state tokens/s by 2-3x
    for _ in range(2):
        params, opt_state, loss = step_fn(params, opt_state, tokens, targets)
    float(jax.device_get(jnp.sum(loss)))
    t0 = time.monotonic()
    for _ in range(steps):
        params, opt_state, loss = step_fn(params, opt_state, tokens, targets)
    # block on the WHOLE output tree: on asynchronous backends waiting
    # only on the scalar loss under-counts the step time.  On the axon
    # relay platform block_until_ready alone returns early, so ALSO
    # force a device->host transfer of a value that depends on the
    # final params (the next step's loss) before stopping the clock.
    jax.block_until_ready((params, opt_state, loss))
    _, _, sync_loss = step_fn(params, opt_state, tokens, targets)
    float(jax.device_get(sync_loss))
    dt = time.monotonic() - t0
    steps += 1  # the sync step is a real timed step too
    tokens_per_s = batch * config.max_seq * steps / dt
    n_params = param_count(params)
    flops_per_token = 6 * n_params  # fwd+bwd dense estimate
    achieved_tflops = tokens_per_s * flops_per_token / 1e12
    device = jax.devices()[0]
    peak_tflops = _peak_bf16_tflops(device)
    return {
        "platform": device.platform,
        "device_kind": getattr(device, "device_kind", "?"),
        "transformer_params_m": round(n_params / 1e6, 1),
        "compile_s": round(compile_s, 2),
        "tokens_per_s": round(tokens_per_s, 1),
        "achieved_tflops": round(achieved_tflops, 2),
        "mfu": round(achieved_tflops / peak_tflops, 4) if peak_tflops else None,
        "final_loss": round(float(loss), 4),
    }


def bench_profile() -> dict:
    """Per-section decomposition of the flagship train step (VERDICT
    r2 item 2): where the non-MFU time goes, with the evidence that
    each remaining point is structural on this chip.

    Sections timed with a forced device->host sync (the axon relay
    returns early from block_until_ready alone):
      * attention kernel fwd / fwd+bwd at flagship shapes — VPU-bound
        (softmax), measured FASTER than jax.experimental's own TPU
        flash kernel at the same shapes (26 vs 31 TF/s fwd)
      * trunk forward vs the dense-matmul roofline — ~100% of ideal
      * full step, from which the backward+recompute share follows;
        the remat recompute is near-structural: the r5 frontier puts
        the optimum at batch 12 with ONE stored layer (0.540) — more
        stored layers or bigger batches cross the HBM boundary
        (bench_mfu_frontier has the table).
    """
    import gc

    import jax
    import jax.numpy as jnp
    import optax

    from dcos_commons_tpu.models import init_params, make_train_step
    from dcos_commons_tpu.models import transformer as tmod
    from dcos_commons_tpu.ops.attention import flash_attention
    from dcos_commons_tpu.utils import param_count, synthetic_tokens

    def sync(out):
        leaf = jax.tree.leaves(out)[0]
        float(jax.device_get(jnp.sum(leaf.astype(jnp.float32))))

    def timeit(fn, *args, iters=8):
        out = fn(*args)
        sync(out)
        t0 = time.monotonic()
        for _ in range(iters):
            out = fn(*args)
        sync(out)
        return (time.monotonic() - t0) / iters

    config = flagship_config()
    # profile at the SAME frontier-optimal point the headline trains
    # (batch 12, no_remat_layers 1): batch 16 with a stored layer is
    # past the HBM boundary
    batch = 12
    out = {}

    # attention kernel at flagship shapes.  CHAINED inside one jit
    # (like the matmul rooflines): the axon relay's ~200ms per-call
    # dispatch overhead would otherwise double the apparent kernel
    # time at these ~9-20ms granularities
    from jax import lax as _lax

    chain = 8
    bhsd = (batch, config.n_heads, config.max_seq, config.head_dim)
    q = jax.random.normal(jax.random.key(0), bhsd, jnp.bfloat16)
    k = jax.random.normal(jax.random.key(1), bhsd, jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), bhsd, jnp.bfloat16)
    attn_flops = 2 * 2 * batch * config.n_heads * config.max_seq ** 2 \
        * config.head_dim / 2

    def one(qq, kk, vv):
        return flash_attention(
            qq, kk, vv,
            block_q=config.attn_block_q, block_k=config.attn_block_k,
        )

    # k/v must be ARGUMENTS: closing over the concrete arrays embeds
    # 268MB of constants into the program the relay refuses to buffer
    fwd = jax.jit(lambda q, k, v: _lax.scan(
        lambda qq, _: (one(qq, k, v), None), q, None, length=chain
    )[0])
    t_attn = timeit(fwd, q, k, v, iters=3) / chain
    # all three grads: dq chains through the scan carry, dk/dv
    # accumulate across iterations — dropping them would prune half
    # the backward kernels and understate the training cost
    grad = jax.jit(jax.grad(lambda q, k, v: _lax.scan(
        lambda qq, _: (one(qq, k, v), None), q, None, length=chain
    )[0].astype(jnp.float32).sum(), argnums=(0, 1, 2)))
    t_attn_fb = timeit(grad, q, k, v, iters=3) / chain
    out["profile_attn_fwd_ms"] = round(t_attn * 1e3, 2)
    out["profile_attn_fwd_tflops"] = round(attn_flops / t_attn / 1e12, 1)
    out["profile_attn_fwd_bwd_ms"] = round(t_attn_fb * 1e3, 2)
    del q, k, v
    gc.collect()

    # trunk forward + fwd-with-loss
    params = init_params(config, jax.random.key(0))
    tokens, targets = synthetic_tokens(
        jax.random.key(1), batch, config.max_seq, config.vocab
    )
    trunk = jax.jit(lambda p, t: tmod._trunk(config, p, t)[0])
    t_trunk = timeit(trunk, params, tokens)
    loss_fn = jax.jit(lambda p, t, tg: tmod.loss_fn(config, p, t, tg))
    t_fwd = timeit(loss_fn, params, tokens, targets)
    out["profile_trunk_fwd_ms"] = round(t_trunk * 1e3, 1)
    out["profile_loss_section_ms"] = round((t_fwd - t_trunk) * 1e3, 1)

    # full step (donated) + derived shares
    optimizer = optax.adamw(3e-4)
    opt_state = optimizer.init(params)
    step_fn = make_train_step(config, optimizer, donate=True)
    p, o = params, opt_state
    p, o, loss = step_fn(p, o, tokens, targets)
    sync(loss)
    t0 = time.monotonic()
    iters = 10
    for _ in range(iters):
        p, o, loss = step_fn(p, o, tokens, targets)
    sync(p)
    t_step = (time.monotonic() - t0) / iters
    n_params = param_count(p)
    peak = _peak_bf16_tflops(jax.devices()[0])
    attn_per_step = config.n_layers * (2 * t_attn + (t_attn_fb - t_attn))
    out["profile_step_ms"] = round(t_step * 1e3, 1)
    out["profile_bwd_and_recompute_ms"] = round((t_step - t_fwd) * 1e3, 1)
    out["profile_attn_per_step_ms"] = round(attn_per_step * 1e3, 1)
    out["profile_attn_share"] = round(attn_per_step / t_step, 3)
    out["profile_recompute_share_est"] = round(t_trunk / t_step, 3)
    if peak:
        dense_fwd_ideal_s = 2 * n_params * batch * config.max_seq / (
            peak * 1e12
        )
        out["profile_dense_fwd_efficiency"] = round(
            dense_fwd_ideal_s
            / max(t_trunk - config.n_layers * t_attn, 1e-9),
            3,
        )
    out["profile_notes"] = (
        "r5 frontier: b12/nr1 0.540 > b16/nr0 0.530; b14/nr1 0.515, "
        "b8/nr2 0.528; b16/nr1, b12/nr2, b24 OOM (full table in "
        "frontier_* extras); attn VPU-bound: beats jax pallas TPU "
        "flash at same shapes; mfu at same tokens: S=1024 0.551 / "
        "S=2048 0.529 / S=4096 0.490 (r4, b16/nr0)"
    )
    del p, o, params, opt_state
    gc.collect()
    return out


def _timed_median_steps(gen, params, prompt, new_tokens,
                        warmups: int = 2, iters: int = 3):
    """(compile_s, median steps/s).  The axon relay needs TWO warm
    executions before reaching steady state (the first post-compile
    run measures ~4x slow — r3's decode numbers were understated by
    exactly this), and block_until_ready returns early, so every run
    is fenced by a device->host read that depends on the result."""
    import statistics

    import jax

    t0 = time.monotonic()
    out = gen(params, prompt)
    float(jax.device_get(out[0, 0]))
    compile_s = time.monotonic() - t0
    for _ in range(warmups - 1):
        out = gen(params, prompt)
        float(jax.device_get(out[0, -1]))
    rates = []
    for _ in range(iters):
        t0 = time.monotonic()
        out = gen(params, prompt)
        float(jax.device_get(out[0, -1]))
        rates.append(new_tokens / (time.monotonic() - t0))
    return compile_s, statistics.median(rates)


def _bench_decode_impl(
    prefix: str, kv_dtype: str = "native",
    quantize_weights: bool = False, bf16_roofline_key: str = "",
) -> dict:
    """Shared scaffolding for the three decode benches (bf16 /
    int8-KV / int8-weights+KV): one flagship generate jitted over the
    requested quantization, timed by _timed_median_steps, with the
    HBM stream roofline for the AS-STORED bytes.  All three run in a
    SUBPROCESS with a hard timeout from main(): the remote compile
    helper has been observed to wedge on this program shape, and a
    hung section must never stall the whole bench."""
    import jax

    from dcos_commons_tpu.models import generate, init_params
    from dcos_commons_tpu.utils import synthetic_tokens

    config = flagship_config()
    batch = int(os.environ.get("BENCH_DECODE_BATCH", "16"))
    new_tokens = int(os.environ.get("BENCH_DECODE_TOKENS", "64"))
    prompt_len, max_len = 128, 512
    params = init_params(config, jax.random.key(0))
    hbm = 819.0e9  # v5e
    out = {}
    if bf16_roofline_key:
        # the comparison column, computed on the UNQUANTIZED tree
        out[bf16_roofline_key] = round(
            hbm / _decode_stream_bytes(config, params, batch, max_len,
                                       int8=False), 1
        )
    if quantize_weights:
        from dcos_commons_tpu.models import quantize_params_int8

        qparams = jax.jit(quantize_params_int8)(params)
        jax.block_until_ready(qparams)
        del params  # both trees live would double the HBM footprint
        params = qparams
    prompt, _ = synthetic_tokens(
        jax.random.key(1), batch, prompt_len, config.vocab
    )
    gen = jax.jit(lambda p, t: generate(
        config, p, t, max_new_tokens=new_tokens, max_len=max_len,
        kv_dtype=kv_dtype,
    ))
    compile_s, steps_per_s = _timed_median_steps(
        gen, params, prompt, new_tokens
    )
    out.update({
        f"{prefix}_batch": batch,
        f"{prefix}_compile_s": round(compile_s, 1),
        f"{prefix}_steps_per_s": round(steps_per_s, 1),
        f"{prefix}_tokens_per_s": round(batch * steps_per_s, 1),
        f"{prefix}_stream_roofline_steps_per_s": round(
            hbm / _decode_stream_bytes(config, params, batch, max_len,
                                       int8=(kv_dtype == "int8")), 1
        ),
    })
    return out


def bench_decode() -> dict:
    """Serving throughput: KV-cache autoregressive generate on the
    flagship (models/decode.py), one device dispatch for the whole
    continuation (lax.scan over steps).  Decode is HBM-bound — each
    step streams the full 1.7 GB bf16 parameter set — so the extras
    report the HBM roofline next to the measured rate."""
    return _bench_decode_impl("decode")


def _decode_stream_bytes(config, params, batch, max_len, int8):
    """Bytes decode streams per step: the full parameter set plus the
    whole KV cache (the dense einsum reads every slot of the static
    cache).  The honest roofline divides HBM bandwidth by THIS, not
    params alone."""
    from dcos_commons_tpu.utils import param_bytes

    cache_elems = (
        config.n_layers * batch * max_len * config.n_kv_heads
        * config.head_dim * 2  # k and v
    )
    if int8:
        scale_bytes = (
            config.n_layers * batch * max_len * config.n_kv_heads * 2 * 4
        )
        cache_bytes = cache_elems * 1 + scale_bytes
    else:
        cache_bytes = cache_elems * 2  # bf16
    return param_bytes(params) + cache_bytes


def bench_decode_int8() -> dict:
    """int8 KV cache decode (VERDICT r3 #4): halving the cache bytes
    raises the HBM-bound ceiling, and the freed HBM admits DOUBLE the
    batch the bf16 cache could hold — the tokens/s headline."""
    return _bench_decode_impl(
        "decode_int8", kv_dtype="int8",
        bf16_roofline_key="decode_bf16_stream_roofline_steps_per_s",
    )


def bench_decode_w8() -> dict:
    """int8 WEIGHTS + int8 KV cache — the full serving quantization
    stack (models/quantize.py): decode streams ~half the weight bytes
    AND half the cache bytes per step, roughly doubling the HBM-bound
    ceiling (the roofline column).  The weight-bytes win is largest at
    SMALL batch (weights dominate per-step bytes there: r5 measured
    b16 2117 tok/s vs 2006 int8-kv-only vs 1533 bf16); at b64 the
    cache and attention compute dominate and w8 adds ~2% for the
    3105 tok/s serving headline."""
    return _bench_decode_impl(
        "decode_w8", kv_dtype="int8", quantize_weights=True,
    )


def bench_serve() -> dict:
    """The FULL serving path on chip (VERDICT r3 #4): deploy
    svc_serve.yml through the control plane, then measure POST
    /generate tok/s and p50/p99 latency through the HTTP hop — the
    number an operator of the serving pod actually gets, tunnel
    overhead and all."""
    import shutil
    import statistics
    import urllib.request

    from dcos_commons_tpu.offer.inventory import TpuHost

    host = TpuHost(
        host_id="tpu-serve-0",
        hostname="127.0.0.1",  # endpoint listing must be dialable
        slice_id="bench-slice",
        generation="v5e",
        grid=(0, 0),
        chip_block=(1, 1),
        cpus=8.0,
        memory_mb=32768,
        # port 10000 on this box is held by a resident service; the
        # serve task REALLY binds its allocated port
        ports=((23400, 23500),),
    )
    n_layers = os.environ.get("BENCH_SERVE_LAYERS", "12")
    d_model = os.environ.get("BENCH_SERVE_DMODEL", "2048")
    new_tokens = int(os.environ.get("BENCH_SERVE_NEW_TOKENS", "32"))
    serve_batch = int(os.environ.get("BENCH_SERVE_BATCH", "8"))
    elapsed, completed, scheduler, agent, workdir = _run_deploy(
        os.path.join(REPO, "frameworks/jax/svc_serve.yml"),
        {
            "JAX_FRAMEWORK_DIR": os.path.join(REPO, "frameworks/jax"),
            "VOCAB": "32768", "D_MODEL": d_model, "N_LAYERS": n_layers,
            "SEQ_LEN": "256", "MAX_LEN": "256",
            "MAX_NEW_TOKENS": str(new_tokens),
            # batched serving: decode on this relay is latency-bound
            # per STEP, so rows per request are nearly free throughput
            "TASKCFG_ALL_SERVE_BATCH": str(serve_batch),
            "TASKCFG_ALL_KV_DTYPE": os.environ.get(
                "BENCH_SERVE_KV_DTYPE", "int8"
            ),
            # int8 weights measured ~neutral THROUGH THIS PATH (r5:
            # 1351 vs 1335 tok/s): the served decode is relay-dispatch
            # bound per step, so halved weight bytes buy nothing here
            # (they do in bench_decode_w8 where bytes bind).  Default
            # stays native; flip via BENCH_SERVE_WEIGHT_DTYPE.
            "TASKCFG_ALL_WEIGHT_DTYPE": os.environ.get(
                "BENCH_SERVE_WEIGHT_DTYPE", "native"
            ),
        },
        [host],
        budget_s=480.0,
    )
    result = {
        "serve_deploy_wall_clock_s": round(elapsed, 1),
        "serve_deploy_completed": completed,
    }
    try:
        if not completed:
            return result
        # endpoint discovery exactly as a client would
        from dcos_commons_tpu.http.api import SchedulerApi

        code, body = SchedulerApi(scheduler).get_endpoint("http")
        address = body["address"][0]
        url = f"http://{address}/generate"
        prompt = list(range(2, 34))  # 32 tokens

        def one_request(rows):
            payload = json.dumps({
                "tokens": [prompt] * rows, "max_new_tokens": new_tokens,
            }).encode()
            req = urllib.request.Request(
                url, data=payload,
                headers={"Content-Type": "application/json"},
            )
            t0 = time.monotonic()
            with urllib.request.urlopen(req, timeout=120) as resp:
                out = json.loads(resp.read())
            latency = time.monotonic() - t0
            n = sum(len(row) for row in out["tokens"])
            return latency, n

        one_request(1)  # warm the HTTP + dispatch path
        # interactive latency: single-prompt requests (the compiled
        # batch is padded, so this IS the per-request floor)
        latencies = []
        requests = int(os.environ.get("BENCH_SERVE_REQUESTS", "20"))
        for _ in range(requests):
            latency, _n = one_request(1)
            latencies.append(latency)
        # throughput: full-batch requests
        tokens_total = 0
        t_start = time.monotonic()
        for _ in range(requests):
            _latency, n = one_request(serve_batch)
            tokens_total += n
        wall = time.monotonic() - t_start
        # concurrent single-prompt CLIENTS: the worker's slot engine
        # admits them into shared pool decode steps — the multi-client
        # number, vs the single-client full-batch number above
        import concurrent.futures as _fut

        conc_total = (requests // serve_batch + 1) * serve_batch
        conc_tokens = 0
        t_conc = time.monotonic()
        # ONE map, no per-round barrier: max_workers bounds the
        # in-flight clients and the worker's batcher does the merging
        with _fut.ThreadPoolExecutor(max_workers=serve_batch) as pool:
            for _latency, n in pool.map(one_request, [1] * conc_total):
                conc_tokens += n
        conc_wall = time.monotonic() - t_conc
        # MIXED-length concurrent clients: realistic traffic has no
        # shared prompt length — per-slot true_len admission must hold
        # the homogeneous concurrent number (>= 80% is the bar)
        def one_mixed_request(i):
            rows = [list(range(2, 2 + 8 + (i * 7) % 48))]
            payload = json.dumps({
                "tokens": rows, "max_new_tokens": new_tokens,
            }).encode()
            req = urllib.request.Request(
                url, data=payload,
                headers={"Content-Type": "application/json"},
            )
            t0 = time.monotonic()
            with urllib.request.urlopen(req, timeout=120) as resp:
                out = json.loads(resp.read())
            return time.monotonic() - t0, sum(
                len(row) for row in out["tokens"]
            )

        mixed_tokens = 0
        t_mixed = time.monotonic()
        with _fut.ThreadPoolExecutor(max_workers=serve_batch) as pool:
            for _latency, n in pool.map(
                one_mixed_request, range(conc_total)
            ):
                mixed_tokens += n
        mixed_wall = time.monotonic() - t_mixed
        latencies.sort()
        result.update({
            "serve_requests": requests,
            "serve_batch": serve_batch,
            "serve_tokens_per_s": round(tokens_total / wall, 1),
            "serve_concurrent_clients_tokens_per_s": round(
                conc_tokens / conc_wall, 1
            ),
            "serve_mixed_len_clients_tokens_per_s": round(
                mixed_tokens / mixed_wall, 1
            ),
            "serve_p50_ms": round(
                statistics.median(latencies) * 1e3, 1
            ),
            "serve_p99_ms": round(
                latencies[
                    min(len(latencies) - 1,
                        max(0, math.ceil(0.99 * len(latencies)) - 1))
                ] * 1e3,
                1,
            ),
        })
        return result
    finally:
        for task_id in list(agent.active_task_ids()):
            agent.kill(task_id, grace_period_s=0.0)
        deadline = time.monotonic() + 15
        while agent.active_task_ids() and time.monotonic() < deadline:
            agent.poll()
            time.sleep(0.2)
        agent.shutdown()
        shutil.rmtree(workdir, ignore_errors=True)


def moe_flagship_config():
    """The MoE flagship variant sized for the 16 GB chip: Adam keeps
    12 bytes/param (bf16 p+g, f32 m+v), so ~1B params is the ceiling —
    4 experts at d_ff 2048 lands the SAME total parameter count as the
    dense flagship while activating half the FFN weight per token
    (top-2 of 4)."""
    import jax.numpy as jnp

    from dcos_commons_tpu.models import TransformerConfig

    return TransformerConfig(
        vocab=32768,
        d_model=2048,
        n_layers=12,
        n_heads=16,
        n_kv_heads=16,
        d_ff=int(os.environ.get("BENCH_MOE_DFF", "2048")),
        max_seq=2048,
        dtype=jnp.bfloat16,
        remat=True,
        attn_block_q=512,
        attn_block_k=512,
        n_experts=int(os.environ.get("BENCH_MOE_EXPERTS", "4")),
        moe_top_k=int(os.environ.get("BENCH_MOE_TOPK", "2")),
        moe_capacity_factor=float(
            os.environ.get("BENCH_MOE_CAPACITY", "1.25")
        ),
        moe_impl=os.environ.get("BENCH_MOE_IMPL", "onehot"),
    )


def bench_moe() -> dict:
    """MoE flagship on-chip numbers (VERDICT r3 #5): train-step MFU
    (counting ACTIVATED FLOPs — top-k of the expert weights — the
    honest MoE utilisation number) and KV-cache decode tok/s.  Run in
    a subprocess: same wedge-prone shapes as the dense flagship."""
    import jax
    import jax.numpy as jnp
    import optax

    from dcos_commons_tpu.models import (
        generate,
        init_params,
        make_train_step,
    )
    from dcos_commons_tpu.utils import param_count, synthetic_tokens

    config = moe_flagship_config()
    batch = int(os.environ.get("BENCH_MOE_BATCH", "8"))
    steps = int(os.environ.get("BENCH_MOE_STEPS", "20"))
    params = init_params(config, jax.random.key(0))
    optimizer = optax.adamw(3e-4)
    opt_state = optimizer.init(params)
    step_fn = make_train_step(config, optimizer, donate=True)
    tokens, targets = synthetic_tokens(
        jax.random.key(1), batch, config.max_seq, config.vocab
    )
    t0 = time.monotonic()
    params, opt_state, loss = step_fn(params, opt_state, tokens, targets)
    jax.block_until_ready((params, opt_state, loss))
    float(jax.device_get(jnp.sum(loss)))
    compile_s = time.monotonic() - t0
    t0 = time.monotonic()
    for _ in range(steps):
        params, opt_state, loss = step_fn(params, opt_state, tokens, targets)
    float(jax.device_get(jnp.sum(loss)))  # axon relay: force the sync
    dt = time.monotonic() - t0
    tokens_per_s = batch * config.max_seq * steps / dt

    # MoE MFU counts ACTIVATED parameters (top_k of n_experts expert
    # FFNs per token) with the same 6*N fwd+bwd convention the dense
    # bench uses — inactive expert weights do no useful FLOPs
    d, f = config.d_model, config.d_ff
    inactive_ffn = (
        config.n_layers * (config.n_experts - config.moe_top_k)
        * 3 * d * f
    )
    n_active = param_count(params) - inactive_ffn
    flops_per_token = 6 * n_active
    peak = _peak_bf16_tflops(jax.devices()[0]) * 1e12
    mfu = tokens_per_s * flops_per_token / peak if peak else 0.0

    result = {
        "moe_batch": batch,
        "moe_experts": config.n_experts,
        "moe_top_k": config.moe_top_k,
        "moe_capacity_factor": config.moe_capacity_factor,
        "moe_params_m": round(param_count(params) / 1e6),
        "moe_compile_s": round(compile_s, 1),
        "moe_train_tokens_per_s": round(tokens_per_s),
        "moe_mfu": round(mfu, 3),
        # measured ceiling (r5 sweeps, clean box): one-hot dispatch
        # beats sorted gather/scatter at STEP level (21.3k vs 14.9k
        # tok/s — the scatter breaks XLA fusion under remat, even
        # though kernel-level microbenches tie); dispatch-einsum dtype
        # is MFU-neutral (XLA folds the f32 convert); batch 12/16 and
        # group 2048 are noise-or-worse; no-remat OOMs at b8; capacity
        # 1.0/1.25/1.5 -> MFU 0.41/0.375/0.34.  The activated-MFU gap
        # to the dense flagship's 0.53 is structural: x1.25 capacity
        # waste on expert FLOPs, small per-expert matmul tiles
        # ([~640,2048]x[2048,2048] vs dense [16k,2048]x[2048,8192]),
        # and routing's VPU work that activated FLOPs never count.
        "moe_profile_notes": (
            "one-hot dispatch > sorted at step level; ceiling is "
            "capacity waste + small expert tiles + routing VPU share "
            "(see bench.py bench_moe comment for the r5 sweep)"
        ),
    }

    # serving: drop-free KV-cache decode
    del opt_state
    dec_batch = int(os.environ.get("BENCH_MOE_DECODE_BATCH", "16"))
    new_tokens = 64
    prompt, _ = synthetic_tokens(
        jax.random.key(2), dec_batch, 128, config.vocab
    )
    gen = jax.jit(lambda p, t: generate(
        config, p, t, max_new_tokens=new_tokens, max_len=512
    ))
    _compile_s, steps_per_s = _timed_median_steps(
        gen, params, prompt, new_tokens
    )
    result["moe_decode_tokens_per_s"] = round(
        dec_batch * steps_per_s, 1
    )
    # the quantized serving stack on MoE: int8 EXPERT weights (ALL
    # experts stream from HBM each step regardless of routing, so the
    # byte saving is over the full expert stack) + int8 KV
    from dcos_commons_tpu.models import quantize_params_int8

    qparams = jax.jit(quantize_params_int8)(params)
    jax.block_until_ready(qparams)
    del params
    gen_q = jax.jit(lambda p, t: generate(
        config, p, t, max_new_tokens=new_tokens, max_len=512,
        kv_dtype="int8",
    ))
    _compile_s, q_steps_per_s = _timed_median_steps(
        gen_q, qparams, prompt, new_tokens
    )
    result["moe_decode_w8_tokens_per_s"] = round(
        dec_batch * q_steps_per_s, 1
    )
    return result


def _peak_bf16_tflops(device) -> float:
    """Per-chip bf16 peak by device kind; 0 disables the MFU extra."""
    kind = getattr(device, "device_kind", "").lower()
    for token, peak in (
        ("v6e", 918.0), ("v6", 918.0), ("v5p", 459.0), ("v5e", 197.0),
        ("v5 lite", 197.0), ("lite", 197.0), ("v4", 275.0),
    ):
        if token in kind:
            return peak
    return 197.0 if device.platform in ("tpu", "axon") else 0.0


def bench_rooflines() -> dict:
    """Chip rooflines + (multi-chip only) ICI collective bandwidth —
    the BASELINE north-star measurement path.  On the single bench
    chip the collective section reports the rooflines the multi-chip
    GB/s numbers will sit under."""
    import jax

    from dcos_commons_tpu.parallel.collectives import (
        collective_bandwidth,
        single_chip_rooflines,
    )

    out = dict(single_chip_rooflines(payload_mb=128.0, iters=10))
    devices = jax.devices()
    if len(devices) >= 2:
        from jax.sharding import Mesh

        mesh = Mesh(devices, ("ici",))
        for key, value in collective_bandwidth(
            mesh, "ici", payload_mb=32.0, iters=10
        ).items():
            out[f"ici_{key}"] = value
    return out


def _run_subprocess_section(
    fn_name: str, timeout_s: float,
    env: dict = None, rename: dict = None,
) -> dict:
    """Run one bench section in a child process with a hard timeout so
    a wedged XLA compile cannot stall the whole bench run.

    Output goes to a FILE (not a pipe) and the child runs in its own
    session: on timeout the whole process GROUP is killed — a wedged
    grandchild (e.g. the remote compile helper) holding an inherited
    pipe FD would otherwise block the read forever.

    ``env`` overlays the child's environment (parameterized reruns);
    ``rename`` remaps result keys (None value = drop the key) so one
    section can report under several names."""
    import signal
    import subprocess
    import tempfile

    code = (
        "import json, sys; sys.path.insert(0, %r); import bench; "
        "print('BENCHJSON ' + json.dumps(getattr(bench, %r)()))"
        % (REPO, fn_name)
    )
    child_env = dict(os.environ)
    child_env.update(env or {})
    with tempfile.TemporaryFile(mode="w+") as out:
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=out,
            stderr=subprocess.STDOUT,
            start_new_session=True,
            text=True,
            env=child_env,
        )
        try:
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            proc.wait(timeout=10)
            raise RuntimeError(
                f"{fn_name} exceeded {timeout_s}s; process group killed"
            )
        out.seek(0)
        text = out.read()
    for line in text.splitlines():
        if line.startswith("BENCHJSON "):
            result = json.loads(line[len("BENCHJSON "):])
            if rename:
                remapped = {}
                for key, value in result.items():
                    target = rename.get(key, key)
                    if target is not None:
                        remapped[target] = value
                result = remapped
            return result
    raise RuntimeError(
        f"{fn_name} subprocess rc={rc}: {text[-180:]}"
    )



def bench_preflight() -> dict:
    """One trivial jit through the relay, subprocess-guarded: if the
    TPU relay's compile path is wedged (observed: a 256x256 matmul
    compile hanging for minutes after heavy OOM probing), every
    compile-bearing section would burn its full budget — better to
    KNOW up front and shrink the budgets so the run still prints its
    JSON line with honest per-section errors."""
    import jax
    import jax.numpy as jnp

    t0 = time.monotonic()
    out = jax.jit(lambda x: (x @ x).sum())(jnp.ones((256, 256)))
    float(jax.device_get(out))
    return {"relay_preflight_s": round(time.monotonic() - t0, 1)}


def _mark(tag, _state={"t": None}):  # noqa — the default IS the state
    """Per-section wall-clock to stderr (stdout carries ONLY the JSON
    line); the driver's bench timeout budget is finite, so the hog
    must be findable from a single run's log."""
    now = time.monotonic()
    if _state["t"] is not None:
        print(f"[bench-timing] {tag}: {now - _state['t']:.1f}s",
              file=sys.stderr, flush=True)
    _state["t"] = now


def main() -> None:
    import tempfile

    extras = {}
    _mark(None)
    # relay health gates the chip sections' budgets (two attempts —
    # transient wedges recover)
    relay_ok = False
    for _attempt in (1, 2):
        try:
            extras.update(_run_subprocess_section(
                "bench_preflight", timeout_s=300
            ))
            relay_ok = True
            break
        except Exception as e:
            extras["relay_preflight_error"] = repr(e)[:200]
    extras["relay_degraded"] = not relay_ok
    _mark("preflight")
    try:
        extras.update(bench_helloworld())
    except Exception as e:
        extras["helloworld_error"] = repr(e)[:200]
    _mark("helloworld")
    try:
        extras.update(bench_scheduler_scale())
    except Exception as e:
        extras["sched_scale_error"] = repr(e)[:200]
    _mark("sched_scale")
    try:
        extras.update(bench_offer_cycle())
    except Exception as e:
        extras["offer_cycle_error"] = repr(e)[:200]
    _mark("offer_cycle")
    # fleet-scale offer cycle (ISSUE 9): incremental dirty-host
    # evaluation + indexed placement at 1k/10k hosts vs full rebuild
    try:
        extras.update(bench_fleet_scale())
    except Exception as e:
        extras["fleet_scale_error"] = repr(e)[:200]
    _mark("fleet_scale")
    try:
        extras.update(bench_trace_overhead())
    except Exception as e:
        extras["trace_overhead_error"] = repr(e)[:200]
    _mark("trace_overhead")
    # fleet health plane (ISSUE 10): detectors + journal overhead on
    # the trace-bench scenario, fenced at <5% of cycle cost
    try:
        extras.update(bench_health_overhead())
    except Exception as e:
        extras["health_overhead_error"] = repr(e)[:200]
    _mark("health_overhead")
    # HA failover latency (ISSUE 8): standby takeover during a 64-host
    # deploy — lease wait / rebuild / first-working-cycle breakdown
    try:
        extras.update(bench_failover())
    except Exception as e:
        extras["failover_error"] = repr(e)[:200]
    _mark("failover")
    # preemption -> gang recovery latency (ISSUE 13): single gang-host
    # kill to training-resumed, and a 4-kill storm (incl. mid-recovery
    # and span-boundary kills) to convergence, invariants asserted
    try:
        extras.update(bench_preemption_recovery())
    except Exception as e:
        extras["preemption_error"] = repr(e)[:200]
    _mark("preemption_recovery")
    # multi-slice gang lifecycle (ISSUE 20): 2-slice deploy on a 10k-
    # host world, whole-slice preemption -> time-to-resumed-shrunken,
    # capacity return -> time-to-regrown, journal verbs asserted
    try:
        extras.update(bench_multislice())
    except Exception as e:
        extras["multislice_error"] = repr(e)[:200]
    _mark("multislice")
    # closed health->action loop (ISSUE 15): seeded SLO breach ->
    # time-to-scale-plan / time-to-recovered-SLO, quiet -> scale-in
    # with the pre-kill drain, zero flap asserted over the run
    try:
        extras.update(bench_slo_recovery())
    except Exception as e:
        extras["slo_recovery_error"] = repr(e)[:200]
    _mark("slo_recovery")
    # CPU-runnable serving data-plane trend (ISSUE 6): subprocess so
    # the forced-cpu jax init cannot leak into the chip sections
    try:
        extras.update(_run_subprocess_section(
            # 900s: the ISSUE 11 paged-vs-slot-pool round added two
            # more compiled pools and three more load pairs
            "bench_continuous_serve", timeout_s=900,
            env={"JAX_PLATFORMS": "cpu"},
        ))
    except Exception as e:
        extras["continuous_serve_error"] = repr(e)[:200]
    _mark("continuous_serve")
    # CPU-runnable routing-tier trend (ISSUE 12): the multi-pod front
    # door's 1/2/4-pod open-loop sweep, affinity-vs-spray prefix hit
    # rate, and the mid-sweep drain round — jax-free, subprocess for
    # the hard timeout
    try:
        extras.update(_run_subprocess_section(
            "bench_router_scale", timeout_s=600,
            env={"JAX_PLATFORMS": "cpu"},
        ))
    except Exception as e:
        extras["router_scale_error"] = repr(e)[:200]
    _mark("router_scale")
    # CPU-runnable disaggregated-serving trend (ISSUE 16): unified vs
    # prefill/decode split under a long-prefill-heavy mix, drain with
    # vs without live KV migration, and one mid-generation move over
    # the modeled DCN — jax-free, subprocess for the hard timeout
    try:
        extras.update(_run_subprocess_section(
            "bench_disagg", timeout_s=600,
            env={"JAX_PLATFORMS": "cpu"},
        ))
    except Exception as e:
        extras["disagg_error"] = repr(e)[:200]
    _mark("disagg")
    # CPU-runnable training step-loop trend (ISSUE 7): the worker fast
    # path (donation + in-flight window + async fenced checkpointing)
    # vs the loop it replaced, plus the cost-model step-time gate
    try:
        extras.update(_run_subprocess_section(
            "bench_train_step", timeout_s=600,
            env={"JAX_PLATFORMS": "cpu"},
        ))
    except Exception as e:
        extras["train_step_error"] = repr(e)[:200]
    _mark("train_step")
    if not relay_ok:
        # every remaining section needs the chip's compile path; each
        # would burn its full timeout against a wedged relay.  Print
        # the JSON line NOW with the control-plane results and an
        # honest degraded flag instead of timing out the whole run.
        print(json.dumps(
            {
                "metric": "jax_mnist_deploy_plan_wall_clock",
                "value": 0.0,
                "unit": "s",
                "vs_baseline": 0.0,
                "extras": extras,
            },
            sort_keys=True,
        ))
        return
    # persistent XLA compilation cache for the deploy's train task
    # (inherited by the agent-launched subprocess).  Three measurements
    # (VERDICT r3 #8):
    #   true cold — fresh cache, no provisioning (r2/r3 continuity)
    #   provisioned — a FRESH cache seeded by the provisioning step
    #     (agent --provision-cmd running warm_cache.py); this is what
    #     a first deploy on a properly provisioned host costs, and the
    #     HEADLINE metric
    #   warm — repeat deploy on the same host
    import subprocess as _sp

    cold_cache = tempfile.mkdtemp(prefix="bench-xla-cold-")
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cold_cache
    try:
        true_cold = bench_deploy()
        extras["deploy_true_cold_wall_clock_s"] = \
            true_cold["deploy_wall_clock_s"]
        extras["deploy_true_cold_completed"] = \
            true_cold["deploy_completed"]
    except Exception as e:
        extras["deploy_true_cold_error"] = repr(e)[:200]
    _mark("deploy_true_cold")
    cache_dir = tempfile.mkdtemp(prefix="bench-xla-cache-")
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    provisioned = False
    try:
        t0 = time.monotonic()
        # route the child's prints to STDERR: bench stdout must carry
        # ONLY the one JSON line (the child inherits stdout otherwise)
        rc = _sp.run(
            [sys.executable,
             os.path.join(REPO, "frameworks/jax/warm_cache.py")],
            env={**os.environ, "REPO_ROOT": REPO},
            timeout=300, stdout=sys.stderr, stderr=sys.stderr,
        ).returncode
        extras["provision_warm_cache_s"] = round(
            time.monotonic() - t0, 1
        )
        extras["provision_rc"] = rc
        provisioned = rc == 0
    except Exception as e:
        extras["provision_error"] = repr(e)[:200]
    _mark("provision")
    # measurement honesty: the headline deploy is only "provisioned"
    # when the seeding actually succeeded
    extras["deploy_provisioned"] = provisioned
    deploy = bench_deploy()
    extras.update(deploy)
    try:
        warm = bench_deploy()
        extras["deploy_warm_wall_clock_s"] = warm["deploy_wall_clock_s"]
        extras["deploy_warm_completed"] = warm["deploy_completed"]
    except Exception as e:
        extras["deploy_warm_error"] = repr(e)[:200]
    _mark("deploys_provisioned_and_warm")
    for attempt in (1, 2):
        # one retry: the relay's compile helper occasionally drops a
        # request right after the deploy phase's task churn
        try:
            extras.update(bench_rooflines())
            extras.pop("roofline_error", None)
            break
        except Exception as e:
            extras["roofline_error"] = repr(e)[:200]
            if attempt == 1:
                time.sleep(5)
    _mark("rooflines")
    try:
        extras.update(bench_transformer())
    except Exception as e:  # deploy result still stands alone
        extras["transformer_error"] = repr(e)[:200]
    _mark("transformer")
    try:
        extras.update(bench_profile())
    except Exception as e:
        extras["profile_error"] = repr(e)[:200]
    _mark("profile")
    try:
        # the (batch, no_remat_layers) frontier — each point is a
        # fresh compile with an OOM boundary, so subprocess-guarded
        extras.update(_run_subprocess_section(
            "bench_mfu_frontier", timeout_s=1200
        ))
    except Exception as e:
        extras["frontier_error"] = repr(e)[:200]
    _mark("frontier")
    try:
        extras.update(_run_subprocess_section("bench_decode", timeout_s=420))
    except Exception as e:
        extras["decode_error"] = repr(e)[:200]
    _mark("decode_b16")
    # decode on this relay is DISPATCH-latency-bound per step (~23
    # steps/s regardless of bytes), so tokens/s scales with batch
    # until HBM bites; bf16 tops out around b=64-128 (cache bytes),
    # int8 halves the cache and keeps scaling — the serving headline
    try:
        extras.update(_run_subprocess_section(
            "bench_decode", timeout_s=420,
            env={"BENCH_DECODE_BATCH": "64"},
            rename={
                "decode_batch": "decode_b64_batch",
                "decode_compile_s": None,
                "decode_steps_per_s": "decode_b64_steps_per_s",
                "decode_tokens_per_s": "decode_b64_tokens_per_s",
                "decode_stream_roofline_steps_per_s": None,
            },
        ))
    except Exception as e:
        extras["decode_b64_error"] = repr(e)[:200]
    _mark("decode_b64")
    try:
        extras.update(_run_subprocess_section(
            "bench_decode_int8", timeout_s=420
        ))
    except Exception as e:
        extras["decode_int8_error"] = repr(e)[:200]
    _mark("decode_int8_b16")
    try:
        extras.update(_run_subprocess_section(
            "bench_decode_int8", timeout_s=480,
            env={"BENCH_DECODE_BATCH": "64"},
            rename={
                "decode_int8_batch": "decode_int8_b64_batch",
                "decode_int8_compile_s": None,
                "decode_int8_steps_per_s": "decode_int8_b64_steps_per_s",
                "decode_int8_tokens_per_s":
                    "decode_int8_b64_tokens_per_s",
                "decode_int8_stream_roofline_steps_per_s":
                    "decode_int8_b64_stream_roofline_steps_per_s",
                "decode_bf16_stream_roofline_steps_per_s": None,
            },
        ))
    except Exception as e:
        extras["decode_int8_b64_error"] = repr(e)[:200]
    _mark("decode_int8_b64")
    # int8 weights + int8 cache: the full serving quantization stack.
    # b16 shows the small-batch weight-bytes win (2117 vs 2006 int8-kv
    # vs 1533 bf16 tok/s, r5 measured); b64 is the serving headline
    # (3105 tok/s) — b128 was measured SLOWER (2892: attention compute
    # over the wider batch outgrows the byte savings), so the frontier
    # stops at 64
    try:
        extras.update(_run_subprocess_section(
            "bench_decode_w8", timeout_s=480
        ))
    except Exception as e:
        extras["decode_w8_error"] = repr(e)[:200]
    _mark("decode_w8_b16")
    try:
        extras.update(_run_subprocess_section(
            "bench_decode_w8", timeout_s=540,
            env={"BENCH_DECODE_BATCH": "64"},
            rename={
                "decode_w8_batch": "decode_w8_b64_batch",
                "decode_w8_compile_s": None,
                "decode_w8_steps_per_s": "decode_w8_b64_steps_per_s",
                "decode_w8_tokens_per_s": "decode_w8_b64_tokens_per_s",
                "decode_w8_stream_roofline_steps_per_s":
                    "decode_w8_b64_stream_roofline_steps_per_s",
            },
        ))
    except Exception as e:
        extras["decode_w8_b64_error"] = repr(e)[:200]
    _mark("decode_w8_b64")
    try:
        extras.update(_run_subprocess_section("bench_serve", timeout_s=540))
    except Exception as e:
        extras["serve_error"] = repr(e)[:200]
    _mark("serve")
    try:
        extras.update(_run_subprocess_section("bench_moe", timeout_s=540))
    except Exception as e:
        extras["moe_error"] = repr(e)[:200]
    _mark("moe")
    # 8-expert point: same total params at finer expert granularity
    # (8 x d_ff 1024 top-2) — higher tok/s, lower activated-MFU (the
    # sparser the activation, the less of the step activated FLOPs
    # can explain); the 4-expert config stays the headline
    try:
        extras.update(_run_subprocess_section(
            "bench_moe", timeout_s=540,
            env={
                "BENCH_MOE_EXPERTS": "8", "BENCH_MOE_DFF": "1024",
                "BENCH_MOE_DECODE_BATCH": "16",
            },
            rename={
                "moe_batch": None,
                "moe_experts": "moe8_experts",
                "moe_top_k": None,
                "moe_capacity_factor": None,
                "moe_params_m": "moe8_params_m",
                "moe_compile_s": "moe8_compile_s",
                "moe_train_tokens_per_s": "moe8_train_tokens_per_s",
                "moe_mfu": "moe8_mfu",
                "moe_profile_notes": None,
                "moe_decode_tokens_per_s": "moe8_decode_tokens_per_s",
                "moe_decode_w8_tokens_per_s":
                    "moe8_decode_w8_tokens_per_s",
            },
        ))
    except Exception as e:
        extras["moe8_error"] = repr(e)[:200]
    _mark("moe8")
    try:
        # analyzer-coverage trend keys: how much of the env/config
        # contract surface configcheck's flow graph tracks (the
        # findings gate itself lives in tests/test_lint_gate.py)
        from dcos_commons_tpu.analysis import configcheck

        config_result = configcheck.analyze_all(
            os.path.dirname(os.path.abspath(__file__))
        )
        extras["config_env_vars"] = len(config_result.env_vars)
        extras["config_flows"] = len(config_result.flows)
        extras["config_findings"] = len(config_result.findings)
        extras["config_suppressed"] = len(config_result.suppressed)
    except Exception as e:
        extras["config_error"] = repr(e)[:200]
    _mark("configcheck")
    try:
        # durability-surface trend keys: how many persistence
        # boundaries durcheck tracks for the auto-derived chaos
        # matrix (the findings gate lives in tests/test_lint_gate.py)
        from dcos_commons_tpu.analysis import durcheck

        dur_result = durcheck.analyze_tree(
            os.path.dirname(os.path.abspath(__file__))
        )
        extras["dur_persistence_points"] = len(
            dur_result.persistence_points
        )
        extras["dur_findings"] = len(dur_result.findings)
        extras["dur_suppressed"] = len(dur_result.suppressed)
        per_kind: dict = {}
        for point in dur_result.persistence_points:
            per_kind[point.kind] = per_kind.get(point.kind, 0) + 1
        extras["dur_per_kind"] = per_kind
    except Exception as e:
        extras["dur_error"] = repr(e)[:200]
    _mark("durcheck")
    value = deploy["deploy_wall_clock_s"]
    print(
        json.dumps(
            {
                "metric": "jax_mnist_deploy_plan_wall_clock",
                "value": value,
                "unit": "s",
                "vs_baseline": round(DEPLOY_BUDGET_S / max(value, 1e-9), 3)
                if deploy["deploy_completed"]
                else 0.0,
                "extras": extras,
            },
            sort_keys=True,
        )
    )


if __name__ == "__main__":
    main()
