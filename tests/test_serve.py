"""The serve entrypoint: real scheduler + agent PROCESSES end to end.

Everything here crosses process boundaries: agents are
``python -m dcos_commons_tpu agent`` subprocesses, the scheduler is a
``serve`` subprocess discovered via announce files and driven purely
over its HTTP API with the integration harness (the sdk_plan/sdk_tasks
analogue flow).  Covers VERDICT.md items 1 (distributed control
plane), 2 (scheduler-process entrypoint + instance lock) and 7
(integration harness) in one place.  Reference call stack:
SchedulerRunner.java:82-101 -> FrameworkRunner.java:90.
"""

import os
import subprocess
import sys
import time

import pytest

from dcos_commons_tpu.runtime.runner import EXIT_LOCKED, load_topology
from dcos_commons_tpu.testing.integration import (
    AgentProcess,
    SchedulerProcess,
    reap_orphan_tasks,
    wait_for,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SVC_YAML = """
name: webfarm
pods:
  app:
    count: 2
    placement: 'max-per-host:1'
    tasks:
      server:
        goal: RUNNING
        cmd: "echo serving-$POD_INSTANCE_INDEX > out.txt && sleep 120"
        cpus: 0.1
        memory: 32
"""


def write_topology(path, agents, spare=()):
    lines = ["hosts:"]
    for agent in agents:
        lines += [
            f"  - host_id: {agent.host_id}",
            f"    agent_url: {agent.url}",
            "    cpus: 4.0",
            "    memory_mb: 8192",
        ]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


@pytest.fixture
def cluster(tmp_path):
    """3 agent daemons + topology + svc.yml, ready to serve."""
    agents = [
        AgentProcess(f"h{i}", str(tmp_path / f"agent-{i}"), REPO)
        for i in range(3)
    ]
    svc = tmp_path / "svc.yml"
    svc.write_text(SVC_YAML)
    topology = tmp_path / "topology.yml"
    write_topology(str(topology), agents)
    yield {"agents": agents, "svc": str(svc), "topology": str(topology)}
    for agent in agents:
        agent.stop()
    reap_orphan_tasks(agents)  # stopped daemons leave tasks running


def test_serve_deploys_and_recovers_across_processes(cluster, tmp_path):
    scheduler = SchedulerProcess(
        cluster["svc"],
        cluster["topology"],
        str(tmp_path / "scheduler"),
        env={
            "ENABLE_BACKOFF": "false",
            # fast TRANSIENT->PERMANENT escalation so a killed agent's
            # task is replaced on a surviving host quickly
            "PERMANENT_FAILURE_TIMEOUT_S": "1",
        },
        repo_root=REPO,
    )
    try:
        client = scheduler.client()
        client.wait_for_completed_deployment(timeout_s=60)
        ids = client.task_ids()
        assert set(ids) == {"app-0-server", "app-1-server"}

        # find which agent process hosts app-0-server and kill it
        placed = {
            t["name"]: t
            for pod in client.get("/v1/pod/status")["pods"]
            for inst in pod["instances"] for t in inst["tasks"]
        }
        infos = client.get("/v1/pod/app-0/info")
        victim_host = infos[0]["agent_id"]
        victim = next(
            a for a in cluster["agents"] if a.host_id == victim_host
        )
        victim.kill()

        # recovery replaces the lost task on another host, new task id
        new_ids = client.wait_for_tasks_updated(
            {"app-0-server": ids["app-0-server"]},
            prefix="app-0",
            timeout_s=90,
        )
        assert new_ids["app-0-server"] != ids["app-0-server"]
        infos = client.get("/v1/pod/app-0/info")
        assert infos[0]["agent_id"] != victim_host
        # the untouched pod never restarted
        client.check_tasks_not_updated(ids, prefix="app-1")

        health = client.get("/v1/health")
        assert health["healthy"]
    finally:
        code = scheduler.terminate()
        assert code == 0, scheduler.log_tail()


def test_second_scheduler_instance_is_locked_out(cluster, tmp_path):
    first = SchedulerProcess(
        cluster["svc"], cluster["topology"], str(tmp_path / "s1"),
        repo_root=REPO,
    )
    try:
        first.client().wait_for_plan_status("deploy", "COMPLETE", 60)
        # same state dir -> must refuse to start
        second = subprocess.run(
            [
                sys.executable, "-m", "dcos_commons_tpu", "serve",
                cluster["svc"],
                "--topology", cluster["topology"],
                "--port", "0",
                "--state-dir", os.path.join(str(tmp_path / "s1"), "state"),
                "--sandbox-root", str(tmp_path / "s2-sandboxes"),
            ],
            cwd=REPO,
            capture_output=True,
            timeout=60,
        )
        assert second.returncode == EXIT_LOCKED, second.stderr.decode()
    finally:
        assert first.terminate() == 0


def test_scheduler_restart_resumes_over_same_state(cluster, tmp_path):
    workdir = str(tmp_path / "scheduler")
    scheduler = SchedulerProcess(
        cluster["svc"], cluster["topology"], workdir, repo_root=REPO,
    )
    client = scheduler.client()
    # generous timeouts: under full-suite load the subprocess trio can
    # take far longer than in isolation (observed flake)
    client.wait_for_completed_deployment(timeout_s=120)
    ids = client.task_ids()
    assert scheduler.terminate() == 0

    # agents keep running their tasks; a new scheduler process over the
    # same state dir reconciles instead of redeploying
    scheduler = SchedulerProcess(
        cluster["svc"], cluster["topology"], workdir, repo_root=REPO,
    )
    try:
        client = scheduler.client()
        client.wait_for_completed_deployment(timeout_s=120)
        client.check_tasks_not_updated(ids)
    finally:
        assert scheduler.terminate() == 0


def test_live_update_overrides_survive_failover(cluster, tmp_path):
    """Feature interaction: options applied via POST /v1/update persist
    in the STATE SERVER, so a standby taking over after the active
    scheduler dies renders the spec WITH the overrides — no rollback
    of a live update on failover."""
    from dcos_commons_tpu.testing.integration import start_state_server

    svc = tmp_path / "svc-upd.yml"
    svc.write_text(UPDATABLE_YAML)
    state, state_url, state_log = start_state_server(
        str(tmp_path / "state"), REPO
    )
    sched_a = sched_b = None
    try:
        env = {"ENABLE_BACKOFF": "false", "STATE_LEASE_TTL_S": "2"}
        extra = ["--state-url", state_url]
        sched_a = SchedulerProcess(
            str(svc), cluster["topology"], str(tmp_path / "sched-a"),
            env=env, repo_root=REPO, extra_args=extra,
        )
        client = sched_a.client()
        client.wait_for_completed_deployment(timeout_s=90)
        before = client.task_ids()  # BEFORE the update: the rollout
        client.post("/v1/update", body={"env": {"MODE": "green"}})
        ids = client.wait_for_tasks_updated(before, timeout_s=120)
        client.wait_for_completed_deployment(timeout_s=120)

        # active dies HARD mid-flight; standby takes over after the
        # lease expires and must keep MODE=green — not roll back to
        # the YAML default
        sched_a.process.kill()
        sched_a.process.wait(timeout=10)
        time.sleep(3.0)  # > lease ttl
        sched_b = SchedulerProcess(
            str(svc), cluster["topology"], str(tmp_path / "sched-b"),
            env=env, repo_root=REPO, extra_args=extra,
        )
        client_b = sched_b.client()
        client_b.wait_for_completed_deployment(timeout_s=120)
        client_b.check_tasks_not_updated(ids)  # nothing rolled back
        infos = client_b.get("/v1/pod/app-0/info")
        assert infos[0]["env"]["MODE"] == "green"
    finally:
        for sched in (sched_a, sched_b):
            if sched is not None:
                sched.terminate()
        state.terminate()
        state.wait(timeout=10)
        state_log.close()


UPDATABLE_YAML = """
name: webfarm
pods:
  app:
    count: {{APP_COUNT:-2}}
    placement: 'max-per-host:1'
    tasks:
      server:
        goal: RUNNING
        cmd: "echo $MODE > mode.txt && sleep 120"
        cpus: 0.1
        memory: 32
        env:
          MODE: {{MODE:-blue}}
"""


def test_live_update_rolls_without_process_restart(cluster, tmp_path):
    """POST /v1/update (CLI: `update start -p K=V`) pushes new service
    options to the RUNNING scheduler: validator-gated, rolled out by
    the update plan, no process restart, and the override survives a
    later restart (reference: the Cosmos update flow + CLI update
    section, cli/commands.go:39,56)."""
    from dcos_commons_tpu.cli.client import CliError
    from dcos_commons_tpu.cli.commands import main as cli_main

    svc = tmp_path / "svc-upd.yml"
    svc.write_text(UPDATABLE_YAML)
    scheduler = SchedulerProcess(
        str(svc), cluster["topology"], str(tmp_path / "sched"),
        env={"ENABLE_BACKOFF": "false"}, repo_root=REPO,
    )
    try:
        client = scheduler.client()
        client.wait_for_completed_deployment(timeout_s=90)
        ids = client.task_ids()
        pid = scheduler.process.pid

        # an update violating a validator is rejected wholesale (400)
        with pytest.raises(CliError) as err:
            client.post("/v1/update", body={"env": {"APP_COUNT": "1"}})
        assert err.value.code == 400
        assert "shrink" in str(err.value.body)

        # a valid update through the CLI update section
        assert cli_main([
            "--url", scheduler.url, "update", "start", "-p", "MODE=green",
        ]) == 0
        new_ids = client.wait_for_tasks_updated(ids, timeout_s=120)
        client.wait_for_completed_deployment(timeout_s=120)
        # rolled on the SAME process — that's the live part
        assert scheduler.process.poll() is None
        assert scheduler.process.pid == pid
        infos = client.get("/v1/pod/app-0/info")
        assert infos[0]["env"]["MODE"] == "green"

        # the override is persisted: a restarted scheduler renders the
        # spec WITH it and does not roll anything back
        assert scheduler.terminate() == 0
        scheduler = SchedulerProcess(
            str(svc), cluster["topology"], str(tmp_path / "sched"),
            env={"ENABLE_BACKOFF": "false"}, repo_root=REPO,
        )
        client = scheduler.client()
        client.wait_for_completed_deployment(timeout_s=90)
        client.check_tasks_not_updated(new_ids)
    finally:
        scheduler.terminate()


MULTISLICE_SVC = """
name: twoslice
pods:
  trainer:
    count: 2
    gang: true
    tpu:
      generation: v5e
      chips-per-host: 4
      topology: 2x2
      slices: 2
    tasks:
      worker:
        goal: RUNNING
        cmd: "echo slice=$TPU_SLICE_INDEX/$TPU_NUM_SLICES coord=$COORDINATOR_ADDRESS && sleep 120"
        cpus: 0.1
        memory: 64
"""


def test_serve_deploys_multislice_gang_over_daemons(tmp_path):
    """A slices: 2 gang deploys over two agent daemon processes, one
    per slice: slice-local sub-gangs, one global coordinator, the
    TPU_SLICE_INDEX/TPU_NUM_SLICES contract visible in the running
    tasks (SURVEY 5.8/7: inter-slice DCN gangs)."""
    agents = [
        AgentProcess(f"ts-h{i}", str(tmp_path / f"agent-{i}"), REPO)
        for i in range(2)
    ]
    svc = tmp_path / "svc.yml"
    svc.write_text(MULTISLICE_SVC)
    lines = ["hosts:"]
    for i, agent in enumerate(agents):
        lines += [
            f"  - host_id: {agent.host_id}",
            f"    agent_url: {agent.url}",
            f"    slice_id: slice-{i}",
            "    generation: v5e",
            "    grid: [0, 0]",
            "    chip_block: [2, 2]",
            "    cpus: 4.0",
            "    memory_mb: 8192",
        ]
    topology = tmp_path / "topology.yml"
    topology.write_text("\n".join(lines) + "\n")
    scheduler = SchedulerProcess(
        str(svc), str(topology), str(tmp_path / "sched"),
        env={"ENABLE_BACKOFF": "false"}, repo_root=REPO,
    )
    try:
        client = scheduler.client()
        client.wait_for_completed_deployment(timeout_s=90)
        infos = {
            i["name"]: i
            for idx in (0, 1)
            for i in client.get(f"/v1/pod/trainer-{idx}/info")
        }
        assert set(infos) == {"trainer-0-worker", "trainer-1-worker"}
        envs = {n: i["env"] for n, i in infos.items()}
        assert {e["TPU_SLICE_INDEX"] for e in envs.values()} == {"0", "1"}
        assert all(e["TPU_NUM_SLICES"] == "2" for e in envs.values())
        coords = {e["COORDINATOR_ADDRESS"] for e in envs.values()}
        assert len(coords) == 1
        # the daemons really ran the workers with the slice contract
        agent_ids = {i["agent_id"] for i in infos.values()}
        assert agent_ids == {"ts-h0", "ts-h1"}
    finally:
        code = scheduler.terminate()
        for agent in agents:
            agent.stop()
        reap_orphan_tasks(agents)
        assert code == 0, scheduler.log_tail()


def test_load_topology_rejects_mixed_mode(tmp_path):
    path = tmp_path / "topology.yml"
    path.write_text(
        "hosts:\n"
        "  - host_id: h0\n"
        "    agent_url: http://127.0.0.1:1\n"
        "  - host_id: h1\n"
    )
    with pytest.raises(ValueError, match="no agent_url"):
        load_topology(str(path))


def test_scheduler_failover_over_state_server(cluster, tmp_path):
    """Real failover: state lives on a state-server process; scheduler
    A deploys, then dies without cleanup; standby B is locked out
    until A's lease expires, then takes over and RESUMES the deployed
    service without relaunching tasks (reference: CuratorPersister +
    CuratorLocker over ZK)."""
    from dcos_commons_tpu.testing.integration import start_state_server

    state, state_url, state_log = start_state_server(
        str(tmp_path / "state"), REPO
    )
    try:
        extra = ["--state-url", state_url]
        env = {"STATE_LEASE_TTL_S": "2"}
        sched_a = SchedulerProcess(
            cluster["svc"], cluster["topology"], str(tmp_path / "sched-a"),
            env=env, repo_root=REPO, extra_args=extra,
        )
        client = sched_a.client()
        client.wait_for_completed_deployment(timeout_s=60)
        before = client.task_ids()
        assert set(before) == {"app-0-server", "app-1-server"}

        # standby is locked out while A holds the lease
        locked = subprocess.run(
            [
                sys.executable, "-m", "dcos_commons_tpu", "serve",
                cluster["svc"],
                "--topology", cluster["topology"],
                "--port", "0",
                "--state-dir", str(tmp_path / "sched-b1-state"),
                "--sandbox-root", str(tmp_path / "sched-b1-sandboxes"),
                *extra,
            ],
            cwd=REPO,
            env={**os.environ, **env},
            capture_output=True,
            timeout=60,
        )
        assert locked.returncode == EXIT_LOCKED, locked.stderr.decode()

        # A dies hard; after lease expiry B takes over and resumes
        sched_a.process.kill()
        sched_a.process.wait(timeout=10)
        time.sleep(3.0)  # > lease ttl
        sched_b = SchedulerProcess(
            cluster["svc"], cluster["topology"], str(tmp_path / "sched-b2"),
            env=env, repo_root=REPO, extra_args=extra,
        )
        client_b = sched_b.client()
        client_b.wait_for_completed_deployment(timeout_s=60)
        client_b.check_tasks_not_updated(before)
        sched_b.terminate()
    finally:
        state.terminate()
        state.wait(timeout=10)
        state_log.close()


def test_multi_serve_dynamic_services(cluster, tmp_path):
    """serve --multi end to end: two seeded services deploy, a third is
    added dynamically over PUT /v1/multi/<name>, one is uninstalled
    over DELETE, and a restart reloads the surviving set from the
    ServiceStore."""
    import urllib.request

    svc_b = tmp_path / "svc-b.yml"
    svc_b.write_text(SVC_YAML.replace("webfarm", "second"))
    workdir = str(tmp_path / "multi")
    os.makedirs(workdir, exist_ok=True)
    announce = os.path.join(workdir, "announce")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dcos_commons_tpu", "serve",
            "--multi", cluster["svc"], str(svc_b),
            "--topology", cluster["topology"],
            "--port", "0",
            "--state-dir", os.path.join(workdir, "state"),
            "--sandbox-root", os.path.join(workdir, "sandboxes"),
            "--announce-file", announce,
        ],
        cwd=REPO,
    )
    try:
        url = wait_for(
            lambda: (
                open(announce).read().strip()
                if os.path.exists(announce) else None
            ),
            30.0, what="multi announce",
        )

        def get(path):
            import json as _json

            with urllib.request.urlopen(url + path, timeout=5) as r:
                return _json.loads(r.read())

        def wait_deployed(name):
            def check():
                # after a restart the rollout plan is named 'update'
                for plan in ("deploy", "update"):
                    try:
                        body = get(f"/v1/multi/{name}/v1/plans/{plan}")
                    except Exception:
                        continue
                    if body["status"] == "COMPLETE":
                        return True
                return None

            wait_for(check, 60.0, what=f"{name} deployed")

        assert set(get("/v1/multi")) == {"webfarm", "second"}
        wait_deployed("webfarm")
        wait_deployed("second")

        # dynamic add over the wire
        third = SVC_YAML.replace("webfarm", "third").encode()
        req = urllib.request.Request(
            url + "/v1/multi/third", data=third, method="PUT"
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            assert r.status == 200
        wait_deployed("third")

        # uninstall one; others untouched
        req = urllib.request.Request(
            url + "/v1/multi/second", method="DELETE"
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            assert r.status == 200
        wait_for(
            lambda: ("second" not in get("/v1/multi")) or None,
            60.0, what="second removed",
        )
        assert get("/v1/multi/webfarm/v1/plans/deploy")["status"] == \
            "COMPLETE"

        # restart: the ServiceStore reloads the surviving services
        proc.terminate()
        assert proc.wait(timeout=20) == 0
        os.remove(announce)
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "dcos_commons_tpu", "serve",
                "--multi",
                "--topology", cluster["topology"],
                "--port", "0",
                "--state-dir", os.path.join(workdir, "state"),
                "--sandbox-root", os.path.join(workdir, "sandboxes"),
                "--announce-file", announce,
            ],
            cwd=REPO,
        )
        url = wait_for(
            lambda: (
                open(announce).read().strip()
                if os.path.exists(announce) else None
            ),
            30.0, what="multi announce after restart",
        )
        wait_for(
            lambda: set(get("/v1/multi")) == {"webfarm", "third"} or None,
            30.0, what="services reloaded",
        )
        wait_deployed("webfarm")
        wait_deployed("third")
    finally:
        proc.terminate()
        proc.wait(timeout=20)


def test_upgrade_rolls_config_change_across_processes(cluster, tmp_path):
    """The sdk_upgrade analogue: a TASKCFG env change on the scheduler
    process rolls every affected task to a new incarnation, across
    real processes, without touching unaffected state."""
    scheduler = SchedulerProcess(
        cluster["svc"], cluster["topology"], str(tmp_path / "sched"),
        env={"TASKCFG_APP_MODE": "v1"},
        repo_root=REPO,
    )
    client = scheduler.client()
    client.wait_for_completed_deployment(timeout_s=60)
    before = client.task_ids()

    scheduler = scheduler.upgrade(env={"TASKCFG_APP_MODE": "v2"})
    try:
        client = scheduler.client()
        after = client.wait_for_tasks_updated(before, timeout_s=90)
        assert set(after) == set(before)
        infos = client.get("/v1/pod/app-0/info")
        assert infos[0]["env"]["MODE"] == "v2"
    finally:
        assert scheduler.terminate() == 0, scheduler.log_tail()


def test_diagnostics_bundle_captures_everything(cluster, tmp_path):
    """sdk_diag analogue: one call harvests plans, pod statuses, debug
    trackers, metrics, logs and task sandbox tails into a bundle —
    resilient to the scheduler being dead."""
    from dcos_commons_tpu.testing.diagnostics import dump_bundle

    scheduler = SchedulerProcess(
        cluster["svc"], cluster["topology"], str(tmp_path / "sched"),
        repo_root=REPO,
    )
    try:
        scheduler.client().wait_for_completed_deployment(timeout_s=60)
        bundle = str(tmp_path / "bundle")
        results = dump_bundle(
            scheduler.url,
            bundle,
            scheduler_log=os.path.join(str(tmp_path / "sched"),
                                       "scheduler.log"),
            sandbox_roots=[
                os.path.join(str(tmp_path / f"agent-{i}"), "sandboxes")
                for i in range(3)
            ],
        )
        assert results["plans.json"] == "ok"
        assert results["plan_trees.json"] == "ok"
        assert results["debug_offers.json"] == "ok"
        import json as _json

        trees = _json.load(open(os.path.join(bundle, "plan_trees.json")))
        assert trees["deploy"]["status"] == "COMPLETE"
        # task sandbox tails came along
        assert any(
            name.startswith("task-app-") for name in os.listdir(bundle)
        )
    finally:
        scheduler.terminate()
    # dead scheduler: the bundle still materializes with errors noted
    results = dump_bundle(scheduler.url, str(tmp_path / "bundle2"))
    assert all("error" in v for k, v in results.items()
               if k.endswith(".json") and k != "MANIFEST.json")


def test_uninstall_via_serve_exits_clean(cluster, tmp_path):
    """SDK_UNINSTALL through the serve entrypoint: the uninstall plan
    kills every task across the real agents, wipes state, and the
    process exits 0 on its own (reference: SDK_UNINSTALL -> Uninstall
    Scheduler -> deregister, FrameworkRunner.java:147-155)."""
    workdir = str(tmp_path / "sched")
    scheduler = SchedulerProcess(
        cluster["svc"], cluster["topology"], workdir, repo_root=REPO,
    )
    try:
        client = scheduler.client()
        client.wait_for_completed_deployment(timeout_s=60)
        ids = client.task_ids()
        assert len(ids) == 2
    finally:
        assert scheduler.terminate() == 0

    # restart in uninstall mode over the same state: it must finish
    # the teardown and exit 0 WITHOUT being asked to stop
    teardown = SchedulerProcess(
        cluster["svc"], cluster["topology"], workdir,
        env={"SDK_UNINSTALL": "1"},
        repo_root=REPO,
        wait_listening=False,
    )
    try:
        assert teardown.process.wait(timeout=90) == 0, teardown.log_tail()
    finally:
        teardown.terminate()
    # every task was torn down on the agents
    import urllib.request
    import json as _json

    for agent in cluster["agents"]:
        with urllib.request.urlopen(
            agent.url + "/v1/agent/tasks", timeout=5
        ) as r:
            assert _json.loads(r.read())["task_ids"] == []
