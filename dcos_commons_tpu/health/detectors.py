"""Anomaly detectors: straggler scoring, SLO watchers, lease churn.

Every detector here runs scheduler-side off data the system already
collects — merged worker steplogs (trace/steplog.py), per-pod serving
gauges (serve/engine.py servestats), the ha.* lease state — and emits
into the event journal.  Detection is advisory by contract: a suspect
host is SORTED LAST in placement scan order (superset-sound, never
excluded), and an SLO alert is a journal record, not an action.

Straggler math — median-ratio over a sliding window: each host's
score is the median of its recent per-step OWN time (``wall_s -
blocked_s``: the barrier probe bills gang-imposed waiting to
``blocked_s``, so own time isolates the host's contribution — in a
synchronized gang every host's ``wall_s`` converges to the slowest
host's, which would hide exactly the host we want to find) divided by
the fleet median of those per-host medians.  Medians at both levels
make the score robust: one preempted step doesn't flag a host, and
one slow HOST doesn't shift the fleet baseline it is compared to
(at ≥3 hosts, where the median excludes the outlier by construction).
"""

from __future__ import annotations

import time
from statistics import median
from typing import Dict, List, Optional

# below this many hosts the fleet median IS (or is dragged by) the
# outlier: scoring 1-2 hosts against themselves only yields noise
MIN_FLEET_FOR_SCORING = 3
# ignore hosts whose own-time median is below this: sub-millisecond
# steps are timer noise, and a ratio of two noise floors flags nothing
# anyone can act on
MIN_OWN_TIME_S = 1e-4


def median_ratio_scores(
    values_by_host: Dict[str, List[float]],
    min_samples: int = 3,
) -> Dict[str, float]:
    """host -> (median of host's values) / (fleet median of those
    medians).  Hosts with fewer than ``min_samples`` values are
    skipped (a freshly-joined host must not be scored off one step);
    {} when fewer than MIN_FLEET_FOR_SCORING hosts qualify.
    Permutation-invariant by construction: medians depend on value
    multisets only, never on dict or list order."""
    per_host: Dict[str, float] = {}
    for host, values in values_by_host.items():
        usable = [v for v in values if v >= 0.0]
        if len(usable) < min_samples:
            continue
        per_host[host] = median(usable)
    if len(per_host) < MIN_FLEET_FOR_SCORING:
        return {}
    fleet = median(per_host.values())
    if fleet < MIN_OWN_TIME_S:
        return {}
    return {host: value / fleet for host, value in per_host.items()}


class StragglerDetector:
    """Scores per-host step own-time from merged steplogs and tracks
    the suspect set with alert edge-triggering.

    ``observe(steplogs_by_host)`` takes {host_id: [steplog records]}
    for one series per host, or {host_id: [[records], [records]]} for
    a host running several tasks (records newest-last either way; the
    trailing ``window`` applies PER SERIES — pooling colocated tasks
    into one flat list would let whichever task was appended last
    evict another task's records entirely, making detection depend on
    task iteration order instead of recency).  Returns the events to
    journal: an ``alert`` when a host's score first crosses
    ``threshold``, and a ``clear`` when a previously-suspect host
    drops back under it — an operator reading the journal sees
    episodes, not one line per cycle.
    """

    def __init__(
        self,
        threshold: float = 2.0,
        window: int = 32,
        min_samples: int = 3,
    ):
        self.threshold = float(threshold)
        self.window = max(1, int(window))
        self.min_samples = max(1, int(min_samples))
        self.scores: Dict[str, float] = {}
        self.suspects: Dict[str, float] = {}

    @staticmethod
    def own_time(record: dict) -> Optional[float]:
        try:
            wall = float(record.get("wall_s", 0.0) or 0.0)
            blocked = float(record.get("blocked_s", 0.0) or 0.0)
        except (TypeError, ValueError):
            return None
        return max(0.0, wall - blocked)

    def observe(
        self, steplogs_by_host: Dict[str, List[dict]]
    ) -> List[dict]:
        values: Dict[str, List[float]] = {}
        for host, records in steplogs_by_host.items():
            series_list = records if records and isinstance(
                records[0], list
            ) else [records]
            owns = []
            for series in series_list:
                for record in series[-self.window:]:
                    own = self.own_time(record)
                    if own is not None:
                        owns.append(own)
            if owns:
                values.setdefault(host, []).extend(owns)
        self.scores = median_ratio_scores(
            values, min_samples=self.min_samples
        )
        now_suspect = {
            host: round(score, 3)
            for host, score in self.scores.items()
            if score >= self.threshold
        }
        events = []
        for host, score in sorted(now_suspect.items()):
            if host not in self.suspects:
                events.append({
                    "kind": "alert",
                    "detector": "straggler",
                    "host": host,
                    "score": score,
                    "threshold": self.threshold,
                    "message": (
                        f"host {host} step own-time is {score}x the "
                        f"fleet median (threshold {self.threshold}x)"
                    ),
                })
        for host in sorted(self.suspects):
            # a host that stopped reporting keeps its suspect mark
            # (silence is not health); only a measured recovery clears
            if host in self.scores and host not in now_suspect:
                events.append({
                    "kind": "alert",
                    "detector": "straggler",
                    "host": host,
                    "score": round(self.scores[host], 3),
                    "cleared": True,
                    "message": f"host {host} back under the straggler "
                               "threshold",
                })
                continue
            if host not in now_suspect:
                now_suspect[host] = self.suspects[host]
        self.suspects = now_suspect
        return events


class ServingSloWatcher:
    """Serving SLO burn off the merged per-task engine gauges.

    Thresholds come from each serving task's own rendered env (the
    options.json serving.* knobs ride the task env contract), falling
    back to the scheduler-level defaults; a threshold of 0 disables
    that check.  Edge-triggered per (task, signal): one alert when the
    breach starts, one clear when it ends.  Signals carry a DIRECTION:
    ``max`` breaches above the threshold (latency, depth, occupancy);
    ``min`` breaches below it — ``kv_pages_free`` is the paged
    engine's memory headroom, and running OUT of pages (503s with a
    kv-page-budget reason) is the breach.

    STALE snapshots are discarded, not scored (ISSUE 12): a wedged
    pod keeps mirroring its last-good gauges, and judging SLOs off
    them would hold a dead pod "healthy" forever.  A snapshot is
    stale when its engine-liveness stamp (``stats_age_s``: seconds
    since the serve loop last ticked, serve/engine.py) or its
    wall-clock write stamp (``t``) exceeds ``stale_stats_s``.  A
    stale snapshot counts as a MISSED sample: open episodes survive
    ``RETIRE_AFTER_MISSES`` collections (no silent recovery), then
    retire as unmeasurable — the same contract as an absent task.
    """

    SIGNALS = (
        # (signal key in stats, env knob, default attr, direction)
        ("ttft_p95_s", "SERVE_TTFT_SLO_S", "ttft_p95_slo_s", "max"),
        ("queue_depth", "SERVE_QUEUE_DEPTH_SLO", "queue_depth_slo",
         "max"),
        ("kv_occupancy", "SERVE_KV_OCCUPANCY_SLO", "kv_occupancy_slo",
         "max"),
        ("kv_pages_free", "SERVE_KV_PAGES_FREE_SLO",
         "kv_pages_free_slo", "min"),
        ("prefill_chunk_backlog", "SERVE_PREFILL_BACKLOG_SLO",
         "prefill_backlog_slo", "max"),
    )
    # signals that are MEANINGLESS for a serving role and must be
    # neither breached on nor counted as quiet evidence there.  A
    # prefill pod (ISSUE 16 disaggregation) holds KV pages only for
    # the instants between finishing a prompt and streaming it to a
    # decode pod: its occupancy/headroom gauges sit near their idle
    # values BY DESIGN, and judging it on them would let the quiet
    # watcher scale in a prefill pod that is saturated with prompt
    # work (its real load lives in prefill_chunk_backlog).
    ROLE_EXCLUDED_SIGNALS = {
        "prefill": frozenset({"kv_occupancy", "kv_pages_free"}),
    }
    # consecutive collections a breaching (task, signal) may go
    # unsampled before its episode is dropped as retired
    RETIRE_AFTER_MISSES = 3

    def __init__(
        self,
        ttft_p95_slo_s: float = 0.0,
        queue_depth_slo: float = 0.0,
        kv_occupancy_slo: float = 0.0,
        kv_pages_free_slo: float = 0.0,
        prefill_backlog_slo: float = 0.0,
        stale_stats_s: float = 30.0,
    ):
        self.ttft_p95_slo_s = float(ttft_p95_slo_s)
        self.queue_depth_slo = float(queue_depth_slo)
        self.kv_occupancy_slo = float(kv_occupancy_slo)
        self.kv_pages_free_slo = float(kv_pages_free_slo)
        self.prefill_backlog_slo = float(prefill_backlog_slo)
        # 0 disables the staleness gate (deterministic tests)
        self.stale_stats_s = float(stale_stats_s)
        self.breaches: Dict[tuple, float] = {}  # (task, signal) -> value
        # episode metadata the action governor consumes (health/
        # actions.py): when each open breach STARTED (the hysteresis
        # hold measures against this) and its current magnitude
        # (value/threshold for max-direction signals, threshold/value
        # for min — >= 1, what scale_out_target is monotone in)
        self.breach_since: Dict[tuple, float] = {}
        self.breach_severity: Dict[tuple, float] = {}
        self._missed: Dict[tuple, int] = {}  # consecutive absent samples
        self.stale_discards = 0  # snapshots discarded as stale

    @classmethod
    def _excluded_signals(cls, stats: dict) -> frozenset:
        """The signals this snapshot's serving role opts out of.
        Pods that never report a role ("" / absent → unified) keep
        the full signal set — pre-disaggregation fleets see zero
        behavior change."""
        role = stats.get("serving_role")
        if not isinstance(role, str):
            return frozenset()
        return cls.ROLE_EXCLUDED_SIGNALS.get(role, frozenset())

    def _threshold(self, env: Dict[str, str], knob: str, attr: str) -> float:
        raw = (env or {}).get(knob, "")
        if raw:
            try:
                return float(raw)
            except ValueError:
                pass
        return getattr(self, attr)

    def _is_stale(self, stats: dict, now: float) -> bool:
        """Either liveness stamp past the horizon marks the snapshot
        unusable: ``stats_age_s`` (the pod's own serve loop wedged)
        or ``t`` (the mirror file stopped being rewritten — the
        whole worker is gone but its last file survives)."""
        if self.stale_stats_s <= 0:
            return False
        for key, basis in (("stats_age_s", 0.0), ("t", now)):
            raw = stats.get(key)
            if raw is None:
                continue
            try:
                age = basis - float(raw) if key == "t" else float(raw)
            except (TypeError, ValueError):
                continue
            if age > self.stale_stats_s:
                return True
        return False

    def observe(
        self,
        stats_by_task: Dict[str, dict],
        env_by_task: Optional[Dict[str, Dict[str, str]]] = None,
        now: Optional[float] = None,
    ) -> List[dict]:
        now = time.time() if now is None else now
        events = []
        seen = set()
        for task, stats in sorted(stats_by_task.items()):
            env = (env_by_task or {}).get(task, {})
            if self._is_stale(stats, now):
                # discard, do not score: last-good gauges from a
                # wedged pod look healthy precisely when it is not.
                # The open episodes ride the missed-sample counter.
                self.stale_discards += 1
                continue
            excluded = self._excluded_signals(stats)
            for signal, knob, attr, direction in self.SIGNALS:
                if signal in excluded:
                    continue  # meaningless for this serving role
                threshold = self._threshold(env, knob, attr)
                if threshold <= 0 or signal not in stats:
                    continue
                try:
                    value = float(stats[signal])
                except (TypeError, ValueError):
                    continue
                key = (task, signal)
                seen.add(key)
                breaching = (
                    value < threshold if direction == "min"
                    else value > threshold
                )
                if breaching:
                    tiny = 1e-9
                    self.breach_severity[key] = (
                        threshold / max(value, tiny)
                        if direction == "min"
                        else value / max(threshold, tiny)
                    )
                if breaching and key in self.breaches:
                    # still breaching: no repeat alert, but keep the
                    # CURRENT magnitude — an operator triaging
                    # /v1/debug/health must see the runaway value,
                    # not the marginal first-breach one
                    self.breaches[key] = value
                elif breaching:
                    self.breaches[key] = value
                    self.breach_since[key] = now
                    events.append({
                        "kind": "alert",
                        "detector": "slo",
                        "task": task,
                        "signal": signal,
                        "value": round(value, 4),
                        "threshold": threshold,
                        "message": (
                            f"{task} {signal}={round(value, 4)} breaches "
                            f"SLO {threshold}"
                            + (" (below minimum)"
                               if direction == "min" else "")
                        ),
                    })
                elif not breaching and key in self.breaches:
                    del self.breaches[key]
                    self.breach_since.pop(key, None)
                    self.breach_severity.pop(key, None)
                    recovery = (
                        "back above minimum SLO"
                        if direction == "min" else "back under SLO"
                    )
                    events.append({
                        "kind": "alert",
                        "detector": "slo",
                        "task": task,
                        "signal": signal,
                        "value": round(value, 4),
                        "cleared": True,
                        "message": f"{task} {signal} {recovery}",
                    })
        # a missing sample is not a recovery: one failed collection
        # (a dropped RPC, an idle window omitting a percentile) must
        # neither end an episode silently nor re-alert when the next
        # sample arrives still breaching.  Only a task absent for
        # several consecutive collections (a retired pod) drops its
        # episodes — silently, since nothing was measured.
        for key in list(self.breaches):
            if key in seen:
                self._missed.pop(key, None)
                continue
            self._missed[key] = self._missed.get(key, 0) + 1
            if self._missed[key] >= self.RETIRE_AFTER_MISSES:
                del self.breaches[key]
                del self._missed[key]
                self.breach_since.pop(key, None)
                self.breach_severity.pop(key, None)
        return events


class QuietPodWatcher:
    """The LOW-watermark detector over the same serving gauges: a pod
    instance is QUIET when every enabled max-direction SLO signal it
    reports sits at or below ``quiet_factor`` x its breach threshold
    (and no min-direction signal is breaching).  The gap between the
    quiet watermark and the breach threshold is the hysteresis dead
    band — a constant signal inside it triggers neither direction.

    Edge-triggered episodes like every detector here: one alert when
    quiet is ESTABLISHED (carrying ``since``), one clear when any
    signal rises back above the watermark.  The scale-in governor
    applies its own ``quiet_hold_s`` on top of ``since`` — this
    watcher marks episodes, the policy decides.

    Threshold resolution is SHARED with the breach watcher (same
    env-knob fallback chain), so the two bands can never drift apart;
    missing/stale samples ride the same missed-sample counter (one
    dropped RPC neither ends a quiet episode nor starts one)."""

    RETIRE_AFTER_MISSES = 3

    def __init__(self, slo: ServingSloWatcher,
                 quiet_factor: float = 0.25):
        self._slo = slo
        self.quiet_factor = float(quiet_factor)
        self.quiet_since: Dict[str, float] = {}
        self._missed: Dict[str, int] = {}

    def _is_quiet(self, stats: dict, env: Dict[str, str]) -> Optional[bool]:
        """True/False, or None when no enabled LOAD signal is present
        (an unknown pod is neither quiet nor loaded).  Quiet EVIDENCE
        comes only from max-direction load signals sitting under the
        watermark; min-direction headroom signals can veto (a starved
        arena is the opposite of quiet) but never attest — a
        deployment with only ``kv_pages_free_slo`` enabled would
        otherwise mark every non-starved pod quiet regardless of
        load, and the scale-in it triggers would breach and flap."""
        any_load_signal = False
        excluded = ServingSloWatcher._excluded_signals(stats)
        for signal, knob, attr, direction in ServingSloWatcher.SIGNALS:
            if signal in excluded:
                # role-excluded gauges attest nothing: a prefill
                # pod's near-zero decode occupancy is its design
                # point, not quiet evidence
                continue
            threshold = self._slo._threshold(env, knob, attr)
            if threshold <= 0 or signal not in stats:
                continue
            try:
                value = float(stats[signal])
            except (TypeError, ValueError):
                continue
            if direction == "min":
                # headroom signal: breaching (below minimum) is the
                # opposite of quiet; plentiful headroom is neutral
                if value < threshold:
                    return False
                continue
            any_load_signal = True
            if value > threshold * self.quiet_factor:
                return False
        return True if any_load_signal else None

    def observe(
        self,
        stats_by_task: Dict[str, dict],
        env_by_task: Optional[Dict[str, Dict[str, str]]] = None,
        now: Optional[float] = None,
    ) -> List[dict]:
        now = time.time() if now is None else now
        events: List[dict] = []
        seen = set()
        for task, stats in sorted(stats_by_task.items()):
            env = (env_by_task or {}).get(task, {})
            if self._slo._is_stale(stats, now):
                continue  # missed sample, not evidence of anything
            verdict = self._is_quiet(stats, env)
            if verdict is None:
                continue
            seen.add(task)
            if verdict and task not in self.quiet_since:
                self.quiet_since[task] = now
                events.append({
                    "kind": "alert",
                    "detector": "quiet",
                    "task": task,
                    "since": round(now, 3),
                    "message": (
                        f"{task} quiet: all serving gauges at or "
                        f"below {self.quiet_factor}x their SLO "
                        "thresholds"
                    ),
                })
            elif not verdict and task in self.quiet_since:
                del self.quiet_since[task]
                events.append({
                    "kind": "alert",
                    "detector": "quiet",
                    "task": task,
                    "cleared": True,
                    "message": f"{task} back above the quiet watermark",
                })
        for task in list(self.quiet_since):
            if task in seen:
                self._missed.pop(task, None)
                continue
            self._missed[task] = self._missed.get(task, 0) + 1
            if self._missed[task] >= self.RETIRE_AFTER_MISSES:
                # retired pod (or the scale-in that quiet triggered
                # already killed it): drop silently, nothing measured
                del self.quiet_since[task]
                del self._missed[task]
        return events


class LeaseChurnWatcher:
    """Flags flapping leadership: ``churn_n`` or more lease-epoch
    changes inside ``window_s`` means schedulers are trading the lease
    instead of holding it (renewal starvation, a crash loop, or a
    split network) — each individual failover looks routine, the RATE
    is the anomaly.  Edge-triggered episodes like the other detectors:
    one alert when the rate crosses ``churn_n``, one clear (and
    re-arm) when it drops back below — NOT when the window fully
    empties, or a steady sub-threshold drip of routine failovers
    would hold the alert suppressed forever."""

    def __init__(self, churn_n: int = 3, window_s: float = 300.0):
        self.churn_n = max(2, int(churn_n))
        self.window_s = float(window_s)
        self._changes: List[float] = []  # times of observed epoch bumps
        self._last_epoch: Optional[int] = None
        self._alerted = False

    @property
    def alerted(self) -> bool:
        """True while a churn episode is OPEN — the action governor's
        flap hold (no automated scale/remediation under flapping
        leadership)."""
        return self._alerted

    def observe(self, epoch: Optional[int], t: Optional[float] = None) -> List[dict]:
        if epoch is None:
            return []
        now = time.time() if t is None else t
        if self._last_epoch is not None and epoch != self._last_epoch:
            self._changes.append(now)
        self._last_epoch = epoch
        self._changes = [
            ts for ts in self._changes if now - ts <= self.window_s
        ]
        if len(self._changes) >= self.churn_n:
            if not self._alerted:
                self._alerted = True
                return [{
                    "kind": "alert",
                    "detector": "lease-churn",
                    "epoch": epoch,
                    "changes": len(self._changes),
                    "window_s": self.window_s,
                    "message": (
                        f"leader lease changed {len(self._changes)} times "
                        f"in {self.window_s:.0f}s (epoch now {epoch}) — "
                        "flapping leadership"
                    ),
                }]
        elif self._alerted:
            self._alerted = False  # episode over: clear and re-arm
            return [{
                "kind": "alert",
                "detector": "lease-churn",
                "epoch": epoch,
                "changes": len(self._changes),
                "cleared": True,
                "message": "leader lease churn back under the "
                           "flapping threshold",
            }]
        return []
