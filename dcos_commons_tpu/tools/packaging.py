"""Framework packages: bundle, verify, extract, install.

Reference: tools/universe/package_builder.py (manifest + artifact
bundling) and the Cosmos install flow (frameworks/*/universe/
package.json + resource.json).  A package is a tar.gz of one framework
directory with a generated ``package.json`` manifest carrying name,
version, and per-file SHA-256 digests; extraction verifies every
digest and confines members to the target directory.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import tarfile
from typing import Dict, Optional

MANIFEST_NAME = "package.json"


class PackageError(Exception):
    pass


def build_package(
    framework_dir: str,
    out_path: str,
    name: str = "",
    version: str = "0.1.0",
    description: str = "",
) -> Dict:
    """Bundle ``framework_dir`` (must contain svc.yml) into a tar.gz
    with a digest manifest; returns the manifest."""
    framework_dir = os.path.abspath(framework_dir)
    svc = os.path.join(framework_dir, "svc.yml")
    if not os.path.isfile(svc):
        raise PackageError(f"{framework_dir} has no svc.yml")
    # a package with a self-inconsistent options schema must never
    # ship (reference: config.json is validated by universe tooling)
    from dcos_commons_tpu.tools.options import options_findings

    schema_findings = options_findings(framework_dir)
    if schema_findings:
        raise PackageError(
            "options.json is inconsistent: " + "; ".join(schema_findings)
        )
    if not name:
        name = os.path.basename(framework_dir.rstrip(os.sep))
    # read each file ONCE: content and digest must come from the same
    # bytes, or a file rewritten mid-build ships with a manifest digest
    # that can never verify
    contents: Dict[str, bytes] = {}
    for root, _dirs, filenames in os.walk(framework_dir):
        for filename in sorted(filenames):
            path = os.path.join(root, filename)
            rel = os.path.relpath(path, framework_dir)
            if rel == MANIFEST_NAME or "__pycache__" in rel:
                continue
            # by CONTENT: a symlinked template becomes a regular file
            # in the package (extract rejects link members)
            with open(path, "rb") as f:
                contents[rel] = f.read()
    manifest = {
        "name": name,
        "version": version,
        "description": description,
        "files": {
            rel: hashlib.sha256(data).hexdigest()
            for rel, data in contents.items()
        },
    }
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with tarfile.open(out_path, "w:gz") as tar:
        def add_bytes(member_name: str, payload: bytes) -> None:
            member = tarfile.TarInfo(member_name)
            member.size = len(payload)
            tar.addfile(member, io.BytesIO(payload))

        add_bytes(
            MANIFEST_NAME, json.dumps(manifest, indent=2).encode("utf-8")
        )
        for rel in sorted(contents):
            add_bytes(rel, contents[rel])
    return manifest


def read_manifest(package_path: str) -> Dict:
    try:
        with tarfile.open(package_path, "r:gz") as tar:
            member = tar.extractfile(MANIFEST_NAME)
            if member is None:
                raise PackageError(f"{package_path}: no {MANIFEST_NAME}")
            return json.loads(member.read().decode("utf-8"))
    except (tarfile.TarError, KeyError, ValueError, OSError) as e:
        raise PackageError(f"{package_path}: not a package: {e}")


def extract_package(package_bytes: bytes, target_dir: str) -> Dict:
    """Extract a package into ``target_dir``, verifying the manifest
    digests and rejecting members that would escape the directory.

    Returns the manifest.  Reference: Cosmos unpacking a universe
    package before handing the scheduler its config."""
    os.makedirs(target_dir, exist_ok=True)
    # realpath on BOTH sides: a symlinked target dir must not make
    # every member look like an escape
    target_dir = os.path.realpath(target_dir)
    try:
        tar = tarfile.open(fileobj=io.BytesIO(package_bytes), mode="r:gz")
    except tarfile.TarError as e:
        raise PackageError(f"not a package tarball: {e}")
    with tar:
        try:
            manifest_member = tar.extractfile(MANIFEST_NAME)
            if manifest_member is None:
                raise KeyError(MANIFEST_NAME)
            manifest = json.loads(manifest_member.read().decode("utf-8"))
        except (KeyError, ValueError) as e:
            raise PackageError(f"bad package manifest: {e}")
        extracted = set()
        for member in tar.getmembers():
            if member.name == MANIFEST_NAME:
                continue
            if not member.isfile():
                raise PackageError(
                    f"package member {member.name!r} is not a regular file"
                )
            dest = os.path.realpath(os.path.join(target_dir, member.name))
            if not dest.startswith(target_dir + os.sep):
                raise PackageError(
                    f"package member escapes target: {member.name!r}"
                )
            expected = manifest.get("files", {}).get(member.name)
            if expected is None:
                raise PackageError(
                    f"package member not in manifest: {member.name!r}"
                )
            data = tar.extractfile(member).read()
            actual = hashlib.sha256(data).hexdigest()
            if actual != expected:
                raise PackageError(
                    f"digest mismatch for {member.name!r}"
                )
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            with open(dest, "wb") as f:
                f.write(data)
            extracted.add(member.name)
    missing = set(manifest.get("files", {})) - extracted
    if missing:
        # a truncated archive must fail NOW, not at task launch when a
        # template turns out to be absent
        raise PackageError(f"package missing manifest files: {sorted(missing)}")
    if "svc.yml" not in manifest.get("files", {}):
        raise PackageError("package has no svc.yml")
    return manifest


def lint_airgap(framework_dir: str) -> list:
    """Air-gap lint (reference: tools/airgap_linter.py): a framework
    destined for a fleet with no egress must not bake external URLs or
    image pulls into its svc.yml / templates / scripts.  Returns a
    list of "path:line: finding" strings; empty = clean."""
    import re as _re

    if not os.path.isdir(framework_dir):
        # a typo'd path must not pass as "clean" (mirrors build_package
        # raising on a missing svc.yml)
        raise PackageError(f"no such framework dir: {framework_dir}")
    url_re = _re.compile(r"https?://[^\s\"']+", _re.IGNORECASE)
    image_re = _re.compile(r"^\s*image:\s*(\S+)")
    findings = []
    for dirpath, dirs, files in os.walk(framework_dir):
        # lint the file set build_package would SHIP (no VCS/cache
        # droppings — a .git/config URL is not a package finding)
        dirs[:] = [
            d for d in dirs if d != "__pycache__" and not d.startswith(".")
        ]
        for name in sorted(files):
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, framework_dir)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    lines = f.readlines()
            except (UnicodeDecodeError, OSError):
                continue  # binaries are the tasks' problem, not ours
            for i, line in enumerate(lines, 1):
                stripped = line.strip()
                # NOTE: '*' is NOT a comment marker — a shell case arm
                # `*) curl https://...` must be flagged
                if stripped.startswith(("#", "//")):
                    continue
                if stripped.startswith("web-url:"):
                    # an ADVERTISED operator-browser URL, not a task
                    # fetch: air-gap egress rules don't apply to it
                    continue
                for url in url_re.findall(stripped):
                    host = url.split("//", 1)[1].split("/", 1)[0]
                    if host.startswith("["):  # bracketed IPv6
                        bare = host[1:].split("]", 1)[0]
                    else:
                        bare = host.split(":")[0]
                    if bare in (
                        "localhost", "127.0.0.1", "0.0.0.0", "::1",
                    ):
                        continue  # loopback is not egress
                    findings.append(
                        f"{rel}:{i}: external URL {url} — unreachable "
                        "in an air-gapped fleet"
                    )
                image = image_re.match(line)
                if image and "/" in image.group(1) and \
                        "." in image.group(1).split("/")[0]:
                    findings.append(
                        f"{rel}:{i}: image {image.group(1)} pulls from "
                        "an external registry"
                    )
    return findings


def main(argv: Optional[list] = None) -> int:
    """``python -m dcos_commons_tpu package`` — build/inspect/install."""
    import argparse
    import sys
    import urllib.request

    parser = argparse.ArgumentParser(prog="dcos_commons_tpu package")
    sub = parser.add_subparsers(dest="verb", required=True)
    p = sub.add_parser("build")
    p.add_argument("framework_dir")
    p.add_argument("-o", "--out", required=True)
    p.add_argument("--name", default="")
    p.add_argument("--version", default="0.1.0")
    p.add_argument("--description", default="")
    p = sub.add_parser("inspect")
    p.add_argument("package")
    p = sub.add_parser("lint")
    p.add_argument("framework_dir")
    p = sub.add_parser(
        "publish",
        help="publish a built package into a registry "
             "(tools/publish_http.py + release_builder.py analogue)",
    )
    p.add_argument("package")
    p.add_argument(
        "--registry", required=True,
        help="registry directory path or HTTP URL",
    )
    p.add_argument("--token", default="", help="registry publish token")
    p = sub.add_parser(
        "registry-prune",
        help="retire old releases from a registry DIRECTORY: keep "
             "the newest K versions per package (release_builder "
             "lifecycle cleanup; runs on the registry host)",
    )
    p.add_argument("--dir", required=True, help="registry directory")
    p.add_argument(
        "--keep", type=int, required=True,
        help="newest versions to retain per package (>= 1)",
    )
    p.add_argument(
        "--name", default="",
        help="prune only this package (default: every package)",
    )
    p.add_argument(
        "--grace-s", type=float, default=0.0,
        help="seconds to keep a pruned artifact's bytes on disk "
             "(parked as .trash-<epoch>, out of the index) before a "
             "later prune unlinks it.  0 deletes immediately — on NFS "
             "a registry-serve client mid-fetch then gets truncated "
             "reads/stale handles, so either quiesce fetches or set a "
             "grace covering your slowest fetch",
    )
    p = sub.add_parser(
        "registry-serve",
        help="serve a registry directory over HTTP",
    )
    p.add_argument("--dir", required=True)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--bind", default="127.0.0.1")
    p.add_argument("--token", default="",
                   help="bearer token required to publish")
    p.add_argument("--announce-file", default="")
    p = sub.add_parser("install")
    p.add_argument(
        "package",
        help="package tarball path, or a package NAME with --registry",
    )
    p.add_argument(
        "--url", required=True, help="multi scheduler API URL"
    )
    p.add_argument(
        "--registry", default="",
        help="resolve the package by name from this registry "
             "(dir path or HTTP URL) instead of a local tarball",
    )
    p.add_argument(
        "--package-version", default="",
        help="with --registry: install this version (default latest)",
    )
    p.add_argument("--token", default="", help="registry read token")
    p.add_argument(
        "--name", default="",
        help="service name (default: manifest name)",
    )
    p.add_argument(
        "--upgrade", action="store_true",
        help="push a new package version to a RUNNING service "
             "(Cosmos `update --package-version` analogue): validated "
             "config diff, rolling update over live state",
    )
    p.add_argument(
        "--options", default="",
        help="user options JSON file validated against the package's "
             "options.json (Cosmos `--options` analogue); on upgrade, "
             "prior options are kept and these overlay them",
    )
    args = parser.parse_args(argv)

    try:
        return _run_verb(args)
    except PackageError as e:
        print(f"package error: {e}", file=sys.stderr)
        return 1


def _run_verb(args) -> int:
    import json
    import sys
    import urllib.request

    if args.verb == "build":
        manifest = build_package(
            args.framework_dir, args.out,
            name=args.name, version=args.version,
            description=args.description,
        )
        print(json.dumps(
            {k: manifest[k] for k in ("name", "version")}
            | {"files": len(manifest["files"]), "out": args.out}
        ))
        return 0
    if args.verb == "inspect":
        print(json.dumps(read_manifest(args.package), indent=2))
        return 0
    if args.verb == "lint":
        findings = lint_airgap(args.framework_dir)
        # the options schema lints with the same verb: a package whose
        # defaults violate their own constraints must not ship
        from dcos_commons_tpu.tools.options import options_findings

        findings += options_findings(args.framework_dir)
        for finding in findings:
            print(finding)
        if findings:
            print(f"{len(findings)} finding(s)", file=sys.stderr)
            return 1
        print("lint clean")
        return 0
    if args.verb == "publish":
        from dcos_commons_tpu.tools.registry import publish_package

        out = publish_package(
            args.package, args.registry, token=args.token
        )
        print(json.dumps(out))
        return 0
    if args.verb == "registry-prune":
        from dcos_commons_tpu.tools.registry import prune_registry

        pruned = prune_registry(
            args.dir, args.keep, name=args.name, grace_s=args.grace_s
        )
        print(json.dumps({"pruned": pruned}))
        return 0
    if args.verb == "registry-serve":
        from dcos_commons_tpu.tools.registry import RegistryServer

        server = RegistryServer(
            args.dir, port=args.port, bind=args.bind,
            auth_token=args.token,
        ).start()
        print(f"registry serving {args.dir} at {server.url}", flush=True)
        if args.announce_file:
            tmp = args.announce_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(server.url)
            os.replace(tmp, args.announce_file)
        import signal
        import threading as _threading

        stop = _threading.Event()
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        stop.wait()
        server.stop()
        return 0
    # install: the tarball travels to the scheduler (Cosmos analogue),
    # from a local build or resolved + digest-verified out of a registry
    if getattr(args, "registry", ""):
        from dcos_commons_tpu.tools.registry import fetch_package

        version, payload = fetch_package(
            args.registry, args.package,
            version=getattr(args, "package_version", ""),
            token=args.token,
        )
        name = args.name or args.package
        print(f"resolved {args.package} {version} from registry",
              file=sys.stderr)
    else:
        with open(args.package, "rb") as f:
            payload = f.read()
        name = args.name or read_manifest(args.package)["name"]
    suffix = "?upgrade=true" if getattr(args, "upgrade", False) else ""
    headers = {"Content-Type": "application/gzip"}
    if getattr(args, "options", ""):
        import base64 as _b64

        with open(args.options, "r", encoding="utf-8") as f:
            try:
                options = json.load(f)
            except ValueError as e:
                print(f"bad options file {args.options}: {e}",
                      file=sys.stderr)
                return 1
        headers["X-Service-Options"] = _b64.b64encode(
            json.dumps(options).encode("utf-8")
        ).decode("ascii")
    req = urllib.request.Request(
        f"{args.url.rstrip('/')}/v1/multi/{name}{suffix}",
        data=payload,
        method="PUT",
        headers=headers,
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            print(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        print(e.read().decode("utf-8"), file=sys.stderr)
        return 1
    except urllib.error.URLError as e:
        print(f"scheduler unreachable at {args.url}: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
