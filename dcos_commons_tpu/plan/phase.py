"""Phase: an ordered set of steps under one strategy.

Reference: scheduler/plan/Phase.java:12, DefaultPhaseFactory.
"""

from __future__ import annotations

from typing import List, Sequence, Set

from dcos_commons_tpu.common import TaskStatus
from dcos_commons_tpu.plan.element import Element
from dcos_commons_tpu.plan.status import Status, aggregate
from dcos_commons_tpu.plan.step import Step
from dcos_commons_tpu.plan.strategy import SerialStrategy, Strategy


class Phase(Element):
    def __init__(self, name: str, steps: Sequence[Step], strategy: Strategy = None):
        super().__init__(name)
        self.steps: List[Step] = list(steps)
        self.strategy = strategy or SerialStrategy()

    def get_status(self) -> Status:
        if self.has_errors():
            return Status.ERROR
        return aggregate(
            (s.get_status() for s in self.steps),
            interrupted=self.strategy.is_interrupted(),
        )

    def candidates(self, dirty_assets: Set[str]) -> List[Step]:
        return [
            s for s in self.strategy.candidates(self.steps, dirty_assets)
            if isinstance(s, Step)
        ]

    def update(self, status: TaskStatus) -> None:
        for step in self.steps:
            step.update(status)

    def interrupt(self) -> None:
        self.strategy.interrupt()

    def proceed(self) -> None:
        self.strategy.proceed()

    def is_interrupted(self) -> bool:
        return self.strategy.is_interrupted()

    def restart(self) -> None:
        for step in self.steps:
            step.restart()

    def force_complete(self) -> None:
        for step in self.steps:
            step.force_complete()
