"""Span: one timed region of the control plane's causal timeline.

A span is created by ``TraceRecorder.span()`` with an EXPLICIT parent
(``trace.span("evaluate", parent=cycle_span)``) — there is no implicit
thread-local/contextvar ambient context to thread through the
JIT-adjacent layers, so a span's lineage is always visible at the call
site.  Spans carry:

* ``trace_id`` — the correlation id shared by everything one offer
  cycle caused (minted by the root span, inherited through parents and
  the launch registry);
* ``span_id``/``parent_id`` — the tree within a trace;
* monotonic start/end stamps (exporters convert to wall time);
* string key/value ``attrs`` (failing requirement, task ids, states);
* ``track`` — the export lane (Chrome ``tid``): "scheduler", a pod
  instance like "trainer-2", or "plan".

Spans must be CLOSED on every path — ``with`` or an explicit
``end()`` — or the flight recorder never sees them and their children
dangle; sdklint's ``span-leak`` rule enforces this.
"""

from __future__ import annotations

import itertools
import os
import time
from typing import Dict, Optional

# span/trace ids: process-random prefix + monotonic counter.  uuid4
# reads os.urandom per call (tens of µs on syscall-bound kernels) —
# 40µs x ~8 spans/cycle would blow the recorder's <5% overhead bound
# all by itself.  One urandom read at import keeps ids unique across
# processes; the counter keeps them unique within one.  The hot path
# hands out the cheap counter value; the prefix is applied when an id
# is RENDERED for export (render_id) — live spans compare ids, they
# never print them.
_ID_PREFIX = os.urandom(4).hex()
_ID_COUNTER = itertools.count(1)


def new_id() -> int:
    return next(_ID_COUNTER)


def render_id(span_or_trace_id) -> str:
    """Export-time form of an id: stable, process-unique hex."""
    if not span_or_trace_id:
        return ""
    return f"{_ID_PREFIX}{span_or_trace_id:08x}"


class Span:
    """A live span; recorded into the recorder's ring buffer on end().

    Context-manager use is the norm::

        with tracer.span("evaluate", parent=cycle) as span:
            span.set_attr("pod", pod.type)
    """

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "track",
        "start_s", "end_s", "attrs", "_recorder", "_dropped",
    )

    def __init__(
        self,
        name: str,
        trace_id: int,
        parent_id: int = 0,
        track: str = "",
        attrs: Optional[Dict[str, object]] = None,
        recorder=None,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_id()
        self.parent_id = parent_id
        self.track = track
        self.start_s = time.monotonic()
        self.end_s: Optional[float] = None
        # NOT copied: the recorder hands over a per-call kwargs dict
        self.attrs: Dict[str, object] = attrs if attrs is not None else {}
        self._recorder = recorder
        self._dropped = False

    # -- lifecycle ----------------------------------------------------

    def set_attr(self, key: str, value) -> "Span":
        # values stringify lazily at export (attrs_text/to_chrome):
        # the hot path pays one dict store, not a str() per attribute
        self.attrs[key] = value
        return self

    def str_attrs(self) -> Dict[str, str]:
        """Attrs with values stringified — the export-time form."""
        return {k: str(v) for k, v in self.attrs.items()}

    def drop(self) -> None:
        """Mark this span uninteresting (an idle heartbeat cycle): it
        still closes normally but is not recorded, keeping the bounded
        flight recorder for cycles that did work."""
        self._dropped = True

    def end(self) -> None:
        """Idempotent close; records into the ring buffer once."""
        if self.end_s is not None:
            return
        self.end_s = time.monotonic()
        if not self._dropped and self._recorder is not None:
            self._recorder._record(self)

    @property
    def duration_s(self) -> float:
        end = self.end_s if self.end_s is not None else time.monotonic()
        return end - self.start_s

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> bool:
        self.end()
        return False

    def __repr__(self) -> str:  # debugging aid, not an export format
        state = "open" if self.end_s is None else f"{self.duration_s:.6f}s"
        return (
            f"Span({self.name!r}, trace={render_id(self.trace_id)}, "
            f"track={self.track!r}, {state})"
        )


class NullSpan(Span):
    """The no-op span a disabled recorder hands out: every operation is
    safe and free, so call sites never branch on tracing-enabled."""

    def __init__(self):
        super().__init__("", trace_id=0, recorder=None)
        self.end_s = self.start_s

    def set_attr(self, key: str, value) -> "Span":
        return self

    def drop(self) -> None:
        pass

    def end(self) -> None:
        pass
