"""Operator CLI (T2): the ``dcos <svc> ...`` subcommand equivalent.

Reference: cli/ (Go) — sections plan/pod/config/state/endpoints/debug
(cli/commands.go:39,56; plan verbs incl. pause/resume/force-restart/
force-complete, cli/commands/plan.go:51-90) speaking HTTP to the
scheduler API.  Invoke as ``python -m dcos_commons_tpu.cli`` with the
scheduler URL from ``--url`` or ``$SCHEDULER_API_URL``.
"""

from dcos_commons_tpu.cli.client import ApiClient, CliError
from dcos_commons_tpu.cli.commands import build_parser, main

__all__ = ["ApiClient", "CliError", "build_parser", "main"]
