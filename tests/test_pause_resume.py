"""Pod pause/resume via GoalStateOverride.

Reference: http/queries/PodQueries.java:183-203 (pause/resume flip a
GoalStateOverride and relaunch with a sleep override cmd),
state/GoalStateOverride.java (PAUSED + progress machine).
"""

from dcos_commons_tpu.offer.evaluate import PAUSE_COMMAND
from dcos_commons_tpu.plan.status import Status
from dcos_commons_tpu.state.state_store import (
    GoalStateOverride,
    OverrideProgress,
)
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    ExpectPlanStatus,
    ExpectTaskKilled,
    SendTaskRunning,
    ServiceTestRunner,
)

YAML = """
name: pausable
pods:
  web:
    count: 1
    tasks:
      srv:
        goal: RUNNING
        cmd: "real-server --serve"
        cpus: 0.1
        memory: 32
        readiness-check:
          cmd: "check-it"
          interval: 1
          timeout: 5
"""


def deploy(runner):
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("web-0-srv"),
        ExpectDeploymentComplete(),
    ])


def test_pause_relaunches_idle_and_resume_restores():
    runner = ServiceTestRunner(YAML)
    deploy(runner)
    world = runner.world
    scheduler = world.scheduler

    touched = scheduler.pause_pod("web", 0)
    assert touched == ["web-0-srv"]
    runner.run([
        AdvanceCycles(1),         # kill ack arrives; recovery relaunches
        ExpectTaskKilled("web-0-srv"),
        AdvanceCycles(1),
        SendTaskRunning("web-0-srv"),
        ExpectPlanStatus("recovery", Status.COMPLETE),
    ])
    info = world.agent.task_info_of("web-0-srv")
    assert info.command == PAUSE_COMMAND
    # paused relaunch must not carry the readiness check
    assert world.agent.checks[info.task_id]["readiness"] is None
    override, progress = scheduler.state_store.fetch_goal_override("web-0-srv")
    assert override is GoalStateOverride.PAUSED
    assert progress is OverrideProgress.COMPLETE

    scheduler.resume_pod("web", 0)
    runner.run([
        AdvanceCycles(1),
        AdvanceCycles(1),
        SendTaskRunning("web-0-srv"),
        ExpectPlanStatus("recovery", Status.COMPLETE),
    ])
    info = world.agent.task_info_of("web-0-srv")
    assert info.command == "real-server --serve"
    assert world.agent.checks[info.task_id]["readiness"] is not None
    override, progress = scheduler.state_store.fetch_goal_override("web-0-srv")
    assert override is GoalStateOverride.NONE
    assert progress is OverrideProgress.COMPLETE


def test_pause_survives_scheduler_restart():
    runner = ServiceTestRunner(YAML)
    deploy(runner)
    runner.world.scheduler.pause_pod("web", 0)
    runner.run([AdvanceCycles(2)])

    restarted = runner.restart()
    restarted.run([
        AdvanceCycles(2),
        SendTaskRunning("web-0-srv"),
    ])
    info = restarted.agent.task_info_of("web-0-srv")
    assert info.command == PAUSE_COMMAND
