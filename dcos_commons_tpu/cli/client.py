"""HTTP client for the scheduler API.

Reference: cli/client/client.go — thin wrapper adding the service URL
prefix and surfacing non-2xx responses as errors with the body text.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Optional
from urllib.parse import urlencode


class CliError(Exception):
    def __init__(self, code: int, body: Any):
        self.code = code
        self.body = body
        super().__init__(f"HTTP {code}: {body}")


class ApiClient:
    def __init__(self, base_url: str, timeout_s: float = 10.0,
                 auth_token: str = "", ca_file: str = ""):
        from dcos_commons_tpu.security import auth as _auth

        self._base = base_url.rstrip("/")
        self._timeout = timeout_s
        self._headers = _auth.auth_headers(auth_token)
        self._ssl_ctx = (
            _auth.client_ssl_context(ca_file)
            if self._base.startswith("https") else None
        )

    def get(self, path: str) -> Any:
        return self._request("GET", path)

    def post(self, path: str, params: Optional[dict] = None,
             body: Optional[Any] = None) -> Any:
        if params:
            clean = {k: v for k, v in params.items() if v is not None}
            if clean:
                path = f"{path}?{urlencode(clean, doseq=True)}"
        return self._request("POST", path, body=body)

    def _request(self, method: str, path: str,
                 body: Optional[Any] = None) -> Any:
        if body is not None:
            data = json.dumps(body).encode("utf-8")
        else:
            data = b"" if method == "POST" else None
        request = urllib.request.Request(
            self._base + path, method=method, data=data,
            headers=dict(self._headers),
        )
        if body is not None:
            request.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(
                request, timeout=self._timeout, context=self._ssl_ctx
            ) as resp:
                code, raw = resp.status, resp.read()
        except urllib.error.HTTPError as e:
            code, raw = e.code, e.read()
        except urllib.error.URLError as e:
            raise CliError(0, f"cannot reach scheduler at {self._base}: {e}")
        body = raw.decode("utf-8", errors="replace")
        try:
            body = json.loads(body)
        except json.JSONDecodeError:
            pass
        if code >= 400:
            raise CliError(code, body)
        return body
