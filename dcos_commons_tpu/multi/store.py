"""ServiceStore: persisted specs of dynamically added services.

Reference: scheduler/multi/ServiceStore.java + ServiceFactory — raw
spec payloads stored per service name so a restarted multi-scheduler
re-creates every service, including ones mid-uninstall.
"""

from __future__ import annotations

import json
from typing import List, Optional

from dcos_commons_tpu.storage import Persister, PersisterError
from dcos_commons_tpu.storage.persister import validate_key

ROOT = "/multi/services"


class ServiceStore:
    def __init__(self, persister: Persister):
        self._persister = persister

    def _path(self, name: str) -> str:
        validate_key(name, "service name")
        return f"{ROOT}/{name}"

    def store(self, name: str, spec_dict: dict, uninstalling: bool = False,
              options: Optional[dict] = None) -> None:
        # options = the operator's raw user-options JSON (the Cosmos
        # plane): kept so upgrades re-render with prior choices when
        # none are passed, exactly like `dcos package update`
        payload = json.dumps(
            {
                "spec": spec_dict,
                "uninstalling": uninstalling,
                "options": options or {},
            },
            sort_keys=True,
        ).encode("utf-8")
        self._persister.set(self._path(name), payload)

    def fetch(self, name: str) -> Optional[dict]:
        raw = self._persister.get_or_none(self._path(name))
        return json.loads(raw.decode("utf-8")) if raw is not None else None

    def list_names(self) -> List[str]:
        return sorted(self._persister.get_children_or_empty(ROOT))

    def remove(self, name: str) -> None:
        try:
            self._persister.recursive_delete(self._path(name))
        except PersisterError:
            pass
