"""HTTP API (L6): the operator surface of the scheduler.

Reference: sdk/scheduler/.../http/ — Jersey resources over the plan
managers and state store, consumed by the CLI and operators.  The
rebuild serves the same /v1 verb set from the Python stdlib HTTP
server (no Jetty): plans CRUD + interrupt/continue/forceComplete/
restart (queries/PlansQueries.java:47-231), pod list/status/info/
pause/resume/restart/replace (queries/PodQueries.java:69-263), config
list/target, state properties, endpoints discovery, artifact config
templates (endpoints/ArtifactResource.java:17,50), health
(HealthResource), debug trackers (DebugEndpoint), and metrics
scrape (Metrics.java:85-97).
"""

from dcos_commons_tpu.http.api import SchedulerApi
from dcos_commons_tpu.http.server import ApiServer

__all__ = ["SchedulerApi", "ApiServer"]
