"""Blocked flash attention for one device.

MXU-first design (pallas_guide.md): Q blocks stream through a grid of
(batch*heads, q_blocks); K/V live in VMEM per grid cell and the kernel
walks K blocks with an online-softmax accumulator, so the [S, S] score
matrix never materializes in HBM.  bf16 in, f32 accumulation,
``preferred_element_type`` on every dot.

For sequences sharded across devices use
dcos_commons_tpu.parallel.ring.ring_attention, which applies the same
accumulation across ring hops.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool):
    from jax.experimental import pallas as pl

    q_index = pl.program_id(1)
    block_q = q_ref.shape[0]
    head_dim = q_ref.shape[1]
    seq_k = k_ref.shape[0]
    scale = head_dim ** -0.5

    q = q_ref[:].astype(jnp.float32) * scale
    m = jnp.full((block_q, 1), _NEG, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, head_dim), jnp.float32)

    q_pos = q_index * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_off = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def body(j, carry):
        m, l, acc = carry
        from jax.experimental import pallas as pl  # noqa: redefined for trace

        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            valid = q_pos >= (j * block_k + k_off)
            s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    if causal:
        # K blocks fully in the future contribute nothing; stop after
        # the block containing the last visible position
        n_blocks = jnp.minimum(
            pl.cdiv((q_index + 1) * block_q, block_k), seq_k // block_k
        )
    else:
        n_blocks = seq_k // block_k
    m, l, acc = lax.fori_loop(0, n_blocks, body, (m, l, acc))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def _pallas_attention(q, k, v, causal, block_q, block_k, interpret):
    from jax.experimental import pallas as pl

    batch, heads, seq_q, head_dim = q.shape
    seq_k = k.shape[2]
    bh = batch * heads
    qr = q.reshape(bh, seq_q, head_dim)
    kr = k.reshape(bh, seq_k, head_dim)
    vr = v.reshape(bh, seq_k, head_dim)
    grid = (bh, seq_q // block_q)
    out = pl.pallas_call(
        functools.partial(_kernel, block_k=block_k, causal=causal),
        out_shape=jax.ShapeDtypeStruct(qr.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, seq_k, head_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, seq_k, head_dim), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, head_dim), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(batch, heads, seq_q, head_dim)


def _impl(q, k, v, causal, block_q, block_k, force_pallas, interpret):
    seq_q, seq_k = q.shape[2], k.shape[2]
    use_pallas = force_pallas or interpret or jax.default_backend() == "tpu"
    tiles = seq_q % block_q == 0 and seq_k % block_k == 0
    if use_pallas and tiles:
        return _pallas_attention(q, k, v, causal, block_q, block_k, interpret)
    from dcos_commons_tpu.parallel.ring import reference_attention

    return reference_attention(q, k, v, causal)


@functools.lru_cache(maxsize=None)
def _make_attention(causal, block_q, block_k, force_pallas, interpret):
    """Per-config differentiable attention: Pallas forward, backward
    through the reference implementation's VJP (recompute-based — the
    fused forward stays kernel-fast; the backward trades one dense
    recompute for not having to persist softmax stats.  A dedicated
    backward kernel is the obvious next optimization)."""
    from dcos_commons_tpu.parallel.ring import reference_attention

    @jax.custom_vjp
    def attn(q, k, v):
        return _impl(q, k, v, causal, block_q, block_k, force_pallas, interpret)

    def fwd(q, k, v):
        return attn(q, k, v), (q, k, v)

    def bwd(residuals, g):
        q, k, v = residuals
        _, vjp = jax.vjp(
            lambda q_, k_, v_: reference_attention(q_, k_, v_, causal), q, k, v
        )
        return vjp(g)

    attn.defvjp(fwd, bwd)
    return attn


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    force_pallas: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """[batch, heads, seq, head_dim] attention, differentiable.

    Dispatch: Pallas kernel on TPU (or when forced / interpreted for
    tests); jnp reference otherwise.  Falls back when shapes do not
    tile (ragged seq), keeping the call always-correct.
    """
    return _make_attention(causal, block_q, block_k, force_pallas, interpret)(
        q, k, v
    )
