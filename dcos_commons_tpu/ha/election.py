# sdklint: disable-file=lease-gated-mutation — this module IS the
# lease-fenced writer: the lease record itself must be written below
# the fence (a deposed leader could never resign otherwise), and
# FencedPersister's backend calls run under the fence verification.
"""Leader election: a TTL lease with a fencing epoch, in the store.

Reference: curator/CuratorLocker.java — one active scheduler per
service, enforced by a ZooKeeper mutex; lock loss exits the process.
This module upgrades the rebuild's equivalent (a TTL lease node) from
mutual exclusion to *split-brain safety*:

* the lease record carries a monotonic **lease epoch**, bumped on
  every change of ownership.  Renewals by the current holder keep the
  epoch; a takeover (expiry, resign) mints epoch+1 — the same
  construction ``storage/replication.py`` uses to fence a superseded
  primary's replication stream, extended here to the SCHEDULER role.
* ``FencedPersister`` wraps the scheduler's persister: every mutation
  first verifies — atomically with any in-process rival's
  ``try_acquire`` — that the lease is still held at OUR epoch.  A
  deposed leader (stalled past the TTL while a standby took over)
  gets ``LeaseFencedError`` instead of a write: split-brain is
  rejected at the write path, not merely discovered at renewal time.

Atomicity scope: verification and takeover serialize on one shared
per-backend lock, so two schedulers over the SAME persister object
(the in-process race tests, the chaos harness, multi-scheduler
processes sharing a PersisterCache) can never interleave
verify-then-write with a takeover.  Across processes the lease lives
in the replicated state tree behind the primary: a takeover is a
replicated write, a deposed leader's verification read observes it,
and the residual read-then-write window is bounded by the renewal
loop firing ``on_lost`` (and the process exiting) the moment a
renewal fails — the same guarantee CuratorLocker gives.

Cost: over remote state every fenced mutation pays one extra
``read_lease`` round trip (correctness-first; the scheduler's write
rate is cycles-per-second, not writes-per-request).  The zero-cost
construction — carrying the lease epoch ON each mutation and
rejecting stale epochs inside the state server's kv lock, exactly as
``_fence`` tokens already fence replication — is the natural next
step and would also close the cross-process residual window.
"""

from __future__ import annotations

import json
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Callable, Optional

from dcos_commons_tpu.storage.persister import Persister, PersisterError

LEADER_PREFIX = "/__ha__/leaders"


class LeaseFencedError(PersisterError):
    """A store mutation was attempted by a scheduler that no longer
    holds the leader lease at its epoch.  Fatal to the writer: the
    cycle fails, ``on_lost`` fires, and the process restarts as a
    candidate (crash-to-restart, the CuratorLocker discipline)."""


# one fence lock per UNDERLYING persister object: every LeaderLease
# and FencedPersister over the same backend serializes takeover and
# verify-then-write through it
_FENCE_LOCKS: "weakref.WeakValueDictionary[int, threading.RLock]" = (
    weakref.WeakValueDictionary()
)
_FENCE_REGISTRY_LOCK = threading.Lock()
# WeakValueDictionary would drop an unreferenced lock; pin each lock
# to its persister so lock lifetime == persister lifetime
_FENCE_ATTR = "_ha_fence_lock"


def fence_lock(persister: Persister) -> threading.RLock:
    """The shared fence lock of ``persister`` (created on first use)."""
    lock = getattr(persister, _FENCE_ATTR, None)
    if lock is not None:
        return lock
    with _FENCE_REGISTRY_LOCK:
        lock = getattr(persister, _FENCE_ATTR, None)
        if lock is None:
            lock = threading.RLock()
            try:
                setattr(persister, _FENCE_ATTR, lock)
            except AttributeError:
                # slotted persister: fall back to the id-keyed registry
                # (kept alive by the caller holding the persister)
                lock = _FENCE_LOCKS.setdefault(id(persister), lock)
        return lock


@dataclass
class LeaseState:
    """One decoded lease record (absent record = epoch 0, no owner)."""

    owner: str = ""
    epoch: int = 0
    expires_at: float = 0.0

    def live(self, now: float) -> bool:
        return bool(self.owner) and self.expires_at > now


def _lease_path(name: str) -> str:
    if not name or "/" in name:
        raise PersisterError(f"invalid lease name: {name!r}")
    return f"{LEADER_PREFIX}/{name}"


def read_lease(persister: Persister, name: str) -> LeaseState:
    raw = persister.get_or_none(_lease_path(name))
    if raw is None:
        return LeaseState()
    try:
        data = json.loads(raw.decode("utf-8"))
        return LeaseState(
            owner=str(data.get("owner", "")),
            epoch=int(data.get("epoch", 0)),
            expires_at=float(data.get("expires_at", 0.0)),
        )
    except (ValueError, TypeError):
        # an unreadable record must not brick the election: treat as
        # expired at epoch 0 — the next acquire overwrites it at
        # epoch 1 and fencing proceeds from there
        return LeaseState()


class LeaderLease:
    """Acquire/renew/resign the leader lease for ``name``.

    Wall-clock expiry (the record must mean the same thing to every
    candidate host); ``clock`` is injectable so the chaos/race tests
    can expire a lease deterministically.  The object is deliberately
    thread-free: ``LeaderLock`` (and the runner) own the renewal loop,
    tests drive ``try_acquire``/``renew`` directly.
    """

    def __init__(
        self,
        persister: Persister,
        name: str,
        owner: str,
        ttl_s: float = 15.0,
        clock: Callable[[], float] = time.time,
    ):
        self._persister = persister
        self.name = name
        self.owner = owner
        self.ttl_s = ttl_s
        self.clock = clock
        self._epoch = 0
        self._is_leader = False
        # takeovers from a DIFFERENT previous holder — the
        # ha.failovers_total gauge (a bootstrap acquire of a virgin
        # lease is a first election, not a failover)
        self.takeovers = 0
        # set by HAState.attach so promote/resign events land in the
        # owning scheduler's flight recorder — and, when a journal is
        # wired, in the durable event journal (the lease-churn
        # detector's raw material survives the churn it measures)
        self.tracer = None
        self.journal = None
        # where the promote event lives: (trace_id, span_id), used by
        # the scheduler to chain rehydrate.replay to the promotion
        self.promote_ref: Optional[tuple] = None
        # callable(reason) fired at most once per deposition
        self.on_lost: Optional[Callable[[str], None]] = None
        self._lost_fired = False

    # -- introspection ------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self._is_leader

    @property
    def epoch(self) -> int:
        return self._epoch

    def state(self) -> LeaseState:
        return read_lease(self._persister, self.name)

    # -- acquire / renew / resign -------------------------------------

    def _write(self, state: LeaseState) -> None:
        self._persister.set(
            _lease_path(self.name),
            json.dumps({
                "owner": state.owner,
                "epoch": state.epoch,
                "expires_at": state.expires_at,
            }, sort_keys=True).encode("utf-8"),
        )

    def try_acquire(self) -> bool:
        """Take (or renew) the lease.  A takeover — the record is
        absent, EXPIRED (even our own), or resigned — mints epoch+1; a
        renewal by the current holder of a LIVE lease keeps the epoch.
        False while another holder's lease is live.  Expiry always
        minting a new epoch keeps the fence deterministic: once a
        lease lapses, every write made under its epoch is dead,
        whether or not a rival ever existed."""
        with fence_lock(self._persister):
            now = self.clock()
            cur = read_lease(self._persister, self.name)
            if cur.owner and cur.owner != self.owner and cur.live(now):
                return False
            took_over = cur.owner != self.owner or not cur.live(now)
            epoch = cur.epoch + 1 if took_over else cur.epoch
            self._write(LeaseState(self.owner, epoch, now + self.ttl_s))
            self._epoch = epoch
            self._is_leader = True
            self._lost_fired = False
            if took_over:
                if cur.epoch > 0:
                    self.takeovers += 1
                self._record_event(
                    "election.promote",
                    epoch=epoch,
                    previous_owner=cur.owner or "(none)",
                )
            return True

    def renew(self) -> bool:
        """Extend the lease IF we still hold it, LIVE, at our epoch.
        A record held by someone else, re-minted at a new epoch, or
        EXPIRED means we were deposed: never silently resurrect
        (writes made by an interim leader would be grafted under, and
        resurrection would race ``verify()``'s strict expiry check —
        whether a stalled sole leader survived would depend on thread
        wakeup order); fire ``on_lost`` and return False so the
        process restarts as a candidate and re-elects at epoch+1."""
        with fence_lock(self._persister):
            now = self.clock()
            cur = read_lease(self._persister, self.name)
            if cur.owner == self.owner and cur.epoch == self._epoch \
                    and cur.live(now):
                self._write(LeaseState(self.owner, cur.epoch,
                                       now + self.ttl_s))
                return True
            if cur.owner == self.owner and cur.epoch == self._epoch:
                reason = (
                    f"lease for {self.name!r} expired un-renewed "
                    f"(stalled past ttl={self.ttl_s}s)"
                )
            else:
                reason = (
                    f"lease for {self.name!r} now held by "
                    f"{cur.owner or '(nobody)'} at epoch {cur.epoch}"
                )
            self._deposed_locked(reason)
            return False

    def resign(self) -> None:
        """Give the lease up cleanly: the record keeps its epoch (the
        successor must still mint epoch+1) but expires immediately, so
        candidates take over without waiting out the TTL."""
        with fence_lock(self._persister):
            cur = read_lease(self._persister, self.name)
            if cur.owner == self.owner:
                self._write(LeaseState("", cur.epoch, 0.0))
                self._record_event("election.resign", epoch=cur.epoch)
            self._is_leader = False

    # -- the fence ----------------------------------------------------

    def verify(self) -> None:
        """Raise ``LeaseFencedError`` unless the persisted record
        still names US at OUR epoch and is unexpired.  Called by
        ``FencedPersister`` under the shared fence lock, so the check
        is atomic with any in-process takeover."""
        now = self.clock()
        cur = read_lease(self._persister, self.name)
        if cur.owner == self.owner and cur.epoch == self._epoch \
                and cur.expires_at > now:
            return
        reason = (
            f"store mutation fenced: lease {self.name!r} is "
            f"{'expired' if cur.owner == self.owner else 'held by ' + (cur.owner or '(nobody)')} "
            f"at epoch {cur.epoch} (ours: {self._epoch})"
        )
        self._deposed_locked(reason)
        raise LeaseFencedError(reason)

    def _deposed_locked(self, reason: str) -> None:
        self._is_leader = False
        if self._lost_fired:
            return
        self._lost_fired = True
        callback = self.on_lost
        if callback is not None:
            try:
                callback(reason)
            except Exception:  # sdklint: disable=swallowed-exception — a broken loss callback must not mask the fencing error itself
                pass

    def _record_event(self, name: str, **attrs) -> None:
        journal = self.journal
        if journal is not None:
            # append only: the flush rides the owning scheduler's
            # cycle — a resign on the way OUT of leadership must not
            # block on (or be rejected by) the store it just lost
            journal.append("election", event=name, owner=self.owner,
                           **attrs)
        tracer = self.tracer
        if tracer is None:
            return
        event = tracer.event(name, track="scheduler", owner=self.owner,
                             **{k: str(v) for k, v in attrs.items()})
        if name == "election.promote":
            self.promote_ref = (event.trace_id, event.span_id)


class FencedPersister(Persister):
    """The lease-fenced writer: every mutation verifies the lease
    (atomically with in-process takeovers) before touching the
    backend.  Reads pass through unverified — a deposed leader may
    keep observing, it just may not write (the replication layer's
    reader/writer asymmetry, extended to the scheduler role)."""

    def __init__(self, backend: Persister, lease: LeaderLease):
        if isinstance(backend, FencedPersister):
            backend = backend.backend  # never stack fences
        self.backend = backend
        self.lease = lease
        self.rejected_writes = 0

    def _verify(self) -> None:
        try:
            self.lease.verify()
        except LeaseFencedError:
            self.rejected_writes += 1
            raise

    # -- reads (unfenced) ---------------------------------------------

    def get(self, path: str):
        return self.backend.get(path)

    def get_children(self, path: str):
        return self.backend.get_children(path)

    # -- mutations (fenced) -------------------------------------------

    def set(self, path: str, value: bytes) -> None:
        with fence_lock(self.backend):
            self._verify()
            self.backend.set(path, value)

    def recursive_delete(self, path: str) -> None:
        with fence_lock(self.backend):
            self._verify()
            self.backend.recursive_delete(path)

    def apply(self, ops) -> None:
        ops = list(ops)
        with fence_lock(self.backend):
            self._verify()
            self.backend.apply(ops)

    def close(self) -> None:
        self.backend.close()


class LeaderLock:
    """The runner-facing adapter: ``RemoteLocker``-shaped (acquire /
    release / on_lost) but HA — ``acquire()`` CANDIDATES instead of
    failing while another scheduler is alive, polling the lease until
    expiry hands it over, then keeps it renewed from a daemon thread.
    Lease loss fires ``on_lost`` exactly once (the runner exits; its
    supervisor restarts it as a candidate again)."""

    def __init__(
        self,
        persister: Persister,
        name: str,
        owner: str,
        ttl_s: float = 15.0,
    ):
        self.lease = LeaderLease(persister, name, owner, ttl_s=ttl_s)
        self.name = name
        self.owner = owner
        self.on_lost: Optional[Callable[[str], None]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def acquire(self) -> bool:
        """Block as a CANDIDATE until the lease is ours (or abort()
        is called).  Poll cadence is a third of the TTL — the same
        rhythm the holder renews at, so takeover latency after a
        holder death is bounded by ~TTL + one poll."""
        self.lease.on_lost = self._lost
        while not self._stop.is_set():
            try:
                if self.lease.try_acquire():
                    self._thread = threading.Thread(
                        target=self._renew_loop,
                        name=f"ha-lease-{self.name}", daemon=True,
                    )
                    self._thread.start()
                    return True
            except PersisterError:
                pass  # state server unreachable: keep candidating
            self._stop.wait(self.lease.ttl_s / 3.0)
        return False

    def _renew_loop(self) -> None:
        last_ok = time.monotonic()
        while not self._stop.wait(self.lease.ttl_s / 3.0):
            try:
                if not self.lease.renew():
                    return  # renew() fired on_lost
                last_ok = time.monotonic()
            except PersisterError as e:
                # transient store outage: survivable while the lease
                # is live; past a full TTL it has lapsed server-side
                # and a standby may hold it
                if time.monotonic() - last_ok > self.lease.ttl_s:
                    self.lease._deposed_locked(
                        f"state server unreachable past TTL: {e}"
                    )
                    return

    def _lost(self, reason: str) -> None:
        self._stop.set()
        callback = self.on_lost
        if callback is not None:
            callback(reason)

    def abort(self) -> None:
        """Stop candidating/renewing without resigning (shutdown)."""
        self._stop.set()

    def release(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.lease.ttl_s)
        try:
            self.lease.resign()
        except PersisterError:
            pass  # the lease will expire on its own


def find_remote_persister(persister) -> Optional[object]:
    """Unwrap FencedPersister/PersisterCache layers down to a
    RemotePersister (None for purely local state) — the handle the HA
    observability surface uses to read /v1/repl/status."""
    from dcos_commons_tpu.storage.remote import RemotePersister

    seen = set()
    node = persister
    while node is not None and id(node) not in seen:
        if isinstance(node, RemotePersister):
            return node
        seen.add(id(node))
        node = (
            getattr(node, "backend", None)
            or getattr(node, "_backend", None)
            or getattr(node, "_persister", None)
        )
    return None


class HAState:
    """The scheduler's HA observability handle: lease identity, the
    failover counter, replication watermarks, and the last
    re-hydration report — exported as ``ha.*`` gauges and served at
    ``GET /v1/debug/ha``."""

    # replication-status reads cross the network: cache them so a
    # metrics scrape costs at most one /v1/repl/status per window
    REPL_REFRESH_S = 10.0

    def __init__(self, persister: Persister, name: str,
                 lease: Optional[LeaderLease] = None):
        self.persister = persister
        self.name = name
        self.lease = lease
        self.last_rehydration: Optional[dict] = None
        self._lock = threading.Lock()
        self._repl: Optional[dict] = None
        self._repl_at = 0.0
        self._lag_gauges = set()
        self._metrics = None

    # -- wiring -------------------------------------------------------

    def attach(self, scheduler) -> "HAState":
        """Bind to a freshly-built scheduler: register the ha.*
        gauges, route election events into its flight recorder, and
        record the promotion that created this incarnation so the
        first cycle's ``rehydrate.replay`` chains to it."""
        scheduler.ha_state = self
        self._metrics = scheduler.metrics
        if self.lease is not None:
            self.lease.tracer = scheduler.tracer
            self.lease.journal = getattr(scheduler, "journal", None)
            if self.lease.is_leader and self.lease.promote_ref is None:
                # promoted before this scheduler (and its tracer)
                # existed: re-record so the failover chain is complete
                self.lease._record_event(
                    "election.promote", epoch=self.lease.epoch,
                    previous_owner="(pre-build)",
                )
        metrics = scheduler.metrics
        metrics.gauge("ha.is_leader", lambda: float(
            1.0 if self.lease is not None and self.lease.is_leader else 0.0
        ))
        metrics.gauge("ha.lease_epoch", lambda: float(
            self.lease.epoch if self.lease is not None else 0
        ))
        metrics.gauge("ha.failovers_total", lambda: float(
            self.lease.takeovers if self.lease is not None else 0
        ))
        metrics.gauge("ha.fenced_writes_rejected", self._rejected_writes)
        return self

    def _rejected_writes(self) -> float:
        fenced = self.persister if isinstance(
            self.persister, FencedPersister
        ) else None
        return float(fenced.rejected_writes if fenced is not None else 0)

    def note_rehydration(self, report: dict) -> None:
        self.last_rehydration = dict(report)

    # -- replication watermarks ---------------------------------------

    def replication_status(self, refresh: bool = False) -> Optional[dict]:
        """Cached /v1/repl/status of the backing state server (None
        for local state).  Discovered standbys get per-puller lag
        gauges ``ha.replication.lag.<id>`` (seq - acked)."""
        remote = find_remote_persister(self.persister)
        if remote is None:
            return None
        with self._lock:
            now = time.monotonic()
            if not refresh and self._repl is not None and \
                    now - self._repl_at < self.REPL_REFRESH_S:
                return self._repl
            try:
                status = remote._call("/v1/repl/status", {})
            except PersisterError:
                return self._repl
            self._repl = status
            self._repl_at = now
            if self._metrics is not None:
                for pid in (status.get("standbys") or {}):
                    if pid not in self._lag_gauges:
                        self._lag_gauges.add(pid)
                        self._metrics.gauge(  # sdklint: disable=metric-cardinality — bounded by the standby TOPOLOGY (a handful of operator-deployed pullers, not per-request ids) and deduped via _lag_gauges
                            f"ha.replication.lag.{pid}",
                            lambda pid=pid: self._lag_of(pid),
                        )
            return status

    def _lag_of(self, puller_id: str) -> float:
        status = self.replication_status()
        if not status:
            return 0.0
        st = (status.get("standbys") or {}).get(puller_id)
        if not st:
            return 0.0
        return float(int(status.get("seq", 0) or 0) - int(st.get("acked", 0)))

    # -- the /v1/debug/ha body ----------------------------------------

    def describe(self, refresh: bool = True) -> dict:
        lease_record = None
        try:
            # read through the LEASE's own persister when one exists:
            # the scheduler-side persister may be a write-through cache
            # that never observes the election's (out-of-band) renewals
            cur = (self.lease.state() if self.lease is not None
                   else read_lease(self.persister, self.name))
            now = (self.lease.clock() if self.lease is not None
                   else time.time())
            lease_record = {
                "owner": cur.owner,
                "epoch": cur.epoch,
                "expires_in_s": round(cur.expires_at - now, 3),
                "live": cur.live(now),
            }
        except PersisterError as e:
            lease_record = {"error": str(e)}
        body = {
            "enabled": True,
            "name": self.name,
            "leader": lease_record,
            "is_leader": bool(self.lease is not None
                              and self.lease.is_leader),
            "lease_epoch": self.lease.epoch if self.lease is not None else 0,
            "failovers_total": (
                self.lease.takeovers if self.lease is not None else 0
            ),
            "fenced_writes_rejected": int(self._rejected_writes()),
        }
        repl = self.replication_status(refresh=refresh)
        if repl is not None:
            seq = int(repl.get("seq", 0) or 0)
            body["replication"] = {
                "role": repl.get("role"),
                "epoch": repl.get("epoch"),
                "seq": seq,
                "acked_seq": repl.get("acked_seq"),
                "standbys": {
                    pid: {
                        "acked": st.get("acked"),
                        "lag": seq - int(st.get("acked", 0) or 0),
                        "lagging": st.get("lagging"),
                    }
                    for pid, st in (repl.get("standbys") or {}).items()
                },
            }
        if self.last_rehydration is not None:
            body["last_rehydration"] = self.last_rehydration
        return body
