"""Native runtime components (C++).

The reference ships native code at the task boundary — the Go
bootstrap binary prepended to every task command (sdk/bootstrap/
main.go) — while the scheduler logic stays managed.  Same split here:
the ``task_exec`` C++ supervisor owns per-task process lifecycle
(sessions, output capture, grace-kill escalation, durable pid/exit
records), and the Python agent orchestrates it.

``task_exec_path()`` builds the binary on first use with the system
g++ and caches it next to the source; environments without a
toolchain fall back to pure-Python supervision transparently.
"""

from __future__ import annotations

import logging
import os
import shutil
import subprocess
import threading

LOG = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "task_exec.cc")
_BIN = os.path.join(_DIR, "bin", "task_exec")
_lock = threading.Lock()
_failed = False


def task_exec_path() -> str:
    """Path to the built supervisor binary, or '' when unavailable.

    Build is attempted once per process; failures (no g++, readonly
    install) disable the native path for the rest of the process.
    """
    global _failed
    if _failed:
        return ""
    if os.path.exists(_BIN) and os.path.getmtime(_BIN) >= os.path.getmtime(
        _SRC
    ):
        return _BIN
    with _lock:
        if _failed:
            return ""
        if os.path.exists(_BIN) and os.path.getmtime(
            _BIN
        ) >= os.path.getmtime(_SRC):
            return _BIN
        gxx = shutil.which("g++") or shutil.which("c++")
        if gxx is None:
            LOG.info("no C++ toolchain: using pure-Python task supervision")
            _failed = True
            return ""
        os.makedirs(os.path.dirname(_BIN), exist_ok=True)
        tmp = _BIN + ".tmp"
        try:
            subprocess.run(
                [gxx, "-O2", "-std=c++17", "-o", tmp, _SRC],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, _BIN)
        except (subprocess.SubprocessError, OSError) as e:
            LOG.warning("task_exec build failed (%s): using pure Python", e)
            _failed = True
            return ""
    return _BIN
