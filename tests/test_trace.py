"""traceview: the cross-layer correlation chain, exporters, steplog.

The acceptance scenario: a simulated 4-host gang deploy (testing/
harness) must produce ONE trace in which the offer-cycle span, its
per-pod evaluation spans, the launch span, the launch WAL, the status
arrivals, and the plan-step COMPLETE transition all share a
correlation chain — the join the operator used to do by timestamp
across /v1/debug/offers, plan state, and sandbox logs.
"""

import json
import os

from dcos_commons_tpu.metrics.registry import Metrics
from dcos_commons_tpu.offer.inventory import TpuHost, make_test_fleet
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    SendTaskRunning,
    ServiceTestRunner,
)
from dcos_commons_tpu.trace import (
    StepLog,
    TraceRecorder,
    read_steplog,
    to_chrome,
    to_text,
)

GANG_YAML = """
name: jax
pods:
  trainer:
    count: 4
    gang: true
    tpu:
      generation: v5e
      chips-per-host: 4
      topology: 4x4
    tasks:
      worker:
        goal: RUNNING
        cmd: "python train.py"
        cpus: 2.0
        memory: 4096
"""


def deploy_gang():
    """4-host gang deploy through the sim harness; returns the world."""
    runner = ServiceTestRunner(
        GANG_YAML,
        hosts=make_test_fleet(host_grid=(2, 2), chip_block=(2, 2)),
    )
    world = runner.run([
        AdvanceCycles(1),
        SendTaskRunning("trainer-0-worker"),
        SendTaskRunning("trainer-1-worker"),
        SendTaskRunning("trainer-2-worker"),
        SendTaskRunning("trainer-3-worker"),
        ExpectDeploymentComplete(),
    ])
    return runner, world


def by_name(spans, prefix):
    return [s for s in spans if s.name.startswith(prefix)]


# -- the correlation chain (acceptance criterion) ---------------------


def test_gang_deploy_single_correlation_chain():
    _runner, world = deploy_gang()
    spans = world.scheduler.tracer.snapshot()

    launches = by_name(spans, "launch:trainer")
    assert len(launches) == 1, [s.name for s in spans]
    launch = launches[0]
    trace = launch.trace_id

    # the offer-cycle span IS the root of the chain
    cycles = [s for s in by_name(spans, "cycle") if s.trace_id == trace]
    assert len(cycles) == 1
    cycle = cycles[0]
    assert not cycle.parent_id  # the chain root has no parent
    assert launch.parent_id == cycle.span_id

    # per-requirement evaluation span, child of the cycle
    evals = [
        s for s in by_name(spans, "evaluate:trainer-[")
        if s.trace_id == trace
    ]
    assert len(evals) == 1 and evals[0].parent_id == cycle.span_id
    assert evals[0].attrs["passed"] == "true"

    # per-pod evaluation outcome spans, one lane per pod instance
    for i in range(4):
        pods = [
            s for s in by_name(spans, f"evaluate:trainer-{i}")
            if s.trace_id == trace and s.track == f"trainer-{i}"
        ]
        assert pods and pods[0].attrs["outcome"] == "pass"

    # the WAL write is a child of the launch span
    wals = [s for s in by_name(spans, "launch.wal") if s.trace_id == trace]
    assert len(wals) == 1 and wals[0].parent_id == launch.span_id
    assert "trainer-0-worker" in wals[0].attrs["tasks"]

    # every task id the launch carried is in the launch span attrs
    task_ids = wals[0].attrs["task_ids"].split(",")
    assert len(task_ids) == 4
    assert set(launch.attrs["task_ids"].split(",")) == set(task_ids)

    # status arrivals (later cycles!) link back to the launch span via
    # the task id, joining the SAME trace
    statuses = [
        s for s in by_name(spans, "status:TASK_RUNNING")
        if s.trace_id == trace
    ]
    assert len(statuses) == 4
    assert all(s.parent_id == launch.span_id for s in statuses)
    assert {s.track for s in statuses} == {
        f"trainer-{i}" for i in range(4)
    }

    # the plan-step transitions reference the chain too: the launch
    # anchors PENDING->STARTING, the final status anchors ->COMPLETE
    steps = [s for s in by_name(spans, "step:") if s.trace_id == trace]
    transitions = {(s.attrs["from"], s.attrs["to"]) for s in steps}
    assert ("PENDING", "STARTING") in transitions
    assert any(to == "COMPLETE" for _from, to in transitions)
    complete = [s for s in steps if s.attrs["to"] == "COMPLETE"][0]
    # ...and the COMPLETE transition's parent is the triggering
    # status's span (the 4th RUNNING)
    assert complete.parent_id in {s.span_id for s in statuses}


def test_chrome_export_round_trips_with_pod_lanes():
    _runner, world = deploy_gang()
    tracer = world.scheduler.tracer
    blob = json.loads(json.dumps(to_chrome(tracer, service="jax")))
    events = blob["traceEvents"]
    assert events
    assert all(e["ph"] == "X" for e in events)
    assert all(e["pid"] == "jax" for e in events)
    tids = {e["tid"] for e in events}
    for i in range(4):
        assert f"trainer-{i}" in tids, tids
    assert "scheduler" in tids and "plan" in tids
    # timestamps are wall µs and durations are positive
    assert all(e["dur"] >= 1 for e in events)
    assert blob["otherData"]["dropped"] == 0


def test_text_timeline_renders():
    _runner, world = deploy_gang()
    text = to_text(world.scheduler.tracer, service="jax")
    assert text.startswith("# trace:")
    assert "cycle" in text and "launch:trainer" in text
    assert "status:TASK_RUNNING" in text


def test_failing_evaluation_records_the_failing_requirement():
    # the gang wants 4 hosts; give it one CPU host: the evaluation
    # span must carry the refusal as an attribute
    runner = ServiceTestRunner(
        GANG_YAML, hosts=[TpuHost(host_id="only-host")]
    )
    runner.run([AdvanceCycles(1)])
    spans = runner.world.scheduler.tracer.snapshot()
    evals = by_name(spans, "evaluate:trainer-[")
    assert evals and evals[0].attrs["passed"] == "false"
    assert evals[0].attrs["failing_requirement"]
    pod_events = by_name(spans, "evaluate:trainer-0")
    assert pod_events and pod_events[0].attrs["outcome"] == "fail"
    assert pod_events[0].attrs["failing_requirement"]


# -- recorder mechanics ----------------------------------------------


def test_ring_buffer_drops_oldest_and_counts():
    metrics = Metrics()
    tracer = TraceRecorder(capacity=4, metrics=metrics)
    for i in range(10):
        tracer.event(f"e{i}")
    spans = tracer.snapshot()
    assert [s.name for s in spans] == ["e6", "e7", "e8", "e9"]
    assert tracer.dropped == 6
    assert metrics.counters()["trace.dropped"] == 6
    # the drop count is surfaced by both exporters
    assert to_chrome(tracer)["otherData"]["dropped"] == 6
    assert "(6 dropped" in to_text(tracer)


def test_disabled_recorder_is_inert():
    tracer = TraceRecorder(capacity=0)
    with tracer.span("cycle", pod="x") as span:
        span.set_attr("k", "v")
        child = tracer.event("child", parent=span)
    assert tracer.snapshot() == []
    assert tracer.dropped == 0
    assert child.attrs == {}
    tracer.register_launch("task-1", span)
    assert tracer.launch_ref("task-1") is None


def test_span_end_is_idempotent_and_drop_skips_recording():
    tracer = TraceRecorder(capacity=8)
    span = tracer.span("once")
    span.end()
    span.end()
    assert len(tracer.snapshot()) == 1
    idle = tracer.span("idle")
    idle.drop()
    idle.end()
    assert len(tracer.snapshot()) == 1  # dropped spans never record
    assert tracer.dropped == 0  # ...and don't count as ring overflow


def test_idle_cycles_do_not_flood_the_ring():
    _runner, world = deploy_gang()
    tracer = world.scheduler.tracer
    before = len(tracer.snapshot())
    for _ in range(50):
        world.scheduler.run_cycle()  # nothing to do: all idle
    assert len(tracer.snapshot()) == before


# -- steplog -----------------------------------------------------------


def test_steplog_write_read_and_merge(tmp_path):
    path = str(tmp_path / "steplog.jsonl")
    log = StepLog(path)
    for i in range(3):
        log.record(i, wall_s=0.5, tokens=4096, blocked_s=0.01 * i,
                   worker=2)
    log.close()
    # a torn half-line (worker killed mid-write) must not break parsing
    with open(path, "a") as f:
        f.write('{"step": 3, "wall')
    records = read_steplog(path)
    assert [r["step"] for r in records] == [0, 1, 2]
    assert records[2]["blocked_s"] == 0.02

    tracer = TraceRecorder(capacity=8)
    tracer.event("cycle")
    steplogs = {"trainer-2-worker": records}
    blob = json.loads(json.dumps(
        to_chrome(tracer, service="jax", steplogs=steplogs)
    ))
    lanes = {e["tid"] for e in blob["traceEvents"]}
    assert "trainer-2-worker/steps" in lanes
    step_events = [
        e for e in blob["traceEvents"]
        if e["tid"] == "trainer-2-worker/steps"
    ]
    assert len(step_events) == 3
    assert step_events[0]["args"]["tokens"] == 4096
    text = to_text(tracer, steplogs=steplogs)
    assert "trainer-2-worker/steps" in text and "blocked_s=0.02" in text


def test_steplog_missing_file_and_write_errors(tmp_path):
    assert read_steplog(str(tmp_path / "absent.jsonl")) == []
    log = StepLog(str(tmp_path / "no-such-dir" / "steplog.jsonl"))
    log.record(0, wall_s=1.0)  # must not raise
    assert log.errors == 1


def test_agent_surfaces_steplog(tmp_path):
    """LocalProcessAgent.steplog_of reads the sandbox steplog the
    worker wrote (the scheduler merges it into /v1/debug/trace)."""
    from dcos_commons_tpu.agent.local import LocalProcessAgent

    agent = LocalProcessAgent(str(tmp_path), use_native=False)
    sandbox = agent.sandbox_of("trainer-0-worker")
    os.makedirs(sandbox, exist_ok=True)
    StepLog(os.path.join(sandbox, "steplog.jsonl")).record(
        7, wall_s=0.25, tokens=1024, blocked_s=0.003
    )
    records = agent.steplog_of("trainer-0-worker")
    assert records and records[0]["step"] == 7
    assert agent.steplog_of("never-launched") == []


def test_api_merges_steplogs_into_the_timeline():
    _runner, world = deploy_gang()
    from dcos_commons_tpu.http.api import SchedulerApi

    # the sim FakeAgent has no sandboxes; give it the surface the real
    # agent exposes so the API-level merge path is exercised
    world.agent.steplog_of = lambda name: (
        [{"step": 0, "t": 1.0, "wall_s": 0.5, "blocked_s": 0.1}]
        if name == "trainer-3-worker" else []
    )
    api = SchedulerApi(world.scheduler)
    code, body = api.debug_trace("chrome")
    assert code == 200
    lanes = {e["tid"] for e in body["traceEvents"]}
    assert "trainer-3-worker/steps" in lanes
    code, text = api.debug_trace(None)
    assert code == 200 and "trainer-3-worker/steps" in text
    assert api.debug_trace("bogus")[0] == 400
