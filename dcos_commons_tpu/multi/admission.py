"""Admission control for the dynamic add-service path.

Reference: the Cosmos/ServiceStore flow accepts any payload and lets
the deploy fail later; here the analyzers that already gate CI
(speccheck's spec checks, shardcheck's mesh derivation) run as
PRODUCTION guardrails: ``PUT /v1/multi/<name>`` validates the spec
BEFORE ``ServiceStore`` persists anything, and a rejected spec
returns 422 with the same line-anchored findings the CLI would print.

Scope: every speccheck spec-level rule (validators, placement
feasibility, port conflicts, plan shape, per-host resources, gpus
vocabulary) plus — when the spec targets a jax workload (a TPU pod
whose task cmd matches a shardcheck profile) — the mesh-derivation
half of shardcheck: the declared topology must derive a MeshSpec and
the mesh must span exactly the chips the spec reserves.  The full
eval_shape footprint analysis stays in CI; admission must answer in
request time.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from dcos_commons_tpu.analysis.linter import Finding


# tails spec-resources rejections on THIS enforcement point: the CI
# walker's --host-cpus/--host-mem/--host-disk flags do not exist for
# an operator PUTting a spec — their remediation is the fleet itself
_FEASIBILITY_HINT = " (no up host fits; add larger hosts or shrink the pod)"


class AdmissionError(Exception):
    """A spec refused by admission control; carries the findings the
    HTTP layer serializes into the 422 body."""

    def __init__(self, findings: List[Finding]):
        super().__init__(
            f"{len(findings)} admission finding(s): "
            + "; ".join(f.render() for f in findings[:3])
        )
        self.findings = findings


def host_models_for(inventory) -> list:
    """Feasibility host models from the LIVE fleet: one per DISTINCT
    up-host shape.  speccheck's CI walker assumes a default shape (it
    has no fleet); admission knows the real ones — a spec sized for
    this fleet's hosts must not be rejected against a smaller
    hypothetical, and a pod is feasible only if SOME actual shape
    fits it (per-dimension maxima across different hosts would build
    a composite host that exists nowhere).  EMPTY when no hosts are
    up (scheduler bootstrap, transient fleet outage): feasibility is
    then SKIPPED rather than judged against the CI default shape —
    registration must not depend on fleet availability; the deploy
    plan simply waits for hosts."""
    from dcos_commons_tpu.analysis.speccheck import HostModel

    hosts = inventory.up_hosts() if inventory is not None else []
    shapes = sorted({(h.cpus, h.memory_mb, h.disk_mb) for h in hosts})
    return [
        HostModel(cpus=c, memory_mb=m, disk_mb=d) for c, m, d in shapes
    ]


def validate_service_yaml(
    text: str, name: str, inventory=None
) -> Tuple[Optional[object], List[Finding]]:
    """Render + validate one service YAML body.  Returns the rendered
    spec (None when it cannot render) and every finding; an empty
    finding list means the spec is admitted UNCHANGED — admission
    never rewrites what the operator sent."""
    from dcos_commons_tpu.analysis.speccheck import (
        check_spec_lines,
        render_spec,
    )
    from dcos_commons_tpu.specification.yaml_spec import from_yaml

    rel = f"{name}.yml"
    lines = text.splitlines()
    spec, render_error = render_spec(rel, lambda: from_yaml(text))
    # apply_suppressions=False: suppression comments live in the
    # operator-submitted body here — honoring them would let any
    # payload waive its own rejection
    findings = check_spec_lines(
        rel, lines, spec, render_error, host_models_for(inventory),
        apply_suppressions=False, feasibility_hint=_FEASIBILITY_HINT,
    )
    if spec is None and not findings:
        # unreachable with suppressions off (a render failure always
        # carries its finding), but admitting None must be impossible
        findings.append(Finding(rel, 1, "spec-render", "spec did not render"))
    if spec is not None and spec.name != name:
        findings.append(Finding(
            rel, 1, "spec-render",
            f"spec name {spec.name!r} does not match URL {name!r}",
        ))
    if spec is not None:
        findings += _mesh_findings(rel, lines, spec)
        findings += _multislice_findings(rel, lines, spec, inventory)
    return spec, findings


def check_rendered_spec(rel: str, lines, spec, inventory=None) -> List[Finding]:
    """Admission findings for an ALREADY-RENDERED spec (the
    package-install path: svc.yml was rendered against its
    options.json env before this runs)."""
    from dcos_commons_tpu.analysis.speccheck import check_spec_lines

    return check_spec_lines(
        rel, lines, spec, None, host_models_for(inventory),
        apply_suppressions=False, feasibility_hint=_FEASIBILITY_HINT,
    ) + _mesh_findings(rel, lines, spec) + _multislice_findings(
        rel, lines, spec, inventory
    )


def _mesh_findings(rel: str, lines, spec) -> List[Finding]:
    """shardcheck's mesh-derivation rule as an admission gate: run
    only for jax workloads (a TPU pod whose cmd names a known
    workload profile) — CPU services must not pay the jax import.

    The mesh comes from the SAME per-profile workload builder CI
    uses (``_analyze_pod_task``), not a bare ``derive(env)``: the
    serve profiles pin their own meshes (single chip / tp=gang), so
    deriving here would admit specs CI rejects and vice versa."""
    from dcos_commons_tpu.analysis.shardcheck import _match_profile

    findings: List[Finding] = []
    jax_tasks = []
    for pod in spec.pods:
        if pod.tpu is None:
            continue
        for task in pod.tasks:
            builder = _match_profile(task.cmd)
            if builder is not None:
                jax_tasks.append((pod, task, builder))
    if not jax_tasks:
        return findings
    from dcos_commons_tpu.analysis.shardcheck import (
        _make_anchor,
        declared_chips,
        mesh_span_message,
        pod_task_mesh_env,
    )
    from dcos_commons_tpu.specification.specs import SpecError

    anchor = _make_anchor(lines)
    for pod, task, builder in jax_tasks:
        where = f"pod {pod.type!r} task {task.name!r}"
        env = pod_task_mesh_env(pod, task)
        try:
            workload = builder(env, pod.tpu, pod, task)
        except SpecError as e:
            findings.append(Finding(
                rel, anchor(pod.type), "shard-mesh", f"{where}: {e}"
            ))
            continue
        except Exception as e:
            findings.append(Finding(
                rel, anchor(pod.type), "shard-mesh",
                f"{where}: workload profile "
                f"{getattr(builder, '__name__', '?')} failed: "
                f"{type(e).__name__}: {e}",
            ))
            continue
        declared = declared_chips(pod)
        if workload.mesh.total != declared:
            findings.append(Finding(
                rel, anchor(pod.type), "shard-mesh",
                mesh_span_message(where, declared, workload.mesh.total,
                                  f"{workload.script}'s mesh"),
            ))
    return findings


def _multislice_findings(rel: str, lines, spec, inventory) -> List[Finding]:
    """The `tpu: slices: N` admission gate (ISSUE 20): a multi-slice
    spec is rejected at PUT when

    * its declared chip span disagrees with slices x hosts-per-slice
      x chips-per-host (the gang could never claim what it reserves),
    * its derived mesh lacks the dcn axis (the worker would lay a
      single-slice mesh over a slice boundary — gradient collectives
      silently riding DCN as if it were ICI), or
    * the fleet registers fewer ``generation`` slices than the spec
      spans (the deploy plan would wait forever; fleet sizing uses
      the same one-formula helper as CI, shardcheck's
      ``fleet_slice_count``).

    Findings anchor to the pod's declaring line, rule ``multislice``.
    Sizing is SKIPPED (like scalar feasibility) while the inventory
    registers no TPU hosts at all — bootstrap must not reject specs.
    """
    from dcos_commons_tpu.analysis.shardcheck import (
        _make_anchor,
        declared_chips,
        fleet_slice_count,
    )
    from dcos_commons_tpu.parallel.mesh import derive
    from dcos_commons_tpu.specification.specs import SpecError

    findings: List[Finding] = []
    multi = [
        pod for pod in spec.pods
        if pod.tpu is not None and pod.tpu.slices > 1
    ]
    if not multi:
        return findings
    anchor = _make_anchor(lines)
    for pod in multi:
        tpu = pod.tpu
        where = f"pod {pod.type!r}"
        span = pod.count * tpu.chips_per_host
        if span != declared_chips(pod):
            findings.append(Finding(
                rel, anchor(pod.type), "multislice",
                f"{where}: count x chips-per-host spans {span} chip(s) "
                f"but slices x topology declares {declared_chips(pod)} "
                f"({tpu.slices} slice(s) of {tpu.topology or '?'})",
            ))
            continue
        try:
            mesh = derive(tpu.mesh_env())
        except SpecError as e:
            findings.append(Finding(
                rel, anchor(pod.type), "multislice", f"{where}: {e}"
            ))
            continue
        if mesh.dcn != tpu.slices:
            findings.append(Finding(
                rel, anchor(pod.type), "multislice",
                f"{where}: {tpu.slices} slices declared but the derived "
                f"mesh lays dcn={mesh.dcn} — cross-slice collectives "
                "would not ride the dcn axis",
            ))
            continue
        registered = fleet_slice_count(inventory, tpu.generation)
        if registered is not None and registered < tpu.slices:
            findings.append(Finding(
                rel, anchor(pod.type), "multislice",
                f"{where}: spans {tpu.slices} slices but the fleet "
                f"registers only {registered} {tpu.generation} "
                "slice(s)",
            ))
    return findings


def _targets_jax(cmd: str) -> bool:
    from dcos_commons_tpu.analysis.shardcheck import _match_profile

    return _match_profile(cmd) is not None
