"""Steps: the leaves of the plan tree that actually launch pods.

Reference: scheduler/plan/Step.java:15, DeploymentStep.java:122-193
(TaskStatus -> step status mapping incl. readiness gating and DELAYED
backoff), PodInstanceRequirement.java, recovery/RecoveryType.java:7-25.

TPU-first: a step covers a whole pod *instance* as in the reference,
but for ``gang: true`` pods the step factory emits one step per pod
covering ALL instances (a pjit mesh launches and fails as a unit —
SURVEY.md section 7 hard part 3).
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from dcos_commons_tpu.common import TaskState, TaskStatus, task_name_of
from dcos_commons_tpu.plan.backoff import Backoff, DisabledBackoff
from dcos_commons_tpu.plan.element import Element
from dcos_commons_tpu.plan.status import Status
from dcos_commons_tpu.specification.specs import GoalState, PodSpec, task_full_name


class RecoveryType(enum.Enum):
    """Reference: recovery/RecoveryType.java:7-25."""

    NONE = "NONE"
    TRANSIENT = "TRANSIENT"    # relaunch in place, keep reservations
    PERMANENT = "PERMANENT"    # destroy + replace elsewhere


@dataclass
class PodInstanceRequirement:
    """What a step asks the offer evaluator for.

    Reference: plan/PodInstanceRequirement.java — pod instance +
    tasks-to-launch + recovery type.  ``instances`` is a list to
    support gang pods (all instances evaluated/launched together).
    """

    pod: PodSpec
    instances: List[int]
    tasks_to_launch: List[str] = field(default_factory=list)
    recovery_type: RecoveryType = RecoveryType.NONE
    # operator-supplied env merged into every launched task, set by a
    # parameterized `plan start` (reference: PlansQueries.java:47-231
    # start-with-env — what makes cassandra's backup/restore sidecar
    # plans operable: snapshot name, external location)
    env_overrides: Dict[str, str] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if not self.tasks_to_launch:
            self.tasks_to_launch = [t.name for t in self.pod.tasks]

    @property
    def asset_names(self) -> Set[str]:
        """Pod-instance names this requirement touches — the "dirty
        assets" the coordinator uses for mutual exclusion
        (DefaultPlanCoordinator.java:33-90)."""
        return {f"{self.pod.type}-{i}" for i in self.instances}

    def task_names(self) -> List[str]:
        return [
            task_full_name(self.pod.type, i, t)
            for i in self.instances
            for t in self.tasks_to_launch
        ]

    @property
    def name(self) -> str:
        idx = ",".join(str(i) for i in self.instances)
        return f"{self.pod.type}-[{idx}]:[{','.join(self.tasks_to_launch)}]"


class Step(Element):
    """Reference: plan/Step.java:15."""

    # traceview hook: callable(step, old_status, new_status, status)
    # invoked on every state transition; ``status`` is the triggering
    # TaskStatus (None for launch-time and operator-verb transitions).
    # Wired by the scheduler via PlanManager.set_transition_listener —
    # steps never import the tracer, keeping the plan layer inert when
    # tracing is disabled.
    transition_listener = None

    def _notify_transition(self, old: Status, new: Status,
                           status: Optional[TaskStatus] = None) -> None:
        listener = self.transition_listener
        if listener is None or old is new:
            return
        try:
            listener(self, old, new, status)
        except Exception:
            # a broken trace listener must never wedge the plan machine
            import logging

            logging.getLogger(__name__).exception(
                "step transition listener failed for %s", self.name
            )

    def start(self) -> Optional[PodInstanceRequirement]:
        """Called when this step is a candidate; returns the work."""
        raise NotImplementedError

    def update_offer_status(self, launched: bool) -> None:
        """Outcome of offer evaluation for this step's requirement."""
        raise NotImplementedError

    def update(self, status: TaskStatus) -> None:
        """Route a TaskStatus belonging to this step."""
        raise NotImplementedError

    def get_asset_names(self) -> Set[str]:
        return set()


class ActionStep(Step):
    """A scheduler-side action instead of a pod launch.

    Reference: the uninstall/decommission step families —
    scheduler/uninstall/ResourceCleanupStep.java, DeregisterStep.java,
    scheduler/decommission/TriggerDecommissionStep.java,
    EraseTaskStateStep.java — steps whose work is performed by the
    scheduler itself against its stores/agent.  ``action(scheduler)``
    returns True when the work is done; False keeps the step pending
    for the next cycle (e.g. waiting for kill acknowledgements).
    """

    def __init__(self, name: str, action, assets=None):
        super().__init__(name)
        self._action = action
        self._assets = set(assets or ())
        self._status = Status.PENDING
        self._interrupted = False

    def start(self) -> Optional[PodInstanceRequirement]:
        return None  # nothing for the offer evaluator

    def execute(self, scheduler) -> None:
        with self._lock:
            if self._status.is_complete or self._interrupted:
                return
            try:
                done = self._action(scheduler)
            except Exception as e:
                # transient failures retry next cycle: replace (don't
                # accumulate) the error, and let a later success clear
                # it so the step isn't wedged at ERROR forever
                self.errors[:] = [f"{self.name}: {e}"]
                return
            self.errors.clear()
            old = self._status
            self._status = Status.COMPLETE if done else Status.PENDING
            self._notify_transition(old, self._status)

    def update_offer_status(self, launched: bool) -> None:
        pass

    def update(self, status: TaskStatus) -> None:
        pass  # progress is re-checked by execute() each cycle

    def get_status(self) -> Status:
        with self._lock:
            if self.has_errors():
                return Status.ERROR
            if self._interrupted and not self._status.is_complete:
                return Status.WAITING
            return self._status

    def interrupt(self) -> None:
        with self._lock:
            self._interrupted = True

    def proceed(self) -> None:
        with self._lock:
            self._interrupted = False

    def is_interrupted(self) -> bool:
        return self._interrupted

    def restart(self) -> None:
        with self._lock:
            self._status = Status.PENDING
            self.errors.clear()

    def force_complete(self) -> None:
        with self._lock:
            self._status = Status.COMPLETE
            self.errors.clear()

    def get_asset_names(self) -> Set[str]:
        return set(self._assets)


class DeploymentStep(Step):
    """Launch one pod instance (or one gang) and drive it to goal.

    Reference: plan/DeploymentStep.java — specifically the status
    mapping at :122-193: launch recorded -> STARTING; TASK_RUNNING ->
    STARTED, then COMPLETE once readiness passes (or immediately if no
    readiness check); TASK_FINISHED -> COMPLETE for FINISH/ONCE goals;
    failures -> PENDING, or DELAYED under launch backoff.
    """

    def __init__(
        self,
        name: str,
        requirement: PodInstanceRequirement,
        backoff: Optional[Backoff] = None,
    ):
        super().__init__(name)
        self.requirement = requirement
        self._status = Status.PENDING
        self._interrupted = False
        self._backoff = backoff or DisabledBackoff()
        self._delay_until = 0.0
        # task full-name -> expected task id (set at launch record time)
        self._expected: Dict[str, str] = {}
        # task full-name -> last seen state
        self._task_states: Dict[str, TaskState] = {}
        self._task_ready: Dict[str, bool] = {}
        # exact full-name -> TaskSpec map (suffix matching would confuse
        # task names that are dash-suffixes of each other)
        self._spec_by_full = {
            task_full_name(requirement.pod.type, i, spec.name): spec
            for i in requirement.instances
            for spec in requirement.pod.tasks
            if spec.name in requirement.tasks_to_launch
        }

    # -- candidate lifecycle -----------------------------------------

    def start(self) -> Optional[PodInstanceRequirement]:
        with self._lock:
            if self._interrupted or self.has_errors():
                return None
            if self._status is Status.DELAYED:
                if time.monotonic() < self._delay_until:
                    return None
                self._status = Status.PENDING
            if self._status is Status.PENDING:
                return self.requirement
            return None

    def record_launch(self, task_ids: Dict[str, str]) -> None:
        """Called after the launch WAL: map task name -> task id."""
        with self._lock:
            self._expected = dict(task_ids)
            self._task_states = {}
            self._task_ready = {}
            old = self._status
            self._status = Status.STARTING
            self._notify_transition(old, self._status)

    def update_offer_status(self, launched: bool) -> None:
        with self._lock:
            if launched:
                # record_launch carries the ids; nothing more here
                return
            # no inventory matched: stay PENDING; the outcome tracker
            # explains why (debug/OfferOutcomeTracker)

    # -- status intake -----------------------------------------------

    def update(self, status: TaskStatus) -> None:
        with self._lock:
            try:
                name = task_name_of(status.task_id)
            except ValueError:
                return
            if name not in self._expected:
                return
            if self._expected[name] and status.task_id != self._expected[name]:
                return  # stale status from an older launch
            if self._status.is_complete:
                # a completed deploy step never regresses: post-deploy
                # failures belong to the recovery plan (reference:
                # DeploymentStep stays COMPLETE; recovery manager owns
                # keep-alive, DefaultRecoveryPlanManager.java:164)
                return
            if status.state is TaskState.ERROR:
                # NON-recoverable: provisioning failed before the
                # command ever ran (missing template/artifact, bad
                # secret) — a retry fails identically, so surface as
                # plan ERROR instead of crash-looping (reference:
                # TASK_ERROR -> step ERROR, DeploymentStep.java:163-193;
                # exits are `plan restart`/forceComplete or a config
                # fix rolling a new target)
                # accumulate per task (a gang can have SEVERAL distinct
                # provisioning failures; hiding all but the last costs
                # the operator one full rollout per hidden error)
                had_errors = self.has_errors()
                message = f"{name}: {status.message or 'task ERROR'}"
                self.errors[:] = [
                    e for e in self.errors
                    if not e.startswith(f"{name}: ")
                ] + [message]
                self._task_states[name] = status.state
                if not had_errors:
                    self._notify_transition(
                        self._status, Status.ERROR, status
                    )
                return
            old = self._status
            self._task_states[name] = status.state
            if status.ready:
                self._task_ready[name] = True
            self._recompute(failed=status.state.is_failure)
            self._notify_transition(old, self._status, status)

    def _goal_of(self, task_full: str) -> GoalState:
        spec = self._spec_by_full.get(task_full)
        return spec.goal if spec is not None else GoalState.RUNNING

    def _needs_readiness(self, task_full: str) -> bool:
        spec = self._spec_by_full.get(task_full)
        return spec is not None and spec.readiness_check is not None

    def _task_done(self, task_full: str) -> bool:
        state = self._task_states.get(task_full)
        if state is None:
            return False
        goal = self._goal_of(task_full)
        if goal in (GoalState.FINISH, GoalState.ONCE):
            return state is TaskState.FINISHED
        if state is TaskState.RUNNING:
            return (not self._needs_readiness(task_full)) or self._task_ready.get(
                task_full, False
            )
        return False

    def _recompute(self, failed: bool) -> None:
        expected = list(self._expected)
        if failed:
            # any failure in the gang resets the whole step: a pjit pod
            # cannot run degraded (gang semantics; for non-gang pods the
            # step covers a single instance anyway).  The aborted
            # launch's state is dropped so a re-delivered status from it
            # cannot lift the step out of PENDING/DELAYED.
            self._expected = {}
            self._task_states = {}
            self._task_ready = {}
            delay = self._backoff.next_delay(self.name)
            if delay > 0:
                self._delay_until = time.monotonic() + delay
                self._status = Status.DELAYED
            else:
                self._status = Status.PENDING
            return
        if expected and all(self._task_done(t) for t in expected):
            self._backoff.clear(self.name)
            self._status = Status.COMPLETE
        elif any(
            self._task_states.get(t) is TaskState.RUNNING for t in expected
        ):
            self._status = Status.STARTED
        # else remain STARTING

    # -- Element -----------------------------------------------------

    def get_status(self) -> Status:
        with self._lock:
            if self.has_errors():
                return Status.ERROR
            if self._interrupted and not self._status.is_complete:
                return Status.WAITING
            if self._status is Status.DELAYED and \
                    time.monotonic() >= self._delay_until:
                return Status.PENDING
            return self._status

    def get_raw_status(self) -> Status:
        return self._status

    def interrupt(self) -> None:
        with self._lock:
            self._interrupted = True

    def proceed(self) -> None:
        with self._lock:
            self._interrupted = False

    def is_interrupted(self) -> bool:
        return self._interrupted

    def restart(self) -> None:
        """Reference: PlansQueries restart verb — back to PENDING.
        Clears recorded ERRORs: restart is one of the operator's two
        exits from a non-recoverable step."""
        with self._lock:
            old = self._status
            self._status = Status.PENDING
            self._expected = {}
            self._task_states = {}
            self._task_ready = {}
            self._delay_until = 0.0
            self.errors.clear()
            self._notify_transition(old, self._status)

    def force_complete(self) -> None:
        with self._lock:
            old = self._status
            self._status = Status.COMPLETE
            self.errors.clear()
            self._notify_transition(old, self._status)

    def get_asset_names(self) -> Set[str]:
        return self.requirement.asset_names

    def expected_task_ids(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._expected)
