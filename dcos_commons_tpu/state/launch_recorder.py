"""PersistentLaunchRecorder: WAL TaskInfos *before* launching.

Reference: state/PersistentLaunchRecorder.java, invoked at
DefaultScheduler.java:454-455 — every launch recommendation is written
to the state store before the accept call goes to Mesos, so a
scheduler crash between "decide" and "launch" resumes with the task
recorded (and reconciliation then discovers whether it actually
launched).  This idempotent WAL-before-act discipline is what makes
the control plane crash-restart safe (SURVEY.md section 7 hard part 1).
"""

from __future__ import annotations

from typing import List

from dcos_commons_tpu.common import TaskInfo
from dcos_commons_tpu.state.state_store import StateStore


class PersistentLaunchRecorder:
    def __init__(self, state_store: StateStore) -> None:
        self._state_store = state_store

    def record(self, infos: List[TaskInfo]) -> None:
        """Atomically persist the pod's TaskInfos + seeded STAGING statuses.

        One persister transaction: a crash can never leave a gang launch
        half-recorded.  The STAGING seed gives reconciliation something
        to reconcile if the actual launch was lost in the crash.
        """
        self._state_store.store_launch(infos)
