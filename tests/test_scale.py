"""Scale test: many services through the multi scheduler at once.

Reference: frameworks/helloworld/tests/scale/test_scale.py deploys N
service instances concurrently and watches them all complete; this is
the sim-speed analogue over a shared fleet, asserting completion,
isolation (every service's tasks land and no reservation collides)
and that the control plane's per-cycle cost stays sane as N grows.
"""

import time

from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.multi import MultiServiceScheduler
from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
from dcos_commons_tpu.scheduler import SchedulerConfig
from dcos_commons_tpu.specification.yaml_spec import from_yaml
from dcos_commons_tpu.storage import MemPersister
from dcos_commons_tpu.testing import FakeAgent

N_SERVICES = 24
PODS_PER_SERVICE = 2


def service_yaml(i: int) -> str:
    return f"""
name: svc-{i:03d}
pods:
  app:
    count: {PODS_PER_SERVICE}
    tasks:
      main:
        goal: RUNNING
        cmd: "serve-{i:03d}"
        cpus: 0.5
        memory: 256
"""


def ack_all_running(multi, agent):
    for info in agent.launched:
        if info.task_id in agent.active_task_ids():
            agent.send(TaskStatus(
                task_id=info.task_id, state=TaskState.RUNNING, ready=True
            ))


def test_scale_many_services_on_shared_fleet():
    hosts = [
        TpuHost(host_id=f"h{i:02d}", cpus=16.0, memory_mb=32768)
        for i in range(8)
    ]
    agent = FakeAgent()
    multi = MultiServiceScheduler(
        persister=MemPersister(),
        inventory=SliceInventory(hosts),
        agent=agent,
        scheduler_config=SchedulerConfig(
            backoff_enabled=False, revive_capacity=1_000_000
        ),
    )
    t0 = time.monotonic()
    for i in range(N_SERVICES):
        multi.add_service(from_yaml(service_yaml(i)))

    deadline = time.monotonic() + 60
    cycles = 0
    while time.monotonic() < deadline:
        multi.run_cycle()
        cycles += 1
        ack_all_running(multi, agent)
        if all(
            multi.get_service(f"svc-{i:03d}").deploy_manager.get_plan()
            .is_complete
            for i in range(N_SERVICES)
        ):
            break
    elapsed = time.monotonic() - t0

    for i in range(N_SERVICES):
        svc = multi.get_service(f"svc-{i:03d}")
        assert svc.deploy_manager.get_plan().is_complete, f"svc-{i:03d}"
        for p in range(PODS_PER_SERVICE):
            info = svc.state_store.fetch_task(f"app-{p}-main")
            assert info is not None
            assert f"serve-{i:03d}" in info.command
    # every launch is alive exactly once: no cross-service task kills
    assert len(agent.launched) == N_SERVICES * PODS_PER_SERVICE
    assert agent.kills == []
    # fleet-level accounting: total cpu claims fit the fleet
    total_cpus = sum(
        r.cpus
        for i in range(N_SERVICES)
        for r in multi.get_service(f"svc-{i:03d}").ledger.all()
    )
    assert total_cpus <= sum(h.cpus for h in hosts)
    assert elapsed < 60, f"scale deploy too slow: {elapsed:.1f}s"


def test_scale_uninstall_one_leaves_rest_running():
    """Scaled-down isolation check under load: removing one service
    kills only its own tasks (the ADVICE.md multi-kill regression at
    fleet scale)."""
    hosts = [
        TpuHost(host_id=f"h{i:02d}", cpus=16.0, memory_mb=32768)
        for i in range(4)
    ]
    agent = FakeAgent()
    multi = MultiServiceScheduler(
        persister=MemPersister(),
        inventory=SliceInventory(hosts),
        agent=agent,
        scheduler_config=SchedulerConfig(
            backoff_enabled=False, revive_capacity=1_000_000
        ),
    )
    n = 6
    for i in range(n):
        multi.add_service(from_yaml(service_yaml(i)))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        multi.run_cycle()
        ack_all_running(multi, agent)
        if all(
            multi.get_service(f"svc-{i:03d}").deploy_manager.get_plan()
            .is_complete
            for i in range(n)
        ):
            break
    victim_tasks = {
        multi.get_service("svc-000").state_store.fetch_task(
            f"app-{p}-main"
        ).task_id
        for p in range(PODS_PER_SERVICE)
    }
    multi.uninstall_service("svc-000")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        multi.run_cycle()
        if multi.get_service("svc-000") is None:
            break
    killed = set(agent.kills)
    assert victim_tasks <= killed
    survivor_ids = {
        multi.get_service(f"svc-{i:03d}").state_store.fetch_task(
            f"app-{p}-main"
        ).task_id
        for i in range(1, n)
        for p in range(PODS_PER_SERVICE)
    }
    assert not (survivor_ids & killed)
