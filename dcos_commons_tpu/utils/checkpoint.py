"""Workload checkpointing: npz with dtype-safe, multi-host-safe leaves.

The control plane WALs its own state (SURVEY.md section 5.4); workload
checkpointing is the service's job, and this is the pattern library:
PERMANENT gang recovery = re-place the sub-slice, restore the latest
step here, resume.

Leaves that numpy cannot round-trip (bfloat16 and friends) are stored
as float32 with the original dtype recorded; global jax.Arrays that
span non-addressable devices (multi-host pjit) are gathered to the
host first.  The step stamp is "next step to run", so resume never
double-applies an update.

Writer-incarnation fencing (ADVICE round 5): recovery can relaunch a
trainer while its superseded predecessor still has one save in
flight, and two misconfigured jobs can share a CHECKPOINT_DIR.  The
old "the caller that just saved step N owns the frontier" rule let
exactly those zombies destroy the genuine latest checkpoints.  A
fenced writer claims a monotonically increasing incarnation token
(:func:`claim_incarnation`, an O_EXCL marker file so concurrent
claimers can never share one) and records it IN the checkpoint name;
save and prune then refuse to cross a NEWER incarnation's frontier —
a stale writer can only prune its own past (and its predecessors'),
never the live writer's future.

:class:`AsyncCheckpointer` is the non-blocking path: ``save()``
snapshots the tree with an asynchronously dispatched device-side copy
(safe against the train step's buffer donation) and hands it to one
background writer thread, so the step loop never waits on the host
gather or file IO.
"""

from __future__ import annotations

import json
import os
import queue
import re
import threading
from typing import Any, List, Optional, Tuple

import numpy as np

# legacy names (step_<digits>.npz) parse as incarnation 0: every
# fenced writer's past, prunable by any of them
_STEP_RE = re.compile(r"^step_(\d+)(?:\.inc_(\d+))?\.npz$")
_INC_RE = re.compile(r"^writer_(\d+)\.inc$")


class StaleWriterError(RuntimeError):
    """A writer tried to save or prune across a NEWER incarnation's
    frontier: it has been superseded (recovery relaunched the trainer,
    or another job owns the directory) and must stop writing."""


def _step_files(directory: str) -> List[Tuple[int, int, str]]:
    """[(step, incarnation, filename)] sorted by (step, incarnation).
    Only exact step_<digits>[.inc_<digits>].npz names count — a stray
    operator file (step_best.npz, a .tmp) must never crash
    saves/restores or be pruned."""
    out = []
    for name in os.listdir(directory):
        m = _STEP_RE.match(name)
        if m:
            out.append((int(m.group(1)), int(m.group(2) or 0), name))
    return sorted(out)


def _max_incarnation(directory: str) -> int:
    """Highest incarnation visible in ``directory``: claimed marker
    files AND checkpoint names (a marker could be lost to a partial
    directory copy; the checkpoints themselves still fence)."""
    top = 0
    if os.path.isdir(directory):
        for name in os.listdir(directory):
            m = _INC_RE.match(name)
            if m:
                top = max(top, int(m.group(1)))
        for _step, inc, _name in _step_files(directory):
            top = max(top, inc)
    return top


def claim_incarnation(directory: str) -> int:
    """Durably claim the next writer incarnation for ``directory``.

    The claim is an O_EXCL-created ``writer_<n>.inc`` marker, so two
    trainers racing a recovery relaunch can never share a token; the
    loser retries above the winner.  In a multi-process mesh only
    process 0 claims (it is the only writer); the token is process-0
    state, not gang state.
    """
    os.makedirs(directory, exist_ok=True)
    n = _max_incarnation(directory) + 1
    while True:
        try:
            fd = os.open(
                os.path.join(directory, f"writer_{n:010d}.inc"),
                os.O_CREAT | os.O_EXCL | os.O_WRONLY,
            )
            os.close(fd)
            return n
        except FileExistsError:
            n += 1


def _host_array(leaf: Any) -> np.ndarray:
    """Fetch a leaf to host memory, gathering multi-host arrays."""
    try:
        import jax

        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            from jax.experimental import multihost_utils

            leaf = multihost_utils.process_allgather(leaf, tiled=True)
    except ImportError:  # pragma: no cover - jax always present here
        pass
    arr = np.asarray(leaf)
    return arr


def save_checkpoint(
    directory: str, step: int, tree: Any, keep: int = 0,
    incarnation: Optional[int] = None,
) -> str:
    """Atomic save of a pytree; ``step`` = next step to run on resume.

    In a multi-process mesh call this from every process (the gather is
    collective) but only process 0 writes.

    ``keep`` > 0 prunes AFTER the new file is durably in place (write
    + fsync + rename first, delete after — a crash mid-save can
    orphan an extra file but never leaves fewer than ``keep``
    restorable steps).  Two kinds of files go: steps older than the
    newest ``keep`` at-or-below the one just saved (a long run would
    otherwise grow the directory by ~3 bytes/param per save until the
    disk fills), and steps newer than the one just saved — an
    abandoned future (operator rolled back and retrained) that would
    otherwise poison the default latest-step resume.  ``keep=0``
    prunes nothing.

    ``incarnation`` (from :func:`claim_incarnation`) fences both
    decisions: the token is recorded in the checkpoint name, saving
    raises :class:`StaleWriterError` when the directory already holds
    a NEWER incarnation's checkpoint, and pruning only ever touches
    files at-or-below this writer's incarnation — "the caller is
    authoritative about the frontier" was exactly wrong for a zombie
    writer flushing one last save after recovery relaunched a newer
    trainer (ADVICE round 5).  ``incarnation=None`` keeps the legacy
    unfenced behavior for single-writer tools.
    """
    import jax

    leaves, _ = jax.tree.flatten(tree)
    arrays = {}
    dtypes = {}
    for i, leaf in enumerate(leaves):
        arr = _host_array(leaf)
        if arr.dtype.kind not in "fiub":
            # numpy's npz cannot round-trip extension dtypes (ml_dtypes
            # bfloat16 reads back as void): widen to f32 and remember
            dtypes[str(i)] = arr.dtype.name
            arr = arr.astype(np.float32)
        arrays[f"leaf_{i}"] = arr

    if getattr(jax, "process_index", lambda: 0)() != 0:
        return ""
    os.makedirs(directory, exist_ok=True)
    if incarnation is not None and _max_incarnation(directory) > incarnation:
        # a newer writer owns this directory: the zombie must neither
        # overwrite the live frontier nor (below) prune it.  In a gang
        # only process 0 sees the directory, so only process 0 raises;
        # its task death makes the scheduler reap and recover the
        # whole gang (the AsyncCheckpointer path instead agrees on the
        # fence gang-wide and skips uniformly — see save()).
        raise StaleWriterError(
            f"writer incarnation {incarnation} superseded by "
            f"{_max_incarnation(directory)} in {directory}; refusing "
            "to save — recovery relaunched a newer trainer"
        )
    suffix = (
        "" if incarnation is None else f".inc_{incarnation:010d}"
    )
    path = os.path.join(directory, f"step_{step:010d}{suffix}.npz")
    tmp = path + ".tmp"
    meta = json.dumps({
        "dtypes": dtypes, "step": step,
        "incarnation": incarnation or 0,
    }).encode()
    with open(tmp, "wb") as f:
        np.savez(f, __meta__=np.frombuffer(meta, dtype=np.uint8), **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    if keep > 0:
        # prune by the LISTED names (not reconstructed ones): a
        # hand-named step_5.npz must actually be removed, and a
        # non-matching stray file must never crash the save.  The
        # just-saved step anchors the frontier: retention counts the
        # newest `keep` AT OR BELOW it (so this call's own file is
        # never deleted — review r5), and anything ABOVE it is an
        # abandoned future from a rollback, pruned so the default
        # latest-step resume cannot restore the state the rollback
        # was meant to undo (review r5, follow-up).  Fencing: only
        # files from THIS incarnation or older are candidates — a
        # newer writer's files are the live frontier, not our
        # abandoned future (unreachable when the save-fence above
        # raised, load-bearing when the newer file landed between
        # that check and this scan).
        mine = incarnation if incarnation is not None else float("inf")
        files = [
            (s, i, n) for s, i, n in _step_files(directory) if i <= mine
        ]
        older = [(s, n) for s, i, n in files if s <= step]
        stale_future = [(s, n) for s, i, n in files if s > step]
        for _s, name in older[:-keep] + stale_future:
            try:
                os.remove(os.path.join(directory, name))
            except OSError:
                pass  # already gone (concurrent pruner) — harmless
    return path


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    files = _step_files(directory)
    return files[-1][0] if files else None


def restore_checkpoint(
    directory: str, like: Any, step: Optional[int] = None
) -> Tuple[Any, Optional[int]]:
    """Restore into the structure of ``like``; returns (tree, step) or
    (like, None) when no checkpoint exists.  Each leaf is cast back to
    ``like``'s dtype (jnp handles bfloat16 casts numpy cannot)."""
    import jax
    import jax.numpy as jnp

    files = _step_files(directory) if os.path.isdir(directory) else []
    target = step if step is not None else (
        files[-1][0] if files else None
    )
    if target is None:
        return like, None
    # open the LISTED filename for the step: a hand-named step_5.npz
    # (unpadded) must restore, not 404 on a reconstructed name.  With
    # same-step files from several incarnations, the NEWEST
    # incarnation's wins (the sort is (step, incarnation)).
    names = [name for s, _inc, name in files if s == target]
    if not names:
        # an EXPLICITLY requested step that is absent is an error,
        # not a silent fresh-start (step is not None here: the
        # latest-step path only yields steps that exist)
        raise FileNotFoundError(
            f"no checkpoint for step {step} in {directory}"
        )
    data = np.load(os.path.join(directory, names[-1]))
    leaves, treedef = jax.tree.flatten(like)
    restored = []
    for i, leaf in enumerate(leaves):
        arr = data[f"leaf_{i}"]
        if hasattr(leaf, "dtype"):
            restored.append(jnp.asarray(arr).astype(leaf.dtype))
        else:
            restored.append(arr)
    return jax.tree.unflatten(treedef, restored), target


_JIT_COPY = None


def _snapshot_tree(tree: Any) -> Any:
    """Device-side copy of a pytree, dispatched as ONE fused program.

    The copies are enqueued BEFORE the train loop's next dispatch
    donates the source buffers, so the background writer reads stable
    values while the step loop overwrites the originals in place.
    Fused matters: a per-leaf ``jnp.copy`` pays one dispatch per leaf
    (~10ms for a 34-leaf adam state on a syscall-bound host — most of
    a small step); one jitted tree-copy pays one.  Trees with non-jax
    leaves fall back to per-leaf host copies."""
    global _JIT_COPY
    import jax

    if all(
        isinstance(leaf, jax.Array) for leaf in jax.tree.leaves(tree)
    ):
        if _JIT_COPY is None:
            import jax.numpy as jnp

            _JIT_COPY = jax.jit(lambda t: jax.tree.map(jnp.copy, t))
        return _JIT_COPY(tree)
    return jax.tree.map(lambda leaf: np.copy(np.asarray(leaf)), tree)


class AsyncCheckpointer:
    """Non-blocking, incarnation-fenced checkpoint writer.

    ``save(step, tree)`` costs the step loop only an async device-side
    copy per leaf; one background thread then gathers to host and runs
    :func:`save_checkpoint` (write + fsync + rename + fenced prune)
    off the hot path.  The queue is BOUNDED: saving faster than the
    disk drains backpressures ``save()`` instead of hoarding
    device-memory snapshots.

    Fencing: the writer claims an incarnation up front (or is handed
    one).  The first save that hits a newer incarnation's frontier
    marks the checkpointer ``fenced`` and every later save drops
    immediately — a zombie trainer must stop fighting the live writer,
    not retry.  Write failures land in ``errors`` (telemetry-grade:
    training continues; the operator reads the list via ``wait()``).

    Multi-process contract is :func:`save_checkpoint`'s: every
    process must call ``save()`` in the same order (the multi-host
    gather runs inside ``save()``, in program order with the training
    collectives), and only process 0 writes; claim the incarnation on
    process 0 and broadcast it so the gang agrees on one token.
    """

    def __init__(
        self, directory: str, keep: int = 0,
        incarnation: Optional[int] = None, max_pending: int = 2,
    ):
        import jax

        self.directory = directory
        self.keep = keep
        if incarnation is None and (
            getattr(jax, "process_index", lambda: 0)() == 0
        ):
            incarnation = claim_incarnation(directory)
        self.incarnation = incarnation
        self.errors: List[str] = []
        self.saved: List[str] = []
        # the fence latch flips on BOTH sides of the queue: the writer
        # thread latches on StaleWriterError, the caller latches on
        # the broadcast verdict — a lock keeps the flip ordered (reads
        # stay lock-free: the latch is monotonic False -> True)
        self._fence_lock = threading.Lock()
        self.fenced = False
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, max_pending))
        self._thread = threading.Thread(
            target=self._drain, name="async-ckpt", daemon=True
        )
        self._thread.start()

    def save(self, step: int, tree: Any) -> None:
        """Snapshot ``tree`` (async device copy) and enqueue the write;
        returns as soon as the copies are DISPATCHED.

        Multi-host leaves (non-addressable global arrays) force the
        gather HERE, on the caller's thread: ``process_allgather`` is
        a collective, and a collective issued from the writer thread
        would race the training loop's collectives in program order —
        a cross-host deadlock waiting to happen.  The gang pays the
        gather synchronously (exactly what the blocking path paid);
        the npz write + fsync + prune still overlap the step loop.

        The FENCE decision is gang-uniform too: only process 0
        observes the directory, so its fenced latch is broadcast and
        every process skips the same saves — a process-0-local skip
        would leave the peers alone in the gather collective and wedge
        the gang (review r7)."""
        import jax

        multi_host = any(
            isinstance(leaf, jax.Array) and not leaf.is_fully_addressable
            for leaf in jax.tree.leaves(tree)
        )
        if multi_host:
            import jax.numpy as jnp
            from jax.experimental import multihost_utils

            fenced = bool(int(multihost_utils.broadcast_one_to_all(
                jnp.int32(int(self.fenced))
            )))
            if fenced:
                with self._fence_lock:
                    self.fenced = True
                return
        elif self.fenced:
            return
        snapshot = _snapshot_tree(tree)
        if multi_host:
            snapshot = jax.tree.map(_host_array, snapshot)
        self._queue.put((step, snapshot))

    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            try:
                if item is None:
                    return
                step, snapshot = item
                try:
                    self.saved.append(save_checkpoint(
                        self.directory, step, snapshot, keep=self.keep,
                        incarnation=self.incarnation,
                    ))
                except StaleWriterError as e:
                    with self._fence_lock:
                        self.fenced = True
                    self.errors.append(str(e))
                except Exception as e:  # noqa: BLE001 — a failed save
                    # (full disk, NFS hiccup) must not kill the writer
                    # thread: later saves may land, and the step loop
                    # reads the failure from .errors
                    self.errors.append(repr(e))
            finally:
                self._queue.task_done()

    def wait(self) -> List[str]:
        """Block until every enqueued save is durable (or failed);
        returns accumulated error strings."""
        self._queue.join()
        return list(self.errors)

    def close(self) -> List[str]:
        """Drain pending saves, stop the writer thread, return errors."""
        self._queue.put(None)
        self._thread.join()
        return list(self.errors)
