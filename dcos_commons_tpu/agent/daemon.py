"""Per-host agent daemon: the task-running half of the control plane.

One daemon process runs on each TPU-VM host and exposes the Agent
contract over HTTP to the scheduler.  This is the rebuild's analogue of
the Mesos agent + the reference's task-side bootstrap binary rolled
into one long-lived process: launch/kill/status cross a real network
boundary (reference: FrameworkScheduler.java:196 callbacks crossing the
Mesos master process boundary; sdk/bootstrap/main.go doing task-side
sandbox preparation), sandboxes are provisioned locally, and config
templates are pulled from the scheduler's /v1/artifacts endpoint and
rendered against the task env (sdk/bootstrap/main.go:291-376).

Protocol (JSON over HTTP, scheduler -> agent):

    GET  /v1/agent/info    {host_id, active, uptime_s}
    POST /v1/agent/launch  {tasks: [{info, readiness?, health?, templates?}]}
    POST /v1/agent/kill    {task_id, grace_period_s}
    GET  /v1/agent/tasks   {task_ids: [...]}
    POST /v1/agent/drain   -> {statuses: [...]}   (drains pending updates)
    POST /v1/agent/reconcile  (re-arm current task states for re-delivery)
    GET  /v1/agent/sandbox?task=<name>&file=<rel> -> file text (debugging)
    GET  /v1/agent/steplog?task=<name>    -> {records: [...]}  (telemetry)
    GET  /v1/agent/servestats?task=<name> -> {stats: {...}}    (telemetry)

Statuses are *pulled* by the scheduler (drain), matching the poll-based
Agent contract — the daemon never needs to know where the scheduler
lives, which keeps scheduler failover trivial.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from dcos_commons_tpu.agent.local import LocalProcessAgent
from dcos_commons_tpu.common import TaskInfo
from dcos_commons_tpu.specification.specs import (
    HealthCheckSpec,
    ReadinessCheckSpec,
)


class AgentDaemon:
    """HTTP front end over a LocalProcessAgent for ONE host."""

    def __init__(
        self,
        host_id: str,
        workdir: str,
        port: int = 0,
        bind: str = "127.0.0.1",
        advertise_host: str = "",
        auth_token: str = "",
        tls=None,
        ca_file: str = "",
    ):
        from dcos_commons_tpu.security import auth as _auth

        self.host_id = host_id
        # a daemon bound to 0.0.0.0 must announce a routable address
        # (the scheduler dials what the announce file says); mirrors the
        # runner's --advertise-url
        self.advertise_host = advertise_host
        self._executor = LocalProcessAgent(
            workdir, auth_token=auth_token, ca_file=ca_file
        )
        self._started_at = time.monotonic()
        self._scheme = _auth.url_scheme(tls)
        daemon = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _authorized(self) -> bool:
                # launch IS remote command execution: with a token set,
                # EVERY agent route (including sandbox reads) requires
                # it — there is no anonymous surface on a daemon
                if _auth.check_bearer(self.headers, auth_token):
                    return True
                self._reply(*_auth.UNAUTHORIZED)
                return False

            def _body(self) -> dict:
                length = int(self.headers.get("Content-Length", 0))
                if not length:
                    return {}
                return json.loads(self.rfile.read(length).decode("utf-8"))

            def _reply(self, code: int, body) -> None:
                if isinstance(body, str):
                    payload = body.encode("utf-8")
                    ctype = "text/plain; charset=utf-8"
                else:
                    payload = json.dumps(body).encode("utf-8")
                    ctype = "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                if not self._authorized():
                    return
                parsed = urlparse(self.path)
                try:
                    if parsed.path == "/v1/agent/info":
                        self._reply(200, daemon.info())
                    elif parsed.path == "/v1/agent/tasks":
                        self._reply(
                            200,
                            {"task_ids": sorted(
                                daemon._executor.active_task_ids()
                            )},
                        )
                    elif parsed.path == "/v1/agent/sandbox":
                        query = parse_qs(parsed.query)
                        task = (query.get("task") or [""])[0]
                        rel = (query.get("file") or ["stdout"])[0]
                        path = daemon.resolve_sandbox_path(task, rel)
                        if path is None or not os.path.isfile(path):
                            self._reply(404, {"message": f"no file {rel}"})
                            return
                        # the file can vanish between the isfile check
                        # and the open (sandbox GC race) — the outer
                        # guard turns that into a 500, not a dropped
                        # connection
                        with open(path, "r", errors="replace") as f:
                            self._reply(200, f.read())
                    elif parsed.path == "/v1/agent/steplog":
                        # worker step telemetry for the scheduler's
                        # traceview merge + straggler detector (the
                        # remote half of LocalProcessAgent.steplog_of)
                        query = parse_qs(parsed.query)
                        task = (query.get("task") or [""])[0]
                        if not daemon.valid_task_name(task):
                            self._reply(404, {"message": "bad task name"})
                            return
                        self._reply(200, {
                            "records": daemon._executor.steplog_of(task)
                        })
                    elif parsed.path == "/v1/agent/servestats":
                        query = parse_qs(parsed.query)
                        task = (query.get("task") or [""])[0]
                        if not daemon.valid_task_name(task):
                            self._reply(404, {"message": "bad task name"})
                            return
                        self._reply(200, {
                            "stats": daemon._executor.serving_stats_of(task)
                        })
                    else:
                        self._reply(
                            404, {"message": f"no route {parsed.path}"}
                        )
                except Exception as e:
                    self._reply(500, {"message": f"agent error: {e}"})

            def do_POST(self):
                if not self._authorized():
                    return
                parsed = urlparse(self.path)
                try:
                    if parsed.path == "/v1/agent/launch":
                        body = self._body()
                        launched = daemon.launch(body.get("tasks", []))
                        self._reply(200, {"launched": launched})
                    elif parsed.path == "/v1/agent/kill":
                        body = self._body()
                        daemon._executor.kill(
                            body["task_id"],
                            float(body.get("grace_period_s", 0.0)),
                        )
                        self._reply(200, {"message": "kill requested"})
                    elif parsed.path == "/v1/agent/drain":
                        statuses = [
                            s.to_dict() for s in daemon._executor.poll()
                        ]
                        self._reply(200, {"statuses": statuses})
                    elif parsed.path == "/v1/agent/reconcile":
                        # explicit reconciliation: a failed-over
                        # scheduler asks for CURRENT task states —
                        # transitions a dead predecessor drained are
                        # re-armed for the next drain
                        daemon._executor.reconcile()
                        self._reply(200, {"message": "reconcile armed"})
                    else:
                        self._reply(404, {"message": f"no route {parsed.path}"})
                except Exception as e:
                    self._reply(500, {"message": f"agent error: {e}"})

        self._server = _auth.wrap_http_server(
            ThreadingHTTPServer((bind, port), Handler), tls
        )
        self._thread: Optional[threading.Thread] = None

    # -- request handling --------------------------------------------

    def valid_task_name(self, task: str) -> bool:
        """Task names are attacker-controlled query params; the
        steplog/servestats readers join them onto the workdir, so the
        same confinement as sandbox reads applies."""
        return bool(task) and os.sep not in task and task not in (".", "..")

    def resolve_sandbox_path(self, task: str, rel: str) -> Optional[str]:
        """Confine sandbox reads to the named task's sandbox: both the
        task name and the relative path are attacker-controlled query
        params, so resolve symlinks/.. and require the result to stay
        under ``<workdir>/<task>/``."""
        if not task or os.sep in task or task in (".", ".."):
            return None
        sandbox = os.path.realpath(self._executor.sandbox_of(task))
        workdir_prefix = os.path.realpath(self._executor._workdir) + os.sep
        if not sandbox.startswith(workdir_prefix):
            return None
        path = os.path.realpath(os.path.join(sandbox, rel))
        if path != sandbox and not path.startswith(sandbox + os.sep):
            return None
        return path

    def info(self) -> dict:
        return {
            "host_id": self.host_id,
            "active": len(self._executor.active_task_ids()),
            "uptime_s": round(time.monotonic() - self._started_at, 1),
            "pid": os.getpid(),
        }

    def launch(self, tasks: list) -> list:
        launched = []
        for entry in tasks:
            info = TaskInfo.from_dict(entry["info"])
            readiness = entry.get("readiness")
            health = entry.get("health")
            self._executor.launch_one(
                info,
                readiness=ReadinessCheckSpec(**readiness) if readiness else None,
                health=HealthCheckSpec(**health) if health else None,
                templates=entry.get("templates"),
                files=entry.get("files"),
                secret_env=entry.get("secret_env"),
                kill_grace_s=float(entry.get("kill_grace_s", 5.0)),
                uris=entry.get("uris"),
                rlimits=entry.get("rlimits"),
            )
            launched.append(info.task_id)
        return launched

    # -- lifecycle ----------------------------------------------------

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        if self.advertise_host:
            host = self.advertise_host
        elif host in ("0.0.0.0", "::"):
            import socket

            host = socket.gethostname()
        return f"{self._scheme}://{host}:{port}"

    def start(self) -> "AgentDaemon":
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"agent-{self.host_id}",
            daemon=True,
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._executor.shutdown()


def serialize_check(check) -> Optional[dict]:
    """Check specs -> JSON for the launch request wire format."""
    if check is None:
        return None
    return dataclasses.asdict(check)


def _tls_pair_or_die(cert: str, key: str):
    from dcos_commons_tpu.security.auth import tls_pair

    try:
        return tls_pair(cert, key)
    except ValueError as e:
        import sys

        print(f"configuration error: {e}", file=sys.stderr)
        raise SystemExit(4)  # EXIT_BAD_CONFIG


def main(argv: Optional[list] = None) -> int:
    """``python -m dcos_commons_tpu agent`` — run one host's daemon."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="dcos_commons_tpu agent", description=__doc__.splitlines()[0]
    )
    parser.add_argument("--host-id", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--bind", default="127.0.0.1")
    parser.add_argument(
        "--advertise-host",
        default="",
        help="hostname/IP to announce instead of the bind address "
             "(required when binding 0.0.0.0 on a multi-host fleet)",
    )
    parser.add_argument("--workdir", default="./agent-sandboxes")
    parser.add_argument(
        "--announce-file",
        default="",
        help="write '<host_id> <url>' here once listening (ephemeral ports)",
    )
    parser.add_argument(
        "--auth-token-file",
        default="",
        help="cluster bearer token file; also $AUTH_TOKEN(_FILE). "
             "REQUIRED for non-loopback binds (launch = remote exec)",
    )
    parser.add_argument("--tls-cert", default="", help="serve HTTPS: cert PEM")
    parser.add_argument("--tls-key", default="", help="serve HTTPS: key PEM")
    parser.add_argument(
        "--tls-ca", default="",
        help="CA bundle for verifying the scheduler's HTTPS artifact "
             "endpoint; also $TLS_CA_FILE",
    )
    parser.add_argument(
        "--provision-cmd", default="",
        help="host provisioning command run ONCE before serving "
             "(shell): e.g. seed the XLA compile cache "
             "(frameworks/jax/warm_cache.py) so a fresh host's first "
             "deploy pays cache-hit time, not a full compile.  A "
             "nonzero exit aborts the daemon — a half-provisioned "
             "host must not take tasks.",
    )
    parser.add_argument(
        "--provision-timeout-s", type=float, default=600.0,
        help="hard cap on --provision-cmd: a wedged provisioning "
             "compile must abort LOUDLY, not leave a host that "
             "silently never joins the fleet",
    )
    args = parser.parse_args(argv)
    from dcos_commons_tpu.security.auth import load_token

    token = load_token(token_file=args.auth_token_file)
    if not token and args.bind not in ("127.0.0.1", "localhost", "::1"):
        import sys

        print(
            "WARNING: agent bound on a non-loopback address with NO auth "
            "token — anyone who can reach this port can run commands. "
            "Pass --auth-token-file (see security/auth.py trust model).",
            file=sys.stderr,
        )
    if args.provision_cmd:
        import signal as _signal
        import subprocess
        import sys
        import time as _time

        t0 = _time.time()
        # own session + group kill on timeout: the provisioning
        # command's typical job is an XLA compile, a known wedge shape
        # on relay-backed fleets — a hung grandchild must die with it
        proc = subprocess.Popen(
            ["/bin/sh", "-c", args.provision_cmd],
            start_new_session=True,
        )
        try:
            rc = proc.wait(timeout=args.provision_timeout_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, _signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            proc.wait(timeout=10)
            print(
                f"provisioning timed out after "
                f"{args.provision_timeout_s:.0f}s: {args.provision_cmd}",
                file=sys.stderr,
            )
            return 1
        if rc != 0:
            print(
                f"provisioning failed (rc={rc}): {args.provision_cmd}",
                file=sys.stderr,
            )
            return rc
        print(
            f"provisioned in {_time.time() - t0:.1f}s: "
            f"{args.provision_cmd}",
            flush=True,
        )
    daemon = AgentDaemon(
        args.host_id,
        args.workdir,
        port=args.port,
        bind=args.bind,
        advertise_host=args.advertise_host,
        auth_token=token,
        tls=_tls_pair_or_die(args.tls_cert, args.tls_key),
        ca_file=args.tls_ca or os.environ.get("TLS_CA_FILE", ""),
    )
    if args.announce_file:
        from dcos_commons_tpu.common import atomic_write_text

        atomic_write_text(
            args.announce_file, f"{daemon.host_id} {daemon.url}\n"
        )
    try:
        daemon.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        daemon.stop()
    return 0
