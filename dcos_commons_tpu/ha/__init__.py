"""HA control plane: leader election, fenced writes, re-hydration.

Reference: the reference SDK runs ONE scheduler behind a ZooKeeper
``CuratorLocker`` and survives scheduler death by restarting anywhere
and replaying the launch WAL plus stored statuses mid-plan
(SchedulerRestartServiceTest).  This package is that story end to end
for the TPU fleet:

* ``election.py`` — a TTL **leader lease** in the replicated state
  tree with a monotonic *lease epoch*; candidates poll and take over
  on expiry, and ``FencedPersister`` extends the replication layer's
  stream fencing to the scheduler's write path (a deposed leader's
  store mutations are rejected, not merely discouraged).
* ``rehydrate.py`` — deterministic scheduler re-hydration: plan state
  checkpoints (operator interrupts / force-completes survive a
  restart), and the WAL-replay report classifying every stored launch
  as adopted / re-issued / lost at takeover.

The chaos harness that kills a scheduler at every traceview
span-boundary kind and asserts convergence lives in
``dcos_commons_tpu/testing/chaos.py``.
"""

from dcos_commons_tpu.ha.election import (  # noqa: F401
    FencedPersister,
    HAState,
    LeaderLease,
    LeaderLock,
    LeaseFencedError,
    LeaseState,
    read_lease,
)
from dcos_commons_tpu.ha.rehydrate import (  # noqa: F401
    PlanCheckpointer,
    RehydrationReport,
    restore_plans,
)

__all__ = [
    "FencedPersister",
    "HAState",
    "LeaderLease",
    "LeaderLock",
    "LeaseFencedError",
    "LeaseState",
    "PlanCheckpointer",
    "RehydrationReport",
    "read_lease",
    "restore_plans",
]
