// task_exec: native per-task supervisor.
//
// The reference prepends a statically-linked Go binary to every task
// command (sdk/bootstrap/main.go, 513 LoC) so task-side lifecycle is
// owned by native code, not the scheduler's runtime.  This is the TPU
// rebuild's equivalent for the *agent* side: one supervisor process
// per task that
//
//   * starts the task in its own session (process group) with
//     stdout/stderr appended to sandbox files,
//   * persists its own pid (task.pid) and, on child exit, the exit
//     status (exit_status) inside the sandbox — so an agent daemon
//     that crashed and restarted can reconstruct every task's fate
//     from the filesystem instead of losing it with its Python heap,
//   * forwards SIGTERM to the whole task group and escalates to
//     SIGKILL after the configured kill-grace period (the Mesos
//     agent's task-kill semantics).
//
// Usage:
//   task_exec --sandbox DIR [--record-dir RD] [--grace SECONDS] \
//             [--rlimit NAME=SOFT:HARD]... -- <shell command...>
//
// --rlimit applies a setrlimit(2) in the child between fork and exec
// (reference: specification/RLimitSpec.java -> Mesos RLimitInfo on
// the ContainerInfo); -1 means RLIM_INFINITY.  A limit that cannot
// be applied fails the task before its command runs — running
// without the isolation the spec demanded would defeat the point.
//
// Records (task.pid/child.pid/exit_status) go to --record-dir, which
// the agent keys by task INCARNATION — two incarnations of one task
// name share the sandbox (volumes, logs) but never their lifecycle
// records, so a dying predecessor cannot poison its successor's fate.
// Exit code: the child's exit code (128+signal when signalled).

#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/resource.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

#include <string>
#include <vector>

namespace {

volatile sig_atomic_t g_term_requested = 0;

void on_term(int) { g_term_requested = 1; }

void write_file(const std::string& path, const std::string& content) {
  std::string tmp = path + ".tmp";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return;
  ssize_t off = 0;
  while (off < static_cast<ssize_t>(content.size())) {
    ssize_t n = write(fd, content.data() + off, content.size() - off);
    if (n <= 0) break;
    off += n;
  }
  fsync(fd);
  close(fd);
  rename(tmp.c_str(), path.c_str());
}

int open_log(const std::string& sandbox, const char* name) {
  std::string path = sandbox + "/" + name;
  return open(path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
}

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec / 1e9;
}

struct RLimitArg {
  int resource;
  rlim_t soft;
  rlim_t hard;
};

int rlimit_by_name(const char* name) {
  struct Entry { const char* name; int resource; };
  static const Entry kTable[] = {
      {"RLIMIT_AS", RLIMIT_AS},           {"RLIMIT_CORE", RLIMIT_CORE},
      {"RLIMIT_CPU", RLIMIT_CPU},         {"RLIMIT_DATA", RLIMIT_DATA},
      {"RLIMIT_FSIZE", RLIMIT_FSIZE},     {"RLIMIT_MEMLOCK", RLIMIT_MEMLOCK},
      {"RLIMIT_NOFILE", RLIMIT_NOFILE},   {"RLIMIT_NPROC", RLIMIT_NPROC},
      {"RLIMIT_RSS", RLIMIT_RSS},         {"RLIMIT_STACK", RLIMIT_STACK},
#ifdef RLIMIT_MSGQUEUE
      {"RLIMIT_MSGQUEUE", RLIMIT_MSGQUEUE},
#endif
#ifdef RLIMIT_NICE
      {"RLIMIT_NICE", RLIMIT_NICE},
#endif
#ifdef RLIMIT_RTPRIO
      {"RLIMIT_RTPRIO", RLIMIT_RTPRIO},
#endif
#ifdef RLIMIT_RTTIME
      {"RLIMIT_RTTIME", RLIMIT_RTTIME},
#endif
#ifdef RLIMIT_SIGPENDING
      {"RLIMIT_SIGPENDING", RLIMIT_SIGPENDING},
#endif
  };
  for (const Entry& e : kTable) {
    if (strcmp(name, e.name) == 0) return e.resource;
  }
  return -1;
}

// "NAME=SOFT:HARD" (-1 = infinity) -> RLimitArg; false on parse error
bool parse_rlimit(const char* arg, RLimitArg* out) {
  const char* eq = strchr(arg, '=');
  const char* colon = eq ? strchr(eq, ':') : nullptr;
  if (!eq || !colon) return false;
  std::string name(arg, eq - arg);
  out->resource = rlimit_by_name(name.c_str());
  if (out->resource < 0) return false;
  long long soft = atoll(eq + 1);
  long long hard = atoll(colon + 1);
  out->soft = soft < 0 ? RLIM_INFINITY : static_cast<rlim_t>(soft);
  out->hard = hard < 0 ? RLIM_INFINITY : static_cast<rlim_t>(hard);
  return true;
}

}  // namespace

int main(int argc, char** argv) {
  std::string sandbox;
  std::string record_dir;
  double grace_s = 5.0;
  int cmd_start = -1;
  std::vector<RLimitArg> rlimits;
  for (int i = 1; i < argc; ++i) {
    if (strcmp(argv[i], "--sandbox") == 0 && i + 1 < argc) {
      sandbox = argv[++i];
    } else if (strcmp(argv[i], "--record-dir") == 0 && i + 1 < argc) {
      record_dir = argv[++i];
    } else if (strcmp(argv[i], "--grace") == 0 && i + 1 < argc) {
      grace_s = atof(argv[++i]);
    } else if (strcmp(argv[i], "--rlimit") == 0 && i + 1 < argc) {
      RLimitArg rl;
      if (!parse_rlimit(argv[i + 1], &rl)) {
        fprintf(stderr, "task_exec: bad --rlimit %s\n", argv[i + 1]);
        return 64;
      }
      rlimits.push_back(rl);
      ++i;
    } else if (strcmp(argv[i], "--") == 0) {
      cmd_start = i + 1;
      break;
    } else {
      fprintf(stderr, "task_exec: unknown arg %s\n", argv[i]);
      return 64;
    }
  }
  if (sandbox.empty() || cmd_start < 0 || cmd_start >= argc) {
    fprintf(stderr,
            "usage: task_exec --sandbox DIR [--grace S] -- command...\n");
    return 64;
  }
  mkdir(sandbox.c_str(), 0755);
  if (record_dir.empty()) record_dir = sandbox;
  mkdir(record_dir.c_str(), 0755);  // parent pre-created by the agent

  // join the command words back into one shell string
  std::string command;
  for (int i = cmd_start; i < argc; ++i) {
    if (!command.empty()) command += " ";
    command += argv[i];
  }

  write_file(record_dir + "/task.pid", std::to_string(getpid()) + "\n");

  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_handler = on_term;
  sigaction(SIGTERM, &sa, nullptr);
  sigaction(SIGINT, &sa, nullptr);

  pid_t child = fork();
  if (child < 0) {
    perror("task_exec: fork");
    return 70;
  }
  if (child == 0) {
    // task side: own session so the whole tree is one kill target
    setsid();
    int out = open_log(sandbox, "stdout");
    int err = open_log(sandbox, "stderr");
    if (out >= 0) dup2(out, STDOUT_FILENO);
    if (err >= 0) dup2(err, STDERR_FILENO);
    if (chdir(sandbox.c_str()) != 0) _exit(71);
    for (const RLimitArg& rl : rlimits) {
      struct rlimit lim = {rl.soft, rl.hard};
      if (setrlimit(rl.resource, &lim) != 0) {
        perror("task_exec: setrlimit");
        _exit(72);
      }
    }
    execl("/bin/sh", "sh", "-c", command.c_str(), (char*)nullptr);
    perror("task_exec: exec");
    _exit(127);
  }

  // the task's session leader pid: lets the agent force-kill the task
  // group directly if this supervisor is ever lost
  write_file(record_dir + "/child.pid", std::to_string(child) + "\n");

  // supervisor side: wait, forwarding kill requests with grace
  bool term_sent = false;
  double kill_deadline = 0.0;
  int status = 0;
  for (;;) {
    if (g_term_requested && !term_sent) {
      // a kill-time override (record_dir/grace, written by the agent
      // just before SIGTERM) wins over the launch-time --grace: a pod
      // replace may want a longer drain than the spec default, and an
      // operator kill a shorter one
      FILE* gf = fopen((record_dir + "/grace").c_str(), "r");
      if (gf) {
        double v = 0.0;
        if (fscanf(gf, "%lf", &v) == 1 && v >= 0.0) grace_s = v;
        fclose(gf);
      }
      kill(-child, SIGTERM);
      term_sent = true;
      kill_deadline = now_s() + grace_s;
    }
    if (term_sent && now_s() >= kill_deadline) {
      kill(-child, SIGKILL);
      kill_deadline = now_s() + 3600;  // once is enough
    }
    pid_t done = waitpid(child, &status, WNOHANG);
    if (done == child) break;
    if (done < 0 && errno != EINTR) break;
    struct timespec nap = {0, 50 * 1000 * 1000};  // 50ms
    nanosleep(&nap, nullptr);
  }

  int code = 0;
  if (WIFEXITED(status)) {
    code = WEXITSTATUS(status);
  } else if (WIFSIGNALED(status)) {
    code = 128 + WTERMSIG(status);
  }
  write_file(record_dir + "/exit_status", std::to_string(code) + "\n");
  return code;
}
