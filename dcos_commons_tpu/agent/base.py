"""Agent contract between the scheduler and host-local executors."""

from __future__ import annotations

from typing import List, Set

from dcos_commons_tpu.common import TaskInfo, TaskStatus


class Agent:
    """What the scheduler needs from the thing that runs tasks.

    Reference analogues: launch = OfferAccepter LAUNCH operations,
    kill = TaskKiller -> driver.killTask, active_task_ids = the task
    reconciliation query (ImplicitReconciler / ExplicitReconciler).
    """

    # True when launch payloads cross a network (per-host daemons):
    # security validators demand an authed channel only then — a
    # local/sim agent writes cert material straight to disk
    is_remote = False

    def launch(self, task_infos: List[TaskInfo]) -> None:
        """Start the given tasks.  Must be idempotent per task_id."""
        raise NotImplementedError

    def kill(self, task_id: str, grace_period_s: float = 0.0) -> None:
        """Request termination; a terminal TaskStatus must follow."""
        raise NotImplementedError

    def active_task_ids(self) -> Set[str]:
        """Task ids currently known (running or starting) — the
        reconciliation source of truth."""
        raise NotImplementedError

    def poll(self) -> List[TaskStatus]:
        """Drain pending status transitions (RUNNING, FINISHED, ...)."""
        raise NotImplementedError

    def advertised_port_of(self, task_name: str, agent_id=None):
        """The HTTP port the task ACTUALLY bound, or None.

        Serving workers annotate their bound port into the servestats
        snapshot (serve/engine.py ``annotate_stats``): on a simulated
        fleet many "hosts" share one machine, so a worker whose
        scheduler-assigned port was taken binds an ephemeral one and
        advertises it here — /v1/endpoints lists what is DIALABLE,
        not what was reserved (ISSUE 12).  Default implementation
        reads the serving snapshot; agents without serving telemetry
        advertise nothing."""
        reader = getattr(self, "serving_stats_of", None)
        if not callable(reader):
            return None
        try:
            stats = reader(task_name, agent_id=agent_id)
        except TypeError:
            stats = reader(task_name)
        except OSError:
            return None
        port = stats.get("http_port") if isinstance(stats, dict) else None
        try:
            return int(port) if port else None
        except (TypeError, ValueError):
            return None

    # -- status listeners (event-driven scheduling) -------------------
    #
    # Agents that learn of a status asynchronously (monitor threads,
    # test fixtures injecting statuses) call _notify_status so the
    # scheduler loop can wake for an immediate poll instead of waiting
    # out its fallback heartbeat.  Purely advisory: an agent that only
    # discovers transitions inside poll() never notifies, and the
    # heartbeat still delivers everything.

    def add_status_listener(self, listener) -> None:
        """Register a no-arg callable invoked when a new status may be
        available.  Called from arbitrary threads; must not block."""
        if not hasattr(self, "_status_listeners"):
            self._status_listeners = []
        self._status_listeners.append(listener)

    def _notify_status(self) -> None:
        for listener in getattr(self, "_status_listeners", []):
            try:
                listener()
            except Exception:  # sdklint: disable=swallowed-exception — a broken listener must not break intake
                pass
