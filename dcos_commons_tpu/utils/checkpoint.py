"""Workload checkpointing: npz with dtype-safe, multi-host-safe leaves.

The control plane WALs its own state (SURVEY.md section 5.4); workload
checkpointing is the service's job, and this is the pattern library:
PERMANENT gang recovery = re-place the sub-slice, restore the latest
step here, resume.

Leaves that numpy cannot round-trip (bfloat16 and friends) are stored
as float32 with the original dtype recorded; global jax.Arrays that
span non-addressable devices (multi-host pjit) are gathered to the
host first.  The step stamp is "next step to run", so resume never
double-applies an update.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, List, Optional, Tuple

import numpy as np

_STEP_RE = re.compile(r"^step_(\d+)\.npz$")


def _step_files(directory: str) -> List[Tuple[int, str]]:
    """[(step, filename)] sorted by step.  Only exact step_<digits>.npz
    names count — a stray operator file (step_best.npz, a .tmp) must
    never crash saves/restores or be pruned."""
    out = []
    for name in os.listdir(directory):
        m = _STEP_RE.match(name)
        if m:
            out.append((int(m.group(1)), name))
    return sorted(out)


def _host_array(leaf: Any) -> np.ndarray:
    """Fetch a leaf to host memory, gathering multi-host arrays."""
    try:
        import jax

        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            from jax.experimental import multihost_utils

            leaf = multihost_utils.process_allgather(leaf, tiled=True)
    except ImportError:  # pragma: no cover - jax always present here
        pass
    arr = np.asarray(leaf)
    return arr


def save_checkpoint(
    directory: str, step: int, tree: Any, keep: int = 0,
) -> str:
    """Atomic save of a pytree; ``step`` = next step to run on resume.

    In a multi-process mesh call this from every process (the gather is
    collective) but only process 0 writes.

    ``keep`` > 0 prunes AFTER the new file is durably in place (write
    + fsync + rename first, delete after — a crash mid-save can
    orphan an extra file but never leaves fewer than ``keep``
    restorable steps).  Two kinds of files go: steps older than the
    newest ``keep`` at-or-below the one just saved (a long run would
    otherwise grow the directory by ~3 bytes/param per save until the
    disk fills), and ANY step newer than the one just saved — the
    caller that just produced step N is authoritative about the
    frontier, so newer files are an abandoned future (operator rolled
    back and retrained) that would otherwise poison the default
    latest-step resume.  ``keep=0`` prunes nothing.
    """
    import jax

    leaves, _ = jax.tree.flatten(tree)
    arrays = {}
    dtypes = {}
    for i, leaf in enumerate(leaves):
        arr = _host_array(leaf)
        if arr.dtype.kind not in "fiub":
            # numpy's npz cannot round-trip extension dtypes (ml_dtypes
            # bfloat16 reads back as void): widen to f32 and remember
            dtypes[str(i)] = arr.dtype.name
            arr = arr.astype(np.float32)
        arrays[f"leaf_{i}"] = arr

    if getattr(jax, "process_index", lambda: 0)() != 0:
        return ""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"step_{step:010d}.npz")
    tmp = path + ".tmp"
    meta = json.dumps({"dtypes": dtypes, "step": step}).encode()
    with open(tmp, "wb") as f:
        np.savez(f, __meta__=np.frombuffer(meta, dtype=np.uint8), **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    if keep > 0:
        # prune by the LISTED names (not reconstructed ones): a
        # hand-named step_5.npz must actually be removed, and a
        # non-matching stray file must never crash the save.  The
        # just-saved step anchors the frontier: retention counts the
        # newest `keep` AT OR BELOW it (so this call's own file is
        # never deleted — review r5), and anything ABOVE it is an
        # abandoned future from a rollback, pruned so the default
        # latest-step resume cannot restore the state the rollback
        # was meant to undo (review r5, follow-up).
        files = _step_files(directory)
        older = [(s, n) for s, n in files if s <= step]
        stale_future = [(s, n) for s, n in files if s > step]
        for _s, name in older[:-keep] + stale_future:
            try:
                os.remove(os.path.join(directory, name))
            except OSError:
                pass  # already gone (concurrent pruner) — harmless
    return path


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    files = _step_files(directory)
    return files[-1][0] if files else None


def restore_checkpoint(
    directory: str, like: Any, step: Optional[int] = None
) -> Tuple[Any, Optional[int]]:
    """Restore into the structure of ``like``; returns (tree, step) or
    (like, None) when no checkpoint exists.  Each leaf is cast back to
    ``like``'s dtype (jnp handles bfloat16 casts numpy cannot)."""
    import jax
    import jax.numpy as jnp

    files = _step_files(directory) if os.path.isdir(directory) else []
    target = step if step is not None else (
        files[-1][0] if files else None
    )
    if target is None:
        return like, None
    # open the LISTED filename for the step: a hand-named step_5.npz
    # (unpadded) must restore, not 404 on a reconstructed name
    names = [name for s, name in files if s == target]
    if not names:
        # an EXPLICITLY requested step that is absent is an error,
        # not a silent fresh-start (step is not None here: the
        # latest-step path only yields steps that exist)
        raise FileNotFoundError(
            f"no checkpoint for step {step} in {directory}"
        )
    data = np.load(os.path.join(directory, names[-1]))
    leaves, treedef = jax.tree.flatten(like)
    restored = []
    for i, leaf in enumerate(leaves):
        arr = data[f"leaf_{i}"]
        if hasattr(leaf, "dtype"):
            restored.append(jnp.asarray(arr).astype(leaf.dtype))
        else:
            restored.append(arr)
    return jax.tree.unflatten(treedef, restored), target
