"""Pytree accounting helpers."""

from __future__ import annotations

import jax


def param_count(tree) -> int:
    return sum(leaf.size for leaf in jax.tree.leaves(tree))


def param_bytes(tree) -> int:
    return sum(
        leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(tree)
    )
