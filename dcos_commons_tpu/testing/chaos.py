"""Chaos harness: kill the scheduler at every span boundary, converge.

Reference: the reference proves restart safety with ONE restart test
(``SchedulerRestartServiceTest``); this harness turns that into a
kill MATRIX.  A crash injector raises out of ``run_cycle`` at a
chosen traceview span-boundary kind — the five places a scheduler
death leaves observably different persisted state:

    post-evaluate        evaluation passed, nothing persisted
    post-wal             reservations + launch WAL durable, agent
                         never heard about the launch
    mid-status-fan-in    a status persisted but not routed to plans
    mid-plan-transition  a plan step moved, post-transition work lost
    mid-checkpoint-prune plan checkpoints partially written/pruned

The dead scheduler object is abandoned exactly as SIGKILL would leave
a process (no cleanup, spans leaked, locks simply released), a
successor is rebuilt over the same persister + agent + inventory —
the production failover path — and the harness drives cycles until
the plan converges, then asserts the invariants split-brain-free
failover promises: the plan completes, no chip is double-reserved, no
task is orphaned, and no step that was COMPLETE before the kill runs
again.

Deterministic: kills fire at exact occurrence counts of exact kinds;
``ChaosMatrix`` derives its schedule from a seed recorded in every
report, so a failing combination replays from the log line alone.

The five hand-wired kinds above are the HISTORY; the durcheck
analyzer (``analysis dur --points``) now emits the full persistence-
point map — every WAL/store/property/persister/delete boundary it
discovered statically — and ``AutoChaosMatrix`` turns each one into a
crash-injection point: a ``PersisterCrashProxy`` wraps the harness
persister, stack-matches every mutation against the map (marking
coverage), and dies immediately BEFORE the targeted mutation — the
crash window ``dur-effect-before-wal`` reasons about.  A coverage
probe run first separates reachable boundaries from unreachable
ones; unreachable boundaries are REPORTED in the result, never
silently skipped, so the map stays probe-verified.
"""

from __future__ import annotations

import os
import random
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
from dcos_commons_tpu.scheduler.builder import SchedulerBuilder
from dcos_commons_tpu.scheduler.config import SchedulerConfig
from dcos_commons_tpu.scheduler.scheduler import DefaultScheduler
from dcos_commons_tpu.specification.yaml_spec import from_yaml
from dcos_commons_tpu.storage import MemPersister
from dcos_commons_tpu.testing.fake_agent import FakeAgent

# the five span-boundary kinds DefaultScheduler exposes via
# _chaos_point (keep in lockstep with the call sites there and in
# ha/rehydrate.PlanCheckpointer)
CHAOS_KINDS = (
    "post-evaluate",
    "post-wal",
    "mid-status-fan-in",
    "mid-plan-transition",
    "mid-checkpoint-prune",
)


class SchedulerKilled(BaseException):
    """Raised by a CrashInjector: the scheduler 'process' died here.
    A ``BaseException`` on purpose — it models SIGKILL, and a
    catch-all ``except Exception`` telemetry guard (health observe,
    journal flush) must not be able to 'survive' process death."""

    def __init__(self, kind: str, occurrence: int):
        super().__init__(f"chaos kill at {kind} (occurrence {occurrence})")
        self.kind = kind
        self.occurrence = occurrence


@dataclass(frozen=True)
class KillPoint:
    """Die at the Nth time ``kind`` is reached (1-based)."""

    kind: str
    occurrence: int = 1

    def __post_init__(self):
        if self.kind not in CHAOS_KINDS:
            raise ValueError(
                f"unknown chaos kind {self.kind!r}; expected one of "
                f"{CHAOS_KINDS}"
            )


class CrashInjector:
    """Installed as ``scheduler.chaos``; counts hits per kind and
    raises once at the scheduled point."""

    def __init__(self, point: KillPoint):
        self.point = point
        self.hits: Dict[str, int] = {}
        self.fired = False

    def __call__(self, kind: str) -> None:
        self.hits[kind] = self.hits.get(kind, 0) + 1
        if (not self.fired and kind == self.point.kind
                and self.hits[kind] == self.point.occurrence):
            self.fired = True
            raise SchedulerKilled(kind, self.point.occurrence)


@dataclass
class ChaosReport:
    """One kill-and-converge run's observable outcome."""

    kill: Optional[KillPoint]
    seed: int = 0
    killed: bool = False
    incarnations: int = 1
    cycles: int = 0
    converged: bool = False
    # persisted view at the moment of death
    prekill_complete_steps: List[Tuple[str, str, str]] = field(
        default_factory=list
    )
    prekill_task_ids: Dict[str, str] = field(default_factory=dict)
    prekill_staging_ids: Dict[str, str] = field(default_factory=dict)
    # successor's first-cycle WAL replay
    rehydration: Optional[dict] = None
    # converged view
    final_task_ids: Dict[str, str] = field(default_factory=dict)

    def describe(self) -> str:
        kill = (f"{self.kill.kind}#{self.kill.occurrence}"
                if self.kill else "none")
        return (
            f"chaos[kill={kill} seed={self.seed} killed={self.killed} "
            f"incarnations={self.incarnations} cycles={self.cycles} "
            f"converged={self.converged} rehydration={self.rehydration}]"
        )


# a control pod that deploys (and completes) BEFORE the gang, so every
# kill during the gang's rollout has a completed step to regress — the
# no-completed-step-re-run invariant needs one to exist
CHAOS_GANG_YAML = """
name: chaossvc
pods:
  ctl:
    count: 1
    tasks:
      server:
        goal: RUNNING
        cmd: "{cmd}"
        cpus: 0.5
        memory: 64
  trainer:
    count: 4
    gang: true
    tpu:
      generation: v5e
      chips-per-host: 4
      topology: 4x4
    tasks:
      worker:
        goal: RUNNING
        cmd: "{cmd}"
        cpus: 1.0
        memory: 256
"""


# the multi-slice storm target (ISSUE 20): one gang spanning two
# 4x4 slices over DCN, elastic so a whole-slice loss can shrink the
# dcn axis instead of waiting for capacity that never returns
CHAOS_MULTISLICE_YAML = """
name: chaossvc
pods:
  ctl:
    count: 1
    tasks:
      server:
        goal: RUNNING
        cmd: "{cmd}"
        cpus: 0.5
        memory: 64
  trainer:
    count: 8
    gang: true
    tpu:
      generation: v5e
      chips-per-host: 4
      topology: 4x4
      slices: 2
      elastic: true
      min-hosts: 4
    tasks:
      worker:
        goal: RUNNING
        cmd: "{cmd}"
        cpus: 1.0
        memory: 256
"""


def chaos_fleet() -> List[TpuHost]:
    from dcos_commons_tpu.offer.inventory import make_test_fleet

    return make_test_fleet(host_grid=(2, 2), chip_block=(2, 2),
                           cpus=16.0, memory_mb=65536)


class ChaosHarness:
    """Drive one service through deploy, killing and restarting the
    scheduler at a chosen point.

    Two agent modes share every other code path:

    * ``workdir=None`` — a ``FakeAgent``; the harness acks launches
      RUNNING between cycles.  Fast and fully deterministic: the
      tier-1 single-kill tests run here.
    * ``workdir=<dir>`` — a real ``LocalProcessAgent`` launching real
      task processes that SURVIVE scheduler death (durable-task
      semantics), exactly like a production failover.  The chaos-tier
      matrix runs here.
    """

    def __init__(
        self,
        yaml_text: Optional[str] = None,
        hosts: Optional[List[TpuHost]] = None,
        workdir: Optional[str] = None,
        seed: int = 0,
        task_cmd: str = "sleep 120",
    ):
        yaml_text = (yaml_text or CHAOS_GANG_YAML).replace(
            "{cmd}", task_cmd
        )
        self.spec = from_yaml(yaml_text)
        self.hosts = hosts if hosts is not None else chaos_fleet()
        self.seed = seed
        self.persister = MemPersister()
        self.inventory = SliceInventory(self.hosts)
        self.config = SchedulerConfig(
            backoff_enabled=False, revive_capacity=10**9
        )
        self.local_mode = workdir is not None
        if self.local_mode:
            from dcos_commons_tpu.agent.local import LocalProcessAgent

            self.agent = LocalProcessAgent(workdir)
        else:
            self.agent = FakeAgent()
            self._acked: set = set()
        self.scheduler: Optional[DefaultScheduler] = None

    # -- lifecycle ----------------------------------------------------

    def build_scheduler(self) -> DefaultScheduler:
        builder = SchedulerBuilder(self.spec, self.config, self.persister)
        builder.set_inventory(self.inventory)
        builder.set_agent(self.agent)
        self.scheduler = builder.build()
        return self.scheduler

    def shutdown(self) -> None:
        """Kill surviving task processes (local mode) — durable tasks
        outlive every scheduler incarnation by design."""
        shutdown = getattr(self.agent, "shutdown", None)
        if callable(shutdown):
            shutdown()

    # -- the kill-and-converge loop -----------------------------------

    def _ack_fake_launches(self) -> None:
        for info in list(self.agent.launched):
            if info.task_id not in self._acked:
                self._acked.add(info.task_id)
                self.agent.send(TaskStatus(
                    task_id=info.task_id, state=TaskState.RUNNING,
                    ready=True, agent_id=info.agent_id,
                ))

    def _snapshot_persisted(self, report: ChaosReport) -> None:
        """The successor's whole world: what the STORE says at death."""
        from dcos_commons_tpu.state.state_store import StateStore

        store = StateStore(self.persister)
        statuses = store.fetch_statuses()
        for name, status in statuses.items():
            if status.state is TaskState.STAGING:
                report.prekill_staging_ids[name] = status.task_id
            elif not status.state.is_terminal:
                report.prekill_task_ids[name] = status.task_id

    def _snapshot_plans(self, scheduler, report: ChaosReport) -> None:
        for plan_name, plan in scheduler.plans().items():
            for phase in plan.phases:
                for step in phase.steps:
                    if step.get_status().is_complete:
                        report.prekill_complete_steps.append(
                            (plan_name, phase.name, step.name)
                        )

    def run(
        self,
        kill: Optional[KillPoint],
        timeout_s: float = 60.0,
        settle_s: float = 0.02,
    ) -> ChaosReport:
        """Deploy to completion, dying once at ``kill`` (when given).
        Raises on non-convergence; requesting a kill that never fires
        is an error too (a silently-skipped matrix entry would read as
        coverage)."""
        report = ChaosReport(kill=kill, seed=self.seed)
        scheduler = self.scheduler or self.build_scheduler()
        if kill is not None:
            scheduler.chaos = CrashInjector(kill)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                scheduler.run_cycle()
            except SchedulerKilled:
                # the 'process' died: snapshot the persisted world the
                # successor inherits, abandon the corpse (no cleanup —
                # that is the point), and fail over
                report.killed = True
                self._snapshot_plans(scheduler, report)
                self._snapshot_persisted(report)
                scheduler = self.build_scheduler()  # successor
                report.incarnations += 1
                continue
            report.cycles += 1
            if report.killed and report.rehydration is None:
                report.rehydration = scheduler.last_rehydration
            if not self.local_mode:
                self._ack_fake_launches()
            if scheduler.deploy_manager.get_plan().is_complete:
                report.converged = True
                break
            if self.local_mode:
                time.sleep(settle_s)  # real processes need wall time
        if kill is not None and not report.killed:
            raise AssertionError(
                f"kill point {kill} never fired: {report.describe()}"
            )
        for info in scheduler.state_store.fetch_tasks():
            report.final_task_ids[info.name] = info.task_id
        self.assert_invariants(scheduler, report)
        return report

    # -- the failover invariants --------------------------------------

    def assert_invariants(self, scheduler, report: ChaosReport) -> None:
        describe = report.describe()
        assert report.converged, f"plan never converged: {describe}"

        # 1. no double reservation: every chip claimed at most once,
        #    and every reservation is owned by a stored task
        claimed: Dict[tuple, str] = {}
        stored_names = {
            info.name for info in scheduler.state_store.fetch_tasks()
        }
        for reservation in scheduler.ledger.all():
            assert reservation.task_name in stored_names, (
                f"reservation {reservation.reservation_id} owned by "
                f"unknown task {reservation.task_name}: {describe}"
            )
            for chip in reservation.chip_ids:
                key = (reservation.host_id, chip)
                assert key not in claimed or \
                    claimed[key] == reservation.reservation_id, (
                        f"chip {key} double-reserved: {describe}"
                    )
                claimed[key] = reservation.reservation_id
        if report.rehydration is not None:
            assert report.rehydration["double_reservations"] == 0, describe

        # 2. no orphaned task: agent reality == store reality
        stored_ids = {
            info.task_id
            for info in scheduler.state_store.fetch_tasks()
        }
        active = scheduler.agent.active_task_ids()
        assert active <= stored_ids, (
            f"orphaned agent tasks {active - stored_ids}: {describe}"
        )
        # ...and every live stored task is actually running somewhere
        for name, status in scheduler.state_store.fetch_statuses().items():
            if status.state is TaskState.RUNNING:
                assert status.task_id in active, (
                    f"store believes {name} runs as {status.task_id} "
                    f"but no agent does: {describe}"
                )

        # 3. no completed step re-ran: tasks of steps COMPLETE before
        #    the kill keep their task ids through the failover
        for plan_name, phase_name, step_name in \
                report.prekill_complete_steps:
            plan = scheduler.plan(plan_name)
            if plan is None:
                continue  # deploy renamed to update across restart
            step = plan.step(phase_name, step_name)
            assert step is not None and step.get_status().is_complete, (
                f"step {plan_name}/{phase_name}/{step_name} was "
                f"COMPLETE before the kill but is "
                f"{step.get_status() if step else 'GONE'} after: "
                f"{describe}"
            )
        for name, task_id in report.prekill_task_ids.items():
            final = report.final_task_ids.get(name)
            assert final == task_id, (
                f"running task {name} was re-launched across the "
                f"failover ({task_id} -> {final}): {describe}"
            )

        # 4. WAL consistency: every stored info has a status for ITS id
        for info in scheduler.state_store.fetch_tasks():
            status = scheduler.state_store.fetch_status(info.name)
            assert status is not None and \
                status.task_id == info.task_id, (
                    f"WAL'd task {info.name} has no status for its "
                    f"launch: {describe}"
                )

    # -- auto-derived boundary runs (durcheck persistence points) -----

    def run_boundary(
        self,
        proxy: "PersisterCrashProxy",
        timeout_s: float = 60.0,
        settle_s: float = 0.02,
    ) -> "BoundaryReport":
        """Like ``run``, but the killer is a ``PersisterCrashProxy``
        already installed as ``self.persister`` instead of a span-kind
        injector.  Differences the boundary semantics force:

        * ``build_scheduler`` runs INSIDE the try — rehydrate and
          builder mutations cross persistence boundaries too, and a
          targeted boundary may only be reachable there.
        * at death the report additionally records the **unWAL'd
          effects**: agent-active task ids the store has no record of.
          Zero for the healthy scheduler at every boundary (the proxy
          dies BEFORE the mutation, so the crash window is maximal) —
          nonzero exactly when an effect escaped ahead of its WAL,
          which is what the seeded-bug fixture demonstrates.

        With ``proxy.target`` None this is the coverage probe: a
        healthy converging run that marks every boundary the harness
        actually crosses in ``proxy.covered``."""
        from dcos_commons_tpu.state.state_store import StateStore

        report = ChaosReport(kill=None, seed=self.seed)
        boundary = BoundaryReport(point=proxy.target, report=report)
        scheduler = self.scheduler
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                if scheduler is None:
                    scheduler = self.build_scheduler()
                scheduler.run_cycle()
            except SchedulerKilled:
                report.killed = True
                if scheduler is not None:
                    self._snapshot_plans(scheduler, report)
                self._snapshot_persisted(report)
                stored_ids = {
                    info.task_id
                    for info in StateStore(self.persister).fetch_tasks()
                }
                active = set(self.agent.active_task_ids())
                boundary.unwald_at_death = sorted(active - stored_ids)
                scheduler = None  # successor rebuilt inside the try
                # Mesos-style status reconciliation: the successor asks
                # the agent to re-send task state, so a status consumed
                # but killed before its store write is re-delivered
                # (span-kind kills never die mid-status, so ``run``
                # does not need this).
                self._acked.clear()
                report.incarnations += 1
                continue
            report.cycles += 1
            if report.killed and report.rehydration is None:
                report.rehydration = scheduler.last_rehydration
            if not self.local_mode:
                self._ack_fake_launches()
            if scheduler.deploy_manager.get_plan().is_complete:
                report.converged = True
                # a targeted boundary may belong to a wall-clock
                # periodic writer (health journal flushes): keep the
                # converged world running until the kill fires, so a
                # point the probe reached is never lost to deploy-vs-
                # interval jitter.  The deadline still bounds a target
                # that genuinely cannot fire.
                if proxy.target is None or report.killed:
                    break
            if self.local_mode:
                time.sleep(settle_s)
        if proxy.target is not None and not report.killed:
            raise AssertionError(
                f"auto boundary {proxy.target} never fired: "
                f"{report.describe()}"
            )
        for info in scheduler.state_store.fetch_tasks():
            report.final_task_ids[info.name] = info.task_id
        self.assert_invariants(scheduler, report)
        return boundary


# -- host-level preemption storms (ISSUE 13) --------------------------

# synthetic triggers beyond the span-boundary kinds:
#   STORM_START      immediately after the healthy deploy completes —
#                    the storm's initiating loss (span boundaries only
#                    fire while the scheduler has work, so the FIRST
#                    preemption cannot ride one)
#   RECOVERY_ACTIVE  the first cycle boundary where the recovery plan
#                    holds incomplete work — the storm-within-recovery
#                    case (a second host dies while the first loss's
#                    gang recovery plan is mid-flight)
STORM_START = "start"
RECOVERY_ACTIVE = "recovery-active"


@dataclass(frozen=True)
class PreemptSpec:
    """Preempt ``hosts`` gang-carrying hosts when ``at`` fires for the
    ``occurrence``-th time.  ``at`` is a span-boundary kind from
    CHAOS_KINDS (the preemption lands MID-CYCLE, exactly where a
    cloud reclaim would; counting starts once the storm is armed,
    post-deploy), STORM_START, or RECOVERY_ACTIVE.
    ``kill_scheduler`` also crashes the scheduler at the same
    boundary — preemption and failover composed.

    ``whole_slice`` reinterprets ``hosts`` as a SLICE count (ISSUE
    20): each victim is one entire slice of a multi-slice gang —
    every host in the slice dies physically, statuses never arrive —
    the cloud-reclaim unit a dcn-spanning gang actually loses."""

    at: str = STORM_START
    occurrence: int = 1
    hosts: int = 1
    kill_scheduler: bool = False
    whole_slice: bool = False

    def __post_init__(self):
        allowed = CHAOS_KINDS + (STORM_START, RECOVERY_ACTIVE)
        if self.at not in allowed:
            raise ValueError(
                f"unknown preemption trigger {self.at!r}; expected one "
                f"of {allowed}"
            )


@dataclass
class StormReport:
    specs: Tuple[PreemptSpec, ...]
    seed: int = 0
    preempted: List[str] = field(default_factory=list)
    incarnations: int = 1
    cycles: int = 0
    converged: bool = False
    recoveries_seen: int = 0
    final_task_ids: Dict[str, str] = field(default_factory=dict)
    final_hosts: Dict[str, str] = field(default_factory=dict)

    def describe(self) -> str:
        return (
            f"storm[specs={list(self.specs)} seed={self.seed} "
            f"preempted={self.preempted} "
            f"incarnations={self.incarnations} cycles={self.cycles} "
            f"converged={self.converged}]"
        )


class _StormInjector:
    """Installed as ``scheduler.chaos``: at the scheduled span
    boundary it PHYSICALLY preempts K gang hosts (agent processes die
    silently, inventory marks the capacity gone) — and optionally
    raises SchedulerKilled on top.  Detection (the LOST synthesis) is
    deliberately NOT done here: it happens at the next cycle boundary
    through the same verb path production uses, so the window where
    the store still believes in dead tasks is part of the test."""

    def __init__(self, storm: "PreemptionStorm",
                 specs: List[PreemptSpec]):
        self.storm = storm
        self.specs = list(specs)
        self.hits: Dict[str, int] = {}

    def __call__(self, kind: str) -> None:
        self.hits[kind] = self.hits.get(kind, 0) + 1
        fired = [
            spec for spec in self.specs
            if spec.at == kind and self.hits[kind] == spec.occurrence
        ]
        for spec in fired:
            self.specs.remove(spec)
            self.storm.preempt_now(
                spec.hosts, whole_slice=spec.whole_slice
            )
            if spec.kill_scheduler:
                raise SchedulerKilled(kind, spec.occurrence)


# three slices so a 4-host gang can re-place twice (the storm's
# second preemption lands on the replacement slice)
def storm_fleet(slices: int = 3) -> List[TpuHost]:
    from dcos_commons_tpu.offer.inventory import make_test_fleet

    hosts: List[TpuHost] = []
    for i in range(slices):
        hosts += make_test_fleet(
            f"pod-{i}", host_grid=(2, 2), chip_block=(2, 2),
            cpus=16.0, memory_mb=65536,
        )
    return hosts


class PreemptionStorm:
    """Deploy a gang, storm it with host preemptions at chosen span
    boundaries (optionally composed with scheduler kills), converge,
    and assert the gang-recovery invariants: zero double-reservations,
    zero reservations left on preempted hosts, and EXACTLY ONE gang
    incarnation running at the end (every older launch's processes
    dead, every current task adopted by exactly one agent process).
    FakeAgent mode — fast and deterministic, tier-1-runnable."""

    def __init__(
        self,
        specs: List[PreemptSpec],
        yaml_text: Optional[str] = None,
        hosts: Optional[List[TpuHost]] = None,
        seed: int = 0,
        gang_pod: str = "trainer",
    ):
        self.specs = list(specs)
        self.gang_pod = gang_pod
        self.harness = ChaosHarness(
            yaml_text=yaml_text or CHAOS_GANG_YAML,
            hosts=hosts if hosts is not None else storm_fleet(),
            seed=seed,
        )
        self.agent = self.harness.agent
        self.scheduler: Optional[DefaultScheduler] = None
        self.report = StormReport(specs=tuple(self.specs), seed=seed)
        # preempted but not yet surfaced to the scheduler (the
        # detection gap between the physical loss and the verb)
        self._unnotified: set = set()
        self._acked: set = set()

    # -- injector callbacks -------------------------------------------

    def preempt_now(self, k: int, whole_slice: bool = False) -> None:
        """Physically preempt up to ``k`` gang-carrying hosts NOW.

        ``whole_slice`` reinterprets ``k`` as a count of SLICES: each
        victim slice loses EVERY host (gang-carrying or not) — the
        reclaim granularity a multi-slice gang sees when a provider
        takes back one slice of its dcn span."""
        scheduler = self.scheduler
        assert scheduler is not None
        by_host: Dict[str, int] = {}
        for info in scheduler.state_store.fetch_tasks():
            if info.pod_type == self.gang_pod:
                by_host[info.agent_id] = by_host.get(info.agent_id, 0) + 1
        victims = [
            h for h in sorted(by_host)
            if scheduler.inventory.host_state(h) != "preempted"
        ]
        if whole_slice:
            dead_slices: List[str] = []
            for h in victims:
                host = scheduler.inventory.host(h)
                sid = host.slice_id if host is not None else ""
                if sid and sid not in dead_slices:
                    dead_slices.append(sid)
            dead_slices = dead_slices[:k]
            victims = sorted(
                h.host_id for h in scheduler.inventory.hosts()
                if h.slice_id in dead_slices
                and scheduler.inventory.host_state(h.host_id)
                != "preempted"
            )
        else:
            victims = victims[:k]
        for host_id in victims:
            self.agent.fail_host(host_id)
            scheduler.inventory.set_preempted(host_id)
            self.report.preempted.append(host_id)
            self._unnotified.add(host_id)

    # -- the storm loop -----------------------------------------------

    def _gang_task_names(self, scheduler) -> List[str]:
        pod = scheduler.spec.pod(self.gang_pod)
        return [
            f"{pod.type}-{i}-{t.name}"
            for i in range(pod.count)
            for t in pod.tasks
        ]

    def _ack_staging(self, scheduler) -> None:
        for info in list(self.agent.launched):
            if info.task_id in self._acked:
                continue
            if info.task_id not in self.agent.active_task_ids():
                continue  # preempted before it could report
            status = scheduler.state_store.fetch_status(info.name)
            if status is not None and status.task_id == info.task_id \
                    and status.state is TaskState.STAGING:
                self._acked.add(info.task_id)
                self.agent.send(TaskStatus(
                    task_id=info.task_id, state=TaskState.RUNNING,
                    ready=True, agent_id=info.agent_id,
                ))

    def _recovery_in_flight(self, scheduler) -> bool:
        plan = scheduler.plan("recovery")
        return plan is not None and bool(plan.phases) \
            and not plan.is_complete

    def _gang_converged(self, scheduler) -> bool:
        if self._recovery_in_flight(scheduler):
            return False
        active = scheduler.agent.active_task_ids()
        names = self._gang_task_names(scheduler)
        seen = 0
        for name in names:
            info = scheduler.state_store.fetch_task(name)
            if info is None:
                continue  # trimmed by an elastic shrink
            status = scheduler.state_store.fetch_status(name)
            if status is None or status.task_id != info.task_id or \
                    status.state is not TaskState.RUNNING or \
                    info.task_id not in active:
                return False
            seen += 1
        return seen > 0

    def run(self, timeout_s: float = 60.0) -> StormReport:
        scheduler = self.harness.build_scheduler()
        self.scheduler = scheduler
        report = self.report
        deadline = time.monotonic() + timeout_s
        # phase 1: the healthy deploy, chaos-free — the storm hits a
        # RUNNING gang, not a rollout
        while time.monotonic() < deadline:
            scheduler.run_cycle()
            report.cycles += 1
            self._ack_staging(scheduler)
            if scheduler.deploy_manager.get_plan().is_complete:
                break
        assert scheduler.deploy_manager.get_plan().is_complete, (
            f"deploy never completed before the storm: "
            f"{report.describe()}"
        )
        # phase 2: arm the storm.  Span-boundary occurrence counting
        # starts HERE, so `post-evaluate occurrence 1` means the first
        # post-evaluate the storm's own recovery work causes.
        injector = _StormInjector(
            self,
            [s for s in self.specs
             if s.at not in (RECOVERY_ACTIVE, STORM_START)],
        )
        recovery_specs = [
            s for s in self.specs if s.at == RECOVERY_ACTIVE
        ]
        recovery_hits = 0
        scheduler.chaos = injector
        for spec in [s for s in self.specs if s.at == STORM_START]:
            self.preempt_now(spec.hosts, whole_slice=spec.whole_slice)
            if spec.kill_scheduler:
                report.incarnations += 1
                scheduler = self.harness.build_scheduler()
                self.scheduler = scheduler
                scheduler.chaos = injector
        while time.monotonic() < deadline:
            try:
                scheduler.run_cycle()
                report.cycles += 1
                # detection: surface physical preemptions through the
                # production verb path (stamp + LOST + gang recovery).
                # Inside the try: the verb routes statuses through the
                # same span boundaries, so a kill_scheduler spec can
                # fire HERE too — that is a real failover timing
                for host_id in sorted(self._unnotified):
                    # discard AFTER the verb completes: a scheduler
                    # kill mid-verb leaves the host unnotified and
                    # the successor repeats the (idempotent) verb
                    scheduler.note_host_preempted(host_id)
                    self._unnotified.discard(host_id)
                if recovery_specs and self._recovery_in_flight(scheduler):
                    recovery_hits += 1
                    fired = [
                        s for s in recovery_specs
                        if s.occurrence == recovery_hits
                    ]
                    for spec in fired:
                        recovery_specs.remove(spec)
                        self.preempt_now(
                            spec.hosts, whole_slice=spec.whole_slice
                        )
            except SchedulerKilled:
                # failover composed with the preemption: successor
                # over the same persister + inventory + agent
                report.incarnations += 1
                scheduler = self.harness.build_scheduler()
                self.scheduler = scheduler
                scheduler.chaos = injector
                continue
            if self._recovery_in_flight(scheduler):
                report.recoveries_seen += 1
            self._ack_staging(scheduler)
            if not injector.specs and not recovery_specs and \
                    not self._unnotified and \
                    scheduler.deploy_manager.get_plan().is_complete and \
                    self._gang_converged(scheduler):
                report.converged = True
                break
        if injector.specs or recovery_specs:
            raise AssertionError(
                f"preemption trigger(s) never fired: "
                f"{injector.specs + recovery_specs}: {report.describe()}"
            )
        for info in scheduler.state_store.fetch_tasks():
            report.final_task_ids[info.name] = info.task_id
            report.final_hosts[info.name] = info.agent_id
        self.assert_invariants(scheduler, report)
        return report

    # -- the preemption invariants ------------------------------------

    def assert_invariants(self, scheduler, report: StormReport) -> None:
        describe = report.describe()
        assert report.converged, f"storm never converged: {describe}"

        # 1. no reservation survives on a preempted host, and no chip
        #    is claimed twice anywhere (the re-slice was clean)
        claimed: Dict[tuple, str] = {}
        for reservation in scheduler.ledger.all():
            assert reservation.host_id not in report.preempted, (
                f"reservation {reservation.reservation_id} orphaned on "
                f"preempted host {reservation.host_id}: {describe}"
            )
            for chip in reservation.chip_ids:
                key = (reservation.host_id, chip)
                assert key not in claimed, (
                    f"chip {key} double-reserved: {describe}"
                )
                claimed[key] = reservation.reservation_id

        # 2. exactly ONE gang incarnation is running: every stored
        #    gang task's CURRENT id is alive on the agent, and no id
        #    from any older gang launch survives anywhere
        active = scheduler.agent.active_task_ids()
        current_ids = set()
        for name in self._gang_task_names(scheduler):
            info = scheduler.state_store.fetch_task(name)
            if info is None:
                continue  # elastically trimmed
            current_ids.add(info.task_id)
            assert info.task_id in active, (
                f"{name} has no live process: {describe}"
            )
            assert info.agent_id not in report.preempted, (
                f"{name} placed on preempted host {info.agent_id}: "
                f"{describe}"
            )
        stale = {
            launched.task_id
            for launched in self.agent.launched
            if launched.pod_type == self.gang_pod
            and launched.task_id not in current_ids
        }
        assert not (stale & active), (
            f"zombie gang incarnation still running: "
            f"{sorted(stale & active)}: {describe}"
        )

        # 3. torus adjacency held: a single-slice gang landed in ONE
        #    slice (find_subslice's contract; trivially true for the
        #    elastic-shrunk gang too)
        slices = {
            scheduler.inventory.host(h).slice_id
            for h in set(report.final_hosts.values())
            if scheduler.inventory.host(h) is not None
        }
        pod = scheduler.spec.pod(self.gang_pod)
        if pod.tpu is not None and pod.tpu.topology and pod.tpu.slices == 1:
            gang_hosts = {
                host for name, host in report.final_hosts.items()
                if name.startswith(f"{self.gang_pod}-")
            }
            gang_slices = {
                scheduler.inventory.host(h).slice_id
                for h in gang_hosts
                if scheduler.inventory.host(h) is not None
            }
            assert len(gang_slices) <= 1, (
                f"gang split across slices {sorted(gang_slices)}: "
                f"{describe}"
            )
        elif pod.tpu is not None and pod.tpu.slices > 1:
            # multi-slice convergence (ISSUE 20): the surviving gang
            # is either the FULL dcn span re-placed or a whole-slice
            # shrink of it — each surviving sub-slice is complete
            # (hosts-per-slice workers, one slice each), no worker
            # sits on a dead slice, and the stored width is a clean
            # slice multiple (the dp x dcn batch axes resharded
            # evenly; a ragged width would mean a torn sub-gang)
            hps = max(1, pod.count // pod.tpu.slices)
            by_slice: Dict[str, int] = {}
            stored = 0
            for name, host in report.final_hosts.items():
                if not name.startswith(f"{self.gang_pod}-"):
                    continue
                if scheduler.state_store.fetch_task(name) is None:
                    continue  # trimmed by the whole-slice shrink
                stored += 1
                h = scheduler.inventory.host(host)
                assert h is not None, (
                    f"{name} on unknown host {host}: {describe}"
                )
                by_slice[h.slice_id] = by_slice.get(h.slice_id, 0) + 1
            assert stored and stored % hps == 0 and stored <= pod.count, (
                f"gang width {stored} is not a whole-slice multiple of "
                f"{hps} (full {pod.count}): {describe}"
            )
            assert len(by_slice) == stored // hps and \
                all(n == hps for n in by_slice.values()), (
                    f"torn sub-slice layout {by_slice} for width "
                    f"{stored}: {describe}"
                )
        del slices

        # 4. the WAL/status consistency the chaos harness promises
        for info in scheduler.state_store.fetch_tasks():
            status = scheduler.state_store.fetch_status(info.name)
            assert status is not None and \
                status.task_id == info.task_id, (
                    f"task {info.name} has no status for its launch: "
                    f"{describe}"
                )

    def shutdown(self) -> None:
        self.harness.shutdown()


class ChaosMatrix:
    """The full kill matrix: every kind x a set of occurrences, run
    order shuffled by ``seed`` (recorded in every report so failures
    replay: CHAOS_SEED=<seed> reruns the identical schedule)."""

    def __init__(self, occurrences: Tuple[int, ...] = (1, 2),
                 seed: int = 0):
        self.seed = seed
        schedule = [
            KillPoint(kind, occurrence)
            for kind in CHAOS_KINDS
            for occurrence in occurrences
        ]
        random.Random(seed).shuffle(schedule)
        self.schedule = schedule

    def run(self, harness_factory, timeout_s: float = 60.0) -> List[ChaosReport]:
        """``harness_factory(seed) -> ChaosHarness`` builds a FRESH
        world per kill point (kills must not compound)."""
        reports = []
        for point in self.schedule:
            harness = harness_factory(self.seed)
            try:
                reports.append(harness.run(point, timeout_s=timeout_s))
            finally:
                harness.shutdown()
        return reports


# -- auto-derived chaos points (durcheck persistence-point map) -------

# the point kinds a persister-level crash proxy can actually observe:
# everything that crosses the persister write API.  Journal appends
# are buffered record writes (the flush's store_property is the
# durability boundary), checkpoints ride a store_property too, and
# file writes bypass the persister entirely — excluding them keeps
# statically-unprobeable kinds out of the probe set, so 'unreached'
# means "this persister boundary was not exercised", never "this kind
# is invisible by construction".
AUTO_CHAOS_KINDS = ("wal", "store", "property", "persister", "delete")


def point_key(point: Dict[str, object]) -> Tuple[str, int, str]:
    """Stable identity of a persistence point across runs."""
    return (str(point["file"]), int(point["line"]), str(point["kind"]))


def auto_chaos_points(root: Optional[str] = None) -> List[Dict[str, object]]:
    """The statically discovered crash-injection candidates: the
    durcheck persistence-point map filtered to persister-crossing
    kinds (cached in durcheck, so every harness in a session shares
    one AST pass)."""
    from dcos_commons_tpu.analysis.durcheck import persistence_point_map

    return [
        point for point in persistence_point_map(root)
        if point["kind"] in AUTO_CHAOS_KINDS
    ]


class PersisterCrashProxy:
    """Wraps the harness persister; every mutation is stack-matched
    against the persistence-point map.  Each matching frame marks
    that point covered (one ``store.store_launch`` call covers both
    the state-store apply site and the scheduler's recorder line —
    every boundary on the stack IS at its crash window).  When the
    designated ``target`` point appears on the stack for the
    ``occurrence``-th time, the proxy raises ``SchedulerKilled``
    BEFORE delegating — crash-before-mutation, the maximal window
    ``dur-effect-before-wal`` reasons about — then disarms so the
    successor converges.  Reads delegate untouched."""

    _MUTATORS = ("set", "apply", "recursive_delete", "clear_all_data")

    def __init__(
        self,
        inner,
        points: List[Dict[str, object]],
        target: Optional[Dict[str, object]] = None,
        occurrence: int = 1,
    ):
        self._inner = inner
        self._points = points
        self.target = target
        self._target_key = point_key(target) if target else None
        self._occurrence = occurrence
        self._hits = 0
        self.fired = False
        self.covered: Set[Tuple[str, int, str]] = set()

    def _observe(self) -> None:
        on_target = False
        frame = sys._getframe(2)
        while frame is not None:
            fname = frame.f_code.co_filename.replace(os.sep, "/")
            lineno = frame.f_lineno
            for point in self._points:
                if fname.endswith(str(point["file"])) and \
                        int(point["line"]) <= lineno <= \
                        int(point["end_line"]):
                    key = point_key(point)
                    self.covered.add(key)
                    if key == self._target_key:
                        on_target = True
            frame = frame.f_back
        if on_target and not self.fired:
            self._hits += 1
            if self._hits >= self._occurrence:
                self.fired = True
                target = self.target
                raise SchedulerKilled(
                    f"auto:{target['file']}:{target['line']}"
                    f":{target['kind']}",
                    self._hits,
                )

    def set(self, *args, **kwargs):
        self._observe()
        return self._inner.set(*args, **kwargs)

    def apply(self, *args, **kwargs):
        self._observe()
        return self._inner.apply(*args, **kwargs)

    def recursive_delete(self, *args, **kwargs):
        self._observe()
        return self._inner.recursive_delete(*args, **kwargs)

    def clear_all_data(self, *args, **kwargs):
        self._observe()
        return self._inner.clear_all_data(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._inner, name)


@dataclass
class BoundaryReport:
    """One auto-derived boundary run: the targeted point (None for
    the coverage probe), the underlying kill-and-converge report, and
    the unWAL'd effects observed at the moment of death."""

    point: Optional[Dict[str, object]]
    report: ChaosReport
    unwald_at_death: List[str] = field(default_factory=list)


@dataclass
class AutoChaosResult:
    """The auto-derived matrix outcome.  ``unreached`` is DATA, not a
    skip: every statically discovered boundary the harness could not
    cross is accounted here, and the integration test pins the set —
    a new unreachable boundary is a finding someone must explain."""

    seed: int
    all_points: List[Dict[str, object]] = field(default_factory=list)
    reached: List[Dict[str, object]] = field(default_factory=list)
    unreached: List[Dict[str, object]] = field(default_factory=list)
    targeted: List[Dict[str, object]] = field(default_factory=list)
    reports: List[BoundaryReport] = field(default_factory=list)

    def describe(self) -> str:
        return (
            f"auto-chaos[seed={self.seed} "
            f"points={len(self.all_points)} "
            f"reached={len(self.reached)} "
            f"unreached={len(self.unreached)} "
            f"crashed={len(self.reports)}]"
        )


class AutoChaosMatrix:
    """The statically derived kill matrix: one uninjected coverage
    probe separates reachable boundaries from unreachable ones, then
    a seed-shuffled budgeted subset of the REACHED points each get a
    fresh-world crash run (CI budget discipline: the full reached set
    is usually larger than one tier can afford; the seed is recorded
    so a failing subset replays exactly)."""

    def __init__(
        self,
        seed: int = 0,
        budget: int = 6,
        root: Optional[str] = None,
    ):
        self.seed = seed
        self.budget = budget
        self.points = auto_chaos_points(root)

    def run(self, harness_factory,
            timeout_s: float = 60.0) -> AutoChaosResult:
        """``harness_factory(seed) -> ChaosHarness`` builds a FRESH
        world per boundary, exactly like ``ChaosMatrix.run``."""
        result = AutoChaosResult(seed=self.seed, all_points=self.points)
        # 1. coverage probe: healthy run, no injection — which of the
        #    statically discovered boundaries does this world cross?
        harness = harness_factory(self.seed)
        probe = PersisterCrashProxy(harness.persister, self.points)
        harness.persister = probe
        try:
            harness.run_boundary(probe, timeout_s=timeout_s)
        finally:
            harness.shutdown()
        reached_keys = set(probe.covered)
        result.reached = [
            p for p in self.points if point_key(p) in reached_keys
        ]
        result.unreached = [
            p for p in self.points if point_key(p) not in reached_keys
        ]
        # 2. seeded budgeted subset of reached boundaries: crash runs
        targeted = list(result.reached)
        random.Random(self.seed).shuffle(targeted)
        result.targeted = targeted[: self.budget]
        for point in result.targeted:
            harness = harness_factory(self.seed)
            proxy = PersisterCrashProxy(
                harness.persister, self.points, target=point
            )
            harness.persister = proxy
            try:
                result.reports.append(
                    harness.run_boundary(proxy, timeout_s=timeout_s)
                )
            finally:
                harness.shutdown()
        return result
