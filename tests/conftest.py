"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh so multi-chip sharding
(dp/tp/sp) is exercised without TPU hardware, mirroring how the
reference tests multi-node scheduling without a Mesos cluster
(reference: sdk/testing/ServiceTestRunner.java runs the full scheduler
against MemPersister + a mocked driver).
"""

import os

# force CPU even when a real TPU is attached: tests exercise sharding
# on the virtual mesh; bench.py is what runs on the chip.  The env var
# alone is not enough — this image's sitecustomize re-selects the TPU
# platform at import, so flip the jax config after import too.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: jax-compiling or multi-process e2e (seconds to minutes); "
        "run the fast tier with -m 'not slow' (docs/testing.md)",
    )
    config.addinivalue_line(
        "markers",
        "chaos: scheduler kill-matrix runs (testing/chaos.py) — real "
        "task processes, one run per kill point; always also marked "
        "slow so tier-1's -m 'not slow' skips them; select with "
        "-m chaos, replay failures with CHAOS_SEED=<seed> "
        "(docs/testing.md)",
    )


# whole modules that are inherently heavy: every test either compiles
# a jax model or spawns scheduler/agent processes.  Mixed files mark
# their heavy tests individually with @pytest.mark.slow.
_SLOW_FILES = {
    "test_serve.py",            # process-level scheduler e2e
    "test_workload.py",         # model training (jax compiles)
    "test_decode.py",           # KV-cache inference (jax compiles)
    "test_soak.py",             # event-loop churn soak
    "test_parallel_pp_ep.py",   # sharded training (jax compiles)
    "test_serve_inference.py",  # real serve_worker processes
    "test_data.py",             # device prefetch (jax)
    "test_provisioning.py",     # warm-cache subprocesses
}


def pytest_collection_modifyitems(config, items):
    import pytest

    for item in items:
        if os.path.basename(str(item.fspath)) in _SLOW_FILES:
            item.add_marker(pytest.mark.slow)


# -- sdklint race checker (opt-in, SDKLINT_RACECHECK=1) ---------------
#
# Instruments threading.Lock/RLock/Condition and Thread.start/join for
# the whole session and fails the run if (a) the observed lock-nesting
# graph contains a cycle (deadlock risk) or (b) the vector-clock
# checker saw two unordered writes to a watched attribute (data race).
# SDKLINT_LOCKCHECK=1 is kept as a back-compat alias for the same
# switch.  tests/test_scheduler_e2e.py and tests/test_multi_service.py
# additionally enable the cycle check per-test regardless of the env
# var; the threaded modules (continuous batching, migration, HA
# failover, health, replication) add per-module write probes via
# racecheck_watch_guard().

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _sdklint_racecheck_session():
    from dcos_commons_tpu.analysis import racecheck

    if not racecheck.env_requested():
        yield
        return
    racecheck.install()
    yield
    report = racecheck.report()
    racecheck.unwatch_types()
    racecheck.uninstall()
    assert not report.cycles, report.describe()
    assert not report.races, report.describe()


def lockcheck_guard():
    """Shared body for the per-test lock-order fixtures in
    tests/test_scheduler_e2e.py and tests/test_multi_service.py
    (``yield from lockcheck_guard()``): install, run the test, fail it
    on any lock-order cycle.  Coexists with the session checker above
    — when that is active, the accumulated cross-test graph is left
    intact (no reset/uninstall)."""
    from dcos_commons_tpu.analysis import racecheck

    already = racecheck.is_enabled()
    racecheck.install()
    if not already:
        racecheck.reset()
    yield
    report = racecheck.report()
    if not already:
        racecheck.uninstall()
    assert not report.cycles, report.describe()


def racecheck_watch_guard(*classes):
    """Shared body for the per-module write-probe fixtures in the
    threaded test modules (``yield from racecheck_watch_guard(Cls,
    ...)``): when SDKLINT_RACECHECK=1 (or the legacy alias) is set,
    watch every attribute the static pass reports as cross-thread
    shared on the given classes, run the module's tests, and fail on
    any unordered write pair.  A no-op when the env var is unset so the
    fast tier pays nothing."""
    from dcos_commons_tpu.analysis import racecheck

    if not racecheck.env_requested():
        yield
        return
    import os as _os

    root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    shared = racecheck.shared_write_map(root)
    for cls in classes:
        attrs = shared.get(cls.__name__)
        if attrs:
            racecheck.watch_type(cls, attrs)
    yield
    # session fixture asserts on the accumulated report at exit; probes
    # stay installed so later modules of the same run keep their watch.
