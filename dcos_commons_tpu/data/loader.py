"""TPU-native training data pipeline: sharded token files -> device.

The task brief's IO component (the reference has no data plane at
all): a complete training framework needs tokens flowing onto the
chip without the train step ever waiting on the host.  Design:

* **Shard files** are raw little-endian int32 token streams
  (``<name>.tokens``), memory-mapped (np.memmap) — no parse step, the
  OS page cache is the read buffer, and a 100GB corpus costs no RSS.
* **Deterministic host sharding**: shard FILES distribute round-robin
  over (worker_id, worker_count) — the scheduler's gang env contract —
  so multi-host pods read disjoint data with no coordination, and a
  PERMANENT replacement re-reads exactly its predecessor's shards.
* **Stateless addressing**: batch ``i`` of epoch ``e`` is a pure
  function of (seed, e, i) — resume from a checkpoint step means
  seeking, not replaying; no loader state needs checkpointing beyond
  the step counter the trainer already saves.
* **Device prefetch**: a background thread stages the NEXT batches to
  the device (``jax.device_put``, or sharded via ``jax.make_array_
  from_process_local_data`` when a sharding is given) while the
  current step computes — the standard double-buffer recipe; depth 2
  hides host memcpy + PCIe/DMA under the MXU work.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Iterator, List, Optional, Tuple

import numpy as np

TOKEN_DTYPE = np.int32
SUFFIX = ".tokens"


def write_token_shard(path: str, tokens) -> None:
    """Write one shard file (tooling/test helper)."""
    arr = np.asarray(tokens, TOKEN_DTYPE)
    with open(path, "wb") as f:
        f.write(arr.tobytes())


def list_shards(data_dir: str) -> List[str]:
    return sorted(
        os.path.join(data_dir, name)
        for name in os.listdir(data_dir)
        if name.endswith(SUFFIX)
    )


class TokenDataset:
    """Memory-mapped view over this worker's shard files.

    ``worker_id``/``worker_count`` follow the scheduler's gang env
    contract; shard files round-robin over workers.  Sequences of
    ``seq_len + 1`` tokens are cut from each shard (input/target
    overlap by one), addressed deterministically by (seed, epoch, i).
    """

    def __init__(
        self,
        data_dir: str,
        seq_len: int,
        worker_id: int = 0,
        worker_count: int = 1,
        seed: int = 0,
    ):
        if worker_count < 1 or not (0 <= worker_id < worker_count):
            raise ValueError(f"bad worker {worker_id}/{worker_count}")
        shards = list_shards(data_dir)
        if not shards:
            raise FileNotFoundError(f"no *{SUFFIX} shards in {data_dir}")
        mine = shards[worker_id::worker_count]
        if not mine:
            raise ValueError(
                f"{len(shards)} shard(s) cannot feed worker "
                f"{worker_id}/{worker_count}; add shards or shrink the gang"
            )
        self.seq_len = seq_len
        self.seed = seed
        self._maps = [
            np.memmap(path, TOKEN_DTYPE, mode="r") for path in mine
        ]
        window = seq_len + 1
        self._per_shard = [len(m) // window for m in self._maps]
        self.n_sequences = sum(self._per_shard)
        if self.n_sequences == 0:
            raise ValueError(
                f"shards shorter than seq_len+1={window}: {mine}"
            )
        # flat index -> (shard, within-shard offset), built once
        self._shard_of = np.repeat(
            np.arange(len(self._maps)), self._per_shard
        )
        self._base = np.concatenate([
            np.arange(n) for n in self._per_shard
        ])

    def sequence(self, index: int) -> np.ndarray:
        """The index-th (seq_len + 1)-token window."""
        index = int(index) % self.n_sequences
        shard = int(self._shard_of[index])
        offset = int(self._base[index]) * (self.seq_len + 1)
        return np.asarray(
            self._maps[shard][offset: offset + self.seq_len + 1]
        )

    def _order(self, epoch: int) -> np.ndarray:
        rng = np.random.default_rng((self.seed, epoch))
        return rng.permutation(self.n_sequences)

    def batches(
        self, batch_size: int, start_step: int = 0
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Infinite (tokens, targets) [batch, seq_len] stream.

        Deterministic in (seed, start_step): resuming from a trainer
        checkpoint at step N means ``batches(b, start_step=N)`` — the
        stream continues exactly where the dead incarnation left off,
        reshuffling per epoch.
        """
        per_epoch = max(self.n_sequences // batch_size, 1)
        step = start_step
        # the O(n) epoch permutation is computed once PER EPOCH, not
        # per batch — at corpus scale a per-step reshuffle would
        # dominate the memmap reads and defeat the prefetch buffer
        order_epoch, order = -1, None
        while True:
            epoch, within = divmod(step, per_epoch)
            if epoch != order_epoch:
                order_epoch, order = epoch, self._order(epoch)
            rows = [
                self.sequence(order[(within * batch_size + j)
                                    % self.n_sequences])
                for j in range(batch_size)
            ]
            block = np.stack(rows)
            yield block[:, :-1].copy(), block[:, 1:].copy()
            step += 1


class DevicePrefetcher:
    """Double-buffer host batches onto the device.

    Wraps any (tokens, targets) numpy iterator; a daemon thread stays
    ``depth`` batches ahead so the train step never waits on host IO.
    With a ``sharding``, arrays are placed as global sharded arrays
    from this process's local data (multi-host dp); otherwise a plain
    ``device_put``.
    """

    def __init__(self, it, depth: int = 2, sharding=None):
        import jax

        self._jax = jax
        self._sharding = sharding
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()

        def put_or_stop(item) -> None:
            # every enqueue respects close(): an unbounded put would
            # leave the pump thread (and its staged device batches)
            # blocked forever when the consumer stops early
            while not self._stop.is_set():
                try:
                    self._queue.put(item, timeout=0.2)
                    return
                except queue.Full:
                    continue

        def pump():
            try:
                for host_batch in it:
                    put_or_stop(tuple(
                        self._place(arr) for arr in host_batch
                    ))
                    if self._stop.is_set():
                        return
                # normal exhaustion (finite eval sets): the sentinel
                # with no error becomes StopIteration, not a deadlock
                put_or_stop(None)
            except BaseException as e:  # surfaced on next __next__
                self._error = e
                put_or_stop(None)

        self._thread = threading.Thread(
            target=pump, name="data-prefetch", daemon=True
        )
        self._thread.start()

    def _place(self, arr: np.ndarray):
        if self._sharding is not None:
            return self._jax.make_array_from_process_local_data(
                self._sharding, arr
            )
        return self._jax.device_put(arr)

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set():
            # after exhaustion/error/close: keep raising (iterator
            # protocol) instead of blocking on an empty queue forever
            raise (self._error or StopIteration)
        item = self._queue.get()
        if item is None:
            self.close()
            raise (self._error or StopIteration)
        return item

    def close(self) -> None:
        self._stop.set()
