"""Multi-host pjit training worker (BASELINE.json config 4).

One of these runs per host of the gang pod.  It consumes the
scheduler's env contract (COORDINATOR_ADDRESS, TPU_WORKER_ID, ...),
rendezvouses via jax.distributed, builds a dp-over-hosts x tp-within-
host mesh, and trains the flagship transformer with orbax-style
checkpointing so PERMANENT gang recovery resumes from the last step.
"""

import os
import sys
import time

sys.path.insert(0, os.environ.get("REPO_ROOT", "/root/repo"))


def main() -> int:
    from dcos_commons_tpu.parallel.distributed import initialize_from_env

    contract = initialize_from_env()

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    from dcos_commons_tpu.models import (
        config_from_env,
        init_params,
        make_train_step,
    )
    from dcos_commons_tpu.parallel.mesh import mesh_from_env
    from dcos_commons_tpu.trace.steplog import StepLog
    from dcos_commons_tpu.utils import (
        enable_compilation_cache,
        restore_checkpoint,
        save_checkpoint,
        synthetic_tokens,
    )

    # a recovered/replaced gang worker re-jits the identical train
    # step; the persistent cache turns that into a disk read
    enable_compilation_cache()

    steps = int(os.environ.get("TRAIN_STEPS", "100"))
    ckpt_dir = os.environ.get("CHECKPOINT_DIR", "checkpoints")
    # per-step telemetry into $SANDBOX/steplog.jsonl: the scheduler's
    # /v1/debug/trace merges every host's lane into one timeline, so
    # gang skew (who waited on whom) is read off the blocked_s column.
    # The barrier probe is a gang-wide sync BEFORE each step's first
    # collective; its wall time on the fast hosts IS the skew the slow
    # host imposed.  STEPLOG_BARRIER_PROBE=0 drops the probe (and the
    # skew column) when even a barrier per step is too much.
    steplog = StepLog()
    probe_gang = os.environ.get("STEPLOG_BARRIER_PROBE", "1") not in (
        "0", "false"
    )
    mesh = mesh_from_env(os.environ)
    # the env->config contract lives in models/transformer.py so
    # analysis/shardcheck verifies the EXACT model this pod trains
    config = config_from_env(os.environ, dtype=jnp.bfloat16)
    optimizer = optax.adamw(3e-4)
    with mesh:
        params = init_params(config, jax.random.key(0))
        opt_state = optimizer.init(params)
        # checkpoint carries params AND optimizer moments; its stamp is
        # the next step to run, so resume never double-applies a step
        state = {"params": params, "opt_state": opt_state}
        state, start = restore_checkpoint(ckpt_dir, state)
        params, opt_state = state["params"], state["opt_state"]
        start = start or 0
        if contract["worker_count"] > 1:
            # the checkpoint stamp came off LOCAL disk: if one host's
            # sandbox holds step 80 and another's holds step 100, the
            # training loops disagree on the trip count and the gang
            # deadlocks in the shorter host's last allreduce
            # (spmdcheck: spmd-per-host-trip-count).  Agree up front
            # and fail the deploy loudly on divergence — recovery
            # relaunches the gang, which beats a silent hang.
            from jax.experimental import multihost_utils

            starts = multihost_utils.process_allgather(jnp.int32(start))
            if int(starts.min()) != int(starts.max()):
                raise RuntimeError(
                    "checkpoint step diverges across the gang: "
                    f"{sorted(int(s) for s in starts)}; wipe the stale "
                    "sandboxes or restore a shared CHECKPOINT_DIR"
                )
            start = int(starts[0])
        step_fn = make_train_step(config, optimizer, mesh=mesh, donate=False)
        batch = max(2, 2 * mesh.devices.size)
        data_dir = os.environ.get("DATA_DIR", "")
        batches = None
        if data_dir:
            # real corpus: memory-mapped token shards round-robin over
            # the gang (disjoint per worker), device-prefetched; the
            # stream is a pure function of (seed, step) so checkpoint
            # resume continues EXACTLY where the dead incarnation left
            from jax.sharding import NamedSharding

            from dcos_commons_tpu.data import DevicePrefetcher, TokenDataset
            from dcos_commons_tpu.parallel.mesh import batch_spec

            dataset = TokenDataset(
                data_dir, config.max_seq,
                worker_id=contract["worker_id"],
                worker_count=contract["worker_count"],
            )
            # batches must land SHARDED like the train step expects
            # (each process's distinct batch is its dp slice of the
            # global batch) — a plain device_put would fight the jit's
            # in_shardings on any multi-device mesh.  Each process
            # therefore yields its SHARE of the global batch: feeding
            # `batch` rows per process would silently train at
            # batch x worker_count (JAX infers global = local x procs)
            local_rows = max(1, batch // contract["worker_count"])
            batches = DevicePrefetcher(
                dataset.batches(local_rows, start_step=start), depth=2,
                sharding=NamedSharding(mesh, batch_spec()),
            )
            print(
                f"data: {dataset.n_sequences} sequences for worker "
                f"{contract['worker_id']}", flush=True,
            )
        else:
            tokens, targets = synthetic_tokens(
                jax.random.key(1), batch, config.max_seq, config.vocab
            )
        gang = contract["worker_count"] > 1
        if gang and probe_gang:
            from jax.experimental import multihost_utils
        t0 = time.time()
        for i in range(start, steps):
            step_t0 = time.time()
            blocked_s = 0.0
            if gang and probe_gang:
                # pre-allreduce barrier probe: meet the gang before
                # this step's first collective; time spent here is
                # time BLOCKED on slower hosts, not compute
                b0 = time.time()
                multihost_utils.sync_global_devices(f"steplog-{i}")
                blocked_s = time.time() - b0
            if batches is not None:
                tokens, targets = next(batches)
            params, opt_state, loss = step_fn(params, opt_state, tokens, targets)
            # drain the step before stamping: jit dispatch returns
            # immediately, so an unsynced wall_s would be dispatch
            # time, and the NEXT step's barrier probe would absorb
            # this step's compute and report it as gang skew
            jax.block_until_ready(loss)
            steplog.record(
                i,
                wall_s=round(time.time() - step_t0, 6),
                tokens=tokens.shape[0] * tokens.shape[1],
                blocked_s=round(blocked_s, 6),
                worker=contract["worker_id"],
            )
            if i % 20 == 0 or i == steps - 1:
                print(f"step {i} loss={float(loss):.4f}", flush=True)
                save_checkpoint(
                    ckpt_dir, i + 1,
                    {"params": params, "opt_state": opt_state},
                    # bound the directory: a long run would otherwise
                    # grow it by ~3 bytes/param per save forever
                    keep=int(os.environ.get("CHECKPOINT_KEEP", "3")),
                )
        steplog.close()
        if batches is not None:
            batches.close()
        dt = time.time() - t0
        tps = batch * config.max_seq * (steps - start) / max(dt, 1e-9)
        print(
            f"worker {contract['worker_id']}/{contract['worker_count']}: "
            f"{steps - start} steps, {tps:,.0f} tokens/s", flush=True,
        )
    # goal RUNNING: stay alive serving the mesh until the scheduler
    # kills or reconfigures the pod
    keepalive = os.environ.get("KEEPALIVE_S")
    if keepalive:
        time.sleep(float(keepalive))
    else:
        while True:
            time.sleep(60)


if __name__ == "__main__":
    sys.exit(main() or 0)
