"""Artifact ``uris:`` — YAML mapping, agent fetch, e2e sandbox proof.

Reference: uri.yml (frameworks/helloworld/src/main/dist/uri.yml:8,37)
mapped at specification/yaml/YAMLToInternalMappers.java:397, fetched
into the sandbox before the task command runs.  TPU additions tested
here: sha256 digest pinning + the per-host artifact cache (a fleet
stages the same corpus on every host; relaunches must not re-download
gigabytes), tar extraction with hostile-archive rejection, and the
rule that the cluster bearer token is never sent to artifact hosts.
"""

import hashlib
import io
import os
import tarfile

import pytest

from dcos_commons_tpu.agent.local import install_uris, stage_uris
from dcos_commons_tpu.specification import UriSpec, from_yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- YAML mapping -----------------------------------------------------


URI_YAML = """
name: urisvc
pods:
  app:
    count: 1
    uris:
      - "https://repo.example/base.bin"
      - uri: "https://repo.example/shared.bin"
        dest: data/shared.bin
    tasks:
      server:
        goal: RUNNING
        cmd: "sleep 1"
        cpus: 0.1
        memory: 32
        uris:
          - uri: "https://repo.example/corpus.tar"
            dest: data/corpus.tar
            sha256: abc123
            extract: true
          - uri: "https://repo.example/tool"
            executable: true
      sidecar:
        goal: ONCE
        cmd: "sleep 1"
        cpus: 0.1
        memory: 32
        uris:
          - uri: "https://other.example/base.bin"
            dest: base.bin
"""


def test_yaml_maps_pod_and_task_uris():
    """String + mapping forms parse; pod-level uris merge into every
    task; task-level declarations win on dest clashes."""
    spec = from_yaml(URI_YAML)
    server = spec.pod("app").task("server")
    dests = {u.effective_dest(): u for u in server.uris}
    assert set(dests) == {
        "base.bin", "data/shared.bin", "data/corpus.tar", "tool",
    }
    assert dests["data/corpus.tar"].sha256 == "abc123"
    assert dests["data/corpus.tar"].extract is True
    assert dests["tool"].executable is True
    # sidecar declared its own base.bin: the pod-level one must not
    # clobber it
    sidecar = spec.pod("app").task("sidecar")
    base = [u for u in sidecar.uris if u.effective_dest() == "base.bin"]
    assert len(base) == 1 and base[0].uri == "https://other.example/base.bin"
    # round-trip through the config store form
    from dcos_commons_tpu.specification import ServiceSpec

    assert ServiceSpec.from_dict(spec.to_dict()) == spec


def test_helloworld_uri_yaml_parses_and_ships_in_launch():
    """The feature-matrix YAML parses, and the scheduler ships uris
    entries with the launch request (FakeAgent records them)."""
    from dcos_commons_tpu.testing import (
        AdvanceCycles,
        ExpectLaunchedTasks,
        ServiceTestRunner,
    )

    with open(os.path.join(REPO, "frameworks/helloworld/uri.yml")) as f:
        text = f.read()
    runner = ServiceTestRunner(
        text, env={"CORPUS_SHA256": "dd" * 32}
    )
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
    ])
    agent = runner.world.agent
    task_id = agent.task_id_of("hello-0-server")
    uris = agent.launch_uris[task_id]
    assert {u["dest"] for u in uris} == {"README.md", "data/corpus.bin"}
    pinned = [u for u in uris if u["dest"] == "data/corpus.bin"][0]
    assert pinned["sha256"] == "dd" * 32


# -- agent fetch/install ----------------------------------------------


def entry(uri, **kw):
    base = {"uri": uri, "dest": "", "sha256": "",
            "extract": False, "executable": False}
    base.update(kw)
    return base


def test_stage_and_install_file_uri(tmp_path):
    src = tmp_path / "artifact.txt"
    src.write_bytes(b"payload")
    sandbox = tmp_path / "sandbox"
    sandbox.mkdir()
    staged = stage_uris(
        [entry(src.as_uri(), dest="data/artifact.txt")],
        cache_dir=str(tmp_path / "cache"),
    )
    install_uris(str(sandbox), staged)
    assert (sandbox / "data/artifact.txt").read_bytes() == b"payload"


def test_digest_pin_and_cache(tmp_path):
    src = tmp_path / "corpus.bin"
    src.write_bytes(b"x" * 1000)
    digest = hashlib.sha256(b"x" * 1000).hexdigest()
    cache = tmp_path / "cache"
    sandbox = tmp_path / "sb"
    sandbox.mkdir()
    e = entry(src.as_uri(), dest="corpus.bin", sha256=digest)
    install_uris(str(sandbox), stage_uris([e], cache_dir=str(cache)))
    assert (cache / digest).exists()
    # the source disappears (host offline): the cache serves relaunch
    src.unlink()
    (sandbox / "corpus.bin").unlink()
    install_uris(str(sandbox), stage_uris([e], cache_dir=str(cache)))
    assert (sandbox / "corpus.bin").read_bytes() == b"x" * 1000
    # a corrupted cache entry is detected and refetched (source still
    # gone -> the fetch fails loudly rather than serving bad bytes)
    (cache / digest).write_bytes(b"tampered")
    with pytest.raises(Exception):
        stage_uris([e], cache_dir=str(cache))


def test_digest_mismatch_refuses(tmp_path):
    src = tmp_path / "a.bin"
    src.write_bytes(b"unexpected")
    with pytest.raises(ValueError, match="digest mismatch"):
        stage_uris(
            [entry(src.as_uri(), dest="a.bin", sha256="ab" * 32)],
            cache_dir=str(tmp_path / "cache"),
        )


def test_install_rejects_traversal_and_hostile_archive(tmp_path):
    src = tmp_path / "a.bin"
    src.write_bytes(b"data")
    sandbox = tmp_path / "sb"
    sandbox.mkdir()
    staged = stage_uris(
        [entry(src.as_uri(), dest="../escape.bin")],
        cache_dir=str(tmp_path / "cache"),
    )
    with pytest.raises(ValueError, match="escapes the sandbox"):
        install_uris(str(sandbox), staged)
    # archive whose member climbs out of the sandbox
    evil = tmp_path / "evil.tar"
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        info = tarfile.TarInfo("../../evil.txt")
        info.size = 4
        tar.addfile(info, io.BytesIO(b"evil"))
    evil.write_bytes(buf.getvalue())
    staged = stage_uris(
        [entry(evil.as_uri(), dest="evil.tar", extract=True)],
        cache_dir=str(tmp_path / "cache"),
    )
    with pytest.raises(ValueError, match="escapes the sandbox"):
        install_uris(str(tmp_path / "sb2"), staged)


def test_extract_and_executable(tmp_path):
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        info = tarfile.TarInfo("inner/data.txt")
        info.size = 5
        tar.addfile(info, io.BytesIO(b"hello"))
    archive = tmp_path / "bundle.tgz"
    archive.write_bytes(buf.getvalue())
    tool = tmp_path / "tool.sh"
    tool.write_bytes(b"#!/bin/sh\necho hi\n")
    sandbox = tmp_path / "sb"
    sandbox.mkdir()
    staged = stage_uris(
        [
            entry(archive.as_uri(), dest="pkg/bundle.tgz", extract=True),
            entry(tool.as_uri(), dest="tool.sh", executable=True),
        ],
        cache_dir=str(tmp_path / "cache"),
    )
    install_uris(str(sandbox), staged)
    assert (sandbox / "pkg/inner/data.txt").read_bytes() == b"hello"
    assert os.access(sandbox / "tool.sh", os.X_OK)


def test_unpinned_uris_never_cached(tmp_path):
    """A mutable URL must be fetched fresh every launch."""
    src = tmp_path / "mutable.txt"
    src.write_bytes(b"v1")
    cache = tmp_path / "cache"
    sandbox = tmp_path / "sb"
    sandbox.mkdir()
    e = entry(src.as_uri(), dest="mutable.txt")
    install_uris(str(sandbox), stage_uris([e], cache_dir=str(cache)))
    src.write_bytes(b"v2")
    install_uris(str(sandbox), stage_uris([e], cache_dir=str(cache)))
    assert (sandbox / "mutable.txt").read_bytes() == b"v2"
    # nothing lingers in the cache dir for unpinned fetches
    assert [p for p in os.listdir(cache) if not p.startswith(".")] == []


def test_effective_dest_derivation():
    assert UriSpec(uri="https://x/y/artifact.bin").effective_dest() == \
        "artifact.bin"
    assert UriSpec(
        uri="https://x/pkg.tar?sig=abc"
    ).effective_dest() == "pkg.tar"
    assert UriSpec(uri="https://x/a", dest="b/c").effective_dest() == "b/c"


# -- e2e: real agent fetches into a real sandbox ----------------------


@pytest.mark.slow
def test_e2e_artifact_lands_in_sandbox(tmp_path):
    """Served scheduler + real agent daemon: the task command READS
    the fetched artifact, so TASK_RUNNING proves the fetch-before-
    launch ordering; the file is then verified on disk."""
    from dcos_commons_tpu.testing.integration import (
        AgentProcess,
        SchedulerProcess,
        reap_orphan_tasks,
    )

    artifact = tmp_path / "model.txt"
    artifact.write_bytes(b"weights")
    digest = hashlib.sha256(b"weights").hexdigest()
    agents = [AgentProcess("h0", str(tmp_path / "agent-0"), REPO)]
    sched = None
    try:
        svc = tmp_path / "svc.yml"
        svc.write_text(f"""
name: urisvc
pods:
  app:
    count: 1
    tasks:
      server:
        goal: RUNNING
        cmd: "cat fetched/model.txt && sleep 120"
        cpus: 0.1
        memory: 32
        uris:
          - uri: "{artifact.as_uri()}"
            dest: fetched/model.txt
            sha256: {digest}
""")
        topology = tmp_path / "topology.yml"
        topology.write_text(
            "hosts:\n"
            f"  - host_id: h0\n"
            f"    agent_url: {agents[0].url}\n"
            "    cpus: 4.0\n"
            "    memory_mb: 8192\n"
        )
        sched = SchedulerProcess(
            str(svc), str(topology), str(tmp_path / "sched"),
            env={"ENABLE_BACKOFF": "false"}, repo_root=REPO,
        )
        client = sched.client()
        client.wait_for_completed_deployment(timeout_s=60)
        sandbox_file = (
            tmp_path / "agent-0" / "sandboxes" / "app-0-server"
            / "fetched" / "model.txt"
        )
        assert sandbox_file.read_bytes() == b"weights"
        # per-host cache holds the pinned artifact
        cache_file = tmp_path / "agent-0" / "sandboxes" / ".uri-cache" / digest
        assert cache_file.exists()
    finally:
        if sched is not None:
            sched.terminate()
        reap_orphan_tasks(agents)
        for agent in agents:
            agent.stop()
