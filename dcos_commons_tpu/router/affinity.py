"""Prefix affinity: route shared-prefix sessions to the pod that
already holds their cached pages.

PR 11's prefix cache interns fully-prefilled prompt pages under an
exact-match CHAIN key — ``(parent entry, the page's token tuple)``,
page-aligned (serve/paging.py ``register``).  Under fan-out that
cache is per POD: spraying shared-system-prompt traffic round-robin
dilutes every pod's hit rate by 1/N, because each pod re-prefills the
same system prompt from scratch.  The router therefore hashes each
prompt with the SAME chain construction — page-aligned full pages,
each key folded over its parent — and remembers which pod last served
each chain node.  A new request walks its chain deepest-first and
follows the pod holding the longest known prefix; the pods' own
allocators then serve the pages from cache.

The chain key here is structurally identical to the paging intern key
with the allocator-private entry id replaced by the parent's HASH:
two prompts collide exactly when their page-aligned prefixes match,
which is precisely when the pod-side cache would hit.  The map is
bounded LRU — affinity is a HINT, not state: an evicted entry costs
one re-prefill on whatever pod least-loaded picks next, never a
correctness problem.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple


def prefix_chain_keys(
    tokens: Sequence[int], page_tokens: int,
) -> List[int]:
    """The prompt's page-aligned prefix chain, root first: key[i]
    covers full pages ``[0, i]``.  Mirrors ``PageAllocator`` matching:
    only FULL pages participate, and the last page is capped so at
    least one prompt token always prefills privately (a fully-cached
    prompt still needs its final-position forward pass) — so the
    router's deepest key can never claim more than a pod could hit."""
    plen = len(tokens)
    if page_tokens < 1 or plen < 1:
        return []
    limit = (plen - 1) // page_tokens
    keys: List[int] = []
    parent = 0
    for i in range(limit):
        page = tuple(tokens[i * page_tokens:(i + 1) * page_tokens])
        parent = hash((parent, page))
        keys.append(parent)
    return keys


class AffinityMap:
    """Bounded chain-node -> pod map with LRU eviction.

    ``record`` claims a chain for a pod after the router commits a
    request there (deepest nodes recorded too: a later LONGER shared
    prefix extends the claim).  ``lookup`` walks deepest-first and
    returns the first node claimed by a still-offered pod.  ``evict``
    drops every claim on a pod leaving the set (drain/death) — its
    cache died with it, and affinity must not keep steering traffic
    at a corpse."""

    def __init__(self, capacity: int = 65536):
        if capacity < 1:
            raise ValueError(f"affinity map needs capacity >= 1, got "
                             f"{capacity}")
        self._capacity = int(capacity)
        self._claims: "OrderedDict[int, str]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._claims)

    def record(self, keys: Sequence[int], pod: str) -> None:
        for key in keys:
            self._claims[key] = pod
            self._claims.move_to_end(key)
        while len(self._claims) > self._capacity:
            self._claims.popitem(last=False)

    def lookup(self, keys: Sequence[int]) -> Tuple[Optional[str], int]:
        """(pod, matched-depth) for the deepest claimed node; (None,
        0) when no node is claimed.  Touches the hit for LRU."""
        for depth in range(len(keys), 0, -1):
            pod = self._claims.get(keys[depth - 1])
            if pod is not None:
                self._claims.move_to_end(keys[depth - 1])
                return pod, depth
        return None, 0

    def evict_pod(self, pod: str) -> int:
        dead = [k for k, p in self._claims.items() if p == pod]
        for key in dead:
            del self._claims[key]
        return len(dead)

    def repoint(self, keys: Sequence[int], pod: str) -> int:
        """Re-point EXISTING claims on the given chain to ``pod`` —
        the cache-preserving half of migration (ISSUE 16): when a
        session's pages move, the knowledge of where its prefix lives
        moves with them instead of being dropped.  Only nodes already
        claimed move (an unclaimed node carries no knowledge); returns
        how many moved."""
        moved = 0
        for key in keys:
            if key in self._claims:
                self._claims[key] = pod
                self._claims.move_to_end(key)
                moved += 1
        return moved

    def repoint_pod(self, old: str, new: str) -> int:
        """Bulk re-point: every claim on ``old`` now names ``new`` —
        the drain-with-migration path, where the whole cache moved."""
        moved = 0
        for key, pod in self._claims.items():
            if pod == old:
                self._claims[key] = new
                moved += 1
        return moved

    def claims_by_pod(self) -> dict:
        """Claim counts per pod — the hotspot-detection signal: a pod
        holding far more chain claims than its peers is where the
        shared prefixes (and their traffic) concentrate."""
        counts: dict = {}
        for pod in self._claims.values():
            counts[pod] = counts.get(pod, 0) + 1
        return counts
