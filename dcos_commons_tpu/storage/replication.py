"""State-server replication: op log, standby tail, fencing epochs.

Reference: the reference's durability story is a ZooKeeper *ensemble*
behind CuratorPersister (curator/CuratorPersister.java:43-110 — atomic
multi-op transactions against a replicated quorum), so the state
backend itself has no single point of failure.  This module gives the
TPU fleet's StateServer the same property with a primary plus N hot
standbys:

* every mutation the primary applies is appended to a seq-numbered
  **replication log**; each standby tails it over long-poll HTTP
  (``/v1/repl/pull``) and applies entries to its own durable backend
  in order — bootstrap (or divergence repair) is a full-tree
  ``/v1/repl/snapshot``.  Standbys carry independent per-puller
  watermarks: one standby's acks never stand in for another's;
* writes are **bounded-sync**: while standbys are attached and caught
  up, the primary acks a mutation only after EVERY in-sync standby
  has pulled it (so promotion may pick any of them without losing an
  acked write — zero-loss failover in the healthy case); a standby
  that stalls past ``sync_timeout_s`` is marked lagging and writes
  continue (availability over strict sync — the lag is repaired by
  the tail and the scheduler's reconciliation-on-restart covers the
  window);
* failover is an explicit **promotion** (``/v1/repl/promote``) that
  mints a new fencing **epoch** (monotonic, persisted).  Every client
  request carries the highest epoch its sender has seen; a primary
  that receives a token above its own epoch has been superseded and
  **fences itself** (refuses all further writes) — a partitioned
  stale primary cannot split-brain the state tree once any client
  has talked to the new one.  Clients reject servers whose epoch is
  below their high-water mark for the same reason.

The scheduler side needs no new machinery: ``RemotePersister`` takes a
comma-separated server list and rotates to the next server when the
current one is unreachable or not primary, and the (already
lease-driven) scheduler keeps running because leases live IN the
replicated tree.
"""

from __future__ import annotations

import base64
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from dcos_commons_tpu.storage.persister import (
    DeleteOp,
    Persister,
    SetOp,
    TransactionOp,
)

# how long after the last pull a standby still counts as attached
# (long-poll wait below must be shorter, so an idle-but-healthy
# standby re-pulls well within the window)
ATTACH_WINDOW_S = 10.0
# server-side cap on one long-poll
MAX_PULL_WAIT_S = 5.0


def encode_ops(ops: List[TransactionOp]) -> List[dict]:
    """Wire form of a transaction (shared with /v1/kv/apply)."""
    out = []
    for op in ops:
        if isinstance(op, SetOp):
            out.append({
                "op": "set", "path": op.path,
                "value": base64.b64encode(op.value).decode()
                if op.value is not None else None,
            })
        else:
            out.append({"op": "delete", "path": op.path})
    return out


def decode_ops(raw: List[dict]) -> List[TransactionOp]:
    ops: List[TransactionOp] = []
    for item in raw:
        if item["op"] == "set":
            value = item.get("value")
            ops.append(SetOp(
                item["path"],
                base64.b64decode(value) if value is not None else b"",
            ))
        else:
            ops.append(DeleteOp(item["path"]))
    return ops


def dump_tree(persister: Persister) -> List[Tuple[str, Optional[str]]]:
    """Flat [(path, b64-value-or-None)] of the whole tree, for
    snapshot shipping.  Works over any Persister via children/get."""
    out: List[Tuple[str, Optional[str]]] = []

    def walk(path: str) -> None:
        for name in persister.get_children_or_empty(path):
            child = f"{path}/{name}" if path != "/" else f"/{name}"
            value = persister.get_or_none(child)
            out.append((
                child,
                base64.b64encode(value).decode() if value is not None
                else None,
            ))
            walk(child)

    walk("/")
    return out


def restore_tree(
    persister: Persister, nodes: List[Tuple[str, Optional[str]]]
) -> None:
    """Replace the persister's contents with a shipped snapshot."""
    persister.clear_all_data()
    ops = [
        SetOp(path, base64.b64decode(value))
        for path, value in nodes
        if value is not None  # value-less inner nodes re-appear via children
    ]
    if ops:
        persister.apply(ops)


class ReplicationLog:
    """Seq-numbered ring of mutation batches with long-poll + acks.

    The ring is in-memory only: the durable log IS the primary's file
    WAL.  A standby asking for a seq the ring no longer holds (primary
    restarted, or the standby fell too far behind) is told to
    re-snapshot — the same repair path as initial bootstrap.

    N standbys may attach (the ZooKeeper-ensemble analogue is a
    quorum, not a pair): each puller carries its OWN watermark, and
    bounded-sync waits on EVERY attached non-lagging standby — so
    "replicated" means any of them can be promoted without losing an
    acked write.  A standby that stalls is marked lagging (excluded
    from the barrier, repaired by its own tail); one that stops
    pulling past the attach window is pruned entirely.
    """

    def __init__(self, max_entries: int = 8192,
                 sync_timeout_s: float = 2.0):
        import uuid

        self._entries: deque = deque()  # (seq, [op dicts])
        self._cv = threading.Condition()
        self._next_seq = 1
        # puller_id -> {"acked": int, "last_pull": float, "lagging": bool}
        self._pullers: Dict[str, dict] = {}
        self._max_entries = max_entries
        self.sync_timeout_s = sync_timeout_s
        # identifies THIS ring of seq numbers: seqs are only comparable
        # within one stream.  A standby whose persisted applied seq came
        # from a DIFFERENT stream (old primary, pre-promotion life) must
        # re-snapshot even when the raw numbers happen to line up.
        self.stream_id = uuid.uuid4().hex

    def _attached_locked(self, now: float) -> Dict[str, dict]:
        """Live pullers; prunes ones silent past the attach window (a
        dead standby must stop gating the write barrier)."""
        for pid in [
            pid for pid, st in self._pullers.items()
            if now - st["last_pull"] > ATTACH_WINDOW_S
        ]:
            del self._pullers[pid]
        return self._pullers

    # -- primary write path -------------------------------------------

    def append(self, ops_payload: List[dict]) -> int:
        with self._cv:
            seq = self._next_seq
            self._next_seq += 1
            self._entries.append((seq, ops_payload))
            while len(self._entries) > self._max_entries:
                self._entries.popleft()
            self._cv.notify_all()
            return seq

    def wait_replicated(self, seq: int) -> bool:
        """Block until EVERY attached, non-lagging standby has acked
        ``seq`` (the bounded-sync barrier) — all-of, not any-of, so
        promotion may pick ANY in-sync standby without losing an acked
        write.  Returns immediately when no standby is in sync; on
        timeout the stragglers are marked lagging (they repair via
        their own tails and re-earn the barrier by catching up).
        True = replicated to every in-sync standby."""
        deadline = time.monotonic() + self.sync_timeout_s
        with self._cv:
            while True:
                now = time.monotonic()
                live = [
                    st for st in self._attached_locked(now).values()
                    if not st["lagging"]
                ]
                if not live:
                    return False  # nobody in sync to wait for
                pending = [st for st in live if st["acked"] < seq]
                if not pending:
                    return True
                if now >= deadline:
                    for st in pending:
                        st["lagging"] = True
                    return False
                self._cv.wait(timeout=min(0.05, deadline - now))

    # -- standby pull path --------------------------------------------

    def pull(self, from_seq: int, wait_s: float,
             puller_id: str = "", stream_id: str = "") -> dict:
        """Entries at/after ``from_seq``; pulling acks ``from_seq-1``
        for THIS puller.  ``snapshot_needed`` when continuity from
        ``from_seq`` cannot be proven (ring trimmed, a fresh/restarted
        primary, or a seq from another stream).

        Each puller_id owns an independent watermark: a fast standby's
        acks never stand in for a slow one's (promoting the slow one
        after an any-of ack would lose writes the primary reported
        replicated).  A RETURNING puller_id restarts at acked 0 — its
        previous watermark may describe a tree that has since been
        wiped — and re-earns the barrier by pulling."""
        wait_s = max(0.0, min(wait_s, MAX_PULL_WAIT_S))
        deadline = time.monotonic() + wait_s
        with self._cv:
            now = time.monotonic()
            self._attached_locked(now)  # prune the silent
            st = self._pullers.get(puller_id)
            if st is None:
                # fresh attach (new standby, or one returning after a
                # silence prune): LAGGING until its ack reaches the tip
                # — otherwise a newcomer whose from_seq still proves
                # continuity (young primary, log replay from 1) joins
                # the bounded-sync barrier at acked 0 and every live
                # write stalls up to sync_timeout_s while it replays.
                # The standard lagging-clear below flips it in-sync the
                # moment it catches up (same pull, if already at tip).
                st = {"acked": 0, "last_pull": now, "lagging": True}
                self._pullers[puller_id] = st
            st["last_pull"] = now
            if stream_id and stream_id != self.stream_id:
                # the standby's applied seq is from a DIFFERENT ring:
                # acking from it would falsely mark this stream's
                # writes replicated even when the raw numbers line up
                # (a reattaching ex-standby after promotion).  Verified
                # HERE, before the ack — the standby-side check alone
                # runs after the primary has already released
                # wait_replicated() waiters.
                st["lagging"] = True
                return {
                    "snapshot_needed": True,
                    "seq": self._next_seq - 1,
                    "stream_id": self.stream_id,
                }
            first = self._entries[0][0] if self._entries else self._next_seq
            if not (first <= from_seq <= self._next_seq):
                # continuity unproven: the standby is behind this ring
                # (or ahead of a restarted primary).  It must NOT ack
                # anything — a from_seq above the ring would otherwise
                # inflate the watermark and bounded-sync would pass
                # writes the standby never copied.  It IS attached but
                # behind: mark lagging so writers don't block on it
                # while it snapshots.
                st["lagging"] = True
                return {
                    "snapshot_needed": True,
                    "seq": self._next_seq - 1,
                    "stream_id": self.stream_id,
                }
            ack = min(from_seq - 1, self._next_seq - 1)
            if ack < st["acked"]:
                # the puller moved BACKWARDS (a standby with a stable
                # id restarted after wiping its tree): its old
                # watermark no longer describes that tree — drop to
                # what this pull actually proves, re-earn the rest,
                # and leave the barrier while replaying (same rule as
                # a fresh attach: bootstrap never gates live writes)
                st["acked"] = max(ack, 0)
                st["lagging"] = True
            elif ack > st["acked"]:
                st["acked"] = ack
            if st["lagging"] and st["acked"] >= self._next_seq - 1:
                st["lagging"] = False
            self._cv.notify_all()
            while self._next_seq <= from_seq:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=remaining)
                st["last_pull"] = time.monotonic()
            entries = [
                {"seq": seq, "ops": ops}
                for seq, ops in self._entries if seq >= from_seq
            ]
            return {"entries": entries, "stream_id": self.stream_id}

    # -- introspection ------------------------------------------------

    def status(self) -> dict:
        with self._cv:
            now = time.monotonic()
            live = self._attached_locked(now)
            return {
                "seq": self._next_seq - 1,
                # the conservative watermark: everything at or below
                # this has reached EVERY attached standby (lagging
                # ones included — their trees are still behind it)
                "acked_seq": (
                    min(st["acked"] for st in live.values())
                    if live else 0
                ),
                "standby_attached": bool(live),
                "standby_lagging": any(
                    st["lagging"] for st in live.values()
                ),
                "standby_count": len(live),
                "standbys": {
                    pid: {
                        "acked": st["acked"],
                        "lagging": st["lagging"],
                        "age_s": round(now - st["last_pull"], 3),
                    }
                    for pid, st in live.items()
                },
            }

    def reset(self, base_seq: int) -> None:
        """Adopt a seq base after promotion: the new primary's log
        continues where its replica stream left off."""
        import uuid

        with self._cv:
            self._entries.clear()
            self._next_seq = base_seq + 1
            self._pullers.clear()
            # a NEW stream: the promoted server's ring is not the old
            # primary's, even though the seq numbering continues
            self.stream_id = uuid.uuid4().hex


class StandbyTail:
    """The standby's replication client: snapshot, then tail.

    Runs as a daemon thread inside a standby StateServer.  All state
    it writes goes through the standby's own (durable) backend, so a
    standby restart resumes from its persisted applied-seq instead of
    re-snapshotting.  A divergence (an entry that fails to apply) or
    a trimmed ring triggers snapshot repair.
    """

    APPLIED_NODE = "/__cluster__/repl_applied"
    # the stream the applied seq belongs to: seqs from one primary's
    # ring say nothing about another's, so a stream mismatch on pull
    # forces snapshot repair even when the numbers line up
    STREAM_NODE = "/__cluster__/repl_stream"

    def __init__(
        self,
        backend: Persister,
        backend_lock,
        primary_url: str,
        auth_token: str = "",
        ca_file: str = "",
        on_epoch=None,
    ):
        import uuid

        from dcos_commons_tpu.storage.remote import RemotePersister

        self._backend = backend
        self._lock = backend_lock
        # identifies THIS standby to the primary's single-puller guard
        self._standby_id = uuid.uuid4().hex
        # reuse the HTTP plumbing; repl endpoints are server-to-server
        self._client = RemotePersister(
            primary_url, timeout_s=MAX_PULL_WAIT_S + 5.0,
            auth_token=auth_token, ca_file=ca_file,
        )
        self._on_epoch = on_epoch  # callable(int) -> None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_error: str = ""
        self.applied_seq = self._load_applied()
        self.stream_id = (
            self._backend.get_or_none(self.STREAM_NODE) or b""
        ).decode()
        from dcos_commons_tpu.storage.remote import FENCED_NODE

        if self.applied_seq and backend.exists(FENCED_NODE):
            # belt-and-braces vs promote()'s applied-seq reset: a tree
            # that carries a fenced marker lived a primary (or fenced-
            # primary) life after this applied seq was written, so the
            # value no longer describes the tree — bootstrap from a
            # full snapshot instead of resuming the tail
            self.applied_seq = 0

    def _load_applied(self) -> int:
        raw = self._backend.get_or_none(self.APPLIED_NODE)
        try:
            return int((raw or b"0").decode())
        except ValueError:
            return 0

    def start(self) -> "StandbyTail":
        self._thread = threading.Thread(
            target=self._run, name="repl-tail", daemon=True
        )
        self._thread.start()
        return self

    def signal_stop(self) -> None:
        """Non-blocking stop: after this returns no further entry is
        applied (checked under the backend lock), even though the tail
        thread may still be blocked in a long-poll.  Promotion uses
        this so failover latency is not bounded by an in-flight pull
        against a dead primary."""
        self._stop.set()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=MAX_PULL_WAIT_S + 10.0)

    # -- the tail loop ------------------------------------------------

    def _run(self) -> None:
        need_snapshot = self.applied_seq == 0
        while not self._stop.is_set():
            try:
                if need_snapshot:
                    self._snapshot()
                    need_snapshot = False
                out = self._client._call("/v1/repl/pull", {
                    "from_seq": self.applied_seq + 1,
                    "wait_s": MAX_PULL_WAIT_S,
                    "standby_id": self._standby_id,
                    # lets the PRIMARY refuse (and not ack) a seq from
                    # another ring before wait_replicated() passes it
                    "stream_id": self.stream_id,
                })
                if self._stop.is_set():
                    return  # promoted mid-pull: nothing more applies
                self._note_epoch(out)
                stream = out.get("stream_id", "")
                if stream and stream != self.stream_id:
                    # a DIFFERENT ring (repointed standby, restarted
                    # or promoted primary): our applied seq is from
                    # another stream and proves nothing even when the
                    # primary's continuity check happens to pass
                    need_snapshot = True
                    continue
                if out.get("snapshot_needed"):
                    need_snapshot = True
                    continue
                if not self._apply_entries(out.get("entries", [])):
                    need_snapshot = True
                self.last_error = ""
            except Exception as e:  # noqa: BLE001 — keep tailing
                self.last_error = str(e)
                self._stop.wait(0.5)

    def _snapshot(self) -> None:
        out = self._client._call("/v1/repl/snapshot", {})
        self._note_epoch(out)
        with self._lock:
            if self._stop.is_set():
                return
            restore_tree(self._backend, [
                tuple(node) for node in out.get("nodes", [])
            ])
            self.applied_seq = int(out["seq"])
            self.stream_id = out.get("stream_id", "")
            self._store_applied()

    def _apply_entries(self, entries: List[dict]) -> bool:
        """Apply in seq order; False = divergence, re-snapshot."""
        for entry in entries:
            seq = int(entry["seq"])
            if seq <= self.applied_seq:
                continue  # replayed tail of a previous pull
            if seq != self.applied_seq + 1:
                return False  # gap — ring moved under us
            ops = decode_ops(entry["ops"])
            with self._lock:
                if self._stop.is_set():
                    # promote() flips role under this same lock AFTER
                    # signal_stop(): once flipped, a late entry must
                    # never clobber the new primary's writes
                    return True
                try:
                    self._backend.apply(ops)
                except Exception:
                    # a DeleteOp for a path we do not have, etc.: the
                    # trees have diverged — repair from snapshot
                    return False
                self.applied_seq = seq
                self._store_applied()
        return True

    def _store_applied(self) -> None:
        self._backend.apply([
            SetOp(self.APPLIED_NODE, str(self.applied_seq).encode()),
            SetOp(self.STREAM_NODE, self.stream_id.encode()),
        ])

    def _note_epoch(self, out: dict) -> None:
        epoch = out.get("epoch")
        if epoch and self._on_epoch is not None:
            self._on_epoch(int(epoch))

    def status(self) -> Dict[str, object]:
        return {
            "applied_seq": self.applied_seq,
            "last_error": self.last_error,
        }
