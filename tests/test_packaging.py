"""Framework packaging: build -> inspect -> install (the Cosmos flow).

Reference: tools/universe/package_builder.py + Cosmos install;
frameworks/*/universe/ manifests.
"""

import json
import os
import subprocess
import sys
import tarfile
import time
import urllib.request

import pytest

from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.multi import MultiServiceScheduler
from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
from dcos_commons_tpu.scheduler import SchedulerConfig
from dcos_commons_tpu.storage import MemPersister
from dcos_commons_tpu.testing import FakeAgent
from dcos_commons_tpu.tools import (
    PackageError,
    build_package,
    extract_package,
    read_manifest,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_framework(tmp_path, name="pkgsvc"):
    d = tmp_path / name
    d.mkdir()
    (d / "svc.yml").write_text(f"""
name: {name}
pods:
  app:
    count: 1
    tasks:
      main:
        goal: RUNNING
        cmd: "cat app.cfg && sleep 100"
        cpus: 0.1
        memory: 32
        configs:
          cfg:
            template: app.cfg.mustache
            dest: app.cfg
""")
    (d / "app.cfg.mustache").write_text("task={{TASK_NAME}}\n")
    return str(d)


def test_build_inspect_roundtrip(tmp_path):
    framework = make_framework(tmp_path)
    out = str(tmp_path / "pkgsvc.tgz")
    manifest = build_package(framework, out, version="1.2.3")
    assert manifest["name"] == "pkgsvc"
    assert set(manifest["files"]) == {"svc.yml", "app.cfg.mustache"}
    assert read_manifest(out)["version"] == "1.2.3"


def test_extract_verifies_digests(tmp_path):
    framework = make_framework(tmp_path)
    out = str(tmp_path / "pkgsvc.tgz")
    build_package(framework, out)
    with open(out, "rb") as f:
        payload = f.read()
    manifest = extract_package(payload, str(tmp_path / "x"))
    assert (tmp_path / "x" / "svc.yml").exists()
    assert manifest["files"]

    # corrupt a member: digest mismatch must reject the package
    bad_dir = tmp_path / "bad"
    bad_dir.mkdir()
    with tarfile.open(out, "r:gz") as tar:
        tar.extractall(bad_dir, filter="data")
    (bad_dir / "svc.yml").write_text("name: tampered\npods: {}\n")
    bad_out = str(tmp_path / "bad.tgz")
    with tarfile.open(bad_out, "w:gz") as tar:
        for name in ("package.json", "svc.yml", "app.cfg.mustache"):
            tar.add(str(bad_dir / name), arcname=name)
    with open(bad_out, "rb") as f:
        bad_payload = f.read()
    with pytest.raises(PackageError, match="digest"):
        extract_package(bad_payload, str(tmp_path / "y"))


def test_extract_rejects_traversal(tmp_path):
    import io

    evil = io.BytesIO()
    with tarfile.open(fileobj=evil, mode="w:gz") as tar:
        manifest = json.dumps(
            {"name": "evil", "files": {"../escape": "0" * 64,
                                       "svc.yml": "0" * 64}}
        ).encode()
        member = tarfile.TarInfo("package.json")
        member.size = len(manifest)
        tar.addfile(member, io.BytesIO(manifest))
        data = b"pwned"
        member = tarfile.TarInfo("../escape")
        member.size = len(data)
        tar.addfile(member, io.BytesIO(data))
    with pytest.raises(PackageError, match="escape"):
        extract_package(evil.getvalue(), str(tmp_path / "t"))
    assert not (tmp_path / "escape").exists()


def test_install_package_into_multi_scheduler(tmp_path):
    framework = make_framework(tmp_path)
    out = str(tmp_path / "pkgsvc.tgz")
    build_package(framework, out)
    multi = MultiServiceScheduler(
        persister=MemPersister(),
        inventory=SliceInventory([TpuHost(host_id="h0")]),
        agent=FakeAgent(),
        scheduler_config=SchedulerConfig(
            backoff_enabled=False,
            revive_capacity=1_000_000,
            state_dir=str(tmp_path / "state"),
        ),
    )
    with open(out, "rb") as f:
        multi.install_package("pkgsvc", f.read())
    assert "pkgsvc" in multi.service_names()
    # the packaged template resolved to the extracted location
    svc = multi.get_service("pkgsvc")
    template_path = svc.spec.pod("app").task("main").config_templates[0][0]
    assert template_path.startswith(str(tmp_path / "state"))
    assert os.path.isfile(template_path)
    multi.run_cycle()
    agent = multi.agent
    assert agent.task_id_of("app-0-main") is not None
    agent.send(TaskStatus(
        task_id=agent.task_id_of("app-0-main"),
        state=TaskState.RUNNING, ready=True,
    ))
    multi.run_cycle()
    assert svc.deploy_manager.get_plan().is_complete


@pytest.mark.slow
def test_package_cli_build_and_wire_install(tmp_path):
    """CLI build + install against a served --multi scheduler, with
    the packaged config template rendered into the task sandbox."""
    framework = make_framework(tmp_path)
    out = str(tmp_path / "pkgsvc.tgz")
    built = subprocess.run(
        [sys.executable, "-m", "dcos_commons_tpu", "package", "build",
         framework, "-o", out],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert built.returncode == 0, built.stderr
    topology = tmp_path / "topology.yml"
    topology.write_text(
        "hosts:\n  - host_id: h0\n    cpus: 8\n    memory_mb: 8192\n"
    )
    announce = tmp_path / "announce"
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dcos_commons_tpu", "serve", "--multi",
            "--topology", str(topology),
            "--port", "0",
            "--state-dir", str(tmp_path / "state"),
            "--sandbox-root", str(tmp_path / "sbx"),
            "--announce-file", str(announce),
        ],
        cwd=REPO,
    )
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not announce.exists():
            time.sleep(0.1)
        url = announce.read_text().strip()
        installed = subprocess.run(
            [sys.executable, "-m", "dcos_commons_tpu", "package",
             "install", out, "--url", url],
            cwd=REPO, capture_output=True, text=True, timeout=60,
        )
        assert installed.returncode == 0, installed.stderr

        def get(path):
            with urllib.request.urlopen(url + path, timeout=5) as r:
                return json.loads(r.read())

        deadline = time.monotonic() + 60
        done = False
        while time.monotonic() < deadline:
            try:
                if get("/v1/multi/pkgsvc/v1/plans/deploy")["status"] == \
                        "COMPLETE":
                    done = True
                    break
            except Exception:
                pass
            time.sleep(0.3)
        assert done
        rendered = tmp_path / "sbx" / "app-0-main" / "app.cfg"
        assert rendered.read_text().strip() == "task=app-0-main"
    finally:
        proc.terminate()
        proc.wait(timeout=20)


def test_install_rejects_traversal_service_name(tmp_path):
    multi = MultiServiceScheduler(
        persister=MemPersister(),
        inventory=SliceInventory([TpuHost(host_id="h0")]),
        agent=FakeAgent(),
        scheduler_config=SchedulerConfig(
            state_dir=str(tmp_path / "state")
        ),
    )
    from dcos_commons_tpu.specification.specs import SpecError

    framework = make_framework(tmp_path, name="okpkg")
    out = str(tmp_path / "okpkg.tgz")
    build_package(framework, out)
    with open(out, "rb") as f:
        payload = f.read()
    for bad in ("..", ".", "a/b", "", ".hidden"):
        with pytest.raises(SpecError):
            multi.install_package(bad, payload)
    # nothing leaked outside the packages dir
    assert not (tmp_path / "state" / "svc.yml").exists()


def test_package_upgrade_rolls_running_service(tmp_path):
    """Cosmos `update --package-version` analogue: a NEW package
    version pushed to a RUNNING service validates the diff and rolls
    the update plan over live state; without upgrade=True an existing
    name is refused, and upgrading a non-existent service fails."""
    import pytest

    from dcos_commons_tpu.specification.specs import SpecError

    framework = make_framework(tmp_path)
    v1 = str(tmp_path / "v1.tgz")
    build_package(framework, v1)
    multi = MultiServiceScheduler(
        persister=MemPersister(),
        inventory=SliceInventory([TpuHost(host_id="h0")]),
        agent=FakeAgent(),
        scheduler_config=SchedulerConfig(
            backoff_enabled=False,
            revive_capacity=1_000_000,
            state_dir=str(tmp_path / "state"),
        ),
    )
    agent = multi.agent
    with open(v1, "rb") as f:
        payload_v1 = f.read()
    with pytest.raises(SpecError, match="no service"):
        multi.install_package("pkgsvc", payload_v1, upgrade=True)
    multi.install_package("pkgsvc", payload_v1)

    def drive_until_complete():
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            multi.run_cycle()
            for task in ("app-0-main",):
                task_id = agent.task_id_of(task)
                if task_id is not None and task_id in agent.active_task_ids():
                    agent.send(TaskStatus(
                        task_id=task_id, state=TaskState.RUNNING, ready=True,
                    ))
            svc = multi.get_service("pkgsvc")
            plans = svc.plans()
            rollout = plans.get("update") or plans.get("deploy")
            if rollout.is_complete:
                return svc
        raise AssertionError("rollout did not complete")

    svc = drive_until_complete()
    first_id = svc.state_store.fetch_task("app-0-main").task_id
    first_cmd = svc.state_store.fetch_task("app-0-main").command

    # re-push the SAME version without the flag: refused
    with pytest.raises(SpecError, match="already exists"):
        multi.install_package("pkgsvc", payload_v1)

    # version 2 changes the task command -> rolling update
    with open(os.path.join(framework, "svc.yml")) as f:
        yaml_v2 = f.read().replace("sleep 100", "sleep 200")
    with open(os.path.join(framework, "svc.yml"), "w") as f:
        f.write(yaml_v2)
    v2 = str(tmp_path / "v2.tgz")
    build_package(framework, v2, version="0.2.0")
    with open(v2, "rb") as f:
        multi.install_package("pkgsvc", f.read(), upgrade=True)
    svc = drive_until_complete()
    info = svc.state_store.fetch_task("app-0-main")
    assert info.task_id != first_id, "upgrade did not roll the task"
    assert "sleep 200" in info.command and "sleep 200" not in first_cmd


def test_package_upgrade_prunes_superseded_version_dirs(tmp_path):
    """Repeated upgrades must not grow state_dir without bound — but
    the prune keep-set is every version dir a STORED config still
    references (a rejected-diff upgrade keeps the old target's
    templates live on disk), plus the newly-installed target."""
    framework = make_framework(tmp_path)
    v1 = str(tmp_path / "v1.tgz")
    build_package(framework, v1, version="0.1.0")
    multi = MultiServiceScheduler(
        persister=MemPersister(),
        inventory=SliceInventory([TpuHost(host_id="h0")]),
        agent=FakeAgent(),
        scheduler_config=SchedulerConfig(
            backoff_enabled=False,
            revive_capacity=1_000_000,
            state_dir=str(tmp_path / "state"),
        ),
    )
    with open(v1, "rb") as f:
        multi.install_package("pkgsvc", f.read())
    pkg_root = tmp_path / "state" / "packages" / "pkgsvc"

    def version_dirs():
        return sorted(
            d for d in os.listdir(pkg_root) if not d.startswith(".")
        )

    assert len(version_dirs()) == 1
    # push three successive versions; each changes the cmd so the
    # config updater accepts the diff and re-targets
    for n, ver in enumerate(("0.2.0", "0.3.0", "0.4.0"), start=2):
        with open(os.path.join(framework, "svc.yml")) as f:
            yaml_n = f.read().replace(
                f"sleep {(n - 1) * 100}", f"sleep {n * 100}"
            )
        with open(os.path.join(framework, "svc.yml"), "w") as f:
            f.write(yaml_n)
        tgz = str(tmp_path / f"v{n}.tgz")
        build_package(framework, tgz, version=ver)
        with open(tgz, "rb") as f:
            multi.install_package("pkgsvc", f.read(), upgrade=True)
    dirs = version_dirs()
    # the new target always survives
    assert any(d.startswith("0.4.0-") for d in dirs), dirs
    # superseded dirs whose configs nothing references are gone:
    # never more than the stored-config fan-out (target + prior
    # config that still holds the pre-roll tasks)
    assert len(dirs) <= 3, dirs
    assert not any(d.startswith("0.1.0-") for d in dirs), (
        "v0.1.0 dir should have been pruned: %s" % dirs
    )
    # the dirs every stored config references are all still present
    svc = multi.get_service("pkgsvc")
    referenced = set()
    for cfg_id in svc.config_store.list_ids():
        blob = json.dumps(svc.config_store.fetch(cfg_id))
        for part in blob.split("packages/pkgsvc/")[1:]:
            referenced.add(part.split("/")[0].split('"')[0])
    assert referenced <= set(dirs), (referenced, dirs)


def test_airgap_lint(tmp_path):
    """Reference tools/airgap_linter.py analogue: external URLs and
    registry image pulls are findings; loopback and comments are not;
    all shipped frameworks/ lint clean."""
    from dcos_commons_tpu.tools.packaging import lint_airgap

    d = tmp_path / "fw"
    d.mkdir()
    (d / "svc.yml").write_text(
        "name: x\n"
        "# comment with https://example.com is fine\n"
        "pods:\n"
        "  app:\n"
        "    count: 1\n"
        "    image: registry.example.com/app:1\n"
        "    tasks:\n"
        "      main:\n"
        "        goal: RUNNING\n"
        '        cmd: "curl https://artifacts.example.com/blob '
        '&& curl http://127.0.0.1:8080/ok && sleep 1"\n'
        "        cpus: 0.1\n"
        "        memory: 32\n"
    )
    (d / "run.sh").write_text(
        "case $1 in\n"
        "*) curl https://sneaky.example.com/payload ;;\n"
        "esac\n"
        "echo http://[::1]:9000/metrics\n"
    )
    git_dir = d / ".git"
    git_dir.mkdir()
    (git_dir / "config").write_text("url = https://github.com/x/y\n")
    findings = lint_airgap(str(d))
    assert any("artifacts.example.com" in f for f in findings)
    assert any("registry.example.com" in f for f in findings)
    # '*' is NOT a comment: the shell case arm is a real violation
    assert any("sneaky.example.com" in f for f in findings)
    assert not any("example.com is fine" in f for f in findings)
    assert not any("127.0.0.1" in f for f in findings)
    assert not any("::1" in f for f in findings)  # IPv6 loopback ok
    assert not any(".git" in f for f in findings)  # unshipped files
    assert len(findings) == 3

    # a typo'd path must raise, not pass as clean
    from dcos_commons_tpu.tools.packaging import PackageError

    with pytest.raises(PackageError, match="no such framework"):
        lint_airgap(str(tmp_path / "definitely-not-here"))

    # every framework this repo ships must BE air-gap clean
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for name in ("helloworld", "hdfs", "jax"):
        clean = lint_airgap(os.path.join(repo, "frameworks", name))
        assert clean == [], f"{name}: {clean}"


# -- registry: publish + install-from-registry ------------------------
# (reference: tools/publish_http.py + release_builder.py in spirit)


def test_registry_publish_resolve_and_immutability(tmp_path):
    from dcos_commons_tpu.tools import (
        fetch_package,
        publish_package,
        registry_index,
    )

    framework = make_framework(tmp_path)
    registry = str(tmp_path / "registry")
    v1 = str(tmp_path / "pkgsvc-1.tgz")
    build_package(framework, v1, version="1.0.0")
    out = publish_package(v1, registry)
    assert out["version"] == "1.0.0"
    # re-publishing identical bytes is idempotent...
    assert publish_package(v1, registry)["sha256"] == out["sha256"]
    # ...but different bytes under the same version are REJECTED
    # (immutable releases, release_builder's stable-artifact rule)
    (tmp_path / "pkgsvc" / "extra.txt").write_text("changed\n")
    mutated = str(tmp_path / "pkgsvc-1b.tgz")
    build_package(framework, mutated, version="1.0.0")
    with pytest.raises(PackageError, match="immutable"):
        publish_package(mutated, registry)
    # a version bump publishes fine and becomes "latest"
    v2 = str(tmp_path / "pkgsvc-2.tgz")
    build_package(framework, v2, version="1.10.0")  # > 1.9 numerically
    publish_package(v2, registry)
    index = registry_index(registry)
    assert set(index["packages"]["pkgsvc"]) == {"1.0.0", "1.10.0"}
    version, payload = fetch_package(registry, "pkgsvc")
    assert version == "1.10.0"  # numeric ordering, not lexicographic
    assert payload == open(v2, "rb").read()
    version, _ = fetch_package(registry, "pkgsvc", version="1.0.0")
    assert version == "1.0.0"
    with pytest.raises(PackageError, match="not in registry"):
        fetch_package(registry, "nope")


def test_registry_http_server_and_digest_verification(tmp_path):
    from dcos_commons_tpu.tools import (
        RegistryServer,
        fetch_package,
        publish_package,
    )

    framework = make_framework(tmp_path)
    pkg = str(tmp_path / "pkgsvc.tgz")
    build_package(framework, pkg, version="2.0.0")
    root = str(tmp_path / "registry")
    server = RegistryServer(root, auth_token="hunter2").start()
    try:
        # publish over HTTP requires the token
        with pytest.raises(PackageError, match="token"):
            publish_package(pkg, server.url)
        out = publish_package(pkg, server.url, token="hunter2")
        assert out["version"] == "2.0.0"
        # reads are open; the payload digest-verifies against the index
        version, payload = fetch_package(server.url, "pkgsvc")
        assert version == "2.0.0"
        assert payload == open(pkg, "rb").read()
        # a tampered artifact on disk is CAUGHT at fetch time
        artifact = os.path.join(root, "artifacts", "pkgsvc-2.0.0.tar.gz")
        with open(artifact, "ab") as f:
            f.write(b"tamper")
        with pytest.raises(PackageError, match="digest mismatch"):
            fetch_package(server.url, "pkgsvc")
    finally:
        server.stop()


@pytest.mark.slow
def test_cli_publish_and_install_from_registry(tmp_path):
    """The full operator flow over real processes: build -> publish
    to a served registry -> install BY NAME from the registry into a
    --multi scheduler -> deploy completes with the packaged template
    rendered (reference: publish_http.py + Cosmos install-by-name)."""
    framework = make_framework(tmp_path)
    pkg = str(tmp_path / "pkgsvc.tgz")
    built = subprocess.run(
        [sys.executable, "-m", "dcos_commons_tpu", "package", "build",
         framework, "-o", pkg, "--version", "3.1.0"],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert built.returncode == 0, built.stderr
    registry_announce = tmp_path / "registry.announce"
    registry_proc = subprocess.Popen(
        [sys.executable, "-m", "dcos_commons_tpu", "package",
         "registry-serve", "--dir", str(tmp_path / "registry"),
         "--announce-file", str(registry_announce)],
        cwd=REPO,
    )
    topology = tmp_path / "topology.yml"
    topology.write_text(
        "hosts:\n  - host_id: h0\n    cpus: 8\n    memory_mb: 8192\n"
    )
    announce = tmp_path / "announce"
    sched_proc = subprocess.Popen(
        [
            sys.executable, "-m", "dcos_commons_tpu", "serve", "--multi",
            "--topology", str(topology),
            "--port", "0",
            "--state-dir", str(tmp_path / "state"),
            "--sandbox-root", str(tmp_path / "sbx"),
            "--announce-file", str(announce),
        ],
        cwd=REPO,
    )
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not (
            announce.exists() and registry_announce.exists()
        ):
            time.sleep(0.1)
        registry_url = registry_announce.read_text().strip()
        url = announce.read_text().strip()
        published = subprocess.run(
            [sys.executable, "-m", "dcos_commons_tpu", "package",
             "publish", pkg, "--registry", registry_url],
            cwd=REPO, capture_output=True, text=True, timeout=60,
        )
        assert published.returncode == 0, published.stderr
        # install BY NAME: the tarball never touches this client's disk
        installed = subprocess.run(
            [sys.executable, "-m", "dcos_commons_tpu", "package",
             "install", "pkgsvc", "--registry", registry_url,
             "--url", url],
            cwd=REPO, capture_output=True, text=True, timeout=60,
        )
        assert installed.returncode == 0, installed.stderr
        assert "3.1.0" in installed.stderr  # resolved version reported

        def get(path):
            with urllib.request.urlopen(url + path, timeout=5) as r:
                return json.loads(r.read())

        deadline = time.monotonic() + 60
        done = False
        while time.monotonic() < deadline:
            try:
                if get("/v1/multi/pkgsvc/v1/plans/deploy")["status"] == \
                        "COMPLETE":
                    done = True
                    break
            except Exception:
                pass
            time.sleep(0.3)
        assert done
        rendered = tmp_path / "sbx" / "app-0-main" / "app.cfg"
        assert rendered.read_text().strip() == "task=app-0-main"
    finally:
        sched_proc.terminate()
        registry_proc.terminate()
        sched_proc.wait(timeout=20)
        registry_proc.wait(timeout=20)


def test_registry_version_ordering_release_beats_prerelease(tmp_path):
    """'1.0.0' must resolve as latest over '1.0.0-rc1' (semver
    prerelease rule), and numeric ordering beats lexicographic."""
    from dcos_commons_tpu.tools import fetch_package, publish_package

    framework = make_framework(tmp_path)
    registry = str(tmp_path / "registry")
    for version in ("1.0.0-rc1", "1.0.0", "0.9.9"):
        out = str(tmp_path / f"p-{version}.tgz")
        build_package(framework, out, version=version)
        publish_package(out, registry)
    version, _ = fetch_package(registry, "pkgsvc")
    assert version == "1.0.0"
    # pinned prerelease still fetchable
    version, _ = fetch_package(registry, "pkgsvc", version="1.0.0-rc1")
    assert version == "1.0.0-rc1"


def test_registry_prune_grace_window_parks_artifacts(tmp_path):
    """--grace-s > 0 (ADVICE r5): a pruned artifact leaves the index
    immediately but its BYTES are parked as .trash-<epoch> so an NFS
    client mid-fetch keeps streaming; a LATER prune reaps trash older
    than the window."""
    from dcos_commons_tpu.tools import publish_package, registry_index
    from dcos_commons_tpu.tools.registry import prune_registry

    framework = make_framework(tmp_path)
    registry = str(tmp_path / "registry")
    for version in ("1.0.0", "1.1.0"):
        artifact = str(tmp_path / f"p-{version}.tgz")
        build_package(framework, artifact, version=version)
        publish_package(artifact, registry)

    pruned = prune_registry(registry, keep=1, grace_s=3600.0)
    assert pruned == {"pkgsvc": ["1.0.0"]}
    assert set(registry_index(registry)["packages"]["pkgsvc"]) == {"1.1.0"}
    artifact_dir = os.path.join(registry, "artifacts")
    names = os.listdir(artifact_dir)
    parked = [n for n in names if n.startswith("pkgsvc-1.0.0") and
              ".trash-" in n]
    assert parked, names  # bytes still on disk, out of the index
    assert "pkgsvc-1.1.0.tar.gz" in names
    # within the window, a later prune leaves the parked bytes alone
    assert prune_registry(registry, keep=1, grace_s=3600.0) == {}
    assert parked[0] in os.listdir(artifact_dir)
    # ... even a later prune with NO grace: the window an artifact
    # was parked under rides in its name and cannot be shortened
    assert prune_registry(registry, keep=1) == {}
    assert parked[0] in os.listdir(artifact_dir)
    # age the parked file past its recorded window: the next prune
    # reaps it (epoch 1000, 60s window, both long elapsed)
    aged = parked[0].rsplit(".trash-", 1)[0] + ".trash-1000-60"
    os.rename(
        os.path.join(artifact_dir, parked[0]),
        os.path.join(artifact_dir, aged),
    )
    assert prune_registry(registry, keep=1, grace_s=3600.0) == {}
    assert aged not in os.listdir(artifact_dir)
    assert "pkgsvc-1.1.0.tar.gz" in os.listdir(artifact_dir)


def test_registry_prune_retires_old_releases(tmp_path):
    """`package registry-prune --keep K` (release_builder lifecycle
    cleanup): old versions leave the index AND their artifact files;
    the newest K stay installable; HTTP registries refuse the verb;
    other packages are untouched when --name scopes the prune."""
    from dcos_commons_tpu.tools import (
        fetch_package,
        publish_package,
        registry_index,
    )
    from dcos_commons_tpu.tools.registry import prune_registry

    framework = make_framework(tmp_path)
    other = make_framework(tmp_path, name="othersvc")
    registry = str(tmp_path / "registry")
    for version in ("1.0.0", "1.1.0", "1.2.0", "1.10.0"):
        artifact = str(tmp_path / f"p-{version}.tgz")
        build_package(framework, artifact, version=version)
        publish_package(artifact, registry)
    artifact = str(tmp_path / "other-1.tgz")
    build_package(other, artifact, version="0.1.0")
    publish_package(artifact, registry)

    pruned = prune_registry(registry, keep=2, name="pkgsvc")
    assert pruned == {"pkgsvc": ["1.0.0", "1.1.0"]}
    index = registry_index(registry)
    assert set(index["packages"]["pkgsvc"]) == {"1.2.0", "1.10.0"}
    assert set(index["packages"]["othersvc"]) == {"0.1.0"}  # untouched
    # artifacts of pruned releases are gone; retained ones remain
    artifacts = set(os.listdir(os.path.join(registry, "artifacts")))
    assert artifacts == {
        "pkgsvc-1.2.0.tar.gz", "pkgsvc-1.10.0.tar.gz",
        "othersvc-0.1.0.tar.gz",
    }
    # latest still resolves and verifies after the prune
    version, _payload = fetch_package(registry, "pkgsvc")
    assert version == "1.10.0"
    # idempotent: nothing more to prune
    assert prune_registry(registry, keep=2) == {}
    # guardrails
    with pytest.raises(PackageError, match="host"):
        prune_registry("http://reg:8081", keep=2)
    with pytest.raises(PackageError, match="keep"):
        prune_registry(registry, keep=0)
    with pytest.raises(PackageError, match="not in the registry"):
        prune_registry(registry, keep=1, name="ghost")
    with pytest.raises(PackageError, match="not found"):
        prune_registry(str(tmp_path / "typo"), keep=1)
    # IMMUTABILITY SURVIVES THE PRUNE: a pruned version is digest-
    # tombstoned — different bytes under it stay rejected, the
    # original bytes restore it
    (tmp_path / "pkgsvc" / "mutated.txt").write_text("different\n")
    remut = str(tmp_path / "p-1.0.0-mut.tgz")
    build_package(framework, remut, version="1.0.0")
    with pytest.raises(PackageError, match="tombstoned"):
        publish_package(remut, registry)
    assert publish_package(
        str(tmp_path / "p-1.0.0.tgz"), registry
    )["version"] == "1.0.0"  # original bytes restore the release
    # CLI verb prints the pruned map as JSON
    import io
    from contextlib import redirect_stdout

    from dcos_commons_tpu.tools.packaging import main as package_main

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = package_main([
            "registry-prune", "--dir", registry, "--keep", "1",
        ])
    assert rc == 0
    out = json.loads(buf.getvalue())
    # 1.0.0 was restored above, so keep=1 retires it again plus 1.2.0
    assert out["pruned"] == {"pkgsvc": ["1.0.0", "1.2.0"]}
    assert set(registry_index(registry)["packages"]["pkgsvc"]) == {
        "1.10.0"
    }
