"""Data pipeline: memory-mapped token shards -> sharded -> prefetched.

The IO component of the workload plane (the reference has no data
plane).  The invariants that matter operationally: workers read
DISJOINT data with no coordination, a replacement worker re-reads its
predecessor's stream exactly, and checkpoint resume continues the
stream where it stopped.
"""

import numpy as np
import pytest

from dcos_commons_tpu.data import (
    DevicePrefetcher,
    TokenDataset,
    write_token_shard,
)


def make_shards(tmp_path, n_shards=4, tokens_per_shard=257):
    for i in range(n_shards):
        write_token_shard(
            str(tmp_path / f"shard-{i:03d}.tokens"),
            np.arange(tokens_per_shard) + i * 10_000,
        )
    return str(tmp_path)


def test_windows_and_targets_align(tmp_path):
    data_dir = make_shards(tmp_path, n_shards=1, tokens_per_shard=65)
    ds = TokenDataset(data_dir, seq_len=8)
    assert ds.n_sequences == 65 // 9
    tokens, targets = next(ds.batches(2))
    assert tokens.shape == targets.shape == (2, 8)
    # next-token objective: targets are tokens shifted by one
    np.testing.assert_array_equal(tokens[:, 1:], targets[:, :-1])


def test_workers_read_disjoint_shards(tmp_path):
    data_dir = make_shards(tmp_path, n_shards=4)
    seen = []
    for wid in range(2):
        ds = TokenDataset(data_dir, seq_len=16, worker_id=wid,
                          worker_count=2)
        tokens = {
            int(ds.sequence(i)[0]) // 10_000 for i in range(ds.n_sequences)
        }
        seen.append(tokens)
    assert seen[0] & seen[1] == set()          # disjoint shard files
    assert seen[0] | seen[1] == {0, 1, 2, 3}   # full coverage


def test_replacement_worker_reads_identical_stream(tmp_path):
    """PERMANENT gang recovery: the replacement gets the same
    (worker_id, seed) and must see the SAME stream."""
    data_dir = make_shards(tmp_path)
    a = TokenDataset(data_dir, seq_len=16, worker_id=1, worker_count=2)
    b = TokenDataset(data_dir, seq_len=16, worker_id=1, worker_count=2)
    for (ta, _), (tb, _), _ in zip(a.batches(2), b.batches(2), range(5)):
        np.testing.assert_array_equal(ta, tb)


def test_resume_continues_stream(tmp_path):
    """batches(start_step=N) == the tail of batches() from step N —
    checkpoint resume replays nothing and skips nothing."""
    data_dir = make_shards(tmp_path)
    ds = TokenDataset(data_dir, seq_len=16)
    full = ds.batches(2)
    head = [next(full) for _ in range(7)]
    resumed = ds.batches(2, start_step=5)
    for expect, _ in zip(head[5:], range(2)):
        got = next(resumed)
        np.testing.assert_array_equal(got[0], expect[0])
        np.testing.assert_array_equal(got[1], expect[1])


def test_epochs_reshuffle(tmp_path):
    data_dir = make_shards(tmp_path, n_shards=2, tokens_per_shard=1700)
    ds = TokenDataset(data_dir, seq_len=16, seed=3)
    per_epoch = max(ds.n_sequences // 4, 1)
    stream = ds.batches(4)
    epoch0 = [next(stream)[0] for _ in range(per_epoch)]
    epoch1 = [next(stream)[0] for _ in range(per_epoch)]
    assert not all(
        np.array_equal(a, b) for a, b in zip(epoch0, epoch1)
    ), "epochs must reshuffle"
    # same multiset of sequence starts either way (full coverage)
    s0 = sorted(int(t[0]) for b in epoch0 for t in b)
    s1 = sorted(int(t[0]) for b in epoch1 for t in b)
    assert s0 == s1


def test_prefetcher_matches_host_iterator_and_lands_on_device(tmp_path):
    import jax

    data_dir = make_shards(tmp_path)
    ds = TokenDataset(data_dir, seq_len=16)
    host = [next(ds.batches(2)) for _ in range(1)][0]
    pre = DevicePrefetcher(ds.batches(2), depth=2)
    tokens, targets = next(pre)
    assert isinstance(tokens, jax.Array)
    np.testing.assert_array_equal(np.asarray(tokens), host[0])
    np.testing.assert_array_equal(np.asarray(targets), host[1])
    pre.close()


def test_prefetcher_surfaces_source_errors():
    def boom():
        yield (np.zeros((1, 4), np.int32), np.zeros((1, 4), np.int32))
        raise RuntimeError("corrupt shard")

    pre = DevicePrefetcher(boom(), depth=1)
    next(pre)
    with pytest.raises(RuntimeError, match="corrupt shard"):
        while True:
            next(pre)


def test_prefetcher_finite_iterator_stops_cleanly():
    """A finite source (eval sets) ends in StopIteration, never a
    deadlocked queue.get — and KEEPS raising on re-next (iterator
    protocol), and close() without draining unblocks the pump."""
    batch = (np.zeros((1, 4), np.int32), np.zeros((1, 4), np.int32))
    pre = DevicePrefetcher(iter([batch] * 3), depth=1)
    assert sum(1 for _ in pre) == 3
    with pytest.raises(StopIteration):
        next(pre)  # a second next() must not hang
    # close-without-drain: the pump (blocked on a full queue with more
    # to send) must exit, not hold staged device batches forever
    pre2 = DevicePrefetcher(iter([batch] * 50), depth=1)
    next(pre2)
    pre2.close()
    pre2._thread.join(timeout=5)
    assert not pre2._thread.is_alive()
    with pytest.raises(StopIteration):
        next(pre2)


def test_prefetcher_with_mesh_sharding_feeds_sharded_train_step(tmp_path):
    """The multi-device contract: batches land SHARDED the way the
    jitted train step's in_shardings expect (this is what a plain
    device_put breaks on any >1-device mesh)."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding

    from dcos_commons_tpu.models import (
        TransformerConfig,
        init_params,
        make_train_step,
    )
    from dcos_commons_tpu.parallel.mesh import (
        MeshSpec,
        batch_spec,
        make_mesh,
    )

    rng = np.random.default_rng(1)
    for i in range(4):  # tokens IN VOCAB (the model embeds them)
        write_token_shard(
            str(tmp_path / f"shard-{i:03d}.tokens"),
            rng.integers(0, 64, 1000),
        )
    data_dir = str(tmp_path)
    config = TransformerConfig(
        vocab=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq=16, dtype=jnp.float32, remat=False,
    )
    mesh = make_mesh(MeshSpec(dp=4, tp=2))
    optimizer = optax.adam(1e-3)
    with mesh:
        params = init_params(config, jax.random.key(0))
        opt_state = optimizer.init(params)
        step = make_train_step(config, optimizer, mesh=mesh, donate=False)
        ds = TokenDataset(data_dir, seq_len=16)
        pre = DevicePrefetcher(
            ds.batches(8), depth=2,
            sharding=NamedSharding(mesh, batch_spec()),
        )
        for _ in range(3):
            tokens, targets = next(pre)
            assert tokens.sharding.is_equivalent_to(
                NamedSharding(mesh, batch_spec()), tokens.ndim
            )
            params, opt_state, loss = step(params, opt_state, tokens, targets)
        pre.close()
    assert bool(jnp.isfinite(loss))


def test_dataset_rejects_bad_inputs(tmp_path):
    with pytest.raises(FileNotFoundError):
        TokenDataset(str(tmp_path), seq_len=8)
    make_shards(tmp_path, n_shards=1)
    with pytest.raises(ValueError, match="cannot feed"):
        TokenDataset(str(tmp_path), seq_len=8, worker_id=1, worker_count=2)


def test_training_on_real_shards_learns(tmp_path):
    """End to end: the flagship-small transformer trains from
    memory-mapped shards through the prefetcher and the loss drops."""
    import jax
    import jax.numpy as jnp
    import optax

    from dcos_commons_tpu.models import (
        TransformerConfig,
        init_params,
        make_train_step,
    )

    rng = np.random.default_rng(0)
    # a learnable corpus: repeated short patterns
    pattern = rng.integers(0, 64, 32)
    corpus = np.tile(pattern, 200)
    write_token_shard(str(tmp_path / "c.tokens"), corpus)
    config = TransformerConfig(
        vocab=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq=32, dtype=jnp.float32, remat=False,
    )
    ds = TokenDataset(str(tmp_path), seq_len=32)
    pre = DevicePrefetcher(ds.batches(4), depth=2)
    params = init_params(config, jax.random.key(0))
    optimizer = optax.adam(1e-2)
    opt_state = optimizer.init(params)
    step = make_train_step(config, optimizer, donate=False)
    first = None
    for i in range(30):
        tokens, targets = next(pre)
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        if first is None:
            first = float(loss)
    pre.close()
    assert float(loss) < first * 0.5, (first, float(loss))
