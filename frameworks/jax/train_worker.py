"""Multi-host pjit training worker (BASELINE.json config 4).

One of these runs per host of the gang pod.  It consumes the
scheduler's env contract (COORDINATOR_ADDRESS, TPU_WORKER_ID, ...),
rendezvouses via jax.distributed, builds a dp-over-hosts x tp-within-
host mesh, and trains the flagship transformer with orbax-style
checkpointing so PERMANENT gang recovery resumes from the last step.
"""

import os
import sys
import time

sys.path.insert(0, os.environ.get("REPO_ROOT", "/root/repo"))


def main() -> int:
    from dcos_commons_tpu.parallel.distributed import initialize_from_env
    from dcos_commons_tpu.parallel.overlap import enable_collective_overlap

    # XLA's latency-hiding scheduler flags must land in XLA_FLAGS
    # before the first jax backend init: without them several libtpu
    # builds serialize the grad reduce-scatters the microbatched step
    # was restructured to overlap (TPU-only; TRAIN_XLA_OVERLAP=0
    # opts out)
    enable_collective_overlap()
    contract = initialize_from_env()
    if contract["num_slices"] > 1:
        # the slice identity an operator needs when reading one
        # sandbox's log against a whole-gang timeline
        print(
            f"multi-slice gang: slice {contract['slice_index']}/"
            f"{contract['num_slices']} "
            f"({contract['hosts_per_slice']} host(s)/slice), "
            f"slice anchor {contract['slice_coordinator'] or 'n/a'}",
            flush=True,
        )

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    from dcos_commons_tpu.models import (
        config_from_env,
        init_params,
        make_train_step,
    )
    from dcos_commons_tpu.parallel.mesh import mesh_from_env
    from dcos_commons_tpu.trace.steplog import InflightWindow, StepLog
    from dcos_commons_tpu.utils import (
        AsyncCheckpointer,
        claim_incarnation,
        enable_compilation_cache,
        restore_checkpoint,
        save_checkpoint,
        synthetic_tokens,
    )

    # a recovered/replaced gang worker re-jits the identical train
    # step; the persistent cache turns that into a disk read
    enable_compilation_cache()

    steps = int(os.environ.get("TRAIN_STEPS", "100"))
    ckpt_dir = os.environ.get("CHECKPOINT_DIR", "checkpoints")
    # per-step telemetry into $SANDBOX/steplog.jsonl: the scheduler's
    # /v1/debug/trace merges every host's lane into one timeline, so
    # gang skew (who waited on whom) is read off the blocked_s column.
    # The barrier probe is a gang-wide sync BEFORE each step's first
    # collective; its wall time on the fast hosts IS the skew the slow
    # host imposed.  STEPLOG_BARRIER_PROBE=0 drops the probe (and the
    # skew column) when even a barrier per step is too much.
    steplog = StepLog()
    probe_gang = os.environ.get("STEPLOG_BARRIER_PROBE", "1") not in (
        "0", "false"
    )
    mesh = mesh_from_env(os.environ)
    if os.environ.get("TPU_TOPOLOGY"):
        # elastic-DP resume guard (ISSUE 13): when the devices actually
        # present disagree with the DECLARED topology (a resized
        # relaunch), proceeding is only safe if the change is a pure
        # batch-axis (dp/dcn) re-layout — params and optimizer state
        # replicate over those axes, so the fenced checkpoint restores
        # bit-identically onto the new mesh.  A model-axis change
        # (tp/fsdp/...) would silently train a different parallelism:
        # refuse loudly.  TRAIN_ELASTIC_DP=1 opts in; the scheduler's
        # own elastic re-slice rewrites TPU_TOPOLOGY consistently and
        # never needs the flag.
        from dcos_commons_tpu.parallel.mesh import (
            derive,
            elastic_reshard_ok,
        )

        declared = derive(os.environ)
        actual = derive(os.environ, n_devices=mesh.devices.size)
        if actual != declared:
            elastic = os.environ.get("TRAIN_ELASTIC_DP", "0") not in (
                "0", "false"
            )
            if not elastic or not elastic_reshard_ok(declared, actual):
                raise RuntimeError(
                    f"mesh mismatch: declared topology derives {declared} "
                    f"but {mesh.devices.size} device(s) derive {actual}; "
                    "only a dp/dcn change is elastically resumable "
                    "(set TRAIN_ELASTIC_DP=1 to allow it)"
                )
            print(
                f"elastic-dp resume: {declared.total} -> {actual.total} "
                f"chips (dp {declared.dp}->{actual.dp}, dcn "
                f"{declared.dcn}->{actual.dcn}); checkpoint reshards as "
                "a pure re-layout", flush=True,
            )
    # the env->config contract lives in models/transformer.py so
    # analysis/shardcheck verifies the EXACT model this pod trains
    config = config_from_env(os.environ, dtype=jnp.bfloat16)
    optimizer = optax.adamw(3e-4)
    with mesh:
        params = init_params(config, jax.random.key(0))
        opt_state = optimizer.init(params)
        # checkpoint carries params AND optimizer moments; its stamp is
        # the next step to run, so resume never double-applies a step
        state = {"params": params, "opt_state": opt_state}
        state, start = restore_checkpoint(ckpt_dir, state)
        params, opt_state = state["params"], state["opt_state"]
        start = start or 0
        if contract["worker_count"] > 1:
            # the checkpoint stamp came off LOCAL disk: if one host's
            # sandbox holds step 80 and another's holds step 100, the
            # training loops disagree on the trip count and the gang
            # deadlocks in the shorter host's last allreduce
            # (spmdcheck: spmd-per-host-trip-count).  Agree up front
            # and fail the deploy loudly on divergence — recovery
            # relaunches the gang, which beats a silent hang.
            from jax.experimental import multihost_utils

            starts = multihost_utils.process_allgather(jnp.int32(start))
            if int(starts.min()) != int(starts.max()):
                raise RuntimeError(
                    "checkpoint step diverges across the gang: "
                    f"{sorted(int(s) for s in starts)}; wipe the stale "
                    "sandboxes or restore a shared CHECKPOINT_DIR"
                )
            start = int(starts[0])
        # the step-time fast path (ISSUE 7): donated buffers (the
        # params/opt-state update happens in place instead of paying a
        # full HBM copy per step), optional microbatched gradient
        # accumulation (per-microbatch collectives overlap the next
        # microbatch's compute), and a bounded async-dispatch window
        # below.  Each has an env opt-out because a debugging session
        # wants the boring synchronous loop back.
        donate = os.environ.get("TRAIN_DONATE", "1") not in ("0", "false")
        grad_accum = max(1, int(os.environ.get("TRAIN_GRAD_ACCUM", "1")))
        # in-flight window: dispatch step N, block on step N-k's loss.
        # 0 = synchronous (block every step, the pre-overlap loop)
        inflight = max(0, int(os.environ.get("TRAIN_INFLIGHT_STEPS", "2")))
        step_fn = make_train_step(
            config, optimizer, mesh=mesh, donate=donate,
            grad_accum=grad_accum,
        )
        batch = max(2, 2 * mesh.devices.size)
        # microbatches must split evenly AND each batch must still
        # shard over the mesh's data axes (in_shardings pins tokens to
        # batch_spec): round up to a multiple of lcm(grad_accum,
        # batch-axis product) — padding to grad_accum alone could
        # break dp/fsdp divisibility and kill the first dispatch
        # (review r7)
        import math

        from dcos_commons_tpu.parallel.mesh import BATCH_AXES

        batch_shard = 1
        for axis in BATCH_AXES:
            batch_shard *= mesh.shape.get(axis, 1)
        multiple = math.lcm(grad_accum, batch_shard)
        if batch % multiple:
            batch += multiple - batch % multiple
        data_dir = os.environ.get("DATA_DIR", "")
        batches = None
        if data_dir:
            # real corpus: memory-mapped token shards round-robin over
            # the gang (disjoint per worker), device-prefetched; the
            # stream is a pure function of (seed, step) so checkpoint
            # resume continues EXACTLY where the dead incarnation left
            from jax.sharding import NamedSharding

            from dcos_commons_tpu.data import DevicePrefetcher, TokenDataset
            from dcos_commons_tpu.parallel.mesh import batch_spec

            dataset = TokenDataset(
                data_dir, config.max_seq,
                worker_id=contract["worker_id"],
                worker_count=contract["worker_count"],
            )
            # batches must land SHARDED like the train step expects
            # (each process's distinct batch is its dp slice of the
            # global batch) — a plain device_put would fight the jit's
            # in_shardings on any multi-device mesh.  Each process
            # therefore yields its SHARE of the global batch: feeding
            # `batch` rows per process would silently train at
            # batch x worker_count (JAX infers global = local x procs)
            local_rows = max(1, batch // contract["worker_count"])
            batches = DevicePrefetcher(
                dataset.batches(local_rows, start_step=start), depth=2,
                sharding=NamedSharding(mesh, batch_spec()),
            )
            print(
                f"data: {dataset.n_sequences} sequences for worker "
                f"{contract['worker_id']}", flush=True,
            )
        else:
            tokens, targets = synthetic_tokens(
                jax.random.key(1), batch, config.max_seq, config.vocab
            )
        gang = contract["worker_count"] > 1
        if gang and probe_gang:
            from jax.experimental import multihost_utils
        # non-blocking checkpointing: save() costs the loop one async
        # device-side copy; the gather + npz write + fenced prune run
        # on a background thread.  The writer incarnation (claimed by
        # process 0 only — it is the only writer) fences a zombie
        # trainer out of a relaunched gang's CHECKPOINT_DIR.
        keep = int(os.environ.get("CHECKPOINT_KEEP", "3"))
        async_ckpt = os.environ.get("TRAIN_ASYNC_CKPT", "1") not in (
            "0", "false"
        )
        if gang:
            # process 0 claims (single writer) and BROADCASTS the
            # token so the whole gang agrees on the incarnation —
            # spmdcheck: every host must issue the same collective
            # sequence, so the claim result is made gang-uniform
            # before anything downstream can branch on it
            from jax.experimental import multihost_utils

            local = (
                claim_incarnation(ckpt_dir)
                if jax.process_index() == 0 else 0
            )
            incarnation = int(multihost_utils.broadcast_one_to_all(
                jnp.int32(local)
            ))
        else:
            incarnation = claim_incarnation(ckpt_dir)
        checkpointer = (
            AsyncCheckpointer(ckpt_dir, keep=keep, incarnation=incarnation)
            if async_ckpt else None
        )
        # the bounded in-flight window bills wall_s/blocked_s to the
        # step that incurred them even though the host runs k steps
        # ahead of the devices (trace/steplog.py InflightWindow)
        window = InflightWindow(steplog, inflight)

        def note_drained(drained):
            for s, ready_loss in drained:
                if s % 20 == 0 or s == steps - 1:
                    # the loss is already on host: float() here cannot
                    # stall the pipeline the way printing the
                    # just-dispatched step's loss would
                    print(
                        f"step {s} loss={float(ready_loss):.4f}",
                        flush=True,
                    )

        t0 = time.time()
        for i in range(start, steps):
            step_t0 = time.time()
            blocked_s = 0.0
            if gang and probe_gang:
                # pre-allreduce barrier probe: meet the gang before
                # this step's first collective; time spent here is
                # time BLOCKED on slower hosts, not compute.  Under
                # overlap the probe still runs at DISPATCH order, so
                # its wait is the skew the slow host imposed at this
                # step's admission, billed to this step.
                b0 = time.time()
                multihost_utils.sync_global_devices(f"steplog-{i}")
                blocked_s = time.time() - b0
            if batches is not None:
                tokens, targets = next(batches)
            params, opt_state, loss = step_fn(params, opt_state, tokens, targets)
            if i % 20 == 0 or i == steps - 1:
                state = {"params": params, "opt_state": opt_state}
                if checkpointer is not None:
                    # snapshot NOW: the async device copy is enqueued
                    # before the next dispatch donates these buffers
                    checkpointer.save(i + 1, state)
                else:
                    save_checkpoint(
                        ckpt_dir, i + 1, state, keep=keep,
                        incarnation=incarnation,
                    )
            # push the dispatched step into the window; it blocks on
            # step i-k's loss (not step i's) and stamps the steplog
            # with the wall/blocked time each DRAINED step incurred
            note_drained(window.push(
                i, loss, step_t0, blocked_s=blocked_s,
                tokens=tokens.shape[0] * tokens.shape[1],
                worker=contract["worker_id"],
            ))
        note_drained(window.drain())
        if checkpointer is not None:
            ckpt_errors = checkpointer.close()
            if ckpt_errors:
                print(
                    f"checkpoint writer errors: {ckpt_errors[:3]}",
                    file=sys.stderr, flush=True,
                )
        steplog.close()
        if batches is not None:
            batches.close()
        dt = time.time() - t0
        tps = batch * config.max_seq * (steps - start) / max(dt, 1e-9)
        print(
            f"worker {contract['worker_id']}/{contract['worker_count']}: "
            f"{steps - start} steps, {tps:,.0f} tokens/s", flush=True,
        )
    # goal RUNNING: stay alive serving the mesh until the scheduler
    # kills or reconfigures the pod
    keepalive = os.environ.get("KEEPALIVE_S")
    if keepalive:
        time.sleep(float(keepalive))
    else:
        while True:
            time.sleep(60)


if __name__ == "__main__":
    sys.exit(main() or 0)
