"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh so multi-chip sharding
(dp/tp/sp) is exercised without TPU hardware, mirroring how the
reference tests multi-node scheduling without a Mesos cluster
(reference: sdk/testing/ServiceTestRunner.java runs the full scheduler
against MemPersister + a mocked driver).
"""

import os

# force CPU even when a real TPU is attached: tests exercise sharding
# on the virtual mesh; bench.py is what runs on the chip.  The env var
# alone is not enough — this image's sitecustomize re-selects the TPU
# platform at import, so flip the jax config after import too.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long multi-process e2e (several minutes wall clock)",
    )
