"""Security plane: secrets materialization + TLS issuance (X2).

Reference: dcos/clients/SecretsClient.java + CertificateAuthority
Client.java + offer/evaluate/TLSEvaluationStage.java + the
TLSRequiresServiceAccount gating validator.
"""

import base64
import os
import stat
import time

import pytest

from dcos_commons_tpu.security import (
    CertificateAuthority,
    FileSecretsProvider,
    InMemorySecretsProvider,
    SecretNotFound,
)
from dcos_commons_tpu.specification.validation import ConfigValidationError
from dcos_commons_tpu.storage import MemPersister
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    ExpectLaunchedTasks,
    SendTaskRunning,
    ServiceTestRunner,
)

HELLOWORLD = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "frameworks", "helloworld",
)


def load(name):
    with open(os.path.join(HELLOWORLD, name)) as f:
        return f.read()


# -- providers --------------------------------------------------------


def test_file_secrets_provider_reads_tree(tmp_path):
    (tmp_path / "app").mkdir()
    (tmp_path / "app" / "password").write_bytes(b"hunter2")
    provider = FileSecretsProvider(str(tmp_path))
    assert provider.fetch("app/password") == b"hunter2"
    with pytest.raises(SecretNotFound):
        provider.fetch("app/missing")


def test_file_secrets_provider_rejects_traversal(tmp_path):
    (tmp_path / "safe").mkdir()
    provider = FileSecretsProvider(str(tmp_path / "safe"))
    (tmp_path / "outside").write_bytes(b"leak")
    with pytest.raises(SecretNotFound):
        provider.fetch("../outside")


# -- certificate authority -------------------------------------------


def test_ca_issues_verifiable_certs():
    from cryptography import x509

    ca = CertificateAuthority.create()
    cert_pem, key_pem = ca.issue("web-0-server", sans=["web-0-server", "h0"])
    cert = x509.load_pem_x509_certificate(cert_pem)
    ca_cert = x509.load_pem_x509_certificate(ca.ca_cert_pem)
    # signature chains to the CA
    cert.verify_directly_issued_by(ca_cert)
    sans = cert.extensions.get_extension_for_class(
        x509.SubjectAlternativeName
    ).value.get_values_for_type(x509.DNSName)
    assert set(sans) == {"web-0-server", "h0"}
    assert b"PRIVATE KEY" in key_pem


def test_ca_persists_root_across_restarts():
    persister = MemPersister()
    first = CertificateAuthority.load_or_create(persister)
    second = CertificateAuthority.load_or_create(persister)
    assert first.ca_cert_pem == second.ca_cert_pem


# -- launch-channel materialization (sim) ----------------------------


def secrets_runner(values):
    provider = InMemorySecretsProvider(values)
    return ServiceTestRunner(
        load("secrets.yml"),
        builder_hook=lambda b: b.set_secrets_provider(provider),
    )


def test_secrets_ride_launch_channel_not_state(tmp_path):
    runner = secrets_runner({
        "hello-world/secret1": b"v-one",
        "hello-world/secret2": b"v-two",
    })
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("hello-0-server"),
        SendTaskRunning("hello-0-server"),
        ExpectDeploymentComplete(),
    ])
    agent = runner.world.agent
    task_id = agent.task_id_of("hello-0-server")
    payload = agent.payloads[task_id]
    by_dest = {f["dest"]: f for f in payload["files"]}
    assert base64.b64decode(
        by_dest["HELLO_SECRET1_FILE"]["content"]
    ) == b"v-one"
    assert base64.b64decode(
        by_dest["HELLO_SECRET2_FILE"]["content"]
    ) == b"v-two"
    assert by_dest["HELLO_SECRET1_FILE"]["mode"] == 0o600
    assert payload["secret_env"]["HELLO_SECRET1_ENV"] == "v-one"
    # the secret value never reaches the persisted TaskInfo
    stored = runner.world.state_store.fetch_task("hello-0-server")
    assert "v-one" not in str(stored.to_dict())
    assert "HELLO_SECRET1_ENV" not in stored.env


def test_missing_secret_fails_launch_payload():
    runner = secrets_runner({"hello-world/secret1": b"only-one"})
    runner.run([AdvanceCycles(1)])
    agent = runner.world.agent
    payload = agent.payloads[agent.task_id_of("hello-0-server")]
    errors = [f for f in payload["files"] if "error" in f]
    assert errors and "hello-world/secret2" in errors[0]["error"]


def test_secrets_without_provider_refuse_to_build():
    """The TLSRequiresServiceAccount gating pattern: a spec that
    references secrets with no provider wired is a configuration
    error, not an eventual launch failure."""
    with pytest.raises(ConfigValidationError):
        ServiceTestRunner(load("secrets.yml")).build()


def test_tls_artifacts_in_payload():
    runner = ServiceTestRunner(load("tls.yml"))
    runner.run([
        AdvanceCycles(1),
        ExpectLaunchedTasks("secure-0-node"),
    ])
    agent = runner.world.agent
    payload = agent.payloads[agent.task_id_of("secure-0-node")]
    by_dest = {f["dest"]: f for f in payload["files"]}
    assert set(by_dest) == {
        "secure-tls-pod.crt", "secure-tls-pod.key", "secure-tls-pod.ca"
    }
    assert by_dest["secure-tls-pod.key"]["mode"] == 0o600

    from cryptography import x509

    cert = x509.load_pem_x509_certificate(
        base64.b64decode(by_dest["secure-tls-pod.crt"]["content"])
    )
    ca_cert = x509.load_pem_x509_certificate(
        base64.b64decode(by_dest["secure-tls-pod.ca"]["content"])
    )
    cert.verify_directly_issued_by(ca_cert)


# -- real agent e2e ---------------------------------------------------


def test_secret_and_tls_files_land_in_real_sandbox(tmp_path):
    """LocalProcessAgent writes 0600 secret files + TLS PEMs into the
    sandbox and the process sees the secret env var."""
    from dcos_commons_tpu.agent.local import LocalProcessAgent
    from dcos_commons_tpu.common import TaskInfo

    agent = LocalProcessAgent(str(tmp_path / "sbx"))
    ca = CertificateAuthority.create()
    cert, key = ca.issue("app-0-main", sans=["app-0-main"])
    info = TaskInfo(
        name="app-0-main",
        task_id="app-0-main__1",
        agent_id="h0",
        command="echo -n $TOKEN > token-out.txt",
    )
    agent.launch_one(
        info,
        files=[
            {"dest": "creds/password", "mode": 0o600,
             "content": base64.b64encode(b"hunter2").decode()},
            {"dest": "tls.crt", "mode": 0o644,
             "content": base64.b64encode(cert).decode()},
            {"dest": "tls.key", "mode": 0o600,
             "content": base64.b64encode(key).decode()},
        ],
        secret_env={"TOKEN": "tok-123"},
    )
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if any(s.state.value == "TASK_FINISHED" for s in agent.poll()):
            break
        time.sleep(0.05)
    sandbox = tmp_path / "sbx" / "app-0-main"
    assert (sandbox / "creds" / "password").read_bytes() == b"hunter2"
    mode = stat.S_IMODE(os.stat(sandbox / "creds" / "password").st_mode)
    assert mode == 0o600
    assert stat.S_IMODE(os.stat(sandbox / "tls.key").st_mode) == 0o600
    assert (sandbox / "token-out.txt").read_text() == "tok-123"
    agent.shutdown()


def test_secure_file_escape_rejected(tmp_path):
    from dcos_commons_tpu.agent.local import LocalProcessAgent
    from dcos_commons_tpu.common import TaskInfo, TaskState

    agent = LocalProcessAgent(str(tmp_path / "sbx"))
    agent.launch_one(
        TaskInfo(name="bad-0-task", task_id="bad-0-task__1", command="true"),
        files=[{
            "dest": "../../etc/stolen",
            "content": base64.b64encode(b"x").decode(),
        }],
    )
    statuses = agent.poll()
    assert any(s.state is TaskState.ERROR for s in statuses)
    assert not (tmp_path / "etc").exists()
    agent.shutdown()
