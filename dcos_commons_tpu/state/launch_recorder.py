"""PersistentLaunchRecorder: WAL TaskInfos *before* launching.

Reference: state/PersistentLaunchRecorder.java, invoked at
DefaultScheduler.java:454-455 — every launch recommendation is written
to the state store before the accept call goes to Mesos, so a
scheduler crash between "decide" and "launch" resumes with the task
recorded (and reconciliation then discovers whether it actually
launched).  This idempotent WAL-before-act discipline is what makes
the control plane crash-restart safe (SURVEY.md section 7 hard part 1).
"""

from __future__ import annotations

from typing import List, Optional

from dcos_commons_tpu.common import TaskInfo
from dcos_commons_tpu.state.state_store import StateStore
from dcos_commons_tpu.trace.recorder import NULL_TRACER


class PersistentLaunchRecorder:
    def __init__(self, state_store: StateStore, tracer=None) -> None:
        self._state_store = state_store
        self._tracer = tracer

    def record(
        self, infos: List[TaskInfo], parent: Optional[object] = None
    ) -> None:
        """Atomically persist the pod's TaskInfos + seeded STAGING statuses.

        One persister transaction: a crash can never leave a gang launch
        half-recorded.  The STAGING seed gives reconciliation something
        to reconcile if the actual launch was lost in the crash.

        ``parent`` is the launch span: the WAL write is timed as its
        child (a slow persister shows up ON the launch it slowed).
        """
        tracer = self._tracer or NULL_TRACER
        with tracer.span(
            "launch.wal", parent=parent, track="scheduler",
            tasks=",".join(i.name for i in infos),
            task_ids=",".join(i.task_id for i in infos),
        ):
            self._state_store.store_launch(infos)
