"""HealthMonitor: the per-scheduler health plane driver.

Owned by ``DefaultScheduler`` and called at the end of every
``run_cycle``.  One ``observe()`` pass:

  * samples the metric registry into its bounded history rings
    (time-throttled: ``history_interval_s``),
  * fans in worker telemetry — steplogs and serving gauges — through
    the agent's sandbox readers (time-throttled:
    ``telemetry_interval_s``; the reads are one file open or HTTP
    round trip PER TASK, so production collection runs on a
    background thread and the cycle never blocks on a slow daemon;
    ``telemetry_interval_s=0`` collects inline for deterministic
    tests/benches),
  * runs the detectors (straggler, serving SLO, lease churn) once per
    COMPLETED collection,
  * pushes the suspect-host set into the inventory as the soft
    placement signal (suspect hosts sort LAST in scan order —
    superset-sound, placement never excludes a host on a score),
  * journals detector alerts and flushes the journal if dirty.

A broken detector degrades to a counted error
(``health.observe_errors``), never a failed scheduler cycle.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from dcos_commons_tpu.health.detectors import (
    LeaseChurnWatcher,
    QuietPodWatcher,
    ServingSloWatcher,
    StragglerDetector,
)
from dcos_commons_tpu.health.journal import EventJournal


class NullHealthMonitor:
    """The disabled plane (``health_enabled=False`` / the bench's
    disabled arm): every scheduler-facing surface exists and costs
    nothing."""

    def __init__(self):
        self.journal = EventJournal(backend=None, capacity=0)
        self.observe_errors = 0

    def attach(self, scheduler) -> "NullHealthMonitor":
        return self

    def observe(self, scheduler, now=None) -> list:
        return []

    def describe(self, scheduler, metric=None) -> dict:
        return {"enabled": False}


class HealthMonitor:
    def __init__(
        self,
        journal: Optional[EventJournal] = None,
        straggler: Optional[StragglerDetector] = None,
        slo: Optional[ServingSloWatcher] = None,
        lease_churn: Optional[LeaseChurnWatcher] = None,
        quiet: Optional[QuietPodWatcher] = None,
        interval_s: float = 0.0,
        telemetry_interval_s: float = 5.0,
        history_interval_s: float = 1.0,
        flush_interval_s: float = 1.0,
        auto_replace: bool = False,
        quiet_factor: float = 0.25,
    ):
        self.journal = journal or EventJournal(backend=None)
        self.straggler = straggler or StragglerDetector()
        self.slo = slo or ServingSloWatcher()
        self.lease_churn = lease_churn or LeaseChurnWatcher()
        # the scale-in low-watermark detector shares the SLO watcher's
        # threshold resolution (the two hysteresis bands must never
        # drift apart)
        self.quiet = quiet or QuietPodWatcher(
            self.slo, quiet_factor=quiet_factor
        )
        # detector cadence: 0 = every observe() call (tests, bench
        # worst case); production default rides the cycle rate
        self.interval_s = float(interval_s)
        # sandbox/wire fan-in cadence: steplog + servestats reads are
        # file opens per task (or HTTP round trips on a remote fleet)
        self.telemetry_interval_s = float(telemetry_interval_s)
        self.history_interval_s = float(history_interval_s)
        # journal flush cadence for cycle-batched events (plan
        # transitions): a flush serializes the whole bounded deque, so
        # per-dirty-cycle flushing is O(events) per cycle on a busy
        # deploy.  Alerts force an immediate flush, and operator verbs
        # flush inline at the HTTP layer — only routine transition
        # batching rides this clock (bounded-loss contract: a crash
        # forfeits at most flush_interval_s of transition events)
        self.flush_interval_s = float(flush_interval_s)
        # health -> action seam, DEFAULT OFF.  The logic lives in the
        # scheduler-owned HealthActionEngine (health/actions.py) —
        # this flag is the legacy ISSUE-13 gate that enables the
        # straggler auto-replace path even when the full action
        # policy is off; the engine also honors its own
        # ``policy.remediation`` gate.
        self.auto_replace = bool(auto_replace)
        self.observe_errors = 0
        self._last_observe = 0.0
        self._last_telemetry = 0.0
        self._last_history = 0.0
        self._last_flush = 0.0
        self._churn_seeded = False
        # completed-collection counter vs last-scored counter: the
        # detectors run exactly once per finished fan-in, whether it
        # ran inline (interval 0) or on the background thread
        self._telemetry_seq = 0
        self._scored_seq = 0
        # publication lock for the completed-collection snapshot: the
        # background collector swaps the three dicts + bumps the seq
        # under it, the scoring pass grabs the seq and dict REFERENCES
        # under it (the dicts themselves are replaced wholesale, never
        # mutated in place, so readers hold a consistent snapshot
        # lock-free once they have the references)
        self._telemetry_lock = threading.Lock()
        self._telemetry_thread: Optional[threading.Thread] = None
        # one steplog series per task, grouped by host (list of
        # record-lists — the straggler window applies per series)
        self._steplogs_by_host: Dict[str, List[List[dict]]] = {}
        self._serving_stats: Dict[str, dict] = {}
        self._serving_env: Dict[str, Dict[str, str]] = {}
        self._alerts = 0

    # -- wiring -------------------------------------------------------

    def attach(self, scheduler) -> "HealthMonitor":
        """Register the health.* gauges on a freshly-built scheduler."""
        metrics = scheduler.metrics
        metrics.gauge(
            "health.suspect_hosts",
            lambda: float(len(self.straggler.suspects)),
        )
        metrics.gauge(
            "health.straggler.max_score",
            lambda: float(max(self.straggler.scores.values(), default=0.0)),
        )
        metrics.gauge(
            "health.slo.breaches",
            lambda: float(len(self.slo.breaches)),
        )
        metrics.gauge(
            "health.journal.seq",
            lambda: float(self.journal.last_seq),
        )
        return self

    # -- the per-cycle pass -------------------------------------------

    def observe(self, scheduler, now: Optional[float] = None) -> List[dict]:
        """One health pass; returns the events journaled.  Never
        raises: the scheduler cycle must not die of its telemetry."""
        try:
            return self._observe(scheduler, now)
        except Exception:
            with self._telemetry_lock:
                self.observe_errors += 1
            scheduler.metrics.incr("health.observe_errors")
            return []

    def _observe(self, scheduler, now: Optional[float]) -> List[dict]:
        now = time.time() if now is None else now
        if self.interval_s and now - self._last_observe < self.interval_s:
            return []
        self._last_observe = now
        if not self.history_interval_s or \
                now - self._last_history >= self.history_interval_s:
            self._last_history = now
            scheduler.metrics.sample_history(t=now)
        telemetry_due = not self.telemetry_interval_s or \
            now - self._last_telemetry >= self.telemetry_interval_s
        if telemetry_due:
            self._last_telemetry = now
            if not self.telemetry_interval_s:
                # deterministic inline mode (tests, bench worst case)
                self._collect_telemetry(scheduler)
            elif self._telemetry_thread is None or \
                    not self._telemetry_thread.is_alive():
                # production: the fan-in is one blocking sandbox read
                # (or HTTP round trip, on a remote fleet) PER TASK —
                # serially inside run_cycle, one slow daemon would
                # stall every scheduler cycle, so collection runs off
                # the cycle thread and detectors score the completed
                # snapshot on a later cycle
                thread = threading.Thread(
                    target=self._collect_background,
                    args=(scheduler,),
                    name="health-telemetry",
                    daemon=True,
                )
                self._telemetry_thread = thread
                thread.start()
        events = []
        # steplog/servestats detectors re-score only when a collection
        # COMPLETED since the last scoring pass: identical cached
        # telemetry yields identical verdicts, and the median-ratio
        # pass over a big fleet's windows is the expensive part
        with self._telemetry_lock:
            telemetry_seq = self._telemetry_seq
            steplogs_by_host = self._steplogs_by_host
            serving_stats = self._serving_stats
            serving_env = self._serving_env
        if telemetry_seq != self._scored_seq:
            self._scored_seq = telemetry_seq
            events += self.straggler.observe(steplogs_by_host)
            self._push_suspects(scheduler)
            events += self.slo.observe(
                serving_stats, serving_env, now=now
            )
            events += self.quiet.observe(
                serving_stats, serving_env, now=now
            )
        ha_state = getattr(scheduler, "ha_state", None)
        lease = getattr(ha_state, "lease", None)
        # the persisted-record probe below is a store read — ride the
        # telemetry cadence rather than every cycle (the epoch moves
        # at most once per failover; a remote store would otherwise
        # pay an HTTP read per busy-poll cycle)
        if lease is not None and telemetry_due:
            # the local LeaderLease epoch is CONSTANT for this
            # incarnation's lifetime (losing the lease restarts the
            # process), so flapping is only visible across
            # incarnations: seed the watcher from the journaled
            # election events (the journal survives failover), then
            # watch the PERSISTED record's epoch — it moves when any
            # scheduler takes over
            if not self._churn_seeded:
                self._churn_seeded = True
                for event in self.journal.events(kinds=("election",)):
                    epoch = event.get("epoch")
                    if isinstance(epoch, (int, float)):
                        events += self.lease_churn.observe(
                            int(epoch), t=float(event.get("t", now))
                        )
            events += self.lease_churn.observe(
                lease.state().epoch, t=now
            )
        for event in events:
            attrs = {
                k: v for k, v in event.items()
                if k not in ("kind", "message")
            }
            self.journal.append(
                event.get("kind", "alert"),
                message=event.get("message", ""),
                **attrs,
            )
            self._alerts += 1
            scheduler.metrics.incr("health.alerts")
        # the action governor (health/actions.py): settle terminal
        # action phases, apply the autoscale decision rule against
        # this pass's episode state, and run the remediation seam on
        # this pass's straggler edges.  The engine journals its own
        # events (they are alerts: inline durability below).
        actions = getattr(scheduler, "actions", None)
        if actions is not None:
            events += actions.observe(scheduler, self, now)
            # one gate expression; remediate() is a cheap no-op when
            # disabled (remediation_allowed re-checks enabled)
            events += actions.remediate(
                scheduler, events,
                self.auto_replace or actions.policy.remediation,
                now,
                # the STATEFUL churn flag: the hold must cover the
                # whole open episode, not just its opening edge
                hold=bool(getattr(self.lease_churn, "alerted", False)),
            )
        # alerts deserve immediate durability; routine transition
        # batches flush on the throttle clock
        if events or not self.flush_interval_s or \
                now - self._last_flush >= self.flush_interval_s:
            self._last_flush = now
            self.journal.flush()
        return events

    @property
    def serving_stats(self):
        """The last completed telemetry snapshot (task -> stats) —
        the action governor's read surface."""
        return self._serving_stats

    def _collect_background(self, scheduler) -> None:
        try:
            self._collect_telemetry(scheduler)
        except Exception:
            with self._telemetry_lock:
                self.observe_errors += 1
            try:
                scheduler.metrics.incr("health.observe_errors")
            except Exception:  # sdklint: disable=swallowed-exception — already inside the error path of a telemetry thread; observe_errors was counted above, and a metrics hiccup must not kill the collector
                pass

    def _collect_telemetry(self, scheduler) -> None:
        read_steplog = getattr(scheduler.agent, "steplog_of", None)
        read_serving = getattr(scheduler.agent, "serving_stats_of", None)
        steplogs: Dict[str, List[List[dict]]] = {}
        serving: Dict[str, dict] = {}
        env_of: Dict[str, Dict[str, str]] = {}
        for info in scheduler.state_store.fetch_tasks():
            if callable(read_steplog):
                try:
                    # agent_id pins the route: on a shared remote
                    # fleet, task names are not service-qualified and
                    # a name-only lookup could read another service's
                    # same-named task
                    records = read_steplog(
                        info.name, agent_id=info.agent_id
                    )
                except OSError:
                    records = []
                if records:
                    # several tasks can share a host (colocated pods):
                    # each task stays its own series so the detector's
                    # trailing window applies per task, never evicting
                    # one colocated task's records with another's
                    steplogs.setdefault(info.agent_id, []).append(records)
            if callable(read_serving):
                try:
                    stats = read_serving(
                        info.name, agent_id=info.agent_id
                    )
                except OSError:
                    stats = {}
                if stats:
                    serving[info.name] = stats
                    env_of[info.name] = info.env
        # publish the completed fan-in atomically: fresh dicts swapped
        # in wholesale (never mutated after this point), seq bumped
        # LAST so a reader seeing the new seq sees the new dicts
        with self._telemetry_lock:
            self._steplogs_by_host = steplogs
            self._serving_stats = serving
            self._serving_env = env_of
            self._telemetry_seq += 1

    def _push_suspects(self, scheduler) -> None:
        setter = getattr(scheduler.inventory, "set_suspect_hosts", None)
        if callable(setter):
            # keyed by service: on a shared multi-service inventory
            # the demotion set is the union across services — this
            # service reporting "no stragglers among MY tasks" must
            # not clear a host another service demoted
            setter(
                set(self.straggler.suspects),
                source=getattr(scheduler.spec, "name", ""),
            )

    # -- the /v1/debug/health body ------------------------------------

    def describe(self, scheduler, metric: Optional[str] = None) -> dict:
        body = {
            "enabled": True,
            "status": "warn" if (
                self.straggler.suspects or self.slo.breaches
            ) else "ok",
            "suspect_hosts": dict(sorted(self.straggler.suspects.items())),
            "straggler": {
                "threshold": self.straggler.threshold,
                "window": self.straggler.window,
                "scores": {
                    host: round(score, 3)
                    for host, score in sorted(self.straggler.scores.items())
                },
            },
            "slo": {
                "breaches": [
                    {"task": task, "signal": signal, "value": value}
                    for (task, signal), value in sorted(
                        self.slo.breaches.items()
                    )
                ],
                # snapshots discarded unscored because their liveness
                # stamps went stale (a wedged pod's last-good gauges)
                "stale_discards": self.slo.stale_discards,
            },
            "quiet": {
                "tasks": {
                    task: round(since, 3)
                    for task, since in sorted(
                        self.quiet.quiet_since.items()
                    )
                },
                "factor": self.quiet.quiet_factor,
            },
            "serving": self._serving_stats,
            "journal": self.journal.describe(),
            "alerts_recent": self.journal.events(kinds=("alert",), limit=20),
            "observe_errors": self.observe_errors,
        }
        actions = getattr(scheduler, "actions", None)
        if actions is not None:
            # the closed-loop state: active scale phases, cooldown
            # clocks, remediation latches (the runbook's first read
            # when triaging an automated action)
            body["actions"] = actions.describe()
            body["actions"]["recent"] = self.journal.events(
                kinds=("health",), limit=20
            )
        history = scheduler.metrics.history
        if metric:
            body["history"] = {
                "metric": metric,
                "samples": [
                    [round(t, 3), v] for t, v in history.series(metric)
                ],
                "rate_per_s": history.rate(metric),
            }
        else:
            body["history"] = history.summary()
        return body
