"""Core task-data types shared by every layer.

The reference smuggles this information inside Mesos protobufs
(``TaskInfo``/``TaskStatus``) plus labels (reference:
sdk/scheduler/src/main/java/com/mesosphere/sdk/offer/taskdata/,
LabelConstants.java:46,66).  The rebuild has no Mesos, so these are
plain serializable dataclasses owned by the framework itself.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List


class TaskState(enum.Enum):
    """Task lifecycle states.

    Mirrors the Mesos TaskState vocabulary the reference consumes
    (reference: framework/FrameworkScheduler.java:273 status fan-in),
    with TPU-specific additions: PREEMPTED (slice preemption) and
    MAINTENANCE (host entering maintenance) play the role the
    reference gives TASK_LOST + PARTITION_AWARE signals
    (FrameworkRunner.java:185-189).
    """

    STAGING = "TASK_STAGING"      # accepted, sandbox being provisioned
    STARTING = "TASK_STARTING"    # process launched, not yet healthy
    RUNNING = "TASK_RUNNING"
    FINISHED = "TASK_FINISHED"    # terminal, success (goal FINISH/ONCE)
    FAILED = "TASK_FAILED"        # terminal, nonzero exit
    KILLED = "TASK_KILLED"        # terminal, killed by scheduler
    LOST = "TASK_LOST"            # terminal, agent disappeared
    PREEMPTED = "TASK_PREEMPTED"  # terminal, TPU slice preempted
    ERROR = "TASK_ERROR"          # terminal, invalid task

    @property
    def is_terminal(self) -> bool:
        return self in _TERMINAL_STATES

    @property
    def is_failure(self) -> bool:
        """Terminal states that should trigger recovery."""
        return self in (
            TaskState.FAILED,
            TaskState.LOST,
            TaskState.PREEMPTED,
            TaskState.ERROR,
        )

    @property
    def is_running(self) -> bool:
        return self is TaskState.RUNNING


_TERMINAL_STATES = frozenset(
    {
        TaskState.FINISHED,
        TaskState.FAILED,
        TaskState.KILLED,
        TaskState.LOST,
        TaskState.PREEMPTED,
        TaskState.ERROR,
    }
)


def new_task_id(task_name: str) -> str:
    """``<name>__<uuid>`` task-id scheme (reference: offer/CommonIdUtils.java)."""
    return f"{task_name}__{uuid.uuid4().hex}"


def task_name_of(task_id: str) -> str:
    """Inverse of :func:`new_task_id`."""
    name, sep, _ = task_id.rpartition("__")
    if not sep:
        raise ValueError(f"not a task id: {task_id!r}")
    return name


# ---------------------------------------------------------------------------
# Serialization helpers (JSON <-> dataclasses, enum-aware)
# ---------------------------------------------------------------------------


def _to_jsonable(value: Any) -> Any:
    if isinstance(value, enum.Enum):
        return value.value
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return {
            f.name: _to_jsonable(getattr(value, f.name))
            for f in dataclasses.fields(value)
        }
    if isinstance(value, dict):
        return {k: _to_jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_to_jsonable(v) for v in value]
    return value


class SerializableMixin:
    """JSON round-tripping for the task-data dataclasses."""

    def to_dict(self) -> Dict[str, Any]:
        return _to_jsonable(self)

    def to_bytes(self) -> bytes:
        return json.dumps(self.to_dict(), sort_keys=True).encode("utf-8")

    @classmethod
    def from_dict(cls, data: Dict[str, Any]):
        kwargs: Dict[str, Any] = {}
        hints = {f.name: f for f in dataclasses.fields(cls)}
        for key, value in data.items():
            if key not in hints:
                continue  # forward compatibility: ignore unknown fields
            kwargs[key] = _coerce(hints[key].type, value)
        return cls(**kwargs)

    @classmethod
    def from_bytes(cls, raw: bytes):
        return cls.from_dict(json.loads(raw.decode("utf-8")))


def _coerce(type_name: Any, value: Any) -> Any:
    # dataclass field types arrive as strings (PEP 563 style annotations).
    if value is None:
        return None
    name = type_name if isinstance(type_name, str) else getattr(type_name, "__name__", "")
    if "TaskState" in name:
        return TaskState(value)
    if "TaskInfo" in name and isinstance(value, dict):
        return TaskInfo.from_dict(value)
    if "List[TaskInfo]" in name and isinstance(value, list):
        return [TaskInfo.from_dict(v) for v in value]
    return value


# ---------------------------------------------------------------------------
# TaskInfo / TaskStatus
# ---------------------------------------------------------------------------


@dataclass
class TaskInfo(SerializableMixin):
    """Everything the scheduler decided about one launched task.

    The reference assembles the equivalent Mesos proto in
    PodInfoBuilder (offer/evaluate/PodInfoBuilder.java, 831 LoC) and
    stores per-task metadata in labels (offer/taskdata/).  Here the
    labels are first-class fields.
    """

    name: str                       # "<pod>-<index>-<task>"
    task_id: str = ""
    agent_id: str = ""              # host the task was placed on
    pod_type: str = ""
    pod_index: int = 0
    command: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    # resource ids from the reservation ledger (reference: resource-id
    # labels stamped by offer/ResourceBuilder.java)
    resource_ids: List[str] = field(default_factory=list)
    tpu_chip_ids: List[str] = field(default_factory=list)
    volume_ids: List[str] = field(default_factory=list)
    # container_path -> durable volume key; the agent materializes each
    # as a persistent directory symlinked into the sandbox, so TRANSIENT
    # relaunches (same reservation -> same key) reattach their data and
    # PERMANENT replaces (fresh reservation -> fresh key) start empty
    volumes: Dict[str, str] = field(default_factory=dict)
    # labels carry the remaining metadata the reference keeps in
    # offer/taskdata/LabelConstants.java: target config id, readiness
    # spec, permanently-failed flag, hostname/zone of launch...
    labels: Dict[str, str] = field(default_factory=dict)

    def with_label(self, key: str, value: str) -> "TaskInfo":
        info = dataclasses.replace(
            self,
            env=dict(self.env),
            resource_ids=list(self.resource_ids),
            tpu_chip_ids=list(self.tpu_chip_ids),
            volume_ids=list(self.volume_ids),
            volumes=dict(self.volumes),
            labels={**self.labels, key: value},
        )
        return info


@dataclass
class TaskStatus(SerializableMixin):
    """One status update for a task (reference: Mesos TaskStatus)."""

    task_id: str
    state: TaskState
    message: str = ""
    agent_id: str = ""
    timestamp: float = 0.0
    # readiness-check-passed travels on the status, mirroring the
    # reference's readiness label flow (PodInfoBuilder.java:511-526).
    ready: bool = False
    container_ip: str = ""

    def __post_init__(self) -> None:
        if isinstance(self.state, str):
            self.state = TaskState(self.state)
        if not self.timestamp:
            self.timestamp = time.time()


def atomic_write_text(path: str, content: str) -> None:
    """Write-tmp-fsync-then-rename so readers never see a partial
    file AND the content survives a power failure at the rename
    (announce files, PID files)."""
    import os

    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(content)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class Label:
    """Well-known label keys (reference: offer/taskdata/LabelConstants.java)."""

    TARGET_CONFIG = "target_configuration"
    READINESS_CHECK_PASSED = "readiness_check_passed"
    PERMANENTLY_FAILED = "permanently_failed"
    DECOMMISSIONED = "decommissioned"
    HOSTNAME = "offer_hostname"
    ZONE = "offer_zone"
    REGION = "offer_region"
    GOAL_STATE = "goal_state"
    GOAL_STATE_OVERRIDE = "goal_state_override"
    NETWORKS = "networks"
    SHARE_PID_NAMESPACE = "share_pid_namespace"
