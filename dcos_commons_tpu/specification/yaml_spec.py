"""YAML front end: svc.yml -> ServiceSpec.

Reference: specification/yaml/ — RawServiceSpec et al. (Jackson beans),
TemplateUtils.java (mustache env substitution with missing-value
errors), YAMLToInternalMappers.java (Raw -> Default* conversion, 805
LoC).  The YAML shape mirrors the reference svc.yml dialect
(frameworks/helloworld/src/main/dist/*.yml): pods and tasks are maps,
scalar resources are inline task keys, plans name phases over pods.

TPU-first: a pod-level ``tpu:`` block replaces per-task ``gpus:``
scalars; ``gang: true`` requests slice-wide gang scheduling.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Mapping, Optional

import yaml

from dcos_commons_tpu.specification.specs import (
    GoalState,
    HealthCheckSpec,
    PodSpec,
    PortSpec,
    ReadinessCheckSpec,
    ReplacementFailurePolicy,
    ResourceSpec,
    SecretSpec,
    ServiceSpec,
    SpecError,
    TaskSpec,
    TpuSpec,
    TransportEncryptionSpec,
    VolumeSpec,
)

_TEMPLATE_RE = re.compile(r"\{\{([A-Za-z_][A-Za-z0-9_]*)(?::-([^}]*))?\}\}")


_SECTION_RE = re.compile(
    r"\{\{([#^])([A-Za-z0-9_]+)\}\}\n?(.*?)\{\{/\2\}\}\n?", re.DOTALL
)


def _truthy(value) -> bool:
    """Mustache truthiness for section tags: unset/empty/false/0 hide
    a ``{{#VAR}}`` block (and show a ``{{^VAR}}`` one)."""
    return str(value).strip().lower() not in ("", "false", "0", "no")


def render_template(text: str, env: Mapping[str, str]) -> str:
    """Mustache-style ``{{VAR}}`` substitution from an env map, plus
    boolean sections ``{{#VAR}}...{{/VAR}}`` (kept when VAR is truthy)
    and ``{{^VAR}}...{{/VAR}}`` (kept when falsy).

    Reference: specification/yaml/TemplateUtils.java — missing values
    are an error (listing every missing variable), so a bad install
    fails loudly at spec-render time rather than at task runtime.
    ``{{VAR:-default}}`` supplies a default.  Sections are the
    enable-disable plane (enable-disable.yml): a plan can include or
    exclude whole steps from one boolean option, and flipping it via
    a config update adds/removes the tasks with a rolling update.
    """
    # sections first (innermost-out via repeated passes), so variables
    # inside a hidden block are never "missing"
    def section_sub(match: re.Match) -> str:
        kind, var, body = match.groups()
        show = _truthy(env.get(var, ""))
        if kind == "^":
            show = not show
        return body if show else ""

    prev = None
    while prev != text:
        prev, text = text, _SECTION_RE.sub(section_sub, text)
    # an unbalanced or malformed tag ({{#VAR}} missing its {{/VAR}},
    # a stray closer, a typo like {{#MY-FLAG}} or {{# FLAG}}) never
    # matched _SECTION_RE and would otherwise pass through SILENTLY
    # into the rendered YAML — fail loudly like missing variables do
    # (TemplateUtils-style).  The detector is deliberately wider than
    # the section grammar: anything section-shaped that survived
    # expansion is an error.
    leftover = re.findall(r"\{\{\s*[#^/][^}]*\}\}", text)
    if leftover:
        raise SpecError(
            f"unbalanced or malformed section tags: "
            f"{sorted(set(leftover))} — every {{{{#VAR}}}}/{{{{^VAR}}}} "
            f"needs a matching {{{{/VAR}}}} and names are [A-Za-z0-9_]"
        )
    missing = []

    def sub(match: re.Match) -> str:
        var, default = match.group(1), match.group(2)
        if var in env:
            return str(env[var])
        if default is not None:
            return default
        missing.append(var)
        return ""

    rendered = _TEMPLATE_RE.sub(sub, text)
    if missing:
        raise SpecError(
            f"missing template values: {sorted(set(missing))}"
        )
    return rendered


def from_yaml_file(path: str, env: Optional[Mapping[str, str]] = None) -> ServiceSpec:
    with open(path, "r", encoding="utf-8") as f:
        # config template paths resolve relative to the YAML's own
        # directory (the reference ships templates next to svc.yml in
        # the scheduler's dist dir)
        return from_yaml(
            f.read(), env, base_dir=os.path.dirname(os.path.abspath(path))
        )


def from_yaml(
    text: str,
    env: Optional[Mapping[str, str]] = None,
    base_dir: str = "",
) -> ServiceSpec:
    raw = yaml.safe_load(render_template(text, env or {}))
    if not isinstance(raw, dict):
        raise SpecError("service YAML must be a mapping")
    return _map_service(raw, env or {}, base_dir)


def _env_name(pod_type: str) -> str:
    """Pod type -> env-var fragment (reference: EnvUtils.toEnvName —
    uppercase, non-alphanumerics to underscores)."""
    return re.sub(r"[^A-Z0-9]", "_", pod_type.upper())


def route_task_env(env: Mapping[str, str], pod_type: str) -> Dict[str, str]:
    """Per-task config-plane routing: ``TASKCFG_ALL_FOO=x`` lands as
    ``FOO=x`` in every task; ``TASKCFG_<PODTYPE>_FOO=x`` only in tasks
    of that pod and wins over the ALL form.

    Reference: config/TaskEnvRouter.java:17-30 — scheduler-process env
    is the routing source, and routed values override YAML task env so
    end users can retune a packaged service without editing its YAML.
    """
    routed: Dict[str, str] = {}
    all_prefix = "TASKCFG_ALL_"
    pod_prefix = f"TASKCFG_{_env_name(pod_type)}_"
    for key, value in env.items():
        if key.startswith(all_prefix) and key not in (all_prefix,):
            routed.setdefault(key[len(all_prefix):], str(value))
    for key, value in env.items():
        if pod_prefix != all_prefix and key.startswith(pod_prefix):
            routed[key[len(pod_prefix):]] = str(value)
    return {k: v for k, v in routed.items() if k}


def _map_service(
    raw: Dict[str, Any],
    env: Optional[Mapping[str, str]] = None,
    base_dir: str = "",
) -> ServiceSpec:
    name = raw.get("name")
    if not name:
        raise SpecError("service requires a name")
    pods_raw = raw.get("pods") or {}
    if not pods_raw:
        raise SpecError(f"service {name!r} requires at least one pod")
    pods = tuple(
        _map_pod(pod_name, pod_raw or {}, env or {}, base_dir)
        for pod_name, pod_raw in pods_raw.items()
    )
    # 'recovery'/'decommission'/'uninstall' are built-in plan names; a
    # custom YAML plan with one of them would shadow the real plan in
    # scheduler.plans() and make its state unobservable
    reserved = {"recovery", "decommission", "uninstall"}
    clash = reserved & set((raw.get("plans") or {}).keys())
    if clash:
        raise SpecError(
            f"service {name!r}: plan names {sorted(clash)} are reserved"
        )
    rfp_raw = raw.get("replacement-failure-policy")
    rfp = None
    if rfp_raw:
        rfp = ReplacementFailurePolicy(
            permanent_failure_timeout_s=float(
                rfp_raw.get("permanent-failure-timeout-secs", 1200)
            ),
            min_replace_delay_s=float(rfp_raw.get("min-replace-delay-secs", 600)),
        )
    return ServiceSpec(
        name=str(name),
        role=str(raw.get("role", "") or f"{name}-role"),
        user=str(raw.get("user", "")),
        region=str(raw.get("region", "")),
        zone=str(raw.get("zone", "")),
        web_url=str(raw.get("web-url", "")),
        service_tld=str(raw.get("service-tld", "fleet.local")),
        pods=pods,
        replacement_failure_policy=rfp,
        plans=raw.get("plans") or {},
    )


def _map_pod(
    pod_name: str,
    raw: Dict[str, Any],
    env: Optional[Mapping[str, str]] = None,
    base_dir: str = "",
) -> PodSpec:
    tasks_raw = raw.get("tasks") or {}
    if not tasks_raw:
        raise SpecError(f"pod {pod_name!r} requires at least one task")
    routed_env = route_task_env(env or {}, pod_name)
    tpu_raw = raw.get("tpu")
    tpu = None
    if tpu_raw:
        tpu = TpuSpec(
            generation=str(tpu_raw.get("generation", "v5e")),
            chips_per_host=int(tpu_raw.get("chips-per-host", 4)),
            topology=str(tpu_raw.get("topology", "")),
            slices=int(tpu_raw.get("slices", 1)),
            elastic=bool(tpu_raw.get("elastic", False)),
            min_hosts=int(tpu_raw.get("min-hosts", 1)),
        )
    from dcos_commons_tpu.specification.specs import (
        merge_pod_uris,
        merge_pod_volumes,
    )

    pod_volumes = _map_volumes(raw)
    pod_uris = _map_uris(raw)
    # shared with from_dict: the evaluator's sibling-sharing then gives
    # all tasks ONE durable key per container path
    tasks = merge_pod_uris(
        merge_pod_volumes(
            tuple(
                _map_task(task_name, task_raw or {}, routed_env, base_dir)
                for task_name, task_raw in tasks_raw.items()
            ),
            pod_volumes,
        ),
        pod_uris,
    )
    return PodSpec(
        type=str(pod_name),
        count=int(raw.get("count", 1)),
        tasks=tasks,
        tpu=tpu,
        gang=bool(raw.get("gang", False)),
        image=str(raw.get("image", "")),
        networks=_map_networks(raw),
        placement=str(raw.get("placement", "")),
        volumes=pod_volumes,
        uris=pod_uris,
        pre_reserved_role=str(raw.get("pre-reserved-role", "")),
        allow_decommission=bool(raw.get("allow-decommission", False)),
        share_pid_namespace=bool(raw.get("share-pid-namespace", False)),
        secrets=_map_secrets(pod_name, raw),
        rlimits=_map_rlimits(pod_name, raw),
    )


def _map_rlimits(pod_name: str, raw: Dict[str, Any]):
    """Reference dialect (svc.yml:9-13): a map of rlimit name ->
    {soft, hard}; both omitted means "named but unlimited"."""
    from dcos_commons_tpu.specification.specs import (
        RLIMIT_INFINITY,
        RLimitSpec,
    )

    rlimits = []
    for rl_name, rl_raw in (raw.get("rlimits") or {}).items():
        rl_raw = rl_raw or {}
        if not isinstance(rl_raw, dict):
            raise SpecError(
                f"pod {pod_name!r}: rlimit {rl_name} must be a "
                f"{{soft, hard}} mapping, got {rl_raw!r}"
            )
        try:
            rlimits.append(RLimitSpec(
                name=str(rl_name),
                soft=int(rl_raw.get("soft", RLIMIT_INFINITY)),
                hard=int(rl_raw.get("hard", RLIMIT_INFINITY)),
            ))
        except SpecError as e:
            raise SpecError(f"pod {pod_name!r}: {e}")
        except (TypeError, ValueError) as e:
            raise SpecError(
                f"pod {pod_name!r}: rlimit {rl_name} has a non-integer "
                f"limit: {e}"
            )
    return tuple(rlimits)


def _map_secrets(pod_name: str, raw: Dict[str, Any]):
    secrets = []
    for sec_name, sec_raw in (raw.get("secrets") or {}).items():
        sec_raw = sec_raw or {}
        source = str(sec_raw.get("secret", ""))
        if not source:
            raise SpecError(
                f"secret {sec_name!r} in pod {pod_name!r} needs a "
                "'secret' ref"
            )
        secrets.append(SecretSpec(
            secret=source,
            env_key=str(sec_raw.get("env-key", "")),
            file=str(sec_raw.get("file", "")),
        ))
    return tuple(secrets)


def _map_task(
    task_name: str,
    raw: Dict[str, Any],
    routed_env: Optional[Dict[str, str]] = None,
    base_dir: str = "",
) -> TaskSpec:
    ports = []
    for port_name, port_raw in (raw.get("ports") or {}).items():
        port_raw = port_raw or {}
        ports.append(
            PortSpec(
                name=str(port_name),
                port=int(port_raw.get("port", 0)),
                vip=str(port_raw.get("vip", "")),
                env_key=str(port_raw.get("env-key", "")),
                advertise=_truthy(port_raw.get("advertise", False)),
            )
        )
    hc_raw = raw.get("health-check")
    hc = None
    if hc_raw:
        hc = HealthCheckSpec(
            cmd=str(hc_raw["cmd"]),
            interval_s=float(hc_raw.get("interval", 30)),
            grace_period_s=float(hc_raw.get("grace-period", 30)),
            timeout_s=float(hc_raw.get("timeout", 20)),
            max_consecutive_failures=int(hc_raw.get("max-consecutive-failures", 3)),
            delay_s=float(hc_raw.get("delay", 0)),
        )
    rc_raw = raw.get("readiness-check")
    rc = None
    if rc_raw:
        rc = ReadinessCheckSpec(
            cmd=str(rc_raw["cmd"]),
            interval_s=float(rc_raw.get("interval", 5)),
            timeout_s=float(rc_raw.get("timeout", 10)),
        )
    templates = []
    for cfg_name, cfg_raw in (raw.get("configs") or {}).items():
        cfg_raw = cfg_raw or {}
        if "template" not in cfg_raw or "dest" not in cfg_raw:
            raise SpecError(
                f"config {cfg_name!r} in task {task_name!r} needs template+dest"
            )
        template_path = str(cfg_raw["template"])
        if base_dir and not os.path.isabs(template_path):
            template_path = os.path.join(base_dir, template_path)
        templates.append((template_path, str(cfg_raw["dest"])))
    return TaskSpec(
        name=str(task_name),
        goal=GoalState(str(raw.get("goal", "RUNNING")).upper()),
        cmd=str(raw.get("cmd", "")),
        env={
            **{str(k): str(v) for k, v in (raw.get("env") or {}).items()},
            **(routed_env or {}),
        },
        resources=ResourceSpec(
            cpus=float(raw.get("cpus", 0.1)),
            memory_mb=int(raw.get("memory", 32)),
            disk_mb=int(raw.get("disk", 0)),
            ports=tuple(ports),
        ),
        volumes=_map_volumes(raw),
        health_check=hc,
        readiness_check=rc,
        config_templates=tuple(templates),
        uris=_map_uris(raw),
        discovery_prefix=str(
            (raw.get("discovery") or {}).get("prefix", "")
        ),
        kill_grace_period_s=float(raw.get("kill-grace-period", 3)),
        essential=bool(raw.get("essential", True)),
        transport_encryption=tuple(
            TransportEncryptionSpec(
                name=str(t.get("name", task_name)),
                type=str(t.get("type", "TLS")).upper(),
            )
            for t in (raw.get("transport-encryption") or [])
        ),
    )


def _map_networks(raw: Dict[str, Any]) -> tuple:
    # reference YAML uses a map (network name -> options); lists accepted too
    nets = raw.get("networks") or ()
    if isinstance(nets, dict):
        return tuple(str(n) for n in nets)
    return tuple(str(n) for n in nets)


def _map_uris(raw: Dict[str, Any]) -> tuple:
    """``uris:`` at pod or task level — the reference's plain-string
    list (uri.yml:8), plus mapping entries for the TPU additions::

        uris:
          - "https://repo/artifact.bin"
          - uri: "https://repo/corpus.tar"
            dest: data/corpus.tar
            sha256: ab34...
            extract: true
    """
    from dcos_commons_tpu.specification.specs import UriSpec

    uris = []
    for entry in raw.get("uris") or []:
        if isinstance(entry, str):
            uris.append(UriSpec(uri=entry))
            continue
        if not isinstance(entry, dict) or not entry.get("uri"):
            raise SpecError(f"uris entries need a 'uri': {entry!r}")
        uris.append(UriSpec(
            uri=str(entry["uri"]),
            dest=str(entry.get("dest", "")),
            sha256=str(entry.get("sha256", "")).lower(),
            extract=bool(entry.get("extract", False)),
            executable=bool(entry.get("executable", False)),
        ))
    return tuple(uris)


def _map_volumes(raw: Dict[str, Any]) -> tuple:
    vols = []
    single = raw.get("volume")
    multi = raw.get("volumes") or {}
    entries = []
    if single:
        entries.append(single)
    if isinstance(multi, dict):
        entries.extend(v for v in multi.values() if v)
    for v in entries:
        vols.append(
            VolumeSpec(
                container_path=str(v["path"]),
                size_mb=int(v.get("size", 0)),
                type=str(v.get("type", "ROOT")).upper(),
                profiles=tuple(v.get("profiles", ()) or ()),
            )
        )
    return tuple(vols)
