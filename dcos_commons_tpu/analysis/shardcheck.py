"""shardcheck: static sharding, HBM-footprint, and collective-cost
analysis for the JAX frameworks.

The one launch failure none of the other analyzers can see is a
LAYOUT failure: a ServiceSpec whose declared torus cannot lay the
mesh its worker derives, a PartitionSpec axis the mesh does not
divide into a param dim, or a model whose per-chip HBM footprint
exceeds what the spec reserved — all of which today surface as an
XLA error (or an OOM) minutes into a multi-host pjit deploy.  This
pass closes that gap at lint time, GSPMD-style partitioning
validation moved ahead of the scheduler: for every
``frameworks/jax/*.yml`` rendered with its ``options.json`` defaults
it rebuilds the EXACT workload the task command would run —
``models.config_from_env`` for the model, ``parallel.mesh.derive``
for the mesh (both the very functions the worker calls), real
``sharding_rules`` / ``init_params`` / ``init_kv_cache`` evaluated
ABSTRACTLY via ``jax.eval_shape`` (shape/dtype only: no devices, no
FLOPs, JAX_PLATFORMS=cpu-safe) — and walks params + optimizer state
+ gradient + activation/KV estimates through the PartitionSpec rules.

Rules (YAML-suppressible like speccheck findings, anchored to the
pod's declaring line; absorbable by ``.sdklint-baseline.json``):

- ``shard-mesh``          the declared topology cannot lay a
  host-aligned mesh (``derive`` raises SpecError), the workload's
  mesh spans a different chip count than the pod reserves (idle or
  oversubscribed chips), or a mesh axis of size > 1 shards nothing.
- ``shard-divisibility``  a mesh axis product does not divide the
  param/activation dim its PartitionSpec shards — GSPMD would pad or
  the pjit would fail outright.
- ``shard-unknown-axis``  a PartitionSpec names an axis outside the
  mesh-axis vocabulary (``MeshSpec`` fields plus spmdcheck's
  harvested ``Mesh(...)``/``axis_name=`` vocabulary).
- ``shard-replicated-giant``  a param above ``--giant-mb`` is
  replicated across mesh axes of size > 1 — usually a missing fsdp/tp
  entry in the rules, each replica burning HBM on every chip.
- ``shard-hbm-overcommit``  the per-chip footprint exceeds the
  generation's HBM (``--hbm-mb`` overrides the table), or the
  per-host footprint exceeds the task's declared ``memory:``.

Beyond findings, every analyzed pod emits a footprint breakdown and
a ring-vs-all-gather collective-cost estimate per training step over
the ICI torus (``--json`` keys ``shard.footprint`` / ``shard.cost``)
so bench trends can track layout regressions.

Footprint model (documented in developer-guide §10): params at their
init dtype (int8 + per-channel scale when ``WEIGHT_DTYPE=int8``),
gradients mirroring params (training), optimizer state via
``jax.eval_shape(optimizer.init)`` with param-shaped leaves
inheriting the param's sharding, live activations = per-layer
residual boundaries (remat's floor) + the f32 logits block, and the
KV cache via the real ``init_kv_cache`` (serving).  Per-chip bytes
divide each dim by the product of its mesh-axis sizes; everything a
spec does not shard replicates.
"""

from __future__ import annotations

import functools
import math
import os
import re
from dataclasses import dataclass, field
from typing import (
    Any,
    Callable,
    Dict,
    FrozenSet,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

from dcos_commons_tpu.analysis.linter import (
    Finding,
    LintResult,
    Suppressions,
)

# per-chip HBM by TPU generation (MB) — the capacity the footprint is
# judged against when the spec's host memory is roomier than the chip
GENERATION_HBM_MB = {
    "v4": 32 * 1024,
    "v5e": 16 * 1024,
    "v5p": 95 * 1024,
    "v6e": 32 * 1024,
}
# per-link ICI bandwidth (GB/s, one direction) for the cost estimate
ICI_GBPS = {"v4": 45.0, "v5e": 45.0, "v5p": 90.0, "v6e": 90.0}
DEFAULT_ICI_GBPS = 45.0
# cross-slice data-center network (dcn axis collectives)
DCN_GBPS = 12.5


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


def normalize_spec(spec, rank: int) -> Tuple[Tuple[str, ...], ...]:
    """PartitionSpec -> per-dim tuples of axis names, length ``rank``.

    ``P("tp", ("dp", "fsdp"), None)`` at rank 4 becomes
    ``(("tp",), ("dp", "fsdp"), (), ())``.
    """
    entries: List[Tuple[str, ...]] = []
    for entry in tuple(spec or ()):
        if entry is None:
            entries.append(())
        elif isinstance(entry, str):
            entries.append((entry,))
        else:
            entries.append(tuple(entry))
    while len(entries) < rank:
        entries.append(())
    return tuple(entries[:rank])


@dataclass(frozen=True)
class AbstractLeaf:
    """One abstract array: a param, grad, optimizer, activation, or
    KV-cache tensor with its sharding rule."""

    path: str                       # e.g. "params/layers/wq"
    shape: Tuple[int, ...]
    dtype_bytes: int
    spec: Tuple[Tuple[str, ...], ...]
    section: str                    # params|grads|opt|activations|kv
    # the sharding-rule path this leaf's spec came from: the dedup
    # identity, so the params/grads/opt copies of one bad rule report
    # ONE finding (defaults to the path minus its section prefix)
    rule_path: str = ""

    @property
    def bytes(self) -> int:
        return _prod(self.shape) * self.dtype_bytes

    @property
    def dedup_path(self) -> str:
        return self.rule_path or self.path.split("/", 1)[-1]


@dataclass
class LeafReport:
    """The sharding arithmetic of one leaf over one mesh."""

    leaf: AbstractLeaf
    per_chip_bytes: int = 0
    shard_product: int = 1
    replication: int = 1
    # (rule-id, dedup-key, message) triples
    problems: List[Tuple[str, str, str]] = field(default_factory=list)


def shard_leaf(
    leaf: AbstractLeaf,
    axes: Mapping[str, int],
    vocab: FrozenSet[str] = frozenset(),
) -> LeafReport:
    """Divide one leaf over the mesh; the exactness property
    (tests/test_shard_properties.py) is
    ``per_chip_bytes * total_chips == bytes * replication``
    whenever every sharded dim divides evenly."""
    report = LeafReport(leaf)
    bare = leaf.dedup_path
    per_chip_elems = 1
    for i, dim in enumerate(leaf.shape):
        names = leaf.spec[i] if i < len(leaf.spec) else ()
        q = 1
        for name in names:
            size = axes.get(name)
            if size is None:
                if name not in vocab:
                    report.problems.append((
                        "shard-unknown-axis",
                        f"{bare}:{name}",
                        f"{leaf.path} dim {i}: PartitionSpec names "
                        f"axis {name!r}, which is in no mesh-axis "
                        "vocabulary of the tree",
                    ))
                # harvested-but-unlaid axes act as size 1 (replicated)
                continue
            q *= size
        if q > 1 and dim % q:
            report.problems.append((
                "shard-divisibility",
                f"{bare}:{i}",
                f"{leaf.path}: mesh axes {'*'.join(names)} (size {q}) "
                f"do not divide dim {i} of shape "
                f"{tuple(leaf.shape)} ({dim} % {q} = {dim % q})",
            ))
        report.shard_product *= q
        per_chip_elems *= math.ceil(dim / q)
    total = _prod(axes.values()) or 1
    report.per_chip_bytes = per_chip_elems * leaf.dtype_bytes
    report.replication = max(total // report.shard_product, 1)
    return report


def _walk_shapes(tree, rules: Mapping[str, Any], section: str,
                 dtype_bytes=None, prefix: str = "") -> List[AbstractLeaf]:
    """Flatten an eval_shape dict tree into AbstractLeafs via the
    path->PartitionSpec rules (the transformer's sharding_rules
    layout)."""
    out: List[AbstractLeaf] = []
    if isinstance(tree, dict):
        for name, sub in sorted(tree.items()):
            out += _walk_shapes(
                sub, rules, section, dtype_bytes,
                f"{prefix}/{name}" if prefix else name,
            )
        return out
    shape = tuple(int(d) for d in tree.shape)
    spec = normalize_spec(rules.get(prefix), len(shape))
    out.append(AbstractLeaf(
        path=f"{section}/{prefix}",
        shape=shape,
        dtype_bytes=int(dtype_bytes or tree.dtype.itemsize),
        spec=spec,
        section=section,
    ))
    return out


@dataclass
class Workload:
    """The abstract workload one pod task runs: its mesh and every
    tensor the footprint model tracks."""

    script: str
    mesh: Any                       # parallel.mesh.MeshSpec
    leaves: List[AbstractLeaf]
    train: bool = False
    # tp-axis activation payload per train step (bytes, pre-sharding)
    # for the cost model; 0 when the profile has no layer activations
    tp_act_bytes: int = 0


# -- workload profiles -------------------------------------------------
#
# script basename -> builder(env, tpu, pod, task) -> Workload.  The
# env is the task's YAML env merged under TpuSpec.mesh_env() — the
# same contract offer/evaluate.py assembles at launch.  Tests (and
# future frameworks) register new entries by assignment.


def _abstract_params(config):
    """(eval_shape param tree, sharding rules) for one config — built
    once per workload and threaded to every consumer."""
    import jax

    from dcos_commons_tpu.models.transformer import (
        init_params,
        sharding_rules,
    )

    shapes = jax.eval_shape(
        functools.partial(init_params, config), jax.random.key(0)
    )
    return shapes, sharding_rules(config)


def _param_leaves(shapes, rules, quantized: bool = False,
                  section: str = "params") -> List[AbstractLeaf]:
    # quantized (serve workers' WEIGHT_DTYPE=int8): matmul weights at
    # ~1 byte/elem (per-output-channel f32 scales, <1%, folded in).
    # Training never quantizes, so its profile never sets this.
    leaves = _walk_shapes(shapes, rules, section)
    if quantized:
        leaves = [
            AbstractLeaf(l.path, l.shape, 1, l.spec, l.section)
            if len(l.shape) >= 2 and l.dtype_bytes > 1 else l
            for l in leaves
        ]
    return leaves


def _opt_leaves(params_shapes, rules, optimizer) -> List[AbstractLeaf]:
    """Optimizer-state leaves: any leaf shaped like a param (path
    suffix matching) inherits the param's sharding; scalars/counters
    replicate — the same inheritance make_train_step applies."""
    import jax

    opt_shapes = jax.eval_shape(optimizer.init, params_shapes)

    def path_key(path):
        return tuple(
            str(getattr(k, "key", getattr(k, "idx", "?"))) for k in path
        )

    flat_params = {
        path_key(path): tuple(leaf.shape)
        for path, leaf in
        jax.tree_util.tree_flatten_with_path(params_shapes)[0]
    }
    out: List[AbstractLeaf] = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(opt_shapes)[0]:
        key = path_key(path)
        shape = tuple(int(d) for d in leaf.shape)
        spec: Tuple[Tuple[str, ...], ...] = ()
        matched = ""
        for ppath, pshape in flat_params.items():
            if shape == pshape and key[-len(ppath):] == ppath:
                matched = "/".join(ppath)
                spec = normalize_spec(rules.get(matched), len(shape))
                break
        out.append(AbstractLeaf(
            path="opt/" + "/".join(key),
            shape=shape,
            dtype_bytes=int(leaf.dtype.itemsize),
            spec=spec or normalize_spec(None, len(shape)),
            section="opt",
            rule_path=matched,
        ))
    return out


def _batch_entry() -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
    """(batch-dim axes, seq-dim axes) from the REAL batch_spec()."""
    from dcos_commons_tpu.parallel.mesh import batch_spec

    spec = normalize_spec(batch_spec(), 2)
    return spec[0], spec[1]


def _train_profile(env, tpu, pod, task) -> Workload:
    from dcos_commons_tpu.models.transformer import config_from_env
    from dcos_commons_tpu.parallel.mesh import derive

    config = config_from_env(env)
    mesh = derive(env)          # SpecError -> shard-mesh at the caller
    shapes, rules = _abstract_params(config)
    leaves = _param_leaves(shapes, rules)
    leaves += [
        AbstractLeaf(l.path.replace("params/", "grads/", 1), l.shape,
                     l.dtype_bytes, l.spec, "grads")
        for l in leaves
    ]
    try:
        import optax

        leaves += _opt_leaves(shapes, rules, optax.adamw(3e-4))
    except ImportError:         # container without optax: adam-shaped
        leaves += [             # f32 mu/nu mirror of the params
            AbstractLeaf(l.path.replace("params/", f"opt/{m}/", 1),
                         l.shape, 4, l.spec, "opt")
            for l in leaves if l.section == "params" for m in ("mu", "nu")
        ]
    import numpy as np

    batch_axes, seq_axes = _batch_entry()
    b = max(2, 2 * mesh.total)
    s, d = config.max_seq, config.d_model
    act_bytes = int(np.dtype(config.dtype).itemsize)
    # remat's floor: one residual-stream boundary per layer stays live
    leaves.append(AbstractLeaf(
        "act/layer-boundaries", (config.n_layers, b, s, d), act_bytes,
        ((), batch_axes, seq_axes, ()), "activations",
    ))
    # the f32 logits block (loss_chunk bounds it when set)
    chunk = config.loss_chunk if 0 < config.loss_chunk < s else s
    leaves.append(AbstractLeaf(
        "act/logits", (b, chunk, config.vocab), 4,
        (batch_axes, seq_axes, ()), "activations",
    ))
    # fwd+bwd activation collectives over tp ride 2 allreduces/layer
    tp_act = 4 * config.n_layers * b * s * d * act_bytes
    return Workload(
        script="train_worker.py", mesh=mesh, leaves=leaves, train=True,
        tp_act_bytes=tp_act,
    )


def _mnist_profile(env, tpu, pod, task) -> Workload:
    import jax

    from dcos_commons_tpu.models.mlp import MlpConfig, mlp_init
    from dcos_commons_tpu.parallel.mesh import MeshSpec

    config = MlpConfig()
    shapes = jax.eval_shape(
        functools.partial(mlp_init, config), jax.random.key(0)
    )
    leaves = _walk_shapes(shapes, {}, "params")
    leaves += [
        AbstractLeaf(l.path.replace("params/", f"opt/{m}/", 1), l.shape,
                     l.dtype_bytes, l.spec, "opt")
        for l in leaves for m in ("mu", "nu")
    ]
    # train_mnist.py runs a plain single-device jit: its "mesh" is one
    # chip, whatever the pod reserves
    return Workload(
        script="train_mnist.py", mesh=MeshSpec(), leaves=leaves,
        train=True,
    )


def _serve_leaves(env, mesh_total_tp: int) -> Tuple[Any, List[AbstractLeaf]]:
    import jax

    from dcos_commons_tpu.models.decode import init_kv_cache
    from dcos_commons_tpu.models.transformer import config_from_env

    config = config_from_env(env, remat=False)
    shapes, rules = _abstract_params(config)
    leaves = _param_leaves(
        shapes, rules,
        quantized=env.get("WEIGHT_DTYPE", "native") == "int8",
    )
    # the serving KV footprint IS the runtime allocation, exactly:
    # by default the PAGED ARENA (serve/paging.py, ISSUE 11) —
    # KV_PAGES usable pages + the trash page, each KV_PAGE_TOKENS
    # positions, shaped by the SAME paged_config_from_env contract
    # the workers and the PR 9 admission gate consume (an
    # under-budgeted arena is a SpecError at derivation, so admission
    # rejects page-budget overcommit at PUT time) — or, when
    # KV_PAGE_TOKENS=0 selects the legacy slot pool, the SLOTS x
    # MAX_LEN carve.  Both honor KV_DTYPE (int8 halves the bytes).
    # A managed budget, not a per-request guess: occupancy within
    # this allocation is the runtime gauge (kv_occupancy /
    # kv_pages_free), the allocation itself is what HBM must hold.
    from dcos_commons_tpu.serve.paging import paged_config_from_env

    slots = int(env.get("SERVE_SLOTS") or 0) or int(
        # mirrors the serve workers' conservative single-request
        # fallback, not the options.json deploy default
        # sdklint: disable=config-default-drift — dev fallback
        env.get("SERVE_BATCH", "1")
    )
    max_len = int(env.get("MAX_LEN", "256"))
    kv_dtype = env.get("KV_DTYPE", "native")
    paged = paged_config_from_env(env)
    if paged is not None:
        from dcos_commons_tpu.models.decode import init_paged_kv_cache

        cache_shapes = jax.eval_shape(functools.partial(
            init_paged_kv_cache, config, paged.arena_pages,
            paged.page_tokens, kv_dtype,
        ))
    else:
        cache_shapes = jax.eval_shape(functools.partial(
            init_kv_cache, config, slots, max_len, kv_dtype
        ))
    # cache dims (layers, pages-or-slots, tokens, kv_heads, head_dim):
    # heads ride tp like the attention weights when divisible (the
    # gang worker's cache_sharding — kv heads sit on dim 3 in BOTH
    # layouts), else the cache replicates; pages/slots replicate
    # across the gang (every rank steps the same broadcast pool)
    kv_sharded = (
        mesh_total_tp > 1 and config.n_kv_heads % mesh_total_tp == 0
    )
    kv_spec = {
        name: ((), (), (), ("tp",) if kv_sharded else (), ())
        for name in cache_shapes
    }
    leaves += _walk_shapes(cache_shapes, kv_spec, "kv")
    import numpy as np

    # pool decode-step residual + final logits: every slot computes
    # each step (static shapes); small next to params + the pool
    leaves.append(AbstractLeaf(
        "act/decode-step", (slots, 1, config.d_model),
        int(np.dtype(config.dtype).itemsize),
        ((), (), ()), "activations",
    ))
    leaves.append(AbstractLeaf(
        "act/logits", (slots, 1, config.vocab), 4,
        ((), (), ()), "activations",
    ))
    return config, leaves


def _serve_profile(env, tpu, pod, task) -> Workload:
    from dcos_commons_tpu.parallel.mesh import MeshSpec

    # serve_worker.py is the dispatch-free single-chip path
    _, leaves = _serve_leaves(env, mesh_total_tp=1)
    return Workload(script="serve_worker.py", mesh=MeshSpec(),
                    leaves=leaves)


def _serve_gang_profile(env, tpu, pod, task) -> Workload:
    from dcos_commons_tpu.parallel.mesh import MeshSpec

    # serve_gang_worker.py lays the WHOLE gang as one tp axis
    total = tpu.total_chips * max(tpu.slices, 1)
    _, leaves = _serve_leaves(env, mesh_total_tp=total)
    return Workload(script="serve_gang_worker.py",
                    mesh=MeshSpec(tp=total), leaves=leaves)


PROFILES: Dict[str, Callable] = {
    "train_worker.py": _train_profile,
    "train_mnist.py": _mnist_profile,
    "serve_worker.py": _serve_profile,
    "serve_gang_worker.py": _serve_gang_profile,
}


# -- the analysis ------------------------------------------------------


@dataclass
class ShardReport:
    """Machine-readable per-pod output (--json shard.footprint/cost)."""

    key: str                        # "frameworks/jax/svc.yml:trainer"
    script: str
    mesh: Dict[str, int]
    chips: int
    footprint: Dict[str, Any]
    cost: Optional[Dict[str, Any]] = None


@dataclass
class ShardResult(LintResult):
    reports: List[ShardReport] = field(default_factory=list)


def _axis_vocabulary(root: str) -> FrozenSet[str]:
    """spmdcheck's harvest: every axis a Mesh(...)/MeshSpec/axis_name=
    default declares across the data-plane tree."""
    from dcos_commons_tpu.analysis import spmdcheck

    try:
        files = spmdcheck._collect_files(
            root, ("dcos_commons_tpu/parallel", "dcos_commons_tpu/models")
        )
        return frozenset(spmdcheck.build_summary(files).axis_vocab)
    except OSError:
        return frozenset()


def _check_workload(
    workload: Workload,
    vocab: FrozenSet[str],
) -> Tuple[List[LeafReport], List[Tuple[str, str, str]]]:
    """Shard every leaf; returns (reports, deduped problems)."""
    axes = workload.mesh.axes()
    reports = [shard_leaf(leaf, axes, vocab) for leaf in workload.leaves]
    seen: Dict[Tuple[str, str], str] = {}
    for report in reports:
        for rule, key, message in report.problems:
            seen.setdefault((rule, key), message)
    problems = [(rule, key, msg) for (rule, key), msg in seen.items()]
    # a laid mesh axis no PartitionSpec consumes is dead weight: every
    # chip along it computes the identical program
    used = {
        name
        for leaf in workload.leaves
        for names in leaf.spec
        for name in names
    }
    for name, size in axes.items():
        if size > 1 and name not in used:
            problems.append((
                "shard-mesh", f"idle-axis:{name}",
                f"mesh lays axis {name}={size} but no PartitionSpec "
                "of the workload shards anything over it",
            ))
    return reports, sorted(problems)


def _footprint(
    workload: Workload, reports: Sequence[LeafReport]
) -> Dict[str, Any]:
    sections: Dict[str, float] = {}
    for report in reports:
        mb = report.per_chip_bytes / (1024.0 * 1024.0)
        sections[report.leaf.section] = (
            sections.get(report.leaf.section, 0.0) + mb
        )
    per_chip = sum(sections.values())
    return {
        "per_chip_mb": round(per_chip, 2),
        "sections_mb": {k: round(v, 2) for k, v in sorted(sections.items())},
        "mesh_chips": workload.mesh.total,
    }


def _ring_vs_allgather(payload_bytes: float, k: int, gbps: float,
                       axis: str, op: str) -> Dict[str, Any]:
    """Wire bytes per chip for a k-way exchange of ``payload_bytes``:
    ring allreduce moves 2(k-1)/k × B; the all-gather-then-reduce
    spelling moves (k-1) × B (every chip pulls every shard).  For
    all_to_all both spellings move (k-1)/k × B."""
    if op == "all_to_all":
        ring = gather = payload_bytes * (k - 1) / k
    else:
        ring = 2.0 * payload_bytes * (k - 1) / k
        gather = payload_bytes * (k - 1)
    to_us = 1e6 / (gbps * 2 ** 30)
    return {
        "axis": axis,
        "participants": k,
        "op": op,
        "payload_mb": round(payload_bytes / 2 ** 20, 3),
        "ring_mb_per_chip": round(ring / 2 ** 20, 3),
        "allgather_mb_per_chip": round(gather / 2 ** 20, 3),
        "ring_us": round(ring * to_us, 1),
        "allgather_us": round(gather * to_us, 1),
        "recommend": "ring" if ring <= gather else "all-gather",
    }


def _cost_model(
    workload: Workload,
    reports: Sequence[LeafReport],
    generation: str,
) -> Optional[Dict[str, Any]]:
    """Per-training-step collective bytes/latency over the ICI torus.

    Gradient reduction rides the data axes (dcn over DCN, dp/fsdp over
    ICI) at the PER-CHIP gradient size; tp moves 2 activation
    allreduces per layer each direction; ep moves the two dispatch
    all_to_alls.  Estimates, not measurements — their value is the
    TREND across config changes, tracked via ``--json``.
    """
    if not workload.train:
        return None
    axes = workload.mesh.axes()
    ici = ICI_GBPS.get(generation, DEFAULT_ICI_GBPS)
    grad_per_chip = sum(
        r.per_chip_bytes for r in reports if r.leaf.section == "grads"
    )
    entries: List[Dict[str, Any]] = []
    for axis in ("dcn", "dp", "fsdp"):
        k = axes[axis]
        if k <= 1:
            continue
        gbps = DCN_GBPS if axis == "dcn" else ici
        op = "reduce_scatter+all_gather" if axis == "fsdp" else "allreduce"
        entries.append(
            _ring_vs_allgather(grad_per_chip, k, gbps, axis, op)
        )
    if axes["tp"] > 1 and workload.tp_act_bytes:
        batch_shard = _prod(
            axes[a] for a in ("dcn", "dp", "fsdp", "sp")
        )
        entries.append(_ring_vs_allgather(
            workload.tp_act_bytes / max(batch_shard, 1), axes["tp"],
            ici, "tp", "allreduce",
        ))
    if axes["ep"] > 1:
        moe_per_chip = sum(
            r.per_chip_bytes for r in reports
            if r.leaf.section == "activations"
            and "layer-boundaries" in r.leaf.path
        )
        entries.append(_ring_vs_allgather(
            2.0 * moe_per_chip, axes["ep"], ici, "ep", "all_to_all",
        ))
    if not entries:
        return {"per_step": [], "total_ring_us": 0.0,
                "total_allgather_us": 0.0}
    return {
        "per_step": entries,
        "total_ring_us": round(sum(e["ring_us"] for e in entries), 1),
        "total_allgather_us": round(
            sum(e["allgather_us"] for e in entries), 1
        ),
    }


def stepcompare(
    cost: Optional[Dict[str, Any]],
    records: Sequence[Dict[str, Any]],
    floor_us: float = 0.0,
    slack: float = 0.25,
    skip: int = 1,
) -> Dict[str, Any]:
    """Predicted-vs-measured step time: the ``shard.cost`` wire-time
    model held against a worker's steplog JSONL records (ISSUE 7).

    ``cost`` is a :func:`_cost_model` dict (or None when the mesh has
    no collectives — a single chip); its wire floor sums, PER AXIS,
    the cheaper of the ring and all-gather spellings — each axis's
    collective picks its own spelling independently, and the dcn
    entry rides the DCN bandwidth table, so a multi-slice gang's
    floor includes its cross-slice gradient leg instead of letting
    the slow DCN hop hide inside a whole-model min (ISSUE 20: the
    gate would otherwise read an honest multi-slice step as a
    regression — or a regressed one as fine).  ``floor_us`` is the
    caller's calibrated compute floor (the cost model speaks only for
    the interconnect; bench_train_step calibrates compute by running
    the bare device loop).  ``records`` are steplog dicts — ``wall_s``
    is what each step actually took, ``blocked_s`` what the gang skew
    cost on top.

    The verdict: ``measured_over_floor_x`` is MEAN measured wall over
    the combined floor, and ``regression`` trips when it exceeds
    ``1 + slack`` — the perf gate "measured step time regressed >X%
    against the cost-model floor".  The mean is the gate statistic
    (not p50) because the window's billing conserves TOTAL wall —
    each step is billed ready-to-ready time, so host-side stalls and
    pipeline-fill land somewhere in the stream even when event
    clustering skews individual records; p50/p95 are reported for
    shape.  ``regression`` is None (ungated) when there is nothing to
    gate against: no records, or a zero combined floor.

    ``skip`` drops the first records in LOG ORDER (default 1): a cold
    worker's step 0 bills the jit compile plus pipeline fill — one
    multi-second record that would dominate the mean of a short log
    and is not a property of the steady-state step.
    """
    from dcos_commons_tpu.metrics.registry import percentile

    records = list(records)[max(0, int(skip)):]
    walls = sorted(
        float(r["wall_s"]) for r in records
        if isinstance(r.get("wall_s"), (int, float))
    )
    blocked = sorted(
        float(r["blocked_s"]) for r in records
        if isinstance(r.get("blocked_s"), (int, float))
    )
    wire_us = 0.0
    dcn_wire_us = 0.0
    if cost and cost.get("per_step"):
        # per-axis cheaper-of: each collective runs ONE spelling, so
        # the floor is the sum of per-axis minima (<= the min of the
        # whole-model sums — the gate only loosens for old specs)
        for e in cost["per_step"]:
            leg = min(
                float(e.get("ring_us", 0.0)),
                float(e.get("allgather_us", 0.0)),
            )
            wire_us += leg
            if e.get("axis") == "dcn":
                dcn_wire_us += leg
    predicted_floor_us = wire_us + max(0.0, float(floor_us))
    out: Dict[str, Any] = {
        "steps": len(walls),
        "predicted_wire_us": round(wire_us, 1),
        "predicted_wire_dcn_us": round(dcn_wire_us, 1),
        "compute_floor_us": round(float(floor_us), 1),
        "predicted_floor_us": round(predicted_floor_us, 1),
        "slack": slack,
        "measured_mean_us": None,
        "measured_p50_us": None,
        "measured_p95_us": None,
        "blocked_p50_us": None,
        "measured_over_floor_x": None,
        "regression": None,
    }
    if not walls:
        return out
    mean_us = sum(walls) / len(walls) * 1e6
    out["measured_mean_us"] = round(mean_us, 1)
    out["measured_p50_us"] = round(percentile(walls, 50) * 1e6, 1)
    out["measured_p95_us"] = round(percentile(walls, 95) * 1e6, 1)
    if blocked:
        out["blocked_p50_us"] = round(percentile(blocked, 50) * 1e6, 1)
    if predicted_floor_us > 0:
        ratio = mean_us / predicted_floor_us
        out["measured_over_floor_x"] = round(ratio, 3)
        out["regression"] = bool(ratio > 1.0 + slack)
    return out


def _yml_files(framework_dir: str) -> List[str]:
    return sorted(
        os.path.join(framework_dir, f)
        for f in os.listdir(framework_dir)
        if f.endswith(".yml")
    )


def _match_profile(cmd: str) -> Optional[Callable]:
    for script, builder in PROFILES.items():
        if script in (cmd or ""):
            return builder
    return None


def analyze_framework(
    framework_dir: str,
    root: str,
    vocab: FrozenSet[str],
    hbm_mb: int = 0,
    giant_mb: float = 256.0,
) -> ShardResult:
    from dcos_commons_tpu.specification.yaml_spec import from_yaml_file
    from dcos_commons_tpu.tools import options as options_mod

    result = ShardResult()
    disabled: set = set()
    try:
        schema = options_mod.load_schema(framework_dir)
        if schema is not None:
            disabled = {str(r) for r in schema.get("x-sdklint-disable") or []}
        env = options_mod.render_options(schema, {}) if schema else {}
    except options_mod.OptionsError:
        env = {}  # speccheck owns schema errors

    for path in _yml_files(framework_dir):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
        try:
            spec = from_yaml_file(path, env)
        except Exception:  # sdklint: disable=swallowed-exception — speccheck owns render/spec errors; shardcheck only reads specs that render
            continue
        anchor = _make_anchor(lines)
        suppressions = Suppressions(lines)
        checked_any = False
        raw: List[Finding] = []
        for pod in spec.pods:
            if pod.tpu is None:
                continue
            for task in pod.tasks:
                builder = _match_profile(task.cmd)
                if builder is None:
                    continue
                checked_any = True
                raw += _analyze_pod_task(
                    rel, pod, task, builder, anchor, vocab,
                    hbm_mb, giant_mb, result.reports,
                )
        if checked_any:
            result.files_checked += 1
        for finding in raw:
            if finding.rule in disabled or "all" in disabled \
                    or suppressions.covers(finding):
                result.suppressed.append(finding)
            else:
                result.findings.append(finding)
    result.findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return result


def _make_anchor(lines: Sequence[str]):
    """Pod findings anchor to (and suppress at) the declaring
    ``<name>:`` line, like speccheck's."""
    def anchor(name: str) -> int:
        pattern = re.compile(rf"^\s*{re.escape(str(name))}\s*:")
        for i, text in enumerate(lines, start=1):
            if pattern.match(text):
                return i
        return 1
    return anchor


def pod_task_mesh_env(pod, task) -> dict:
    """The one env→mesh contract shared with the admission gate
    (multi/admission.py): the task's env overlaid with the pod's tpu
    mesh env, exactly what the launch path hands the worker."""
    env = dict(task.env)
    env.update(pod.tpu.mesh_env())
    return env


def declared_chips(pod) -> int:
    """Chips the spec reserves for ONE workload: the whole gang for
    gang/topology pods, one instance's host chips otherwise.  Shared
    with the admission gate so the two enforcement points can never
    drift."""
    tpu = pod.tpu
    return (
        tpu.total_chips * max(tpu.slices, 1)
        if pod.gang or tpu.topology else tpu.chips_per_host
    )


def mesh_span_message(where: str, declared: int, total: int,
                      laid_by: str) -> str:
    """The shard-mesh reserved-vs-laid mismatch text, shared by CI and
    admission."""
    return (
        f"{where}: the spec reserves {declared} chip(s) but "
        f"{laid_by} spans {total} — "
        + ("reserved chips sit idle" if declared > total
           else "the workload cannot get the chips it lays")
    )


def fleet_slice_count(inventory, generation: str) -> Optional[int]:
    """Distinct registered slices of ``generation`` TPU hosts — the
    one formula the multi-slice admission gate sizes `tpu: slices: N`
    against (multi/admission.py).  None when the inventory holds no
    TPU hosts at all (scheduler bootstrap): sizing against an empty
    fleet would reject every multi-slice spec exactly when
    registration must not depend on fleet availability."""
    if inventory is None:
        return None
    slices = set()
    any_tpu = False
    for host in inventory.hosts():
        if not host.generation:
            continue
        any_tpu = True
        if host.generation == generation:
            slices.add(host.slice_id)
    return len(slices) if any_tpu else None


def _analyze_pod_task(
    rel: str, pod, task, builder, anchor, vocab,
    hbm_mb: int, giant_mb: float, reports_out: List[ShardReport],
) -> List[Finding]:
    from dcos_commons_tpu.specification.specs import SpecError

    tpu = pod.tpu
    line = anchor(pod.type)
    where = f"pod {pod.type!r} task {task.name!r}"
    env = pod_task_mesh_env(pod, task)
    try:
        workload = builder(env, tpu, pod, task)
    except SpecError as e:
        return [Finding(rel, line, "shard-mesh", f"{where}: {e}")]
    except Exception as e:
        # a malformed env value (VOCAB: "not-a-number") or a broken
        # profile must fail THIS pod with a suppressible, anchored
        # finding — not abort the whole analysis CLI with a traceback
        return [Finding(
            rel, line, "shard-mesh",
            f"{where}: workload profile {builder.__name__} failed: "
            f"{type(e).__name__}: {e}",
        )]
    findings: List[Finding] = []

    declared = declared_chips(pod)
    if workload.mesh.total != declared:
        findings.append(Finding(
            rel, line, "shard-mesh",
            mesh_span_message(where, declared, workload.mesh.total,
                              f"{workload.script}'s mesh"),
        ))

    leaf_reports, problems = _check_workload(workload, vocab)
    for rule, _key, message in problems:
        findings.append(Finding(rel, line, rule, f"{where}: {message}"))

    threshold = giant_mb * 1024 * 1024
    for report in leaf_reports:
        leaf = report.leaf
        if leaf.section == "params" and leaf.bytes >= threshold \
                and report.replication > 1:
            findings.append(Finding(
                rel, line, "shard-replicated-giant",
                f"{where}: {leaf.path} "
                f"({leaf.bytes / 2 ** 20:.0f} MB) is replicated "
                f"{report.replication}x across the mesh — add an "
                "fsdp/tp entry to its PartitionSpec or raise "
                "--giant-mb if intentional",
            ))

    footprint = _footprint(workload, leaf_reports)
    per_chip_mb = footprint["per_chip_mb"]
    hbm_budget = hbm_mb or GENERATION_HBM_MB.get(tpu.generation, 0)
    if hbm_budget and per_chip_mb > hbm_budget:
        findings.append(Finding(
            rel, line, "shard-hbm-overcommit",
            f"{where}: per-chip footprint {per_chip_mb:.0f} MB exceeds "
            f"{tpu.generation} HBM ({hbm_budget} MB); shard more axes "
            "or shrink the model",
        ))
    chips_per_host_used = min(tpu.chips_per_host, workload.mesh.total)
    per_host_mb = per_chip_mb * max(chips_per_host_used, 1)
    declared_mem = task.resources.memory_mb
    if declared_mem and per_host_mb > declared_mem:
        findings.append(Finding(
            rel, line, "shard-hbm-overcommit",
            f"{where}: per-host footprint {per_host_mb:.0f} MB exceeds "
            f"the declared memory: {declared_mem} MB — raise the "
            "task's memory or shard the state further",
        ))
    footprint["per_host_mb"] = round(per_host_mb, 2)
    footprint["hbm_budget_mb"] = hbm_budget
    footprint["declared_memory_mb"] = declared_mem

    reports_out.append(ShardReport(
        key=f"{rel}:{pod.type}",
        script=workload.script,
        mesh={k: v for k, v in workload.mesh.axes().items() if v > 1},
        chips=workload.mesh.total,
        footprint=footprint,
        cost=_cost_model(workload, leaf_reports, tpu.generation),
    ))
    return findings


def analyze_all(
    root: str, hbm_mb: int = 0, giant_mb: float = 256.0
) -> ShardResult:
    frameworks_dir = os.path.join(root, "frameworks")
    result = ShardResult()
    if not os.path.isdir(frameworks_dir):
        return result
    vocab = _axis_vocabulary(root)
    for name in sorted(os.listdir(frameworks_dir)):
        framework_dir = os.path.join(frameworks_dir, name)
        if not os.path.isdir(framework_dir):
            continue
        sub = analyze_framework(
            framework_dir, root, vocab, hbm_mb, giant_mb
        )
        result.findings += sub.findings
        result.suppressed += sub.suppressed
        result.files_checked += sub.files_checked
        result.reports += sub.reports
    result.findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return result


SHARD_RULES = (
    ("shard-mesh",
     "topology cannot lay a host-aligned mesh / reserved vs laid chip "
     "mismatch / idle mesh axis"),
    ("shard-divisibility",
     "a mesh axis product does not divide the dim it shards"),
    ("shard-unknown-axis",
     "a PartitionSpec axis outside the mesh-axis vocabulary"),
    ("shard-replicated-giant",
     "a giant param replicated across mesh axes (above --giant-mb)"),
    ("shard-hbm-overcommit",
     "per-chip footprint exceeds generation HBM or declared memory"),
)


def shard_rule_catalog() -> str:
    lines = ["shardcheck rules (static sharding / HBM / layout):", ""]
    for rule_id, description in SHARD_RULES:
        lines.append(f"  {rule_id}")
        lines.append(f"      {description}")
    return "\n".join(lines)
