"""Single-host MNIST training task (BASELINE.json config 3).

Launched by the scheduler inside a sandbox; trains the MLP on
synthetic MNIST for TRAIN_STEPS steps on whatever device JAX finds
(the real TPU chip in the bench, CPU in tests), then exits 0 so the
FINISH goal completes the deploy step.
"""

import os
import sys
import time

sys.path.insert(0, os.environ.get("REPO_ROOT", "/root/repo"))


def main() -> int:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # this image's sitecustomize re-selects the TPU platform at
        # import; honor an explicit CPU request (tests / CPU fleets)
        jax.config.update("jax_platforms", "cpu")
    import optax

    from dcos_commons_tpu.models import MlpConfig, mlp_init, mlp_train_step
    from dcos_commons_tpu.utils import (
        enable_compilation_cache,
        synthetic_mnist,
    )

    # warm relaunches (scheduler restart, recovery, repeat deploys)
    # skip XLA recompilation entirely ($JAX_COMPILATION_CACHE_DIR)
    enable_compilation_cache()

    # demo-scale run: 60 steps converges MNIST; the options default
    # 100 sizes the full trainer
    # sdklint: disable=config-default-drift — demo scale
    steps = int(os.environ.get("TRAIN_STEPS", "60"))
    config = MlpConfig()
    params = mlp_init(config, jax.random.key(0))
    optimizer = optax.adam(1e-3)
    opt_state = optimizer.init(params)
    step_fn = mlp_train_step(optimizer)
    x, y = synthetic_mnist(jax.random.key(1), 256)

    t0 = time.time()
    first = last = None
    for i in range(steps):
        params, opt_state, loss = step_fn(params, opt_state, x, y)
        if i == 0:
            loss.block_until_ready()
            first = float(loss)
            print(f"step 0 loss={first:.4f} (compile {time.time()-t0:.1f}s)",
                  flush=True)
    last = float(loss)
    print(
        f"trained {steps} steps on {jax.devices()[0].platform}: "
        f"loss {first:.4f} -> {last:.4f}",
        flush=True,
    )
    return 0 if last < first else 1


if __name__ == "__main__":
    sys.exit(main())
