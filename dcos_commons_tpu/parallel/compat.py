"""JAX version compatibility for the parallelism layer.

``shard_map`` moved from ``jax.experimental.shard_map`` into the
top-level ``jax`` namespace (jax 0.6+), and its replication-checker
kwarg was renamed ``check_rep`` -> ``check_vma`` in the same era.
Everything in this repo (and its tests) imports it from here so one
shim tracks both moves: prefer the top-level export, fall back to the
experimental path on the older jax the container ships, translating
``check_vma`` to the old spelling.
"""

from __future__ import annotations

import functools

try:  # jax >= 0.6: top-level export, check_vma spelling
    from jax import shard_map
except ImportError:  # older jax: experimental home, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

try:  # jax >= 0.5: static mesh-axis size as a lax primitive helper
    from jax.lax import axis_size
except ImportError:
    def axis_size(axis_name):
        """Static size of a bound mesh axis (``lax.axis_size``
        backport).  On old jax ``jax.core.axis_frame`` returns the
        size directly (an int); newer intermediates return a frame
        object carrying ``.size``."""
        import jax.core as core

        frame = core.axis_frame(axis_name)
        return getattr(frame, "size", frame)

def pvary(x, axis_names):
    """Mark ``x`` device-varying over ``axis_names`` for shard_map's
    replication (vma) checker.  The primitive has gone through three
    spellings — ``lax.pcast(..., to="varying")``, ``lax.pvary`` — and
    does not exist at all on old jax, where no vma checker runs and
    identity is correct."""
    from jax import lax

    pcast = getattr(lax, "pcast", None)
    if pcast is not None:
        return pcast(x, tuple(axis_names), to="varying")
    fn = getattr(lax, "pvary", None)
    if fn is not None:
        return fn(x, tuple(axis_names))
    return x


__all__ = ["axis_size", "pvary", "shard_map"]
