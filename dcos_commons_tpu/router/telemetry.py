"""Pod telemetry behind the staleness gate — the ONE router module
allowed to touch raw ``GET /stats`` dicts.

Everything the router learns about a pod's load arrives as a stats
snapshot (serve/engine.py ``stats()``: queue depth, free decode rows,
kv_pages_free, tokens/s, and — ISSUE 12 — the monotonic
``stats_age_s`` wedge stamp).  Snapshots go stale two ways:

* the POLL went stale — the router failed to refresh (pod
  unreachable, poll thread behind): age is measured router-side from
  the observation clock;
* the ENGINE went stale — the pod answered /stats but its engine loop
  has not completed a tick in ``stats_age_s`` seconds (a wedged
  decode, a stuck collective): the gauges are the pod's LAST-GOOD
  numbers, exactly what a router must not balance on.

``PodTelemetry`` parses a snapshot once and answers every load
question through freshness-aware accessors, so routing code never
reads a raw gauge without the gate.  sdklint's
``router-stats-staleness`` rule enforces the boundary: outside this
module, router code may not subscript/.get() a stats dict at all.
"""

from __future__ import annotations

from typing import Optional

# a pod whose engine loop has not ticked for this many seconds is
# routed around even when its HTTP server still answers /stats (the
# serving loop and the HTTP thread are separate; the whole point of
# the stamp is telling them apart)
DEFAULT_STALE_AFTER_S = 10.0


def _as_float(value, default: float = 0.0) -> float:
    try:
        return float(value)
    except (TypeError, ValueError):
        return default


class PodTelemetry:
    """One pod's parsed load gauges + the freshness verdict.

    ``observe(stats, now)`` ingests a raw snapshot (the only raw-dict
    access in the router); ``fresh(now)`` is the staleness gate every
    reader crosses.  Accessors return pessimistic defaults for a pod
    that never reported — an unknown pod is assumed LOADED, so traffic
    prefers pods that prove their headroom.
    """

    __slots__ = (
        "stale_after_s", "observed_at", "engine_age_s", "queue_depth",
        "active_slots", "free_slots", "kv_pages_free", "kv_occupancy",
        "tokens_per_s", "prefix_hit_rate", "ttft_p95_s", "has_snapshot",
        "serving_role", "prefill_backlog",
    )

    def __init__(self, stale_after_s: float = DEFAULT_STALE_AFTER_S):
        self.stale_after_s = float(stale_after_s)
        self.observed_at: Optional[float] = None  # router monotonic
        self.engine_age_s = 0.0
        self.queue_depth = 0.0
        self.active_slots = 0.0
        self.free_slots = 0.0
        self.kv_pages_free = 0.0
        self.kv_occupancy = 0.0
        self.tokens_per_s = 0.0
        self.prefix_hit_rate = 0.0
        self.ttft_p95_s = 0.0
        self.has_snapshot = False
        # disaggregation gauges (ISSUE 16): the pod's declared
        # serving role ("" until it reports one) and its unfilled
        # prompt-token backlog — the load signal that matters on a
        # prefill pod, whose decode gauges sit near zero by design
        self.serving_role = ""
        self.prefill_backlog = 0.0

    # -- ingestion (the single raw-dict touchpoint) -------------------

    def observe(self, stats: dict, now: float) -> None:
        """Parse one ``GET /stats`` snapshot observed at router
        monotonic time ``now``.  Malformed/partial dicts degrade to
        the pessimistic defaults rather than raising — a half-written
        snapshot must not take the pod's router state down with it."""
        if not isinstance(stats, dict) or not stats:
            return
        self.observed_at = now
        self.has_snapshot = True
        self.engine_age_s = _as_float(stats.get("stats_age_s"))
        self.queue_depth = _as_float(stats.get("queue_depth"))
        self.active_slots = _as_float(stats.get("active_slots"))
        self.free_slots = _as_float(stats.get("free_slots"))
        self.kv_pages_free = _as_float(stats.get("kv_pages_free"))
        self.kv_occupancy = _as_float(stats.get("kv_occupancy"))
        self.tokens_per_s = _as_float(stats.get("tokens_per_s"))
        self.prefix_hit_rate = _as_float(stats.get("prefix_cache_hit_rate"))
        self.ttft_p95_s = _as_float(stats.get("ttft_p95_s"))
        role = stats.get("serving_role")
        if isinstance(role, str):
            self.serving_role = role
        self.prefill_backlog = _as_float(
            stats.get("prefill_chunk_backlog")
        )

    # -- the staleness gate -------------------------------------------

    def fresh(self, now: float) -> bool:
        """True when the gauges are safe to balance on: a snapshot
        exists, the router observed it recently, and the pod's own
        engine loop was alive when it was taken."""
        if not self.has_snapshot or self.observed_at is None:
            return False
        if now - self.observed_at > self.stale_after_s:
            return False  # the POLL went stale
        return self.engine_age_s <= self.stale_after_s  # engine wedge

    def load_score(self, now: float) -> Optional[float]:
        """The pod's polled-load contribution for least-loaded
        placement: waiting + running work, with a KV-headroom tiebreak
        (a pod out of pages queues the next admission even with idle
        decode rows).  ``None`` when the gauges are stale — the caller
        must fall back to router-side in-flight counts, never to the
        last-good numbers."""
        if not self.fresh(now):
            return None
        headroom_penalty = 0.0
        if self.kv_occupancy > 0.9:
            headroom_penalty = (self.kv_occupancy - 0.9) * 10.0
        score = self.queue_depth + self.active_slots + headroom_penalty
        if self.serving_role == "prefill":
            # a prefill pod's real load is its unfilled prompt
            # backlog (rows sit in _prefilling, not the queue, and
            # hand off before decode): scale tokens to request-ish
            # units so prefill pods spread like any other capacity
            score += self.prefill_backlog / 64.0
        return score

    def describe(self, now: float) -> dict:
        """Debug-surface row (front door ``GET /pods``)."""
        return {
            "fresh": self.fresh(now),
            "observed_age_s": (
                round(now - self.observed_at, 3)
                if self.observed_at is not None else None
            ),
            "engine_stats_age_s": round(self.engine_age_s, 3),
            "queue_depth": self.queue_depth,
            "active_slots": self.active_slots,
            "free_slots": self.free_slots,
            "kv_pages_free": self.kv_pages_free,
            "kv_occupancy": self.kv_occupancy,
            "tokens_per_s": self.tokens_per_s,
            "prefix_cache_hit_rate": self.prefix_hit_rate,
            "ttft_p95_s": self.ttft_p95_s,
            "serving_role": self.serving_role or "unified",
            "prefill_chunk_backlog": self.prefill_backlog,
        }
