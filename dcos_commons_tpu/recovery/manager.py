"""DefaultRecoveryPlanManager: synthesize recovery steps from failures.

Reference: recovery/DefaultRecoveryPlanManager.java — updatePlan
(:164) scans the state store for failed tasks each status update and
appends recovery steps for pods not already being recovered; the
FailureMonitor decides TRANSIENT (relaunch in place, reservations
kept) vs PERMANENT (destroy + replace, :378-420); per-service
RecoveryPlanOverriders may replace the default steps with a custom
phase (Cassandra seed-replace choreography is the reference example).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Set

from dcos_commons_tpu.common import Label, TaskState, TaskStatus
from dcos_commons_tpu.plan.backoff import Backoff
from dcos_commons_tpu.plan.phase import Phase
from dcos_commons_tpu.plan.plan import RECOVERY_PLAN_NAME, Plan
from dcos_commons_tpu.plan.plan_manager import PlanManager
from dcos_commons_tpu.plan.step import (
    ActionStep,
    DeploymentStep,
    PodInstanceRequirement,
    RecoveryType,
    Step,
)
from dcos_commons_tpu.plan.strategy import ParallelStrategy, SerialStrategy
from dcos_commons_tpu.recovery.elastic import ElasticGangStep, ElasticPolicy
from dcos_commons_tpu.recovery.monitor import FailureMonitor, NeverFailureMonitor
from dcos_commons_tpu.specification.specs import (
    GoalState,
    ServiceSpec,
    pod_instance_name,
    task_full_name,
)
from dcos_commons_tpu.state.state_store import StateStore

# A RecoveryPlanOverrider may return a replacement Phase for a failed
# pod instance (reference: RecoveryPlanOverrider(Factory)); return
# None to keep the default single-step recovery.
RecoveryPlanOverrider = Callable[
    [str, List[int], RecoveryType], Optional[Phase]
]


class DefaultRecoveryPlanManager(PlanManager):
    def __init__(
        self,
        spec: ServiceSpec,
        state_store: StateStore,
        failure_monitor: Optional[FailureMonitor] = None,
        backoff: Optional[Backoff] = None,
        overriders: Optional[List[RecoveryPlanOverrider]] = None,
        externally_managed: Optional[Callable[[str], bool]] = None,
    ):
        self._spec = spec
        self._state_store = state_store
        self._monitor = failure_monitor or NeverFailureMonitor()
        self._backoff = backoff
        self._overriders = list(overriders or [])
        # pods with incomplete work in another plan (deploy/update) are
        # that plan's responsibility — recovering them here would race
        # the rollout (reference: recovery defers to dirtied assets)
        self._externally_managed = externally_managed or (lambda _name: False)
        self._lock = threading.RLock()
        # active recovery elements keyed by pod instance name
        self._phases: Dict[str, Phase] = {}
        # keys whose phase came from a RecoveryPlanOverrider: custom
        # choreography is authoritative — never rebuilt/widened by the
        # default scoping logic
        self._custom_keys: Set[str] = set()
        self._plan = Plan(RECOVERY_PLAN_NAME, [], ParallelStrategy())
        # health-plane event journal (set by the owning scheduler):
        # every synthesized recovery phase is a "recovery" event, so
        # an operator can reconstruct WHEN a pod started recovering
        # long after the recovery plan pruned the completed phase
        self.journal = None
        # the shared fleet inventory (set by the builder): the gang
        # recovery phase's elastic step probes maintenance windows
        # through it to choose waiting over shrinking.  None (hand-
        # wired tests) means "no window ever promises capacity back".
        self.inventory = None

    def _journal_phase(self, key: str, recovery_type, rebuilt: bool) -> None:
        if self.journal is None:
            return
        self.journal.append(
            "recovery",
            pod=key,
            type=recovery_type.value
            if hasattr(recovery_type, "value") else str(recovery_type),
            rebuilt=rebuilt,
            message=f"recovery phase {'rebuilt' if rebuilt else 'created'} "
                    f"for {key}",
        )

    def set_spec(self, spec: ServiceSpec) -> None:
        with self._lock:
            self._spec = spec

    def add_externally_managed(self, predicate) -> None:
        """OR another owner into the externally-managed check: the
        scheduler registers the autoscale plan here so recovery never
        races an in-flight scale action for the same instance (a
        failed scale-out launch is the scale phase's to retry, like a
        failed deploy launch is the deploy plan's)."""
        with self._lock:
            prev = self._externally_managed
            self._externally_managed = (
                lambda name: prev(name) or predicate(name)
            )

    # -- PlanManager --------------------------------------------------

    def get_plan(self) -> Plan:
        with self._lock:
            self._prune_completed()
            self._plan.phases = list(self._phases.values())
            return self._plan

    def get_candidates(self, dirty_assets: Set[str]) -> List[Step]:
        with self._lock:
            self._refresh()
            return self.get_plan().candidates(dirty_assets)

    def update(self, status: TaskStatus) -> None:
        with self._lock:
            for phase in self._phases.values():
                phase.update(status)
            # plan synthesis happens once per cycle in get_candidates,
            # NOT per status: _refresh scans every pod's stored state,
            # and a fleet-scale status burst (100 pods reporting in one
            # intake) would turn that into an O(statuses x pods) sweep
            # with identical end-of-cycle behavior

    # -- plan synthesis ----------------------------------------------

    def _prune_completed(self) -> None:
        for key in [k for k, p in self._phases.items() if p.is_complete]:
            del self._phases[key]
            self._custom_keys.discard(key)

    def _refresh(self) -> None:
        """Reference: updatePlan (DefaultRecoveryPlanManager.java:164)."""
        self._prune_completed()
        failed = self._find_failed_pods()
        self._maybe_regrow(failed)
        for (pod_type, instances), (recovery_type, tasks) in failed.items():
            key = pod_instance_name(pod_type, instances[0])
            if any(
                self._externally_managed(pod_instance_name(pod_type, i))
                for i in instances
            ):
                continue
            if recovery_type is RecoveryType.PERMANENT:
                # PERMANENT is whole-pod destroy+replace: a subset of a
                # pod re-placed from scratch would split colocation
                # (fresh host, fresh volumes) from its live siblings.
                # "Whole pod" = its LAUNCHED footprint: launched FINISH
                # init tasks rerun on the fresh volumes, but sidecars
                # whose plan never ran must not be resurrected.
                tasks = self._launched_tasks(pod_type, instances)
            existing = self._phases.get(key)
            if existing is not None:
                if getattr(existing, "gang_recovery", False):
                    # the gang recovery phase IS the widest possible
                    # scope (kill all -> unreserve -> re-place whole
                    # gang, PERMANENT): nothing escalates past it, and
                    # its ActionSteps must never be "rebuilt" by the
                    # DeploymentStep-shaped widening logic below
                    continue
                if key in self._custom_keys:
                    # overrider choreography is authoritative: escalate
                    # its steps in place, never rebuild around it
                    if recovery_type is RecoveryType.PERMANENT:
                        for step in existing.steps:
                            if isinstance(step, DeploymentStep) and \
                                    step.requirement.recovery_type is \
                                    RecoveryType.TRANSIENT:
                                step.requirement.recovery_type = \
                                    RecoveryType.PERMANENT
                    continue
                covered = self._phase_tasks(existing)
                required = self._required_tasks(pod_type, instances, tasks)
                if recovery_type is RecoveryType.PERMANENT and not all(
                    isinstance(s, DeploymentStep)
                    and s.requirement.recovery_type is RecoveryType.PERMANENT
                    for s in existing.steps
                ):
                    # escalate by REBUILDING at the scoped task set —
                    # an in-place flip of a subset phase would
                    # permanently re-place only part of the pod, and a
                    # None (all-tasks) rebuild would resurrect
                    # completed FINISH tasks and never-launched
                    # sidecars the scoping in _find_failed_pods
                    # deliberately excludes.  The rebuild is a replace,
                    # so it counts against the rate limit.
                    phase = self._make_phase(
                        pod_type, list(instances), recovery_type, tasks
                    )
                    if phase is not None:
                        self._phases[key] = phase
                        self._record_replace(pod_type, instances)
                        self._journal_phase(key, recovery_type, True)
                elif covered is not None and not required <= covered:
                    # a wider failure (an essential task died) arrived
                    # while a subset phase was in flight: rebuild so the
                    # new casualties are not deferred behind it —
                    # again at the SCOPED task set
                    phase = self._make_phase(
                        pod_type, list(instances), recovery_type, tasks
                    )
                    if phase is not None:
                        self._phases[key] = phase
                        self._journal_phase(key, recovery_type, True)
                continue
            phase = self._make_phase(
                pod_type, list(instances), recovery_type, tasks
            )
            if phase is not None:
                self._phases[key] = phase
                if recovery_type is RecoveryType.PERMANENT:
                    self._record_replace(pod_type, instances)
                self._journal_phase(key, recovery_type, False)

    def _launched_tasks(
        self, pod_type: str, instances
    ) -> Optional[List[str]]:
        """Union of task names with stored TaskInfos across the
        instances; None when every spec task has launched (the
        all-tasks fast path)."""
        pod = self._spec.pod(pod_type)
        launched = set()
        for task_spec in pod.tasks:
            for index in instances:
                full = task_full_name(pod_type, index, task_spec.name)
                if self._state_store.fetch_task(full) is not None:
                    launched.add(task_spec.name)
                    break
        if len(launched) == len(pod.tasks):
            return None
        return sorted(launched)

    def _phase_tasks(self, phase: Phase) -> Optional[Set[str]]:
        """Full task names a recovery phase covers; None when the phase
        holds non-introspectable custom steps."""
        covered: Set[str] = set()
        for step in phase.steps:
            if not isinstance(step, DeploymentStep):
                return None
            covered |= set(step.requirement.task_names())
        return covered

    def _required_tasks(
        self, pod_type: str, instances, tasks: Optional[List[str]]
    ) -> Set[str]:
        pod = self._spec.pod(pod_type)
        names = tasks if tasks is not None else [
            t.name for t in pod.tasks
        ]
        return {
            task_full_name(pod_type, i, n)
            for i in instances
            for n in names
        }

    def _find_failed_pods(self) -> Dict[tuple, tuple]:
        """Scan stored statuses for tasks needing recovery, grouped by
        pod instance (whole pod for gang pods).

        Values are (recovery_type, tasks_to_launch or None).  Essential
        semantics (reference: TaskSpec.isEssential): an essential
        task's failure relaunches the whole pod instance; failures of
        ONLY non-essential tasks relaunch just those tasks, leaving
        their essential siblings running.
        """
        out: Dict[tuple, tuple] = {}
        for pod in self._spec.pods:
            gang_failed: Set[int] = set()
            gang_type = RecoveryType.TRANSIENT
            for index in range(pod.count):
                failed_tasks: Dict[str, RecoveryType] = {}
                essential_failed = False
                launched: Set[str] = set()
                for task_spec in pod.tasks:
                    full = task_full_name(pod.type, index, task_spec.name)
                    info = self._state_store.fetch_task(full)
                    status = self._state_store.fetch_status(full)
                    if info is not None:
                        launched.add(task_spec.name)
                    if info is None or status is None:
                        continue
                    needs, rtype = self._needs_recovery(
                        full, info, status, task_spec.goal,
                        pod_instance_name(pod.type, index),
                    )
                    if not needs:
                        continue
                    failed_tasks[task_spec.name] = rtype
                    essential_failed |= task_spec.essential
                if not failed_tasks:
                    continue
                rtype = (
                    RecoveryType.PERMANENT
                    if RecoveryType.PERMANENT in failed_tasks.values()
                    else RecoveryType.TRANSIENT
                )
                if pod.gang:
                    gang_failed.add(index)
                    if rtype is RecoveryType.PERMANENT:
                        gang_type = RecoveryType.PERMANENT
                elif essential_failed:
                    # "whole pod" = the instance's LAUNCHED footprint:
                    # the failed tasks plus running-goal siblings.
                    # Tasks that never launched (sidecars whose plan
                    # hasn't run) and FINISH/ONCE tasks that already
                    # completed must NOT (re)run — pods whose replace
                    # needs init choreography use a RecoveryPlanOverrider
                    # (reference: DefaultRecoveryPlanManager recovering
                    # stored tasks; HDFS/Cassandra overriders exist
                    # precisely because default recovery does not rerun
                    # bootstrap/format).
                    relaunch = []
                    for task_spec in pod.tasks:
                        if task_spec.name not in launched:
                            continue  # never launched
                        if task_spec.name in failed_tasks or \
                                task_spec.goal is GoalState.RUNNING:
                            relaunch.append(task_spec.name)
                    out[(pod.type, (index,))] = (rtype, sorted(relaunch))
                else:
                    out[(pod.type, (index,))] = (
                        rtype, sorted(failed_tasks)
                    )
            if pod.gang and gang_failed:
                # one worker down takes the whole slice through recovery
                out[(pod.type, tuple(range(pod.count)))] = (gang_type, None)
        return out

    # -- min replace delay (reference: ReplacementFailurePolicy
    #    minReplaceDelay — successive PERMANENT replaces of one pod
    #    instance are rate limited) --------------------------------

    def _record_replace(self, pod_type: str, instances) -> None:
        """Stamp EVERY replaced instance (a gang replace covers all of
        them — rate limiting keyed to instance 0 alone would let
        failures seen on other workers bypass the delay)."""
        now = str(time.time()).encode()
        for index in instances:
            self._state_store.store_property(
                f"last-replace-{pod_instance_name(pod_type, index)}", now
            )

    def _replace_delay_elapsed(self, pod_instance: str) -> bool:
        policy = self._spec.replacement_failure_policy
        if policy is None or policy.min_replace_delay_s <= 0:
            return True
        raw = self._state_store.fetch_property(
            f"last-replace-{pod_instance}"
        )
        if raw is None:
            return True
        try:
            last = float(raw.decode())
        except ValueError:
            return True
        return time.time() - last >= policy.min_replace_delay_s

    def _needs_recovery(self, full, info, status, goal, pod_instance):
        if info.labels.get(Label.PERMANENTLY_FAILED):
            # explicit operator intent (pod replace) or an already-
            # stamped escalation: the replace delay never blocks these
            return True, RecoveryType.PERMANENT
        if not status.state.is_terminal:
            self._monitor.clear(full)
            return False, RecoveryType.NONE
        # a terminal state satisfying the goal is success, not failure:
        # FINISHED satisfies FINISH/ONCE; nothing terminal satisfies
        # RUNNING (even exit 0 means the server died — relaunch it)
        if goal in (GoalState.FINISH, GoalState.ONCE) and \
                status.state is TaskState.FINISHED:
            return False, RecoveryType.NONE
        if self._monitor.has_failed_permanently(full, status):
            if not self._replace_delay_elapsed(pod_instance):
                # monitor says replace, but the last replace of this
                # instance was too recent: stay TRANSIENT for now
                # (reference: minReplaceDelay)
                return True, RecoveryType.TRANSIENT
            # stamp the label so the escalation survives restart
            self._state_store.store_tasks(
                [info.with_label(Label.PERMANENTLY_FAILED, "true")]
            )
            return True, RecoveryType.PERMANENT
        return True, RecoveryType.TRANSIENT

    def _make_phase(
        self,
        pod_type: str,
        instances: List[int],
        recovery_type: RecoveryType,
        tasks: Optional[List[str]] = None,
    ) -> Optional[Phase]:
        key = pod_instance_name(pod_type, instances[0])
        for overrider in self._overriders:
            phase = overrider(pod_type, instances, recovery_type)
            if phase is not None:
                self._custom_keys.add(key)
                return phase
        self._custom_keys.discard(key)
        pod = self._spec.pod(pod_type)
        if recovery_type is RecoveryType.PERMANENT and pod.gang and \
                len(instances) > 1:
            # whole-gang PERMANENT loss (preemption, operator replace,
            # monitor escalation): a pile of per-task relaunches would
            # leave survivors wedged in a dead collective and the
            # broken sub-slice reserved — synthesize the plan-driven
            # choreography instead
            return self._make_gang_phase(pod, instances, tasks)
        requirement = PodInstanceRequirement(
            pod=pod, instances=instances, recovery_type=recovery_type,
            tasks_to_launch=tasks,
        )
        name = f"recover-{pod_instance_name(pod_type, instances[0])}" if len(
            instances
        ) == 1 else f"recover-{pod_type}-gang"
        step = DeploymentStep(name, requirement, backoff=self._backoff)
        return Phase(name, [step], ParallelStrategy())

    # -- whole-slice regrow (ISSUE 20) --------------------------------

    def _maybe_regrow(self, failed: Dict[tuple, tuple]) -> None:
        """Regrow a multi-slice elastic gang to its declared width.

        After a whole-slice elastic shrink the gang trains healthily
        at fewer slices — nothing is FAILED, so the failure scan will
        never touch it again.  This scan watches for exactly that
        state (a clean shrunken prefix of RUNNING instances) and,
        once the fleet again holds enough fully-up slices, synthesizes
        the SAME gang choreography at declared width: kill the
        shrunken incarnation, unreserve, re-place all slices, trim.
        The fenced-checkpoint restore re-lays the dcn axis back up
        exactly as the shrink laid it down.

        Rate-limited by the replacement-failure policy's
        min-replace-delay (a regrow IS a replace) and journaled as
        verb=elastic-regrow.  Scoped to multi-slice gangs: a
        single-slice elastic shrink changes the per-slice topology,
        and regrowing it is the update plan's `pod replace` path.
        """
        if self.inventory is None:
            return
        failed_types = {pt for (pt, _i) in failed}
        for pod in self._spec.pods:
            if not (
                pod.gang and pod.tpu is not None and pod.tpu.elastic
                and pod.tpu.slices > 1
            ):
                continue
            if pod.type in failed_types:
                continue  # active failure: the gang phase owns it
            key = pod_instance_name(pod.type, 0)
            if key in self._phases:
                continue
            if any(
                self._externally_managed(pod_instance_name(pod.type, i))
                for i in range(pod.count)
            ):
                continue
            width = self._running_width(pod)
            if width is None:
                continue
            if not self._replace_delay_elapsed(key):
                continue
            if not self._regrow_capacity(pod):
                continue
            instances = list(range(pod.count))
            phase = self._make_gang_phase(pod, instances, None)
            self._phases[key] = phase
            self._record_replace(pod.type, instances)
            if self.journal is not None:
                self.journal.append(
                    "recovery", pod=pod.type, verb="elastic-regrow",
                    hosts=pod.count, width=width,
                    message=(
                        f"regrowing {pod.type} from {width} to "
                        f"{pod.count} host(s): capacity returned"
                    ),
                )

    def _running_width(self, pod) -> Optional[int]:
        """The width of a HEALTHY shrunken gang: instances 0..w-1 have
        stored tasks whose latest status satisfies their goal, and
        instances w.. have none (the trim step's clean prefix).  None
        for anything else — full width, holes, or any unhealthy task
        (those are the failure scan's business, not regrow's)."""
        width = 0
        for index in range(pod.count):
            present = False
            for task_spec in pod.tasks:
                full = task_full_name(pod.type, index, task_spec.name)
                info = self._state_store.fetch_task(full)
                if info is None:
                    continue
                present = True
                status = self._state_store.fetch_status(full)
                if status is None or status.task_id != info.task_id:
                    return None
                if task_spec.goal in (GoalState.FINISH, GoalState.ONCE):
                    if status.state is not TaskState.FINISHED:
                        return None
                elif status.state is not TaskState.RUNNING:
                    return None
            if present:
                if index != width:
                    return None  # hole: not a clean shrunken prefix
                width += 1
        return width if 0 < width < pod.count else None

    def _regrow_capacity(self, pod) -> bool:
        """True when the fleet holds enough fully-up matching slices
        to place the gang at declared width.  The shrunken gang's own
        slices COUNT — the regrow choreography unreserves them before
        re-placing.  Other services' claims are not visible here, so
        this over-approximates; a regrow that then cannot place
        re-shrinks through the same decision rule and converges back.
        """
        hps = max(1, pod.count // max(1, pod.tpu.slices))
        by_slice: Dict[str, int] = {}
        for host in self.inventory.hosts():
            if host.generation != pod.tpu.generation:
                continue
            if self.inventory.host_state(host.host_id) != "up":
                continue
            by_slice[host.slice_id] = by_slice.get(host.slice_id, 0) + 1
        full = sum(1 for n in by_slice.values() if n >= hps)
        return full >= pod.tpu.slices

    # -- gang-granular recovery (ISSUE 13) ----------------------------

    def _maintenance_returning(self, pod) -> bool:
        """True while some drained host's FINITE maintenance window
        (still in the future) could actually restore a full-size
        placement for ``pod`` — the elastic rule then waits instead
        of shrinking through it.

        Scoped to slices that could hold the gang: a window on an
        unrelated slice too small for the gang must NOT suppress the
        shrink (on a fleet doing routine rolling maintenance, some
        host always has a window somewhere — fleet-global waiting
        would disable elastic exactly at the scale it exists for).
        A slice qualifies when its hosts that are up-or-returning
        (up now, or draining with a finite future window) reach the
        gang's host count."""
        inventory = self.inventory
        if inventory is None or not hasattr(inventory, "maintenance_hosts"):
            return False
        now = time.time()
        returning = {
            h for h, end in inventory.maintenance_hosts().items()
            if end > now
        }
        if not returning:
            return False
        by_slice: Dict[str, List[str]] = {}
        for host in inventory.hosts():
            by_slice.setdefault(host.slice_id, []).append(host.host_id)
        need = pod.count
        for host_id in returning:
            host = inventory.host(host_id)
            if host is None:
                continue
            usable = [
                h for h in by_slice.get(host.slice_id, ())
                if h in returning or inventory.host_state(h) == "up"
            ]
            if len(usable) >= need:
                return True
        return False

    def _make_gang_phase(
        self,
        pod,
        instances: List[int],
        tasks: Optional[List[str]],
    ) -> Phase:
        """The gang recovery choreography, one serial phase:

            kill-survivors   a worker that lost a gang peer is wedged
                             in a dead collective — reap every live
                             member (tasks whose process no agent
                             reports count as already dead)
            unreserve-slice  release the broken footprint so the
                             re-placement may claim freed capacity
                             (incl. the survivors' own hosts)
            replace-gang     re-place the WHOLE gang PERMANENT,
                             honoring torus adjacency; shrinks to a
                             smaller mesh when the pod is elastic and
                             the decision rule allows
            trim-surplus     after an elastic shrink, erase the
                             surplus instances' task state so the
                             failure scan stops chasing ghosts

        Restart-safe by construction: every step is idempotent (a
        successor that re-runs kill/unreserve against an already-clean
        world completes them immediately) and the replace step's
        incarnation fencing (utils/checkpoint.py) makes any zombie
        survivor's late writes harmless.
        """
        names = sorted(self._required_tasks(pod.type, instances, tasks))
        assets = {pod_instance_name(pod.type, i) for i in instances}
        phase_name = f"recover-{pod.type}-gang"

        def kill_survivors(scheduler) -> bool:
            pending = False
            active = scheduler.agent.active_task_ids()
            for full in names:
                info = scheduler.state_store.fetch_task(full)
                if info is None:
                    continue
                status = scheduler.state_store.fetch_status(full)
                if status is not None and status.task_id == info.task_id \
                        and status.state.is_terminal:
                    continue
                if info.task_id not in active:
                    # no agent runs this process (preempted host, an
                    # already-reaped kill whose status was lost): dead
                    # in fact, even without a terminal status
                    continue
                scheduler.task_killer.kill(info.task_id)
                pending = True
            return not pending

        def unreserve_slice(scheduler) -> bool:
            released = 0
            for full in names:
                for res in list(scheduler.ledger.for_task(full)):
                    scheduler.ledger.release(res.reservation_id)
                    released += 1
            if released and scheduler.journal is not None:
                scheduler.journal.append(
                    "recovery", pod=pod.type, verb="unreserve",
                    reservations=released,
                    message=f"released {released} reservation(s) of the "
                            f"broken {pod.type} gang sub-slice",
                )
            return True

        policy = ElasticPolicy(
            enabled=bool(pod.tpu is not None and pod.tpu.elastic),
            min_hosts=pod.tpu.min_hosts if pod.tpu is not None else 1,
        )
        replace = ElasticGangStep(
            f"replace-{pod.type}-gang",
            pod,
            tasks,
            self._backoff,
            policy,
            maintenance_probe=lambda: self._maintenance_returning(pod),
            journal=self.journal,
        )

        def trim_surplus(scheduler) -> bool:
            erased = 0
            for i in replace.surplus_instances():
                for task_spec in pod.tasks:
                    full = task_full_name(pod.type, i, task_spec.name)
                    for res in list(scheduler.ledger.for_task(full)):
                        scheduler.ledger.release(res.reservation_id)
                    if scheduler.state_store.fetch_task(full) is not None:
                        scheduler.state_store.clear_task(full)
                        erased += 1
            if erased and scheduler.journal is not None:
                scheduler.journal.append(
                    "recovery", pod=pod.type, verb="trim-surplus",
                    tasks=erased,
                    message=f"erased {erased} surplus task(s) after "
                            f"elastic re-slice of {pod.type}",
                )
            return True

        steps: List[Step] = [
            ActionStep(
                f"kill-{pod.type}-survivors", kill_survivors, assets=assets
            ),
            ActionStep(
                f"unreserve-{pod.type}-slice", unreserve_slice, assets=assets
            ),
            replace,
            ActionStep(
                f"trim-{pod.type}-surplus", trim_surplus, assets=assets
            ),
        ]
        phase = Phase(phase_name, steps, SerialStrategy())
        phase.gang_recovery = True
        return phase
