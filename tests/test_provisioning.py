"""Host provisioning: agent --provision-cmd + compile-cache seeding.

VERDICT r3 #8: the first deploy on a fresh host must not pay a full
XLA compile — provisioning seeds the persistent compilation cache
(frameworks/jax/warm_cache.py) before the daemon takes tasks.
"""

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_warm_cache_seeds_compilation_cache(tmp_path):
    cache = tmp_path / "xla-cache"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "JAX_COMPILATION_CACHE_DIR": str(cache),
        "REPO_ROOT": REPO,
    }
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "frameworks/jax/warm_cache.py")],
        env=env, capture_output=True, text=True, timeout=180,
    )
    assert proc.returncode == 0, proc.stderr
    assert "seeded mnist" in proc.stdout
    entries = os.listdir(cache)
    assert entries, "no cache entries written"


def test_warm_cache_requires_cache_dir(tmp_path):
    env = {
        **os.environ, "JAX_PLATFORMS": "cpu", "REPO_ROOT": REPO,
    }
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "frameworks/jax/warm_cache.py")],
        env=env, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1
    assert "JAX_COMPILATION_CACHE_DIR" in proc.stderr


def test_agent_provision_cmd_runs_before_serving(tmp_path):
    marker = tmp_path / "provisioned"
    announce = tmp_path / "announce"
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dcos_commons_tpu", "agent",
            "--host-id", "h0",
            "--workdir", str(tmp_path / "sandboxes"),
            "--announce-file", str(announce),
            "--provision-cmd", f"echo ok > {marker}",
        ],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not announce.exists():
            time.sleep(0.1)
        # serving implies provisioning already finished
        assert announce.exists(), "daemon never announced"
        assert marker.read_text().strip() == "ok"
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_agent_provision_failure_aborts(tmp_path):
    proc = subprocess.run(
        [
            sys.executable, "-m", "dcos_commons_tpu", "agent",
            "--host-id", "h0",
            "--workdir", str(tmp_path / "sandboxes"),
            "--provision-cmd", "exit 7",
        ],
        cwd=REPO, capture_output=True, text=True, timeout=30,
    )
    assert proc.returncode == 7
    assert "provisioning failed" in proc.stderr
