"""The distributed control plane: agent daemons + RemoteFleet.

Statuses cross real sockets here: each "host" is an AgentDaemon HTTP
server with its own sandbox tree, the scheduler talks to them through
RemoteFleet, and killing a daemon triggers host-down detection +
PERMANENT recovery onto a surviving host — the category gap called out
in VERDICT.md item 1 (reference: FrameworkScheduler callbacks crossing
the Mesos process boundary, FrameworkScheduler.java:196).
"""

import time

import pytest

from dcos_commons_tpu.agent.daemon import AgentDaemon
from dcos_commons_tpu.agent.remote import RemoteAgentClient, RemoteFleet
from dcos_commons_tpu.common import TaskInfo, TaskState
from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
from dcos_commons_tpu.recovery.monitor import TestingFailureMonitor
from dcos_commons_tpu.scheduler import SchedulerBuilder, SchedulerConfig
from dcos_commons_tpu.specification import from_yaml
from dcos_commons_tpu.storage import MemPersister

SERVERS_YAML = """
name: web
pods:
  app:
    count: 2
    placement: 'max-per-host:1'
    tasks:
      server:
        goal: RUNNING
        cmd: "echo serving > out.txt && sleep 60"
        cpus: 0.1
        memory: 32
"""


@pytest.fixture
def daemons(tmp_path):
    started = []

    def make(host_id):
        daemon = AgentDaemon(
            host_id, str(tmp_path / f"sandbox-{host_id}")
        ).start()
        started.append(daemon)
        return daemon

    yield make
    for daemon in started:
        daemon.stop()


def drive(scheduler, until, timeout_s=15.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        scheduler.run_cycle()
        if until(scheduler):
            return True
        time.sleep(interval_s)
    return False


def test_daemon_launch_drain_roundtrip(daemons):
    daemon = daemons("h0")
    client = RemoteAgentClient("h0", daemon.url)
    assert client.info()["host_id"] == "h0"
    info = TaskInfo(
        name="app-0-server",
        task_id="app-0-server__1",
        agent_id="h0",
        command="echo hi > out.txt && sleep 0.5",
    )
    client.launch([{"info": info.to_dict()}])
    assert "app-0-server__1" in client.tasks()
    states = []
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        states += [s.state for s in client.drain()]
        if TaskState.FINISHED in states:
            break
        time.sleep(0.05)
    assert TaskState.RUNNING in states
    assert TaskState.FINISHED in states
    assert client.sandbox_file("app-0-server", "out.txt").strip() == "hi"


def test_daemon_reconcile_rearms_drained_statuses(daemons):
    """Explicit reconciliation over the wire (the HA failover hook):
    a status drained by a dead scheduler is re-delivered — with its
    earned readiness — after POST /v1/agent/reconcile, via the client
    AND the fleet fan-out."""
    daemon = daemons("h0")
    client = RemoteAgentClient("h0", daemon.url)
    info = TaskInfo(
        name="app-0-server",
        task_id="app-0-server__1",
        agent_id="h0",
        command="sleep 30",
    )
    client.launch([{"info": info.to_dict()}])
    states = []
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        states += [s for s in client.drain() if s.state.is_running]
        if states:
            break
        time.sleep(0.05)
    assert states, "task never reported RUNNING"
    # drained: a plain re-drain has nothing (edge-triggered)
    assert not [s for s in client.drain() if s.state.is_running]
    # the successor scheduler reconciles: RUNNING re-delivers
    client.reconcile()
    redelivered = [s for s in client.drain() if s.state.is_running]
    assert [s.task_id for s in redelivered] == ["app-0-server__1"]
    assert redelivered[0].ready  # no readiness check: ready rides along
    # the fleet fan-out reaches every daemon (and the Reconciler's
    # getattr hook finds it)
    fleet = RemoteFleet()
    fleet.add_host("h0", daemon.url)
    fleet.reconcile()
    assert [
        s.task_id for s in fleet.poll() if s.state.is_running
    ] == ["app-0-server__1"]
    fleet.kill("app-0-server__1")


def test_daemon_renders_templates_before_launch(daemons):
    daemon = daemons("h0")
    client = RemoteAgentClient("h0", daemon.url)
    info = TaskInfo(
        name="app-0-server",
        task_id="app-0-server__t",
        agent_id="h0",
        command="cat conf/app.cfg > rendered.txt",
        env={"APP_PORT": "8080"},
    )
    client.launch([{
        "info": info.to_dict(),
        "templates": [{
            "name": "app.cfg",
            "dest": "conf/app.cfg",
            "content": "port={{APP_PORT}} mode={{MODE:-prod}}\n",
        }],
    }])
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if any(s.state is TaskState.FINISHED for s in client.drain()):
            break
        time.sleep(0.05)
    assert client.sandbox_file("app-0-server", "rendered.txt").strip() == \
        "port=8080 mode=prod"


def test_template_render_failure_fails_task(daemons):
    daemon = daemons("h0")
    client = RemoteAgentClient("h0", daemon.url)
    info = TaskInfo(
        name="app-0-server", task_id="app-0-server__e", agent_id="h0",
        command="sleep 60",
    )
    client.launch([{
        "info": info.to_dict(),
        "templates": [{
            "name": "bad.cfg", "dest": "bad.cfg",
            "content": "value={{UNSET_VARIABLE}}\n",
        }],
    }])
    deadline = time.monotonic() + 5
    errored = []
    while time.monotonic() < deadline and not errored:
        errored = [s for s in client.drain() if s.state is TaskState.ERROR]
        time.sleep(0.05)
    assert errored and "template" in errored[0].message


def test_sandbox_read_confined_to_task_sandbox(daemons, tmp_path):
    daemon = daemons("h0")
    secret = tmp_path / "secret.txt"
    secret.write_text("s3cret")
    import urllib.error
    import urllib.request
    from urllib.parse import quote

    for task, rel in [
        ("../..", "secret.txt"),             # traversal via task name
        ("app-0-server", "../../secret.txt"),  # traversal via file path
        ("app-0-server", str(secret)),       # absolute path
    ]:
        url = (
            f"{daemon.url}/v1/agent/sandbox"
            f"?task={quote(task)}&file={quote(rel)}"
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(url, timeout=5)
        assert err.value.code == 404


def test_template_dest_escape_fails_task(daemons, tmp_path):
    daemon = daemons("h0")
    client = RemoteAgentClient("h0", daemon.url)
    info = TaskInfo(
        name="app-0-server", task_id="app-0-server__esc", agent_id="h0",
        command="sleep 60",
    )
    client.launch([{
        "info": info.to_dict(),
        "templates": [{
            "name": "evil", "dest": "../outside.txt", "content": "x",
        }],
    }])
    deadline = time.monotonic() + 5
    errored = []
    while time.monotonic() < deadline and not errored:
        errored = [s for s in client.drain() if s.state is TaskState.ERROR]
        time.sleep(0.05)
    assert errored and "escapes the sandbox" in errored[0].message
    assert not (tmp_path / "sandbox-h0" / "outside.txt").exists()


def build_remote_scheduler(yaml_text, fleet, hosts, tmp_path, monitor=None):
    spec = from_yaml(yaml_text)
    builder = SchedulerBuilder(
        spec,
        SchedulerConfig(
            sandbox_root=str(tmp_path / "unused"), backoff_enabled=False
        ),
        MemPersister(),
    )
    builder.set_inventory(SliceInventory(hosts))
    builder.set_agent(fleet)
    if monitor is not None:
        builder.set_failure_monitor(monitor)
    return builder.build()


def test_deploy_across_remote_daemons(daemons, tmp_path):
    fleet = RemoteFleet()
    hosts = []
    for i in range(2):
        daemon = daemons(f"h{i}")
        fleet.add_host(f"h{i}", daemon.url)
        hosts.append(TpuHost(host_id=f"h{i}"))
    scheduler = build_remote_scheduler(SERVERS_YAML, fleet, hosts, tmp_path)
    assert drive(
        scheduler, lambda s: s.deploy_manager.get_plan().is_complete
    )
    # one instance per host, placed and launched over the wire
    placed = {
        scheduler.state_store.fetch_task(f"app-{i}-server").agent_id
        for i in range(2)
    }
    assert placed == {"h0", "h1"}
    for i in range(2):
        info = scheduler.state_store.fetch_task(f"app-{i}-server")
        out = fleet.client(info.agent_id).sandbox_file(
            "app-%d-server" % i, "out.txt"
        )
        assert out.strip() == "serving"


def test_daemon_death_triggers_host_down_and_replace(daemons, tmp_path):
    inventory_hosts = [TpuHost(host_id=f"h{i}") for i in range(3)]
    fleet = RemoteFleet(down_after=2, timeout_s=1.0)
    victim = daemons("h0")
    for i, host in enumerate(inventory_hosts[:2]):
        daemon = victim if i == 0 else daemons(f"h{i}")
        fleet.add_host(f"h{i}", daemon.url)
    spare = daemons("h2")
    fleet.add_host("h2", spare.url)
    scheduler = build_remote_scheduler(
        SERVERS_YAML,
        fleet,
        inventory_hosts,
        tmp_path,
        # any terminal failure of these tasks escalates to PERMANENT
        monitor=TestingFailureMonitor(
            ["app-0-server", "app-1-server"]
        ),
    )
    fleet.on_host_down = scheduler.inventory.mark_down
    fleet.on_host_up = scheduler.inventory.mark_up
    assert drive(
        scheduler, lambda s: s.deploy_manager.get_plan().is_complete
    )
    placed = {
        i: scheduler.state_store.fetch_task(f"app-{i}-server").agent_id
        for i in range(2)
    }
    victim_index = next(i for i, h in placed.items() if h == "h0")

    victim.stop()  # the host dies

    def replaced(s):
        info = s.state_store.fetch_task(f"app-{victim_index}-server")
        status = s.state_store.fetch_status(f"app-{victim_index}-server")
        return (
            info is not None
            and info.agent_id != "h0"
            and status is not None
            and status.task_id == info.task_id
            and status.state is TaskState.RUNNING
        )

    assert drive(scheduler, replaced, timeout_s=30.0)
    assert "h0" in fleet.down_hosts()
    assert not scheduler.inventory.is_up("h0")
    # the survivor never flapped
    other_index = 1 - victim_index
    other = scheduler.state_store.fetch_task(f"app-{other_index}-server")
    assert other.agent_id == placed[other_index]


def test_fleet_telemetry_fan_in_over_the_wire(daemons, tmp_path):
    """Remote-fleet telemetry parity (the PR 10 satellite): steplogs
    and serving gauges written into a DAEMON's sandbox surface through
    RemoteAgentClient and RemoteFleet exactly as LocalProcessAgent
    surfaces them in-process — so /v1/debug/trace, /v1/debug/serving
    and the straggler detector see the production topology too."""
    import json as _json

    daemon = daemons("h0")
    client = RemoteAgentClient("h0", daemon.url)
    steplog_line = _json.dumps(
        {"step": 3, "t": 10.0, "wall_s": 0.5, "blocked_s": 0.1}
    )
    servestats = _json.dumps(
        {"queue_depth": 2, "active_slots": 1, "ttft_p95_s": 0.8}
    )
    info = TaskInfo(
        name="app-0-server",
        task_id="app-0-server__tl",
        agent_id="h0",
        command=(
            f"echo '{steplog_line}' > steplog.jsonl && "
            f"echo '{servestats}' > servestats.json && sleep 60"
        ),
    )
    client.launch([{"info": info.to_dict()}])
    fleet = RemoteFleet()
    fleet.add_host("h0", daemon.url)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if any(s.state.is_running for s in fleet.poll()):
            break
        time.sleep(0.05)
    # files may land a beat after RUNNING: poll the reader
    records = []
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not records:
        records = client.steplog_of("app-0-server")
        time.sleep(0.05)
    assert records == [
        {"step": 3, "t": 10.0, "wall_s": 0.5, "blocked_s": 0.1}
    ]
    assert client.serving_stats_of("app-0-server") == {
        "queue_depth": 2, "active_slots": 1, "ttft_p95_s": 0.8
    }
    # the fleet routes by task NAME through the owner map (learned
    # from the poll above)
    assert fleet.steplog_of("app-0-server") == records
    assert fleet.serving_stats_of("app-0-server")["queue_depth"] == 2
    # best-effort contract: unknown tasks and dead daemons read empty
    assert fleet.steplog_of("never-launched") == []
    assert fleet.serving_stats_of("never-launched") == {}
    # an explicit agent_id routes EXACTLY (the health monitor passes
    # the owner from its own state store — immune to cross-service
    # task-name collisions on a shared fleet); an unknown host reads
    # empty, never guesses by name
    assert fleet.steplog_of("app-0-server", agent_id="h0") == records
    assert fleet.steplog_of("app-0-server", agent_id="h-unknown") == []
    # steady state: polls that change nothing do not invalidate the
    # name index (the generation only moves on real owner changes)
    fleet.poll()
    gen_before = fleet._owners_gen
    fleet.poll()
    assert fleet._owners_gen == gen_before
    # owner CHANGE refreshes the name-keyed routing index: the task's
    # replacement lands on another daemon under the same name, and
    # telemetry must follow it there (kill -> terminal pops the old
    # owner; the relaunch poll inserts the new one)
    d1 = daemons("h1")
    fleet.add_host("h1", d1.url)
    fleet.kill(info.task_id)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if any(s.state.is_terminal for s in fleet.poll()):
            break
        time.sleep(0.05)
    replacement_line = _json.dumps(
        {"step": 9, "t": 20.0, "wall_s": 0.7, "blocked_s": 0.2}
    )
    moved = TaskInfo(
        name="app-0-server",
        task_id="app-0-server__tl2",
        agent_id="h1",
        command=f"echo '{replacement_line}' > steplog.jsonl && sleep 60",
    )
    RemoteAgentClient("h1", d1.url).launch([{"info": moved.to_dict()}])
    deadline = time.monotonic() + 10
    routed = []
    while time.monotonic() < deadline:
        fleet.poll()
        routed = fleet.steplog_of("app-0-server")
        if routed:
            break
        time.sleep(0.05)
    assert routed == [
        {"step": 9, "t": 20.0, "wall_s": 0.7, "blocked_s": 0.2}
    ]
    daemon.stop()
    d1.stop()
    assert fleet.steplog_of("app-0-server") == []
    assert fleet.serving_stats_of("app-0-server") == {}
    # telemetry probes never move the down-detection counters
    assert not fleet.down_hosts()


def test_fleet_kill_unknown_owner_broadcasts(daemons):
    fleet = RemoteFleet()
    d0, d1 = daemons("h0"), daemons("h1")
    fleet.add_host("h0", d0.url)
    fleet.add_host("h1", d1.url)
    info = TaskInfo(
        name="app-0-server", task_id="app-0-server__b", agent_id="h1",
        command="sleep 60",
    )
    RemoteAgentClient("h1", d1.url).launch([{"info": info.to_dict()}])
    # fleet has no owner record (scheduler restart scenario)
    fleet.kill("app-0-server__b")
    deadline = time.monotonic() + 10
    killed = False
    while time.monotonic() < deadline and not killed:
        killed = any(
            s.state is TaskState.KILLED for s in fleet.poll()
        )
        time.sleep(0.05)
    assert killed


def test_daemon_crash_restart_recovers_tasks(daemons, tmp_path):
    """A daemon that dies WITHOUT cleanup loses no tasks: the C++
    supervisor's durable sandbox records (task.json/exit_status) let a
    fresh daemon over the same workdir resume live tasks and report
    exited ones' fates over the wire."""
    workdir = str(tmp_path / "sandbox-crash")
    first = AgentDaemon("hx", workdir).start()
    client = RemoteAgentClient("hx", first.url)
    client.launch([
        {"info": TaskInfo(
            name="app-0-long", task_id="app-0-long__1", agent_id="hx",
            command="sleep 30",
        ).to_dict()},
        {"info": TaskInfo(
            name="app-0-short", task_id="app-0-short__1", agent_id="hx",
            command="exit 0",
        ).to_dict()},
    ])
    deadline = time.monotonic() + 10
    exit_file = (tmp_path / "sandbox-crash" / "app-0-short" / ".super"
                 / "app-0-short__1" / "exit_status")
    while time.monotonic() < deadline and not exit_file.exists():
        time.sleep(0.05)
    assert exit_file.exists()
    # crash: HTTP server torn down, NO executor shutdown (tasks live)
    first._server.shutdown()
    first._server.server_close()

    second = AgentDaemon("hx", workdir).start()
    try:
        client2 = RemoteAgentClient("hx", second.url)
        assert "app-0-long__1" in client2.tasks()
        states = {}
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            for s in client2.drain():
                states[(s.task_id, s.state)] = True
            if (("app-0-short__1", TaskState.FINISHED) in states
                    and ("app-0-long__1", TaskState.RUNNING) in states):
                break
            time.sleep(0.05)
        assert ("app-0-short__1", TaskState.FINISHED) in states
        assert ("app-0-long__1", TaskState.RUNNING) in states
        client2.kill("app-0-long__1", 0.5)
        deadline = time.monotonic() + 10
        killed = False
        while time.monotonic() < deadline:
            if any(
                s.task_id == "app-0-long__1" and s.state is TaskState.KILLED
                for s in client2.drain()
            ):
                killed = True
                break
            time.sleep(0.05)
        assert killed
    finally:
        second.stop()
