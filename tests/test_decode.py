"""KV-cache inference correctness: cached decode == full re-forward.

The serving half of the flagship (models/decode.py).  The oracle for
every test is transformer.forward run on the growing sequence — the
cache must be a pure optimization, never a semantic change.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcos_commons_tpu.models import (
    TransformerConfig,
    forward,
    generate,
    init_params,
    prefill,
)
from dcos_commons_tpu.models.decode import decode_step
from dcos_commons_tpu.utils import synthetic_tokens

CFG = TransformerConfig(
    vocab=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq=64, dtype=jnp.float32, remat=False,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0))


def test_prefill_logits_match_forward(params):
    tokens, _ = synthetic_tokens(jax.random.key(1), 2, 16, CFG.vocab)
    logits, cache = prefill(CFG, params, tokens, max_len=32)
    oracle = forward(CFG, params, tokens)[:, -1]
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(oracle), atol=2e-4, rtol=2e-4
    )
    assert cache["k"].shape == (2, 2, 32, CFG.n_kv_heads, CFG.head_dim)


def test_decode_step_matches_full_forward(params):
    """Each cached step's logits == re-running the whole prefix."""
    tokens, _ = synthetic_tokens(jax.random.key(2), 2, 8, CFG.vocab)
    logits, cache = prefill(CFG, params, tokens, max_len=16)
    seq = tokens
    step_fn = jax.jit(
        lambda c, t, p: decode_step(CFG, params, c, t, p)
    )
    for i in range(4):
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        logits, cache = step_fn(cache, nxt, jnp.int32(8 + i))
        oracle = forward(CFG, params, seq)[:, -1]
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(oracle), atol=3e-4, rtol=3e-4,
            err_msg=f"divergence at decode step {i}",
        )


def test_greedy_generate_equals_argmax_chain(params):
    """generate(T=0) token-for-token equals chaining full forwards."""
    prompt, _ = synthetic_tokens(jax.random.key(3), 2, 6, CFG.vocab)
    out = generate(CFG, params, prompt, max_new_tokens=6)
    assert out.shape == (2, 6)
    seq = prompt
    expect = []
    for _ in range(6):
        nxt = jnp.argmax(forward(CFG, params, seq)[:, -1], axis=-1)
        expect.append(nxt)
        seq = jnp.concatenate(
            [seq, nxt[:, None].astype(jnp.int32)], axis=1
        )
    expect = jnp.stack(expect, axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(expect))


def test_generate_is_jittable_once(params):
    """One compile serves any prompt CONTENT (static shapes only)."""
    fn = jax.jit(
        lambda p, t: generate(CFG, p, t, max_new_tokens=4, max_len=16)
    )
    a, _ = synthetic_tokens(jax.random.key(4), 1, 8, CFG.vocab)
    b, _ = synthetic_tokens(jax.random.key(5), 1, 8, CFG.vocab)
    out_a = fn(params, a)
    out_b = fn(params, b)  # cache hit: same shapes
    assert out_a.shape == out_b.shape == (1, 4)
    assert fn._cache_size() == 1


def test_right_padded_prompt_with_true_len_matches_exact(params):
    """The serving contract: a RIGHT-padded prompt with a traced
    true_len generates exactly what the unpadded prompt does (causal
    attention hides the pads; logits read at true_len-1; decode
    overwrites/masks pad slots) — and one compile serves any length."""
    gen = jax.jit(lambda p, t, n: generate(
        CFG, p, t, max_new_tokens=4, max_len=24, true_len=n
    ))
    for true_len in (3, 6, 9):
        prompt, _ = synthetic_tokens(
            jax.random.key(10 + true_len), 2, true_len, CFG.vocab
        )
        exact = generate(CFG, params, prompt, max_new_tokens=4)
        padded = jnp.zeros((2, 12), jnp.int32).at[:, :true_len].set(prompt)
        out = gen(params, padded, jnp.int32(true_len))
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(exact),
            err_msg=f"padding changed generation at true_len {true_len}",
        )
    assert gen._cache_size() == 1  # one compile for all three lengths


def test_temperature_is_traced_not_static(params):
    """Novel temperatures must not recompile (a server takes them
    from requests)."""
    fn = jax.jit(lambda p, t, temp: generate(
        CFG, p, t, max_new_tokens=3, max_len=12,
        temperature=temp, key=jax.random.key(0),
    ))
    prompt, _ = synthetic_tokens(jax.random.key(20), 1, 4, CFG.vocab)
    for temp in (0.0, 0.7, 1.3):
        out = fn(params, prompt, jnp.float32(temp))
        assert out.shape == (1, 3)
    assert fn._cache_size() == 1
    # traced temp 0.0 still means greedy
    greedy = generate(CFG, params, prompt, max_new_tokens=3)
    np.testing.assert_array_equal(
        np.asarray(fn(params, prompt, jnp.float32(0.0))),
        np.asarray(greedy),
    )


def test_sharded_generate_matches_unsharded(params):
    """Multi-chip SERVING: prefill + decode under a dp x tp mesh
    (params tp-sharded, batch dp-sharded, GSPMD activation
    collectives) reproduce the unsharded LOGITS to float tolerance —
    exact token equality would be tie-fragile because the tp psum
    reorders the f32 reduction — and the full sharded generate runs
    end to end."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dcos_commons_tpu.models.decode import init_kv_cache
    from dcos_commons_tpu.models.transformer import param_shardings
    from dcos_commons_tpu.parallel.mesh import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(dp=2, tp=4))
    prompt, _ = synthetic_tokens(jax.random.key(30), 4, 8, CFG.vocab)
    ref_logits, ref_cache = prefill(CFG, params, prompt, max_len=16)
    with mesh:
        shards = param_shardings(CFG, mesh)
        sparams = jax.tree.map(jax.device_put, params, shards)
        sprompt = jax.device_put(
            prompt, NamedSharding(mesh, P(("dcn", "dp", "fsdp"), None))
        )
        logits, cache = jax.jit(
            lambda p, t: prefill(CFG, p, t, max_len=16)
        )(sparams, sprompt)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(ref_logits),
            atol=2e-4, rtol=2e-4,
        )
        # one sharded decode step reproduces the unsharded step logits
        nxt = jnp.argmax(ref_logits, axis=-1).astype(jnp.int32)
        step_logits, _ = jax.jit(lambda p, c, t: decode_step(
            CFG, p, c, t, jnp.int32(8)
        ))(sparams, cache, nxt)
        ref_step, _ = decode_step(CFG, params, ref_cache, nxt, jnp.int32(8))
        np.testing.assert_allclose(
            np.asarray(step_logits), np.asarray(ref_step),
            atol=3e-4, rtol=3e-4,
        )
        # the full scan-decode generate runs sharded end to end
        out = jax.jit(lambda p, t: generate(
            CFG, p, t, max_new_tokens=4, max_len=16
        ))(sparams, sprompt)
        jax.block_until_ready(out)
    assert out.shape == (4, 4)
    assert bool(jnp.all((out >= 0) & (out < CFG.vocab)))


def test_int8_kv_cache_tracks_bf16_cache(params):
    """The quantized cache is a throughput optimization, not a model
    change: per-step logits stay within per-vector int8 error of the
    exact cache, and greedy continuations near-always agree."""
    tokens, _ = synthetic_tokens(jax.random.key(5), 4, 16, CFG.vocab)
    exact_logits, exact_cache = prefill(CFG, params, tokens, max_len=32)
    q_logits, q_cache = prefill(
        CFG, params, tokens, max_len=32, kv_dtype="int8"
    )
    assert q_cache["k"].dtype == jnp.int8
    assert q_cache["k_scale"].shape == (2, 4, 32, CFG.n_kv_heads, 1)
    # prefill logits are computed from full-precision activations in
    # both paths (quantization only affects the STORED cache)
    np.testing.assert_allclose(
        np.asarray(q_logits), np.asarray(exact_logits),
        atol=2e-4, rtol=2e-4,
    )
    # decode steps read the quantized cache: bounded drift
    token = jnp.argmax(exact_logits, axis=-1).astype(jnp.int32)
    exact_l, exact_cache = decode_step(
        CFG, params, exact_cache, token, jnp.int32(16)
    )
    q_l, q_cache = decode_step(
        CFG, params, q_cache, token, jnp.int32(16)
    )
    denom = np.maximum(np.abs(np.asarray(exact_l)).max(), 1e-6)
    rel = np.abs(np.asarray(q_l) - np.asarray(exact_l)).max() / denom
    assert rel < 0.05, f"int8 cache drifted {rel:.3f} from exact"
    # and the cache write path stayed quantized
    assert q_cache["k"].dtype == jnp.int8


def test_int8_generate_greedy_mostly_agrees(params):
    """End-to-end greedy generation with the int8 cache agrees with
    the exact cache on the vast majority of steps (random tiny-model
    logits are nearly flat — exact argmax agreement is not a fair
    bar, token-level agreement is)."""
    prompt, _ = synthetic_tokens(jax.random.key(6), 4, 12, CFG.vocab)
    exact = generate(CFG, params, prompt, max_new_tokens=16, max_len=32)
    quant = generate(
        CFG, params, prompt, max_new_tokens=16, max_len=32,
        kv_dtype="int8",
    )
    agree = float(jnp.mean((exact == quant).astype(jnp.float32)))
    assert agree >= 0.8, f"only {agree:.0%} of greedy tokens agree"


def test_sampling_needs_key_and_respects_temperature(params):
    prompt, _ = synthetic_tokens(jax.random.key(6), 1, 4, CFG.vocab)
    with pytest.raises(ValueError, match="PRNG key"):
        generate(CFG, params, prompt, 2, temperature=0.8)
    s1 = generate(CFG, params, prompt, 8, temperature=1.5,
                  key=jax.random.key(1))
    s2 = generate(CFG, params, prompt, 8, temperature=1.5,
                  key=jax.random.key(2))
    # different keys, (almost surely) different samples at this temp
    assert not np.array_equal(np.asarray(s1), np.asarray(s2))


def test_prompt_longer_than_cache_rejected(params):
    prompt, _ = synthetic_tokens(jax.random.key(7), 1, 8, CFG.vocab)
    with pytest.raises(ValueError, match="exceeds cache"):
        prefill(CFG, params, prompt, max_len=4)
    # an explicit max_len too small for the continuation is equally an
    # error, not silent cache corruption (dynamic_update_slice clamps)
    with pytest.raises(ValueError, match="cannot hold"):
        generate(CFG, params, prompt, max_new_tokens=16, max_len=16)


@pytest.mark.parametrize("kv_dtype", ["native", "int8"])
def test_per_row_true_len_matches_individual_generates(params, kv_dtype):
    """A MIXED-length right-padded batch with a [b] true_len vector
    produces, row for row, exactly what each prompt gets on its own —
    one dispatch serves heterogeneous requests (the serving
    micro-batcher's mixed-traffic path)."""
    lens = [3, 7, 5, 1]
    width, new = 8, 6
    rng = np.random.default_rng(7)
    rows = [rng.integers(1, CFG.vocab, n).tolist() for n in lens]
    padded = np.zeros((len(rows), width), np.int32)
    for i, row in enumerate(rows):
        padded[i, : len(row)] = row
    batched = generate(
        CFG, params, jnp.asarray(padded), max_new_tokens=new,
        max_len=width + new,
        true_len=jnp.asarray(lens, jnp.int32), kv_dtype=kv_dtype,
    )
    for i, row in enumerate(rows):
        solo = generate(
            CFG, params, jnp.asarray([row], jnp.int32),
            max_new_tokens=new, max_len=width + new,
            kv_dtype=kv_dtype,
        )
        assert np.asarray(batched)[i].tolist() == \
            np.asarray(solo)[0].tolist(), f"row {i} (len {row}) diverged"


def test_per_row_true_len_one_compile_for_any_mix(params):
    """The per-row path compiles ONCE for every length mix."""
    compiles = 0
    width, new = 8, 4

    @jax.jit
    def gen(p, t, lens):
        nonlocal compiles
        compiles += 1
        return generate(
            CFG, p, t, max_new_tokens=new, max_len=width + new,
            true_len=lens,
        )

    tokens = jnp.ones((3, width), jnp.int32)
    gen(params, tokens, jnp.asarray([2, 5, 8], jnp.int32))
    gen(params, tokens, jnp.asarray([8, 1, 3], jnp.int32))
    gen(params, tokens, jnp.asarray([4, 4, 4], jnp.int32))
    assert compiles == 1
