"""Inference serving task: the flagship behind an HTTP endpoint.

The scheduler deploys this like any other task (svc_serve.yml): it
builds the model, warms the KV-cache generate path (one compile), then
serves POST /generate on the scheduler-assigned port — discoverable
via /v1/endpoints and the VIP.  Readiness: the task's readiness check
passes once the warmup file exists, so the deploy plan completes only
when the server can actually answer.

Request:  {"tokens": [[...]], "max_new_tokens": N, "temperature": T}
Response: {"tokens": [[...]]} — the continuations only.

Concurrency: with SERVE_BATCH > 1 the server MICRO-BATCHES — a decode
step costs nearly the same wall time for 1 or 64 rows, so concurrent
single-prompt clients that would otherwise serialize behind the chip
are collected for MICROBATCH_WINDOW_MS and answered by ONE generate.
MIXED prompt lengths merge too: the compiled function takes a traced
PER-ROW true_len vector (models/decode.py), so heterogeneous clients
share one dispatch — only the temperature groups requests (it is one
traced scalar for the whole batch).
"""

import json
import math
import os
import sys
import threading

import numpy as np
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.environ.get("REPO_ROOT", "/root/repo"))

from dcos_commons_tpu.utils.microbatch import (  # noqa: E402
    MicroBatcher,
    WorkItem,
    pack_mixed_rows,
    unpack_results,
)

# back-compat aliases (unit tests drive the batcher through this
# module's names; the implementation is shared with the gang server)
_MicroBatcher = MicroBatcher
_WorkItem = WorkItem


def main() -> int:
    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from dcos_commons_tpu.models import (
        config_from_env,
        generate,
        init_params,
    )
    from dcos_commons_tpu.utils import (
        enable_compilation_cache,
        restore_checkpoint,
    )

    enable_compilation_cache()
    config = config_from_env(
        os.environ,
        dtype=jnp.bfloat16 if os.environ.get(
            "JAX_PLATFORMS"
        ) != "cpu" else jnp.float32,
        remat=False,
    )
    max_len = int(os.environ.get("MAX_LEN", "256"))
    batch = int(os.environ.get("SERVE_BATCH", "1"))
    new_tokens = int(os.environ.get("MAX_NEW_TOKENS", "32"))

    params = init_params(config, jax.random.key(0))
    ckpt_dir = os.environ.get("CHECKPOINT_DIR", "")
    if ckpt_dir:
        # serve the TRAINED weights when a checkpoint tree exists
        # (the train pod's orbax-style output); params-only restore
        state, step = restore_checkpoint(ckpt_dir, {"params": params})
        if step is not None:
            params = state["params"]
            print(f"restored checkpoint step {step}", flush=True)

    # WEIGHT_DTYPE=int8 stores the layer matmul weights quantized
    # (models/quantize.py): decode streams half the weight bytes per
    # step — the dominant HBM term at small serving batches
    if os.environ.get("WEIGHT_DTYPE", "native") == "int8":
        from dcos_commons_tpu.models import quantize_params_int8

        params = jax.device_put(quantize_params_int8(params))
        print("weights quantized to int8 (per-channel)", flush=True)

    # ONE compile covers every request: static (batch, prompt_len)
    # shapes with prompts RIGHT-padded and the true length TRACED
    # (causal attention means real tokens never see the padding, and
    # decode overwrites/masks the pad slots); temperature is a traced
    # operand too — novel temperatures must not recompile
    prompt_len = max_len - new_tokens
    # KV_DTYPE=int8 halves the cache bytes per decode step: the lever
    # for large serving batches on a full chip (models/decode.py)
    kv_dtype = os.environ.get("KV_DTYPE", "native")
    gen = jax.jit(lambda p, t, key, temp, n: generate(
        config, p, t, max_new_tokens=new_tokens, max_len=max_len,
        temperature=temp, key=key, true_len=n, kv_dtype=kv_dtype,
    ))
    lock = threading.Lock()

    def run_group(items):
        """ONE generate for a compatible group of requests — mixed
        prompt lengths ride the per-row true_len vector."""
        if len(items) > 1:
            print(
                f"microbatch: {len(items)} requests / "
                f"{sum(len(i.rows) for i in items)} rows in one generate",
                flush=True,
            )
        padded, lens, _used = pack_mixed_rows(items, batch, prompt_len)
        # fresh entropy per batch: hashing only the prompt made
        # temperature>0 replies deterministic per process
        seed = int.from_bytes(os.urandom(4), "little")
        with lock:  # one generate at a time per chip
            out = gen(
                params, jnp.asarray(padded),
                jax.random.key(seed),
                jnp.float32(items[0].temp),
                jnp.asarray(lens),
            )
        # ONE bulk device->host fetch, then slice in numpy: per-element
        # int(out[i, j]) would be a separate transfer each (~100ms over
        # a TPU relay — 256 of them turned a 1.5s generate into a 36s
        # reply)
        unpack_results(items, np.asarray(jax.device_get(out)))

    window_s = float(os.environ.get("MICROBATCH_WINDOW_MS", "5")) / 1e3
    # with a 1-row server there is nothing to batch: the direct path
    # keeps zero added latency (and bit-identical single-client flow)
    queue_timeout_s = float(os.environ.get("SERVE_QUEUE_TIMEOUT_S", "600"))
    batcher = (
        _MicroBatcher(
            run_group, capacity=batch, window_s=window_s,
            queue_timeout_s=queue_timeout_s,
        )
        if batch > 1 else None
    )

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            if self.path != "/generate":
                self.send_error(404)
                return
            length = int(self.headers.get("Content-Length", 0))
            try:
                body = json.loads(self.rfile.read(length))
                rows = body["tokens"]
                if len(rows) > batch:
                    raise ValueError(
                        f"{len(rows)} prompts > server batch {batch}; "
                        "split the request"
                    )
                # rows may have MIXED lengths (per-row true_len); an
                # over-length prompt is refused, never silently
                # continued as a DIFFERENT (truncated) prompt
                if not rows:
                    raise ValueError("tokens must be non-empty")
                for row in rows:
                    if len(row) < 1:
                        raise ValueError("prompts must be non-empty")
                    if len(row) > prompt_len:
                        raise ValueError(
                            f"prompt length {len(row)} exceeds the "
                            f"server's context {prompt_len}"
                        )
                temp = float(body.get("temperature", 0.0))
                if not math.isfinite(temp) or temp < 0.0:
                    # json.loads accepts NaN/Infinity: a NaN group key
                    # is never equal to itself and must not reach the
                    # batcher (or the chip, where it poisons sampling)
                    raise ValueError(
                        f"temperature must be finite and >= 0, got {temp}"
                    )
                n = int(body.get("max_new_tokens", new_tokens))
                if n < 1:
                    raise ValueError(
                        f"max_new_tokens must be >= 1, got {n}"
                    )
                n = min(n, new_tokens)
                clean_rows = [
                    [int(t) % config.vocab for t in row] for row in rows
                ]
                item = _WorkItem(clean_rows, n, temp)
                if batcher is not None:
                    result = batcher.submit(item)
                else:
                    run_group([item])
                    result = item.result
                payload = json.dumps({"tokens": result}).encode()
                self.send_response(200)
            except Exception as e:  # noqa: BLE001 — surface to client
                payload = json.dumps({"error": str(e)}).encode()
                self.send_response(400)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

    # a RELAUNCH reuses the sandbox: a stale ready file from the
    # previous incarnation must not pass readiness while we are cold
    try:
        os.remove("ready")
    except OSError:
        pass
    # bind BEFORE warming and only then write the readiness file — a
    # bind failure (port collision) must fail readiness, not pass it
    port = int(os.environ.get("PORT_HTTP", "0"))
    server = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    warm = jnp.zeros((batch, prompt_len), jnp.int32)
    out = gen(
        params, warm, jax.random.key(0), jnp.float32(0.0),
        jnp.full((batch,), prompt_len, jnp.int32),
    )
    jax.block_until_ready(out)
    with open("ready", "w") as f:
        f.write("warm\n")
    print(
        f"warm: serving generate({batch}x{prompt_len}->{new_tokens}) "
        f"on {server.server_address[1]}",
        flush=True,
    )
    server.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
