"""Control-plane authentication: shared bearer token + TLS transport.

Reference: dcos/auth/ token providers and
dcos/clients/ServiceAccountIAMTokenClient.java — every hop of the
reference's control plane authenticates (scheduler -> Mesos, CLI ->
scheduler via admin-router, scheduler -> ZK via CuratorPersister ACLs,
curator/CuratorPersister.java:43-110).  This module is the rebuild's
analogue for the three HTTP surfaces (scheduler API, agent daemons,
state server):

* a **cluster auth token** — one shared secret distributed to every
  control-plane process (operator-managed file, like a service-account
  secret).  Servers reject any request without
  ``Authorization: Bearer <token>`` (401); comparison is
  constant-time.  ``/v1/health`` stays open for liveness probes.
* optional **TLS** — each server can serve HTTPS with a certificate
  issued by the in-repo CA (security/tls.py); clients verify against
  the CA bundle.  ``python -m dcos_commons_tpu certs`` provisions a
  CA + per-host server certs into a directory.

Trust model (documented per ADVICE r2): without a token the control
plane is **loopback/trusted-network only** — anyone who can reach an
agent port can run commands.  ``--bind 0.0.0.0`` fleets must set a
token (all entrypoints warn if they don't) and should add ``--tls-*``
so task secrets/TLS keys never transit plaintext.
"""

from __future__ import annotations

import hmac
import os
import secrets
import ssl
from typing import Mapping, Optional, Tuple

AUTH_HEADER = "Authorization"


def generate_token() -> str:
    """256-bit random bearer token (hex)."""
    return secrets.token_hex(32)


def load_token(token: str = "", token_file: str = "",
               env: Optional[Mapping[str, str]] = None) -> str:
    """Resolve the cluster token: explicit > file > $AUTH_TOKEN(_FILE)."""
    if token:
        return token
    env = env if env is not None else os.environ
    token_file = token_file or env.get("AUTH_TOKEN_FILE", "")
    if token_file:
        with open(token_file) as f:
            return f.read().strip()
    return env.get("AUTH_TOKEN", "")


def check_bearer(headers, token: str) -> bool:
    """True when the request may proceed.  ``token == ''`` disables
    auth (single-machine dev mode; see trust model above)."""
    if not token:
        return True
    presented = headers.get(AUTH_HEADER, "") or ""
    return hmac.compare_digest(
        presented.encode("utf-8"), f"Bearer {token}".encode("utf-8")
    )


def auth_headers(token: str) -> dict:
    return {AUTH_HEADER: f"Bearer {token}"} if token else {}


UNAUTHORIZED = (401, {"message": "missing or invalid bearer token"})


# ---------------------------------------------------------------------------
# TLS transport
# ---------------------------------------------------------------------------


def server_ssl_context(certfile: str, keyfile: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile, keyfile)
    return ctx


def client_ssl_context(ca_file: str = "") -> ssl.SSLContext:
    """Verify servers against the cluster CA bundle; an empty ca_file
    falls back to system trust (public certs)."""
    if ca_file:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_verify_locations(ca_file)
        ctx.check_hostname = True
        ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx
    return ssl.create_default_context()


def tls_pair(cert: str, key: str) -> Optional[Tuple[str, str]]:
    """Normalize a cert/key file pair; HALF a pair is a config error —
    silently serving plaintext when the operator asked for TLS is the
    one downgrade this module exists to prevent."""
    if bool(cert) != bool(key):
        raise ValueError(
            "TLS requires BOTH a certificate and a key file; got only "
            f"{'cert' if cert else 'key'} — refusing to serve plaintext"
        )
    return (cert, key) if cert else None


def wrap_http_server(httpd, tls: Optional[Tuple[str, str]]):
    """Wrap a stdlib HTTPServer's listening socket for HTTPS.

    ``tls`` is (certfile, keyfile) or None (plain HTTP).  The TLS
    handshake runs in the per-connection handler thread with a
    timeout, NOT in the accept loop: a client that opens TCP and never
    sends a ClientHello must not freeze the whole control-plane server
    (these servers gate launches, state, and lease renewals — an
    accept-loop stall would look like fleet-wide lease loss)."""
    if tls:
        ctx = server_ssl_context(tls[0], tls[1])
        httpd.socket = ctx.wrap_socket(
            httpd.socket, server_side=True, do_handshake_on_connect=False
        )
        inner_finish = httpd.finish_request

        def finish_request(request, client_address):
            request.settimeout(10.0)
            request.do_handshake()
            request.settimeout(None)
            inner_finish(request, client_address)

        httpd.finish_request = finish_request
    return httpd


def url_scheme(tls) -> str:
    return "https" if tls else "http"


# ---------------------------------------------------------------------------
# `python -m dcos_commons_tpu certs` — provision CA + server certs
# ---------------------------------------------------------------------------


def certs_main(argv=None) -> int:
    """Provision control-plane TLS material into a directory:

        python -m dcos_commons_tpu certs --dir ./cp-certs \\
            --hosts scheduler-host,agent-host-1,agent-host-2

    Writes ca.pem (hand to every client via --tls-ca / TLS_CA_FILE)
    and per-host <host>.cert.pem / <host>.key.pem (hand to the server
    bound on that host), plus a fresh auth token in token (0600).
    """
    import argparse

    from dcos_commons_tpu.security.tls import CertificateAuthority

    parser = argparse.ArgumentParser(prog="dcos_commons_tpu certs")
    parser.add_argument("--dir", required=True)
    parser.add_argument(
        "--hosts", default="localhost",
        help="comma-separated hostnames/IPs to issue server certs for",
    )
    args = parser.parse_args(argv)
    os.makedirs(args.dir, exist_ok=True)
    ca = CertificateAuthority.create("dcos-commons-tpu control plane CA")
    with open(os.path.join(args.dir, "ca.pem"), "wb") as f:
        f.write(ca.ca_cert_pem)
    for host in [h.strip() for h in args.hosts.split(",") if h.strip()]:
        cert, key = ca.issue(host, sans=[host, "localhost", "127.0.0.1"])
        cert_path = os.path.join(args.dir, f"{host}.cert.pem")
        key_path = os.path.join(args.dir, f"{host}.key.pem")
        with open(cert_path, "wb") as f:
            f.write(cert)
        fd = os.open(key_path, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "wb") as f:
            f.write(key)
    token_path = os.path.join(args.dir, "token")
    fd = os.open(token_path, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        f.write(generate_token() + "\n")
    print(f"wrote CA, server certs, and auth token under {args.dir}")
    return 0
