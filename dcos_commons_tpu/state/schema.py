"""Schema versioning for the persisted tree.

Reference: state/SchemaVersionStore.java — a stored integer checked at
startup; an unsupported version aborts before any writes happen.
"""

from __future__ import annotations

from dcos_commons_tpu.storage import Persister


class SchemaVersionStore:
    PATH = "/schema-version"
    CURRENT = 1

    def __init__(self, persister: Persister) -> None:
        self._persister = persister

    def fetch(self) -> int:
        raw = self._persister.get_or_none(self.PATH)
        return int(raw.decode("utf-8")) if raw else 0

    def store(self, version: int) -> None:
        self._persister.set(self.PATH, str(version).encode("utf-8"))

    def check(self) -> None:
        """Initialize on first boot; abort on incompatible schema."""
        existing = self.fetch()
        if existing == 0:
            self.store(self.CURRENT)
        elif existing != self.CURRENT:
            raise RuntimeError(
                f"unsupported schema version {existing} "
                f"(supported: {self.CURRENT}); refusing to start"
            )
