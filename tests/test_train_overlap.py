"""Step-time fast path (ISSUE 7): InflightWindow accounting under
async dispatch, loss equivalence of the overlapped loop, grad-accum
numerics, async + incarnation-fenced checkpointing, and the worker's
env knobs end to end.

The accounting tests drive the window with a FAKE device (a ready_fn
that sleeps until each step's scheduled completion) so the billing
contract is pinned independently of any backend's dispatch semantics:
this container's CPU backend executes inline, a TPU's dispatch is
async — wall_s must mean the same thing on both.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from dcos_commons_tpu.models import (  # noqa: E402
    TransformerConfig,
    init_params,
    make_train_step,
)
from dcos_commons_tpu.trace.steplog import (  # noqa: E402
    InflightWindow,
    StepLog,
    read_steplog,
)
from dcos_commons_tpu.utils import (  # noqa: E402
    AsyncCheckpointer,
    StaleWriterError,
    claim_incarnation,
    restore_checkpoint,
    save_checkpoint,
)


class _Recorder:
    """StepLog stand-in capturing records in memory."""

    def __init__(self):
        self.records = []

    def record(self, step, **fields):
        self.records.append(dict(step=step, **fields))


class _FakeDevice:
    """A device whose step N completes at a scheduled wall time:
    ready(result) blocks until that step's completion, like
    block_until_ready on a genuinely async backend."""

    def __init__(self):
        self.done_at = {}

    def dispatch(self, step, duration_s):
        # steps execute in order: step N completes duration after
        # the LATER of its dispatch and step N-1's completion
        prev = max(self.done_at.values()) if self.done_at else time.time()
        self.done_at[step] = max(prev, time.time()) + duration_s
        return step

    def ready(self, step):
        delay = self.done_at[step] - time.time()
        if delay > 0:
            time.sleep(delay)
        return step


# -- window accounting -------------------------------------------------


def test_window_bills_wall_to_incurring_step():
    """Async dispatch k=2: the host runs ahead, yet each step's
    wall_s converges to the device time THAT step added, and
    blocked_s stays with the step whose barrier it was."""
    device = _FakeDevice()
    rec = _Recorder()
    window = InflightWindow(rec, 2, ready_fn=device.ready)
    device_s = 0.05
    t_start = time.time()
    for i in range(6):
        t0 = time.time()
        result = device.dispatch(i, device_s)
        window.push(i, result, t0, blocked_s=0.001 * i, worker=7)
    window.drain()
    total = time.time() - t_start

    assert [r["step"] for r in rec.records] == list(range(6))
    assert all(r["worker"] == 7 for r in rec.records)
    # blocked_s billed to the step that measured it, untouched
    assert [r["blocked_s"] for r in rec.records] == [
        pytest.approx(0.001 * i) for i in range(6)
    ]
    # conservation: the records account for the whole run (pipeline
    # fill included), no step double-billed
    assert sum(r["wall_s"] for r in rec.records) == pytest.approx(
        total, abs=0.03
    )
    # steady state: each drained step bills ~one device step, NOT the
    # dispatch-to-ready span (which covers k+1 steps under overlap)
    for r in rec.records[1:]:
        assert r["wall_s"] == pytest.approx(device_s, abs=0.03)


def test_window_zero_matches_synchronous_loop():
    """k=0 is the pre-overlap loop: drain at every push, wall_s spans
    dispatch start to ready."""
    device = _FakeDevice()
    rec = _Recorder()
    window = InflightWindow(rec, 0, ready_fn=device.ready)
    for i in range(3):
        t0 = time.time()
        result = device.dispatch(i, 0.03)
        drained = window.push(i, result, t0)
        # synchronous: this step drained before push returned
        assert [s for s, _ in drained] == [i]
    assert window.drain() == []
    for r in rec.records:
        assert r["wall_s"] == pytest.approx(0.03, abs=0.02)


def test_window_caps_in_flight_depth():
    """The window never holds more than k undrained steps: dispatch
    runs at most k ahead of the oldest unfinished result."""
    device = _FakeDevice()
    rec = _Recorder()
    window = InflightWindow(rec, 3, ready_fn=device.ready)
    for i in range(10):
        window.push(i, device.dispatch(i, 0.001), time.time())
        assert len(window._pending) <= 3
    window.drain()
    assert window.drained == 10
    assert [r["step"] for r in rec.records] == list(range(10))


def test_window_idle_gap_billed_to_nobody():
    """A host-side pause between steps (a blocking save in the legacy
    path, a stall in the data loader) is NOT device time: the next
    step's wall_s starts at its own dispatch, not at the previous
    ready."""
    device = _FakeDevice()
    rec = _Recorder()
    window = InflightWindow(rec, 0, ready_fn=device.ready)
    window.push(0, device.dispatch(0, 0.02), time.time())
    time.sleep(0.08)  # the host stall
    window.push(1, device.dispatch(1, 0.02), time.time())
    window.drain()
    assert rec.records[1]["wall_s"] == pytest.approx(0.02, abs=0.02)


# -- loop equivalence --------------------------------------------------


def _tiny_config():
    return TransformerConfig(
        vocab=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=176, max_seq=32, dtype=jnp.float32, remat=False,
    )


def _loop(step_fn, config, window_size, steps=6, batch=4):
    corpus = np.random.RandomState(0).randint(
        0, config.vocab, size=(steps, batch, config.max_seq + 1),
        dtype=np.int32,
    )
    params = init_params(config, jax.random.key(0))
    optimizer = optax.adamw(3e-4)
    opt_state = optimizer.init(params)
    rec = _Recorder()
    window = InflightWindow(rec, window_size)
    losses = {}
    for i in range(steps):
        t0 = time.time()
        tokens = jnp.asarray(corpus[i, :, :-1])
        targets = jnp.asarray(corpus[i, :, 1:])
        params, opt_state, loss = step_fn(
            params, opt_state, tokens, targets
        )
        for s, ready in window.push(i, loss, t0):
            losses[s] = float(ready)
    for s, ready in window.drain():
        losses[s] = float(ready)
    return losses, params


def test_overlapped_donated_loop_is_loss_equivalent():
    """The fast path (donated buffers + bounded in-flight window)
    must reproduce the synchronous undonated loop's losses EXACTLY
    under a deterministic config — buffer aliasing and host blocking
    order must never change the math (the PR 6 token-equality
    discipline applied to training)."""
    config = _tiny_config()
    optimizer = optax.adamw(3e-4)
    legacy = make_train_step(config, optimizer, donate=False)
    fast = make_train_step(config, optimizer, donate=True)
    legacy_losses, legacy_params = _loop(legacy, config, 0)
    fast_losses, fast_params = _loop(fast, config, 2)
    assert legacy_losses == fast_losses
    for a, b in zip(
        jax.tree.leaves(legacy_params), jax.tree.leaves(fast_params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_grad_accum_matches_full_batch():
    """Equal-size microbatch accumulation is the full-batch gradient
    up to float reassociation: losses and updated params agree to
    numerical tolerance, over several steps."""
    config = _tiny_config()
    optimizer = optax.adamw(3e-4)
    full = make_train_step(config, optimizer, donate=False)
    accum = make_train_step(
        config, optimizer, donate=False, grad_accum=4
    )
    params = init_params(config, jax.random.key(0))
    state_f = (params, optimizer.init(params))
    state_a = (params, optimizer.init(params))
    tokens = jax.random.randint(
        jax.random.key(1), (8, config.max_seq), 0, config.vocab
    )
    targets = jax.random.randint(
        jax.random.key(2), (8, config.max_seq), 0, config.vocab
    )
    for _ in range(3):
        pf, sf, lf = full(*state_f, tokens, targets)
        pa, sa, la = accum(*state_a, tokens, targets)
        state_f, state_a = (pf, sf), (pa, sa)
        assert float(lf) == pytest.approx(float(la), abs=1e-5)
    for a, b in zip(jax.tree.leaves(pf), jax.tree.leaves(pa)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
        )


def test_grad_accum_rejects_indivisible_batch():
    config = _tiny_config()
    step = make_train_step(
        config, optax.adamw(3e-4), donate=False, grad_accum=3
    )
    params = init_params(config, jax.random.key(0))
    opt_state = optax.adamw(3e-4).init(params)
    tokens = jnp.zeros((4, config.max_seq), jnp.int32)
    with pytest.raises(ValueError, match="not divisible"):
        step(params, opt_state, tokens, tokens)


# -- async + fenced checkpointing -------------------------------------


def test_async_checkpointer_snapshot_isolated_from_donation(tmp_path):
    """save() must capture the state AT SAVE TIME even though the
    loop keeps training (and donating those buffers) while the writer
    drains: the snapshot is a device-side copy, not a reference."""
    config = _tiny_config()
    optimizer = optax.adamw(3e-4)
    step_fn = make_train_step(config, optimizer, donate=True)
    params = init_params(config, jax.random.key(0))
    opt_state = optimizer.init(params)
    tokens = jax.random.randint(
        jax.random.key(1), (4, config.max_seq), 0, config.vocab
    )
    checkpointer = AsyncCheckpointer(str(tmp_path), keep=0)
    saved_at = {}
    for i in range(4):
        params, opt_state, loss = step_fn(
            params, opt_state, tokens, tokens
        )
        if i in (1, 3):
            checkpointer.save(
                i + 1, {"params": params, "opt_state": opt_state}
            )
            saved_at[i + 1] = jax.tree.map(
                lambda a: np.asarray(a).copy(), params
            )
    assert checkpointer.close() == []
    like = {
        "params": init_params(config, jax.random.key(9)),
        "opt_state": optimizer.init(params),
    }
    for step in (2, 4):
        restored, got = restore_checkpoint(
            str(tmp_path), like, step=step
        )
        assert got == step
        for want, have in zip(
            jax.tree.leaves(saved_at[step]),
            jax.tree.leaves(restored["params"]),
        ):
            np.testing.assert_array_equal(want, np.asarray(have))


def test_zombie_writer_cannot_destroy_newer_frontier(tmp_path):
    """The ADVICE round-5 regression: recovery relaunches a trainer
    (new incarnation) while the superseded one still has a save in
    flight.  The zombie's save must refuse — and the live writer's
    newer checkpoint must survive untouched."""
    d = str(tmp_path)
    tree = {"w": jnp.ones((2, 2), jnp.float32)}
    zombie_inc = claim_incarnation(d)
    live_inc = claim_incarnation(d)
    assert live_inc > zombie_inc
    live_path = save_checkpoint(d, 120, tree, keep=3, incarnation=live_inc)
    # the zombie flushes one last save BELOW the live frontier: the
    # old "caller owns the frontier" rule would have pruned step 120
    # as an 'abandoned future'
    with pytest.raises(StaleWriterError):
        save_checkpoint(d, 100, tree, keep=3, incarnation=zombie_inc)
    assert os.path.exists(live_path)
    restored, step = restore_checkpoint(d, tree)
    assert step == 120

    # same fence through the async writer: the failure is recorded,
    # the checkpointer latches fenced, and later saves drop silently
    checkpointer = AsyncCheckpointer(d, keep=3, incarnation=zombie_inc)
    checkpointer.save(101, tree)
    errors = checkpointer.wait()
    assert errors and "superseded" in errors[0]
    assert checkpointer.fenced is True
    checkpointer.save(102, tree)  # dropped, not raised
    assert checkpointer.close() == errors
    assert os.path.exists(live_path)
    _, step = restore_checkpoint(d, tree)
    assert step == 120


def test_fenced_prune_scopes_to_own_incarnation(tmp_path):
    """Retention and rollback pruning act on the writer's own past
    (its incarnation and older — legacy unfenced files included),
    never a newer incarnation's files."""
    d = str(tmp_path)
    tree = {"w": jnp.ones((2, 2), jnp.float32)}
    save_checkpoint(d, 5, tree)  # legacy, incarnation 0
    inc = claim_incarnation(d)
    save_checkpoint(d, 7, tree, keep=2, incarnation=inc)
    save_checkpoint(d, 9, tree, keep=2, incarnation=inc)
    names = sorted(
        n for n in os.listdir(d) if n.startswith("step_")
    )
    # keep=2 retained its own two newest; the legacy step 5 is this
    # writer's prunable past
    assert names == [
        "step_0000000007.inc_%010d.npz" % inc,
        "step_0000000009.inc_%010d.npz" % inc,
    ]
    # rollback WITHIN the incarnation still prunes its own abandoned
    # future (the pre-fencing semantics, now scoped)
    save_checkpoint(d, 3, tree, keep=1, incarnation=inc)
    _, step = restore_checkpoint(d, tree)
    assert step == 3


def test_claim_incarnation_is_race_free(tmp_path):
    """Concurrent claimers (a recovery relaunch racing the zombie's
    restart) can never share a token."""
    d = str(tmp_path)
    claimed = []
    lock = threading.Lock()

    def claim():
        inc = claim_incarnation(d)
        with lock:
            claimed.append(inc)

    threads = [threading.Thread(target=claim) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(set(claimed)) == 8


def test_restore_prefers_newest_incarnation_at_same_step(tmp_path):
    """Two writers stamped the same step (zombie save landed before
    fencing existed / before the newer writer's first save): the
    newest incarnation's file wins the restore."""
    d = str(tmp_path)
    old = {"w": jnp.ones((2, 2), jnp.float32)}
    new = {"w": jnp.full((2, 2), 7.0, jnp.float32)}
    save_checkpoint(d, 10, old, incarnation=1)
    save_checkpoint(d, 10, new, incarnation=2)
    restored, step = restore_checkpoint(d, old)
    assert step == 10
    np.testing.assert_array_equal(
        np.asarray(restored["w"]), np.full((2, 2), 7.0, np.float32)
    )


# -- XLA overlap flags -------------------------------------------------


def test_collective_overlap_flags_tpu_only_and_operator_wins():
    """The latency-hiding flag set lands only for TPU tasks, never
    clobbers an operator's explicit spelling, and honors the
    TRAIN_XLA_OVERLAP opt-out."""
    from dcos_commons_tpu.parallel.overlap import (
        OVERLAP_FLAGS,
        enable_collective_overlap,
    )

    # not a TPU task: untouched
    env = {"JAX_PLATFORMS": "cpu", "TPU_GENERATION": "v5e"}
    assert enable_collective_overlap(env) == []
    assert "XLA_FLAGS" not in env
    env = {}
    assert enable_collective_overlap(env) == []

    # TPU task: the full set lands, idempotently
    env = {"TPU_GENERATION": "v5e"}
    assert enable_collective_overlap(env) == list(OVERLAP_FLAGS)
    assert enable_collective_overlap(env) == []
    for flag in OVERLAP_FLAGS:
        assert flag in env["XLA_FLAGS"]

    # the operator's polarity survives (their spelling stays, ours is
    # not added for that flag)
    theirs = "--xla_tpu_enable_async_collective_fusion=false"
    env = {"TPU_GENERATION": "v5e", "XLA_FLAGS": theirs}
    added = enable_collective_overlap(env)
    assert OVERLAP_FLAGS[0] not in added
    assert env["XLA_FLAGS"].count(
        "--xla_tpu_enable_async_collective_fusion="
    ) >= 1
    assert theirs in env["XLA_FLAGS"]

    # name matching is token-wise: spelling only the LONGER
    # fuse_all_gather flag must not suppress the shorter fusion flag
    # (review r7: substring containment did exactly that)
    sub = "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=false"
    env = {"TPU_GENERATION": "v5e", "XLA_FLAGS": sub}
    added = enable_collective_overlap(env)
    assert OVERLAP_FLAGS[0] in added
    assert OVERLAP_FLAGS[1] not in added
    assert sub in env["XLA_FLAGS"]

    # the opt-out knob
    env = {"TPU_GENERATION": "v5e", "TRAIN_XLA_OVERLAP": "0"}
    assert enable_collective_overlap(env) == []


# -- the worker end to end --------------------------------------------


def _run_worker(sandbox, env_overrides):
    env = {
        **os.environ,
        "REPO_ROOT": REPO,
        "JAX_PLATFORMS": "cpu",
        "SANDBOX": sandbox,
        "CHECKPOINT_DIR": os.path.join(sandbox, "ckpt"),
        "VOCAB": "64", "D_MODEL": "32", "N_LAYERS": "1",
        "N_HEADS": "2", "N_KV_HEADS": "2", "D_FF": "96",
        "SEQ_LEN": "16",
        "KEEPALIVE_S": "0",
        "JAX_COMPILATION_CACHE_DIR": os.path.join(sandbox, "xla-cache"),
        **env_overrides,
    }
    return subprocess.run(
        [sys.executable,
         os.path.join(REPO, "frameworks/jax/train_worker.py")],
        env=env, capture_output=True, text=True, timeout=240,
    )


def test_worker_overlap_and_knobs_end_to_end(tmp_path):
    """The real worker with the fast-path defaults (window 2, async
    fenced checkpointing), then a RESUME with every knob opted out
    (TRAIN_INFLIGHT_STEPS=0, TRAIN_ASYNC_CKPT=0, mirroring
    STEPLOG_BARRIER_PROBE): both bill every step exactly once in the
    steplog, the resume continues at the checkpoint stamp, and the
    second incarnation's file takes over the directory."""
    sandbox = str(tmp_path)
    out = _run_worker(sandbox, {"TRAIN_STEPS": "5"})
    assert out.returncode == 0, out.stderr[-2000:]
    records = read_steplog(os.path.join(sandbox, "steplog.jsonl"))
    assert [r["step"] for r in records] == list(range(5))
    for r in records:
        assert r["wall_s"] >= 0 and r["blocked_s"] == 0.0
        assert r["tokens"] > 0
    ckpt = os.path.join(sandbox, "ckpt")
    fenced = [n for n in os.listdir(ckpt) if ".inc_" in n]
    assert fenced, os.listdir(ckpt)

    # resume with the synchronous opt-outs: same loop semantics, new
    # writer incarnation
    out = _run_worker(sandbox, {
        "TRAIN_STEPS": "7",
        "TRAIN_INFLIGHT_STEPS": "0",
        "TRAIN_ASYNC_CKPT": "0",
        "TRAIN_DONATE": "0",
    })
    assert out.returncode == 0, out.stderr[-2000:]
    records = read_steplog(os.path.join(sandbox, "steplog.jsonl"))
    # appended: steps 5..6 exactly once after the first run's 0..4
    assert [r["step"] for r in records] == list(range(5)) + [5, 6]
    names = sorted(n for n in os.listdir(ckpt) if n.startswith("step_"))
    incs = {n.split(".inc_")[1].split(".npz")[0] for n in names
            if ".inc_" in n}
    assert len(incs) == 2, names  # the resume claimed a new token
    assert any("step_0000000007" in n for n in names)
