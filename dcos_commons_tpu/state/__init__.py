"""L5 state: task/config/framework state over a Persister.

Reference: sdk/scheduler/.../state/ — StateStore.java:58,213-569,
ConfigStore.java, FrameworkStore.java, GoalStateOverride.java,
PersistentLaunchRecorder.java, SchemaVersionStore.java,
StateStoreUtils.java.
"""

from dcos_commons_tpu.state.state_store import (
    GoalStateOverride,
    OverrideProgress,
    StateStore,
    StateStoreException,
)
from dcos_commons_tpu.state.config_store import ConfigStore
from dcos_commons_tpu.state.framework_store import FrameworkStore
from dcos_commons_tpu.state.launch_recorder import PersistentLaunchRecorder
from dcos_commons_tpu.state.schema import SchemaVersionStore

__all__ = [
    "ConfigStore",
    "FrameworkStore",
    "GoalStateOverride",
    "OverrideProgress",
    "PersistentLaunchRecorder",
    "SchemaVersionStore",
    "StateStore",
    "StateStoreException",
]
