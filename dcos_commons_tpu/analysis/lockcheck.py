"""Runtime lock-order checker: the dynamic half of sdklint.

The static ``lock-discipline`` rule sees one class at a time; what it
cannot see is the ORDER locks nest across objects at runtime — the
scheduler cycle holding ``DefaultScheduler._lock`` while stepping
into ``StateStore._lock``, a plan element's RLock taken inside both.
A cycle in that nesting graph is a latent deadlock: thread A holds
L1 wanting L2 while thread B holds L2 wanting L1.

Opt-in instrumentation (reference: findbugs' JSR-166 lock analysis,
here done dynamically like TSan's lock-order graph):

- ``install()`` patches ``threading.Lock``/``RLock`` factories with a
  recording wrapper.  Every lock is named by its creation site
  (``file:line``), so the 20+ ``self._lock = threading.RLock()``
  sites in this codebase each become one graph node.
- Each thread keeps its held-lock stack; acquiring B while holding A
  records the edge A->B (with the acquiring stack, for the report).
- ``report()`` returns the edge list and every cycle found in the
  graph; the e2e suites assert the cycle list is empty.
- ``watch(obj)`` additionally instruments one object's attribute
  writes, reporting attributes written by multiple threads where at
  least one write held no instrumented lock (cross-thread unguarded
  writes).

Enabled in tests via ``SDKLINT_LOCKCHECK=1`` (conftest installs) or
explicitly by a fixture.  The wrappers stay functional after
``uninstall()`` — recording is gated, delegation is not — so locks
created during an instrumented window keep working forever.
"""

from __future__ import annotations

import os
import threading
import traceback
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

ENV_VAR = "SDKLINT_LOCKCHECK"

_state_lock = threading.Lock()  # guards the module-level graph below
_enabled = False
_originals: Optional[Tuple] = None
_tls = threading.local()

# lock-order graph: (outer_site, inner_site) -> one sample acquiring
# stack (the first observed, enough to locate the nesting)
_edges: Dict[Tuple[str, str], str] = {}
# site -> set of thread names that ever acquired it
_threads_per_site: Dict[str, Set[str]] = {}
# watch(): (class_name, attr) -> {thread: ALL writes held a lock}
_watched_writes: Dict[Tuple[str, str], Dict[str, bool]] = {}


def _held_stack() -> List["InstrumentedLock"]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _creation_site() -> str:
    """file:line of the frame that called threading.Lock()/RLock(),
    relative to the repo so sites read like lint findings."""
    for frame in reversed(traceback.extract_stack(limit=8)[:-2]):
        if os.sep + "analysis" + os.sep + "lockcheck" in frame.filename:
            continue
        if frame.filename.startswith("<"):
            continue
        name = frame.filename
        for marker in ("dcos_commons_tpu", "frameworks", "tests"):
            idx = name.find(os.sep + marker + os.sep)
            if idx >= 0:
                name = name[idx + 1:]
                break
        return f"{name.replace(os.sep, '/')}:{frame.lineno}"
    return "<unknown>"


class InstrumentedLock:
    """Wraps one real Lock/RLock; records nesting edges on acquire."""

    def __init__(self, inner, site: str, reentrant: bool):
        self._inner = inner
        self.site = site
        self._reentrant = reentrant

    # -- recording ----------------------------------------------------

    def _record_acquire(self) -> None:
        if not _enabled:
            return
        try:
            stack = _held_stack()
            if self._reentrant and any(h is self for h in stack):
                stack.append(self)  # reentry: no new edges
                return
            held_sites = {h.site for h in stack if h is not self}
            new_edges = [
                (outer, self.site) for outer in held_sites
                if outer != self.site and (outer, self.site) not in _edges
            ]
            if new_edges:
                # format the (expensive) sample stack only for a
                # first-seen edge; steady-state nested acquires just
                # re-confirm known edges
                sample = "".join(traceback.format_stack(limit=12)[:-2])
                with _state_lock:
                    for edge in new_edges:
                        _edges.setdefault(edge, sample)
            with _state_lock:
                _threads_per_site.setdefault(self.site, set()).add(
                    threading.current_thread().name
                )
            stack.append(self)
        except Exception:  # sdklint: disable=swallowed-exception — the checker must never break the code under test
            pass

    def _record_release(self) -> None:
        if not _enabled:
            return
        try:
            stack = _held_stack()
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] is self:
                    del stack[i]
                    break
        except Exception:  # sdklint: disable=swallowed-exception — see _record_acquire
            pass

    # -- the lock protocol -------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._record_acquire()
        return got

    def release(self) -> None:
        self._record_release()
        self._inner.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        if locked is not None:
            return locked()
        # RLock pre-3.12 has no locked(); _is_owned is close enough
        return bool(self._inner._is_owned())

    def __enter__(self) -> bool:
        self.acquire()
        return True

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<InstrumentedLock {self.site} wrapping {self._inner!r}>"


def install() -> None:
    """Patch threading's lock factories; idempotent."""
    global _enabled, _originals
    with _state_lock:
        if _originals is None:
            real_lock, real_rlock = threading.Lock, threading.RLock
            real_condition = threading.Condition

            def make_lock():
                return InstrumentedLock(real_lock(), _creation_site(), False)

            def make_rlock():
                return InstrumentedLock(real_rlock(), _creation_site(), True)

            def make_condition(lock=None):
                # Condition needs the real lock's _release_save /
                # _is_owned internals; hand it an unwrapped lock
                # (cv-guarded state is the static rule's concern)
                if isinstance(lock, InstrumentedLock):
                    lock = lock._inner
                return real_condition(real_rlock() if lock is None else lock)

            threading.Lock = make_lock
            threading.RLock = make_rlock
            threading.Condition = make_condition
            _originals = (real_lock, real_rlock, real_condition)
        _enabled = True


def uninstall() -> None:
    """Restore the factories and stop recording.  Wrappers already
    handed out keep delegating to their inner locks."""
    global _enabled, _originals
    with _state_lock:
        if _originals is not None:
            threading.Lock, threading.RLock, threading.Condition = _originals
            _originals = None
        _enabled = False


def reset() -> None:
    with _state_lock:
        _edges.clear()
        _threads_per_site.clear()
        _watched_writes.clear()


def is_enabled() -> bool:
    return _enabled


def env_requested() -> bool:
    return os.environ.get(ENV_VAR, "") not in ("", "0", "false")


# -- watch(): cross-thread unguarded writes ---------------------------


def watch(obj) -> None:
    """Record attribute writes on ``obj``: which threads wrote, and
    whether any instrumented lock was held.  Implemented by swapping
    in a one-off subclass overriding ``__setattr__``."""
    cls = type(obj)
    if getattr(cls, "_sdklint_watched", False):
        return
    base_name = cls.__name__

    def recording_setattr(self, name, value):
        if _enabled:
            try:
                held = bool(_held_stack())
                thread = threading.current_thread().name
                with _state_lock:
                    by_thread = _watched_writes.setdefault(
                        (base_name, name), {}
                    )
                    # AND across the thread's writes: one unguarded
                    # write taints the thread forever — a guarded
                    # write later must never mask it
                    by_thread[thread] = by_thread.get(thread, True) and held
            except Exception:  # sdklint: disable=swallowed-exception — never break the watched object
                pass
        super(watched, self).__setattr__(name, value)

    watched = type(
        f"{base_name}_sdklint",
        (cls,),
        {"__setattr__": recording_setattr, "_sdklint_watched": True},
    )
    obj.__class__ = watched


# -- report -----------------------------------------------------------


@dataclass
class LockReport:
    edges: Dict[Tuple[str, str], str] = field(default_factory=dict)
    cycles: List[List[str]] = field(default_factory=list)
    unguarded_writes: List[str] = field(default_factory=list)

    def describe(self) -> str:
        lines = [
            f"lock-order edges: {len(self.edges)}, "
            f"cycles: {len(self.cycles)}, "
            f"cross-thread unguarded writes: {len(self.unguarded_writes)}"
        ]
        for cycle in self.cycles:
            lines.append("  DEADLOCK RISK: " + " -> ".join(cycle + cycle[:1]))
            first = (cycle[0], cycle[1 % len(cycle)])
            if first in self.edges:
                lines.append("  sample acquiring stack:\n" + self.edges[first])
        lines += [f"  UNGUARDED: {w}" for w in self.unguarded_writes]
        return "\n".join(lines)


def _find_cycles(adjacency: Dict[str, Set[str]]) -> List[List[str]]:
    """Simple elementary-cycle scan: DFS from each node, reporting
    each cycle once (canonicalized by its smallest rotation)."""
    seen_cycles: Set[Tuple[str, ...]] = set()
    cycles: List[List[str]] = []

    def canonical(path: List[str]) -> Tuple[str, ...]:
        pivot = min(range(len(path)), key=lambda i: path[i])
        return tuple(path[pivot:] + path[:pivot])

    def dfs(node: str, path: List[str], on_path: Set[str]) -> None:
        for nxt in sorted(adjacency.get(node, ())):
            if nxt in on_path:
                cycle = path[path.index(nxt):]
                key = canonical(cycle)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(list(key))
                continue
            if len(path) < 32:  # bound pathological graphs
                dfs(nxt, path + [nxt], on_path | {nxt})

    for start in sorted(adjacency):
        dfs(start, [start], {start})
    return cycles


def report() -> LockReport:
    with _state_lock:
        edges = dict(_edges)
        watched = {k: dict(v) for k, v in _watched_writes.items()}
    adjacency: Dict[str, Set[str]] = {}
    for outer, inner in edges:
        adjacency.setdefault(outer, set()).add(inner)
    unguarded = [
        f"{cls}.{attr} written by threads {sorted(by_thread)} "
        "with at least one write holding no lock"
        for (cls, attr), by_thread in sorted(watched.items())
        if len(by_thread) > 1 and not all(by_thread.values())
    ]
    return LockReport(
        edges=edges,
        cycles=_find_cycles(adjacency),
        unguarded_writes=unguarded,
    )
