"""Live KV page migration: sessions move between pods mid-generation.

PR 11 made the KV page the unit of serving MEMORY; this module makes
it the unit of serving MOBILITY.  A session's state is small and
closed — its prompt, its sampling parameters, the tokens produced so
far, and the arena pages its page table points at — so a pod can
snapshot it, stream it over the inter-pod (DCN) lane, and a peer can
splice it into its own ``PageAllocator`` under the exact admission
rule a fresh request would face.  Three consumers share the one
protocol (ISSUE 16):

* **drain** — a scale-in/maintenance drain moves in-flight sessions
  to surviving pods instead of waiting out every generation
  (``drain_sessions``);
* **rebalance** — a prefix-hotspot pod sheds sessions WITH their
  cached pages, so the router's affinity claims re-point instead of
  being dropped (the chain keys ride the drain report);
* **disaggregation** — dedicated prefill pods run chunked prefill
  and hand finished pages to decode pools (``PrefillHandoff``), so
  long prompts never sit inside a decode pod's tick.

The cutover protocol (the plancheck ``migration`` config model-checks
it under abort and pod death at every state):

    source serving
      -> FREEZE    source fences the row at a tick boundary: decode
                   stops, the row's pages stop changing (writes of
                   the in-flight tick are idempotent — K/V at a
                   position is a pure function of token and position)
      -> SNAPSHOT  page payloads read on the source's loop thread
                   (the engine's single-device-caller discipline)
      -> STREAM    the snapshot crosses the transport lane
      -> SPLICE    destination admits the session transactionally
                   (its own prefix cache serves any matched prefix —
                   matched pages are never streamed twice), copies
                   the remaining payloads into freshly drawn pages,
                   and parks the row
      -> CUTOVER   destination activates the parked row; from this
                   state the move is FINAL — abort must refuse
      -> RELEASE   source retires the frozen row, frees its pages,
                   and answers its blocked client with
                   ``SessionMigratedError`` naming the destination

Exactly-once by construction: the source is fenced before anything
streams and only ever resumes via an abort that the destination has
not activated; the destination only decodes after CUTOVER.  Greedy
output is bit-identical across the move because decode resumes from
the same (token, position) against byte-identical pages; SAMPLED
output is too, because the per-row PRNG folds the row's seed with
its POSITION (serve/pool.py) — never the slot or the pod it runs on.

Everything here is transport-agnostic: engines are ducks exposing
the PagedEngine migration verbs, and the wire format
(``SessionSnapshot.to_wire``) is JSON-safe so the HTTP workers can
carry it pod-to-pod (frameworks/jax/serve_worker.py POST /migrate).
"""

from __future__ import annotations

import base64
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


class MigrationError(RuntimeError):
    """The move could not proceed (no budget, no free row, geometry
    mismatch, transport failure).  The source session is resumed —
    a failed migration is an abort, never a loss."""


class ReleasePendingError(MigrationError):
    """The move CUT OVER — the destination serves the session — but
    releasing the source failed (a crash at the worst boundary).  The
    source row must stay frozen: resuming it would double-serve, and
    re-streaming would double-splice.  The only legal continuation is
    retrying ``source.release_migrated`` with the fields here."""

    def __init__(self, rid: int, moved_to: str, dest_rid: int):
        super().__init__(
            f"session {rid} cut over to {moved_to} (rid {dest_rid}) "
            "but the source release is pending"
        )
        self.rid = rid
        self.moved_to = moved_to
        self.dest_rid = dest_rid


class SessionMigratedError(RuntimeError):
    """Raised to the SOURCE pod's blocked client after cutover: the
    session now lives on ``moved_to`` as ``dest_rid``.  The router
    follows it with a collect request ({"collect": dest_rid}) and the
    client sees one uninterrupted reply — zero tokens lost, none
    doubled."""

    def __init__(self, rid: int, moved_to: str, dest_rid: int):
        super().__init__(
            f"session {rid} migrated to {moved_to} (rid {dest_rid})"
        )
        self.rid = rid
        self.moved_to = moved_to
        self.dest_rid = dest_rid


# -- the snapshot -----------------------------------------------------


def _payload_bytes(payload) -> int:
    if isinstance(payload, np.ndarray):
        return int(payload.nbytes)
    if isinstance(payload, dict):
        return sum(
            _payload_bytes(k) + _payload_bytes(v)
            for k, v in payload.items()
        )
    if isinstance(payload, (list, tuple)):
        return sum(_payload_bytes(v) for v in payload)
    return 8  # scalar


def _enc(x):
    """JSON-safe encoding for page payloads (numpy arrays, nested
    dicts with non-string keys — both real arena slices and the test
    harnesses' cell dicts)."""
    if isinstance(x, np.ndarray):
        return {
            "__nd__": [
                x.dtype.str, list(x.shape),
                base64.b64encode(np.ascontiguousarray(x).tobytes())
                .decode("ascii"),
            ]
        }
    if isinstance(x, dict):
        return {"__kv__": [[_enc(k), _enc(v)] for k, v in x.items()]}
    if isinstance(x, (list, tuple)):
        return {"__seq__": [_enc(v) for v in x]}
    return x


def _dec(x):
    if isinstance(x, dict):
        if "__nd__" in x:
            dtype, shape, raw = x["__nd__"]
            return np.frombuffer(
                base64.b64decode(raw), dtype=np.dtype(dtype)
            ).reshape(shape).copy()
        if "__kv__" in x:
            return {_dec(k): _dec(v) for k, v in x["__kv__"]}
        if "__seq__" in x:
            return [_dec(v) for v in x["__seq__"]]
    return x


@dataclass
class SessionSnapshot:
    """One frozen session, closed over everything the destination
    needs: the request (prompt + sampling parameters), the progress
    (tokens out, prefill position), and the page payloads keyed by
    VIRTUAL page index — physical page ids are pod-private and never
    cross the wire."""

    rid: int
    tokens: List[int]
    max_new: int
    temperature: float
    eos: Optional[int]
    seed: int
    out: List[int]
    fill_pos: int          # prompt positions prefilled so far
    kv_end: int            # KV positions materialized ([0, kv_end))
    page_tokens: int
    pages: List[Tuple[int, object]] = field(default_factory=list)
    source: str = ""

    def nbytes(self) -> int:
        """Approximate wire size (the transport model's basis)."""
        return (
            8 * (len(self.tokens) + len(self.out) + 8)
            + sum(_payload_bytes(p) for _v, p in self.pages)
        )

    def to_wire(self) -> dict:
        return {
            "rid": self.rid,
            "tokens": list(self.tokens),
            "max_new": self.max_new,
            "temperature": self.temperature,
            "eos": self.eos,
            "seed": self.seed,
            "out": list(self.out),
            "fill_pos": self.fill_pos,
            "kv_end": self.kv_end,
            "page_tokens": self.page_tokens,
            "pages": [[v, _enc(p)] for v, p in self.pages],
            "source": self.source,
        }

    @classmethod
    def from_wire(cls, data: dict) -> "SessionSnapshot":
        return cls(
            rid=int(data["rid"]),
            tokens=[int(t) for t in data["tokens"]],
            max_new=int(data["max_new"]),
            temperature=float(data["temperature"]),
            eos=None if data.get("eos") is None else int(data["eos"]),
            seed=int(data["seed"]),
            out=[int(t) for t in data["out"]],
            fill_pos=int(data["fill_pos"]),
            kv_end=int(data["kv_end"]),
            page_tokens=int(data["page_tokens"]),
            pages=[(int(v), _dec(p)) for v, p in data["pages"]],
            source=str(data.get("source", "")),
        )


# -- transports -------------------------------------------------------


class InProcessTransport:
    """The identity lane (tests, single-process benches): the
    snapshot IS the wire message.  Counts bytes and sessions so every
    consumer reports transfer volume the same way."""

    def __init__(self) -> None:
        self.sessions = 0
        self.bytes_sent = 0

    def send(self, snap: SessionSnapshot) -> SessionSnapshot:
        self.sessions += 1
        self.bytes_sent += snap.nbytes()
        return snap


class SimulatedDcnTransport(InProcessTransport):
    """The in-process lane with a DCN cost model on top: per-session
    latency plus bytes over a bandwidth budget (SURVEY §5.8's
    inter-slice numbers are the defaults' shape — the bench uses this
    so drain-time fences measure protocol cost, not host memcpy)."""

    def __init__(self, gbytes_per_s: float = 12.5,
                 latency_s: float = 0.002) -> None:
        super().__init__()
        self.gbytes_per_s = float(gbytes_per_s)
        self.latency_s = float(latency_s)

    def send(self, snap: SessionSnapshot) -> SessionSnapshot:
        nbytes = snap.nbytes()
        # the modeled wire time IS this transport's contract; it runs
        # on the migration caller's thread, never an engine loop
        time.sleep(  # sdklint: disable=no-blocking-sleep — modeled DCN latency, bench-only lane
            self.latency_s + nbytes / (self.gbytes_per_s * 1e9)
        )
        return super().send(snap)


class HttpEngineClient:
    """A remote PagedEngine's migration verbs over the serve worker's
    ``POST /migrate`` surface (frameworks/jax/serve_worker.py) — the
    destination duck ``migrate_session``/``drain_sessions``/
    ``PrefillHandoff`` drive when the peer lives in another process.
    Every transport or HTTP failure surfaces as ``MigrationError``,
    which the callers already treat as try-the-next-destination; a
    timed-out ``activate`` is the one ambiguous boundary (the peer may
    have activated) — the operations guide's stuck-transfer triage
    covers it."""

    def __init__(self, name: str, address: str,
                 timeout_s: float = 60.0):
        self.name = name
        self.address = address
        self.timeout_s = float(timeout_s)

    def _post(self, body: dict) -> dict:
        import json
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            f"http://{self.address}/migrate",
            data=json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout_s
            ) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            raise MigrationError(
                f"{self.name} refused {body.get('verb')}: "
                f"{e.read().decode('utf-8', 'replace')[:200]}"
            ) from e
        except (urllib.error.URLError, OSError, ValueError) as e:
            raise MigrationError(
                f"{self.name} ({self.address}) unreachable during "
                f"{body.get('verb')}: {e}"
            ) from e

    def splice(self, snap: SessionSnapshot) -> int:
        return int(
            self._post({"verb": "splice",
                        "snapshot": snap.to_wire()})["dest_rid"]
        )

    def activate(self, rid: int) -> None:
        self._post({"verb": "activate", "rid": int(rid)})

    def abort_splice(self, rid: int) -> None:
        self._post({"verb": "abort", "rid": int(rid)})

    def stats(self) -> dict:
        import json
        import urllib.request

        try:
            with urllib.request.urlopen(
                f"http://{self.address}/stats", timeout=self.timeout_s
            ) as resp:
                body = json.loads(resp.read().decode("utf-8"))
            return body if isinstance(body, dict) else {}
        except (OSError, ValueError):
            return {}  # ranked last by the free-pages sort


# -- the protocol -----------------------------------------------------

# boundary names, in protocol order: chaos hooks fire at each (the
# chaos tests kill at every one and assert exactly-once cutover)
STAGES = ("snapshot", "stream", "splice", "cutover", "release")


@dataclass
class MigrationRecord:
    """One completed (or failed) move — the debug-surface row."""

    rid: int
    dest_rid: int
    dest: str
    pages: int
    bytes: int
    duration_s: float
    stage: str          # last stage reached ("release" = complete)
    ok: bool


def migrate_session(
    source,
    dest,
    rid: int,
    *,
    dest_name: str = "",
    transport: Optional[InProcessTransport] = None,
    chaos: Optional[Callable[[str], None]] = None,
    already_frozen: bool = False,
    log: Optional[Callable[[str], None]] = None,
) -> MigrationRecord:
    """Move one session from ``source`` to ``dest`` under the fenced
    cutover protocol.  Any failure BEFORE cutover aborts cleanly: the
    destination's splice (if any) is retired and the source resumes
    decoding exactly where it froze.  A failure AFTER cutover never
    resumes the source (that would double-serve) — the destination
    owns the session and the source row stays frozen for a retried
    release (``release_migrated`` is idempotent per rid).

    ``chaos(stage)`` is the fault-injection hook: it runs at each
    boundary and may raise to simulate a death there.
    """
    transport = transport or InProcessTransport()
    chaos = chaos or (lambda stage: None)
    t0 = time.monotonic()
    stage = "snapshot"
    if not already_frozen:
        source.freeze(rid)
    dest_rid = -1
    try:
        chaos("snapshot")
        snap = source.export_frozen(rid)
        stage = "stream"
        chaos("stream")
        snap = transport.send(snap)
        stage = "splice"
        chaos("splice")
        dest_rid = dest.splice(snap)
    except BaseException:
        # pre-cutover failure: nothing activated, the source resumes
        if dest_rid >= 0:
            dest.abort_splice(dest_rid)
        source.unfreeze(rid)
        raise
    try:
        stage = "cutover"
        chaos("cutover")
        dest.activate(dest_rid)
    except BaseException:
        dest.abort_splice(dest_rid)
        source.unfreeze(rid)
        raise
    # CUTOVER DONE: from here the destination serves.  A failure in
    # release leaves the source frozen (never resumed — resuming now
    # is the double-serve plancheck forbids); release is retryable.
    stage = "release"
    try:
        chaos("release")
        source.release_migrated(
            rid, moved_to=dest_name, dest_rid=dest_rid
        )
    except BaseException as e:
        raise ReleasePendingError(rid, dest_name, dest_rid) from e
    record = MigrationRecord(
        rid=rid, dest_rid=dest_rid, dest=dest_name,
        pages=len(snap.pages), bytes=snap.nbytes(),
        duration_s=time.monotonic() - t0, stage=stage, ok=True,
    )
    if log is not None:
        log(
            f"migrated session {rid} -> {dest_name or 'peer'}#"
            f"{dest_rid}: {record.pages} pages, {record.bytes}B in "
            f"{record.duration_s * 1e3:.1f}ms"
        )
    return record


def drain_sessions(
    source,
    dests: Dict[str, object],
    *,
    transport: Optional[InProcessTransport] = None,
    log: Optional[Callable[[str], None]] = None,
) -> List[dict]:
    """Drain-with-migration: move every live session off ``source``
    to the peer with the most free pages (re-picked per session — one
    small peer must not absorb a whole drain).  Returns one report
    row per session: ``{"rid", "dest", "dest_rid", "tokens", "ok"}``
    — ``tokens`` carries the prompt so the router side can re-point
    the session's prefix-chain claims (router/core.py
    ``repoint_prompt``) instead of dropping them.

    A session that cannot move (budget-full peers, transport failure)
    is resumed and reported ``ok=False`` — the legacy wait-out drain
    covers it; migration never strands a client."""
    report: List[dict] = []
    for sess in source.sessions():
        rid = sess["rid"]
        ranked = sorted(
            dests.items(),
            key=lambda kv: -float(
                kv[1].stats().get("kv_pages_free", 0)
            ),
        )
        moved = False
        err: Optional[BaseException] = None
        for name, dest in ranked:
            if dest is source:
                continue
            try:
                record = migrate_session(
                    source, dest, rid, dest_name=name,
                    transport=transport, log=log,
                )
            except ReleasePendingError as e:
                # the session DID move — retry the release once and
                # report the move either way; trying another
                # destination here would double-splice
                try:
                    source.release_migrated(
                        rid, moved_to=e.moved_to, dest_rid=e.dest_rid
                    )
                except MigrationError:
                    pass
                report.append({
                    "rid": rid, "dest": e.moved_to,
                    "dest_rid": e.dest_rid,
                    "tokens": sess["tokens"], "ok": True,
                })
                moved = True
                break
            except (MigrationError, KeyError) as e:
                err = e
                continue
            report.append({
                "rid": rid, "dest": name,
                "dest_rid": record.dest_rid,
                "tokens": sess["tokens"], "ok": True,
            })
            moved = True
            break
        if not moved:
            report.append({
                "rid": rid, "dest": None, "dest_rid": -1,
                "tokens": sess["tokens"], "ok": False,
                "error": str(err) if err else "no destination",
            })
    return report


class PrefillHandoff:
    """The disaggregation hook: installed as ``PagedEngine(role=
    "prefill", handoff=...)``, called on the engine loop thread the
    moment a prompt finishes chunked prefill (first token sampled,
    row frozen).  Picks the decode pod with the most free pages and
    runs the migration protocol; returning None (no pool, move
    failed) makes the engine decode locally — a prefill pod degrades
    to unified rather than failing the request."""

    def __init__(
        self,
        decode_pods: Callable[[], Dict[str, object]],
        transport: Optional[InProcessTransport] = None,
        log: Optional[Callable[[str], None]] = None,
    ):
        self._decode_pods = decode_pods
        self._transport = transport
        self._log = log
        self.handoffs = 0
        self.fallbacks = 0

    def __call__(self, engine, rid: int) -> Optional[MigrationRecord]:
        pods = dict(self._decode_pods() or {})
        ranked = sorted(
            pods.items(),
            key=lambda kv: -float(
                kv[1].stats().get("kv_pages_free", 0)
            ),
        )
        for name, dest in ranked:
            if dest is engine:
                continue
            try:
                # freeze=fresh on every attempt: a previous failed
                # attempt's abort path resumed the row locally, and
                # the engine loop (our caller) cannot decode it in
                # between — re-fencing is free
                record = migrate_session(
                    engine, dest, rid, dest_name=name,
                    transport=self._transport, log=self._log,
                )
            except ReleasePendingError:
                raise  # the engine holds the frozen row for a retry
            except MigrationError:
                continue
            self.handoffs += 1
            return record
        self.fallbacks += 1
        return None
