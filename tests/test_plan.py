"""Plan engine tests (mirrors reference plan/ + strategy/ test suites)."""


import pytest

from dcos_commons_tpu.common import Label, TaskInfo, TaskState, TaskStatus, new_task_id
from dcos_commons_tpu.plan import (
    CanaryStrategy,
    DefaultPlanCoordinator,
    DefaultPlanManager,
    DependencyStrategy,
    DeployPlanFactory,
    DeploymentStep,
    ExponentialBackoff,
    ParallelStrategy,
    Plan,
    PlanGenerator,
    PodInstanceRequirement,
    SerialStrategy,
    Status,
    strategy_for_name,
)
from dcos_commons_tpu.specification import from_yaml
from dcos_commons_tpu.specification.specs import task_full_name
from dcos_commons_tpu.state import StateStore
from dcos_commons_tpu.storage import MemPersister

YAML = """
name: svc
pods:
  hello:
    count: 3
    tasks:
      server:
        goal: RUNNING
        cmd: "sleep 1000"
  once:
    count: 1
    tasks:
      init:
        goal: ONCE
        cmd: "echo done"
"""

GANG_YAML = """
name: jax
pods:
  trainer:
    count: 4
    gang: true
    tpu:
      topology: 4x4
      chips-per-host: 4
    tasks:
      worker:
        goal: RUNNING
        cmd: "python train.py"
"""


def make_step(name="hello-0", pod_yaml=YAML, pod="hello", instances=None, backoff=None):
    spec = from_yaml(pod_yaml)
    req = PodInstanceRequirement(
        pod=spec.pod(pod), instances=instances or [0]
    )
    return DeploymentStep(name, req, backoff=backoff)


def drive_to_running(step, ready=True):
    req = step.start()
    assert req is not None
    ids = {n: new_task_id(n) for n in req.task_names()}
    step.record_launch(ids)
    for name, tid in ids.items():
        step.update(TaskStatus(task_id=tid, state=TaskState.RUNNING, ready=ready))
    return ids


# -- step lifecycle ---------------------------------------------------


def test_step_happy_path():
    step = make_step()
    assert step.get_status() == Status.PENDING
    req = step.start()
    assert req.asset_names == {"hello-0"}
    assert req.task_names() == ["hello-0-server"]
    ids = {n: new_task_id(n) for n in req.task_names()}
    step.record_launch(ids)
    assert step.get_status() == Status.STARTING
    step.update(
        TaskStatus(task_id=ids["hello-0-server"], state=TaskState.RUNNING, ready=True)
    )
    assert step.get_status() == Status.COMPLETE
    # complete step offers no more work
    assert step.start() is None


def test_step_readiness_gate():
    yaml_rc = YAML.replace(
        'cmd: "sleep 1000"',
        'cmd: "sleep 1000"\n        readiness-check:\n          cmd: "test -f ready"',
    )
    step = make_step(pod_yaml=yaml_rc)
    req = step.start()
    ids = {n: new_task_id(n) for n in req.task_names()}
    step.record_launch(ids)
    tid = ids["hello-0-server"]
    step.update(TaskStatus(task_id=tid, state=TaskState.RUNNING, ready=False))
    assert step.get_status() == Status.STARTED  # running but not ready
    step.update(TaskStatus(task_id=tid, state=TaskState.RUNNING, ready=True))
    assert step.get_status() == Status.COMPLETE


def test_step_once_goal():
    step = make_step(pod="once", name="once-0")
    req = step.start()
    ids = {n: new_task_id(n) for n in req.task_names()}
    step.record_launch(ids)
    tid = ids["once-0-init"]
    step.update(TaskStatus(task_id=tid, state=TaskState.RUNNING))
    assert step.get_status() == Status.STARTED  # running isn't done for ONCE
    step.update(TaskStatus(task_id=tid, state=TaskState.FINISHED))
    assert step.get_status() == Status.COMPLETE


def test_step_failure_resets():
    step = make_step()
    ids = drive_to_running(step)
    assert step.get_status() == Status.COMPLETE
    step.restart()
    req = step.start()
    ids = {n: new_task_id(n) for n in req.task_names()}
    step.record_launch(ids)
    step.update(
        TaskStatus(task_id=ids["hello-0-server"], state=TaskState.FAILED)
    )
    assert step.get_status() == Status.PENDING  # no backoff -> straight back


def test_step_failure_backoff_delays():
    backoff = ExponentialBackoff(initial_s=30, factor=2, max_s=300)
    step = make_step(backoff=backoff)
    req = step.start()
    ids = {n: new_task_id(n) for n in req.task_names()}
    step.record_launch(ids)
    step.update(TaskStatus(task_id=ids["hello-0-server"], state=TaskState.FAILED))
    assert step.get_status() == Status.DELAYED
    assert step.start() is None  # delayed step yields no work


def test_step_stale_status_ignored():
    step = make_step()
    ids = drive_to_running(step)
    step.update(
        TaskStatus(task_id=new_task_id("hello-0-server"), state=TaskState.FAILED)
    )
    assert step.get_status() == Status.COMPLETE  # stale id dropped


def test_gang_step_covers_all_instances():
    step = make_step(
        name="trainer-gang", pod_yaml=GANG_YAML, pod="trainer",
        instances=[0, 1, 2, 3],
    )
    req = step.start()
    assert req.asset_names == {"trainer-0", "trainer-1", "trainer-2", "trainer-3"}
    ids = {n: new_task_id(n) for n in req.task_names()}
    assert len(ids) == 4
    step.record_launch(ids)
    items = list(ids.items())
    for name, tid in items[:3]:
        step.update(TaskStatus(task_id=tid, state=TaskState.RUNNING, ready=True))
    assert step.get_status() == Status.STARTED  # 3 of 4 running
    step.update(TaskStatus(task_id=items[3][1], state=TaskState.RUNNING, ready=True))
    assert step.get_status() == Status.COMPLETE
    # post-completion failures do NOT regress the deploy step — the
    # recovery plan owns keep-alive (gang recovery covers all workers)
    step.update(TaskStatus(task_id=items[0][1], state=TaskState.FAILED))
    assert step.get_status() == Status.COMPLETE


def test_gang_step_mid_deploy_failure_resets_whole_gang():
    step = make_step(
        name="trainer-gang", pod_yaml=GANG_YAML, pod="trainer",
        instances=[0, 1, 2, 3],
    )
    req = step.start()
    ids = {n: new_task_id(n) for n in req.task_names()}
    step.record_launch(ids)
    items = list(ids.items())
    for name, tid in items[:3]:
        step.update(TaskStatus(task_id=tid, state=TaskState.RUNNING, ready=True))
    # 4th worker fails before the gang completed: whole step resets
    step.update(TaskStatus(task_id=items[3][1], state=TaskState.FAILED))
    assert step.get_status() == Status.PENDING


def test_step_failure_drops_aborted_launch_state():
    """A re-delivered status from an aborted launch must not lift the
    step out of PENDING (review regression: deploy wedge)."""
    step = make_step(
        name="trainer-gang", pod_yaml=GANG_YAML, pod="trainer",
        instances=[0, 1],
    )
    # note: GANG_YAML trainer count is 4; 2 instances is fine for a step
    req = step.start()
    ids = {n: new_task_id(n) for n in req.task_names()}
    step.record_launch(ids)
    items = list(ids.items())
    step.update(TaskStatus(task_id=items[0][1], state=TaskState.RUNNING, ready=True))
    step.update(TaskStatus(task_id=items[1][1], state=TaskState.FAILED))
    assert step.get_status() == Status.PENDING
    # duplicate delivery of worker 0's RUNNING status: stays PENDING
    step.update(TaskStatus(task_id=items[0][1], state=TaskState.RUNNING, ready=True))
    assert step.get_status() == Status.PENDING
    assert step.start() is not None  # still offers work


def test_generator_rejects_bad_step_indices():
    yaml_bad = YAML + """
plans:
  deploy:
    phases:
      p:
        pod: hello
        steps:
          - 5: [[server]]
"""
    from dcos_commons_tpu.specification import SpecError
    spec = from_yaml(yaml_bad)
    store = StateStore(MemPersister())
    with pytest.raises(SpecError) as err:
        PlanGenerator().generate(spec, "deploy", spec.plans["deploy"], store, "c")
    assert "out of range" in str(err.value)
    yaml_bad2 = yaml_bad.replace("- 5: [[server]]", "- 0: [[bogus-task]]")
    spec2 = from_yaml(yaml_bad2)
    with pytest.raises(SpecError) as err2:
        PlanGenerator().generate(spec2, "deploy", spec2.plans["deploy"], store, "c")
    assert "unknown tasks" in str(err2.value)


def test_step_interrupt():
    step = make_step()
    step.interrupt()
    assert step.get_status() == Status.WAITING
    assert step.start() is None
    step.proceed()
    assert step.get_status() == Status.PENDING


# -- strategies -------------------------------------------------------


def completed_step(name):
    step = make_step(name=name)
    step.force_complete()
    return step


def test_serial_strategy():
    steps = [make_step(f"s{i}", instances=[i]) for i in range(3)]
    strat = SerialStrategy()
    assert strat.candidates(steps, set()) == [steps[0]]
    steps[0].force_complete()
    assert strat.candidates(steps, set()) == [steps[1]]
    # dirty asset blocks the candidate AND everything after it
    assert strat.candidates(steps, {"hello-1"}) == []


def test_parallel_strategy():
    steps = [make_step(f"s{i}", instances=[i]) for i in range(3)]
    strat = ParallelStrategy()
    assert strat.candidates(steps, set()) == steps
    steps[1].force_complete()
    assert strat.candidates(steps, set()) == [steps[0], steps[2]]
    assert strat.candidates(steps, {"hello-2"}) == [steps[0]]


def test_canary_strategy():
    steps = [make_step(f"s{i}", instances=[i]) for i in range(3)]
    strat = CanaryStrategy(SerialStrategy(), canary_count=1)
    assert strat.is_interrupted()
    assert strat.candidates(steps, set()) == []
    strat.proceed()  # release the canary
    assert strat.candidates(steps, set()) == [steps[0]]
    steps[0].force_complete()
    assert strat.candidates(steps, set()) == []  # waits for 2nd proceed
    strat.proceed()
    assert strat.candidates(steps, set()) == [steps[1]]


def test_dependency_strategy():
    steps = {name: make_step(name, instances=[i])
             for i, name in enumerate(["a", "b", "c"])}
    strat = DependencyStrategy({"c": ["a", "b"], "b": ["a"]})
    ordered = list(steps.values())
    assert strat.candidates(ordered, set()) == [steps["a"]]
    steps["a"].force_complete()
    assert strat.candidates(ordered, set()) == [steps["b"]]
    steps["b"].force_complete()
    assert strat.candidates(ordered, set()) == [steps["c"]]


def test_strategy_names():
    assert isinstance(strategy_for_name("serial"), SerialStrategy)
    assert isinstance(strategy_for_name("parallel"), ParallelStrategy)
    assert isinstance(strategy_for_name("serial-canary"), CanaryStrategy)
    with pytest.raises(ValueError):
        strategy_for_name("bogus")


# -- phases/plans/aggregation ----------------------------------------


def test_plan_aggregation():
    spec = from_yaml(YAML)
    factory = DeployPlanFactory()
    store = StateStore(MemPersister())
    plan = factory.build(spec, store, "cfg-1")
    assert plan.get_status() == Status.PENDING
    assert [p.name for p in plan.phases] == ["hello", "once"]
    # serial over phases: only first phase's first step is a candidate
    candidates = plan.candidates(set())
    assert [s.name for s in candidates] == ["hello-0:[server]"]
    drive_to_running(candidates[0])
    assert plan.get_status() == Status.IN_PROGRESS
    # complete everything
    for step in plan.all_steps():
        step.force_complete()
    assert plan.get_status() == Status.COMPLETE


def test_plan_interrupt_waiting():
    spec = from_yaml(YAML)
    plan = DeployPlanFactory().build(spec, StateStore(MemPersister()), "c")
    plan.interrupt()
    assert plan.get_status() == Status.WAITING
    assert plan.candidates(set()) == []
    plan.proceed()
    assert plan.get_status() == Status.PENDING


def test_child_interrupt_surfaces_as_waiting():
    """A parked child dominates the rollup while incomplete — the
    aggregate fix plancheck's interrupt-visible invariant forced
    (minimal trace: force_complete(step-0); interrupt(step-1) used to
    read IN_PROGRESS, hiding the operator's own interrupt)."""
    spec = from_yaml(YAML)
    plan = DeployPlanFactory().build(spec, StateStore(MemPersister()), "c")
    steps = plan.phases[0].steps
    steps[0].force_complete()
    steps[1].interrupt()
    assert plan.phases[0].get_status() == Status.WAITING
    assert plan.get_status() == Status.WAITING
    # the interrupt stays visible even while a sibling is moving
    drive_to_running(steps[2])
    assert plan.get_status() == Status.WAITING
    steps[1].proceed()
    assert plan.get_status() == Status.IN_PROGRESS
    for step in plan.all_steps():
        step.force_complete()
    assert plan.get_status() == Status.COMPLETE


def test_coordinator_dirty_assets():
    spec = from_yaml(YAML)
    store = StateStore(MemPersister())
    deploy = DeployPlanFactory().build(spec, store, "c")
    # a second plan touching the same pod instances
    other = DeployPlanFactory().build(spec, store, "c", plan_name="other")
    coordinator = DefaultPlanCoordinator(
        [DefaultPlanManager(deploy), DefaultPlanManager(other)]
    )
    candidates = coordinator.get_candidates()
    # both plans want hello-0 — only one may have it
    assert len([s for s in candidates if "hello-0" in s.get_asset_names()]) == 1
    assert coordinator.has_work()


def test_coordinator_excludes_in_progress():
    spec = from_yaml(YAML)
    store = StateStore(MemPersister())
    deploy = DeployPlanFactory().build(spec, store, "c")
    other = DeployPlanFactory().build(spec, store, "c", plan_name="other")
    coordinator = DefaultPlanCoordinator(
        [DefaultPlanManager(deploy), DefaultPlanManager(other)]
    )
    # drive deploy's hello-0 to STARTING: it holds the asset
    step = deploy.candidates(set())[0]
    req = step.start()
    step.record_launch({n: new_task_id(n) for n in req.task_names()})
    assert step.get_status() == Status.STARTING
    for cand in coordinator.get_candidates():
        assert "hello-0" not in cand.get_asset_names()


# -- factory + resume -------------------------------------------------


def seed_running_task(store, pod_type, index, task, config_id):
    full = task_full_name(pod_type, index, task)
    info = TaskInfo(
        name=full,
        task_id=new_task_id(full),
        pod_type=pod_type,
        pod_index=index,
        labels={Label.TARGET_CONFIG: config_id},
    )
    store.store_tasks([info])
    store.store_status(
        full, TaskStatus(task_id=info.task_id, state=TaskState.RUNNING, ready=True)
    )
    return info


def test_factory_resumes_completed_steps():
    """Scheduler-restart semantics (reference: SchedulerRestartServiceTest)."""
    spec = from_yaml(YAML)
    store = StateStore(MemPersister())
    seed_running_task(store, "hello", 0, "server", "cfg")
    plan = DeployPlanFactory().build(spec, store, "cfg")
    statuses = {s.name: s.get_status() for s in plan.all_steps()}
    assert statuses["hello-0:[server]"] == Status.COMPLETE
    assert statuses["hello-1:[server]"] == Status.PENDING


def test_factory_old_config_pending():
    spec = from_yaml(YAML)
    store = StateStore(MemPersister())
    seed_running_task(store, "hello", 0, "server", "OLD-cfg")
    plan = DeployPlanFactory().build(spec, store, "NEW-cfg")
    assert plan.all_steps()[0].get_status() == Status.PENDING


def test_factory_gang_plan():
    spec = from_yaml(GANG_YAML)
    store = StateStore(MemPersister())
    plan = DeployPlanFactory().build(spec, store, "cfg")
    steps = plan.all_steps()
    assert len(steps) == 1
    assert steps[0].requirement.instances == [0, 1, 2, 3]


def test_plan_generator_custom_phases():
    yaml_plans = YAML + """
plans:
  deploy:
    strategy: serial
    phases:
      first:
        strategy: parallel
        pod: hello
      boot:
        strategy: serial
        pod: once
        steps:
          - 0: [[init]]
"""
    spec = from_yaml(yaml_plans)
    store = StateStore(MemPersister())
    plan = PlanGenerator().generate(
        spec, "deploy", spec.plans["deploy"], store, "cfg"
    )
    assert [p.name for p in plan.phases] == ["first", "boot"]
    assert len(plan.phases[0].steps) == 3
    assert isinstance(plan.phases[0].strategy, ParallelStrategy)
    assert plan.phases[1].steps[0].requirement.tasks_to_launch == ["init"]


# -- YAML phase dependencies (DAG plans) ------------------------------


DEPS_YAML = YAML + """
plans:
  deploy:
    phases:
      first:
        pod: once
      second:
        pod: hello
        dependencies: [first]
"""


def test_generator_phase_dependencies_gate_ordering():
    """`dependencies:` builds a DependencyStrategy plan: a phase is
    not a candidate until every prerequisite phase completed."""
    spec = from_yaml(DEPS_YAML)
    store = StateStore(MemPersister())
    plan = PlanGenerator().generate(
        spec, "deploy", spec.plans["deploy"], store, "c"
    )
    assert isinstance(plan.strategy, DependencyStrategy)
    candidates = plan.strategy.candidates(plan.phases, set())
    assert [p.name for p in candidates] == ["first"]
    # completing the prerequisite unlocks the dependent phase
    for step in plan.phases[0].steps:
        step.force_complete()
    candidates = plan.strategy.candidates(plan.phases, set())
    assert [p.name for p in candidates] == ["second"]


def test_generator_rejects_unknown_dependency():
    from dcos_commons_tpu.specification import SpecError

    bad = DEPS_YAML.replace("dependencies: [first]",
                            "dependencies: [nonexistent]")
    spec = from_yaml(bad)
    store = StateStore(MemPersister())
    with pytest.raises(SpecError) as err:
        PlanGenerator().generate(
            spec, "deploy", spec.plans["deploy"], store, "c"
        )
    assert "unknown phase" in str(err.value)


def test_generator_rejects_dependency_cycle():
    from dcos_commons_tpu.specification import SpecError

    bad = DEPS_YAML.replace(
        "      first:\n        pod: once\n",
        "      first:\n        pod: once\n        dependencies: [second]\n",
    )
    assert "dependencies: [second]" in bad  # replacement anchored
    spec = from_yaml(bad)
    store = StateStore(MemPersister())
    with pytest.raises(SpecError) as err:
        PlanGenerator().generate(
            spec, "deploy", spec.plans["deploy"], store, "c"
        )
    assert "cycle" in str(err.value)


def test_generator_rejects_strategy_with_dependencies():
    from dcos_commons_tpu.specification import SpecError

    bad = DEPS_YAML.replace("plans:\n  deploy:\n",
                            "plans:\n  deploy:\n    strategy: serial\n")
    assert "strategy: serial" in bad
    spec = from_yaml(bad)
    store = StateStore(MemPersister())
    with pytest.raises(SpecError) as err:
        PlanGenerator().generate(
            spec, "deploy", spec.plans["deploy"], store, "c"
        )
    assert "cannot be combined" in str(err.value)
