"""ML parallelism for the TPU workload plane.

The reference has NO tensor parallelism anywhere — its "parallel" is
plan rollout (SURVEY.md section 2 census).  This package is the
green-field ML-parallelism axis the rebuild adds: device meshes +
named shardings (dp/fsdp/tp/sp) consumed by pjit, ring-attention
context parallelism over the sp axis, and the worker-side
jax.distributed bootstrap consuming the scheduler's env contract
(COORDINATOR_ADDRESS et al., offer/evaluate.py).

Design per the scaling-book recipe: pick a mesh, annotate shardings,
let XLA insert the collectives, profile, iterate.  Collectives ride
ICI because the scheduler's torus placement made mesh neighbors
ICI-adjacent (offer/torus.py).
"""

from dcos_commons_tpu.parallel.collectives import (
    collective_bandwidth,
    single_chip_rooflines,
)
from dcos_commons_tpu.parallel.compat import shard_map
from dcos_commons_tpu.parallel.mesh import (
    MeshSpec,
    derive,
    make_mesh,
    mesh_from_env,
)
from dcos_commons_tpu.parallel.overlap import enable_collective_overlap
from dcos_commons_tpu.parallel.ring import ring_attention
from dcos_commons_tpu.parallel.distributed import initialize_from_env

__all__ = [
    "MeshSpec",
    "collective_bandwidth",
    "derive",
    "enable_collective_overlap",
    "initialize_from_env",
    "make_mesh",
    "mesh_from_env",
    "ring_attention",
    "shard_map",
    "single_chip_rooflines",
]
