"""ConfigStore: UUID -> serialized ServiceSpec, plus target pointer.

Reference: state/ConfigStore.java — configs are content-addressed by
UUID; a separate "target" pointer names the config tasks should be
running.  Config updates store a new UUID then flip the pointer
(config/DefaultConfigurationUpdater.java:159).
"""

from __future__ import annotations

import json
import uuid as uuid_mod
from typing import Any, Dict, List, Optional

from dcos_commons_tpu.storage import Persister
from dcos_commons_tpu.storage.persister import namespace_root, validate_key


class ConfigStore:
    """Stores configs as JSON dicts; the spec layer provides codecs."""

    def __init__(self, persister: Persister, namespace: str = "") -> None:
        self._persister = persister
        self._root = namespace_root(namespace)

    def _path(self, leaf: str) -> str:
        return f"{self._root}/{leaf}"

    def _config_path(self, config_id: str) -> str:
        validate_key(config_id, "config id")
        return self._path(f"configurations/{config_id}")

    def store(self, config: Dict[str, Any]) -> str:
        config_id = str(uuid_mod.uuid4())
        # NO sort_keys: plan phase order is semantic (journal -> name
        # -> data) and json round-trips preserve insertion order
        self._persister.set(
            self._config_path(config_id),
            json.dumps(config).encode("utf-8"),
        )
        return config_id

    def fetch(self, config_id: str) -> Optional[Dict[str, Any]]:
        raw = self._persister.get_or_none(self._config_path(config_id))
        return json.loads(raw.decode("utf-8")) if raw is not None else None

    def list_ids(self) -> List[str]:
        return self._persister.get_children_or_empty(self._path("configurations"))

    def clear(self, config_id: str) -> None:
        from dcos_commons_tpu.storage import PersisterError

        path = self._config_path(config_id)  # validates the id
        try:
            self._persister.recursive_delete(path)
        except PersisterError:
            pass  # missing config: already cleared

    # -- target pointer ----------------------------------------------

    def set_target_config(self, config_id: str) -> None:
        validate_key(config_id, "config id")
        self._persister.set(
            self._path("config-target"), config_id.encode("utf-8")
        )

    def get_target_config(self) -> Optional[str]:
        raw = self._persister.get_or_none(self._path("config-target"))
        return raw.decode("utf-8") if raw is not None else None

    def fetch_target(self) -> Optional[Dict[str, Any]]:
        target = self.get_target_config()
        return self.fetch(target) if target else None

    # -- GC (reference: DefaultConfigurationUpdater cleanup of configs
    #    no longer referenced by any task) ---------------------------

    def prune(self, referenced_ids: List[str]) -> List[str]:
        keep = set(referenced_ids)
        target = self.get_target_config()
        if target:
            keep.add(target)
        removed = []
        for config_id in self.list_ids():
            if config_id not in keep:
                self.clear(config_id)
                removed.append(config_id)
        return removed


class OptionsStore:
    """Persisted operator option overrides (the live `update` flow).

    Reference: the Cosmos options JSON a package `update` pushes onto
    a running scheduler.  A store class so the runner's option writes
    flow through the same wired (lease-fenced, in HA mode) persister
    as every other scheduler-path mutation — sdklint's
    ``lease-gated-mutation`` rule bans raw persister writes there.
    """

    NODE = "service_options"

    def __init__(self, persister: Persister) -> None:
        self._persister = persister

    def fetch(self) -> Dict[str, str]:
        raw = self._persister.get_or_none(self.NODE)
        if not raw:
            return {}
        try:
            data = json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return {}
        return {
            str(k): str(v) for k, v in data.items()
        } if isinstance(data, dict) else {}

    def store(self, options: Dict[str, str]) -> None:
        self._persister.set(
            self.NODE,
            json.dumps(options, sort_keys=True).encode("utf-8"),
        )

    # raw snapshot/restore: the runner's rebuild-failure rollback must
    # reproduce the EXACT pre-update bytes (or absence)

    def snapshot_raw(self) -> Optional[bytes]:
        return self._persister.get_or_none(self.NODE)

    def restore_raw(self, raw: Optional[bytes]) -> None:
        from dcos_commons_tpu.storage import PersisterError

        if raw is None:
            try:
                self._persister.recursive_delete(self.NODE)
            except PersisterError:
                pass  # nothing to roll back
        else:
            self._persister.set(self.NODE, raw)
