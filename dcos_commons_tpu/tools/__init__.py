"""Operator tooling: packaging and distribution.

Reference: tools/universe/ (package_builder.py / package_manager.py /
package_publisher.py) + the Cosmos install flow — a framework is
bundled (svc.yml + templates + scripts + manifest), published to a
catalog, and installed by name.  TPU-first shape: the package tarball
travels TO the scheduler (PUT /v1/multi/<name> with a gzip body), which
extracts it into its packages dir and serves the bundled config
templates itself — no external catalog service required.
"""

from dcos_commons_tpu.tools.packaging import (
    PackageError,
    build_package,
    extract_package,
    read_manifest,
)
from dcos_commons_tpu.tools.registry import (
    RegistryServer,
    fetch_package,
    prune_registry,
    publish_package,
    registry_index,
)

__all__ = [
    "PackageError",
    "RegistryServer",
    "build_package",
    "extract_package",
    "fetch_package",
    "prune_registry",
    "publish_package",
    "read_manifest",
    "registry_index",
]
