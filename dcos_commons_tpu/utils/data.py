"""Synthetic data generators (deterministic, device-friendly).

Real input pipelines are service-specific (the reference's SDK ships
none either); these feed the demo workloads and benches without
host-side IO in the measured loop.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def synthetic_tokens(
    key: jax.Array, batch: int, seq: int, vocab: int
) -> Tuple[jax.Array, jax.Array]:
    """(tokens, next-token targets) — a fixed random corpus slice."""
    tokens = jax.random.randint(key, (batch, seq + 1), 0, vocab, jnp.int32)
    return tokens[:, :-1], tokens[:, 1:]


def synthetic_mnist(key: jax.Array, batch: int) -> Tuple[jax.Array, jax.Array]:
    kx, ky = jax.random.split(key)
    x = jax.random.uniform(kx, (batch, 784), jnp.float32)
    y = jax.random.randint(ky, (batch,), 0, 10, jnp.int32)
    return x, y
