"""sdklint gate: the repo must satisfy its own static analysis.

The sibling of tests/test_build_gate.py (syntax/imports/style): this
gate runs the FRAMEWORK-INVARIANT linter and the ahead-of-time spec
analyzer over the whole repo and fails on any non-baselined finding,
plus one unit test per rule demonstrating a caught violation and a
suppressed one (the documented ``# sdklint: disable`` contract).

Reference: the root build gates on checkstyle/findbugs before any
test runs; this is the analogue for OUR invariants (event-driven
loop, generation-bumped caches, lock discipline, TPU-first resource
vocabulary, tracer safety).
"""

import json
import os
import textwrap
import threading
import time

from dcos_commons_tpu.analysis import baseline as baseline_mod
from dcos_commons_tpu.analysis import (
    configcheck,
    durcheck,
    lockcheck,
    plancheck,
    racecheck,
    shardcheck,
    speccheck,
    spmdcheck,
)
from dcos_commons_tpu.analysis.__main__ import main as analysis_main
from dcos_commons_tpu.analysis.linter import lint_paths, lint_tree
from dcos_commons_tpu.analysis.rules import all_rules, rule_catalog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- the repo-wide gates ----------------------------------------------


def test_repo_lint_gate():
    """Zero non-baselined lint findings across the package."""
    result = lint_tree(REPO)
    known = baseline_mod.load_baseline(baseline_mod.baseline_path(REPO))
    fresh, _ = baseline_mod.apply_baseline(result.findings, known)
    assert not fresh, "\n".join(f.render() for f in fresh)


def test_repo_spec_analyzer_gate():
    """Every packaged framework's YAMLs deploy-check clean."""
    findings = speccheck.analyze_all(REPO)
    assert not findings, "\n".join(f.render() for f in findings)


def test_repo_race_gate():
    """Zero non-baselined thread-ownership findings across the package
    — the racecheck baseline ships EMPTY, so every cross-thread write
    in tree is lock-guarded, channel-handed-off, or carries an
    annotated `# racecheck: handoff=` invariant."""
    result = racecheck.analyze_tree(REPO)
    known = baseline_mod.load_baseline(baseline_mod.baseline_path(REPO))
    fresh, _ = baseline_mod.apply_baseline(result.findings, known)
    assert not fresh, "\n".join(f.render() for f in fresh)
    assert not any(k.startswith("race-") for k in known), \
        "the race baseline must stay empty: fix or annotate instead"
    assert result.files_checked >= 100


def test_cli_all_exits_zero(capsys):
    """The CI entry point: `python -m dcos_commons_tpu.analysis --all`
    (lint + specs + spmd + plan + shard + race + config + dur; the
    plancheck cap is trimmed here — test_plancheck_repo_gate owns the
    full-depth run).  The whole sweep stays inside the ~40s CI
    budget."""
    start = time.monotonic()
    rc = analysis_main([
        "--all", "--root", REPO, "--plan-max-states", "1500",
    ])
    elapsed = time.monotonic() - start
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "lint:" in out and "specs:" in out
    assert "spmd:" in out and "plan:" in out and "shard:" in out
    assert "race:" in out and "config:" in out and "dur:" in out
    assert elapsed < 40.0, f"analysis all took {elapsed:.1f}s"


def test_rule_catalog_lists_every_rule():
    catalog = rule_catalog()
    for rule in all_rules():
        assert rule.id in catalog


# -- per-rule fixtures: violation caught, suppression honored ---------


def _lint_fixture(tmp_path, source, rel="dcos_commons_tpu/mod.py",
                  rule_id=None):
    """Lint one fixture file placed at ``rel`` under a fake repo root;
    returns (findings, suppressed) filtered to ``rule_id``."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    result = lint_paths([str(path)], str(tmp_path))
    pick = lambda fs: [f for f in fs if rule_id is None or f.rule == rule_id]  # noqa: E731
    return pick(result.findings), pick(result.suppressed)


def test_rule_no_blocking_sleep(tmp_path):
    src = """
    import time

    def poll():
        time.sleep(0.1)
    """
    findings, _ = _lint_fixture(tmp_path, src, rule_id="no-blocking-sleep")
    assert len(findings) == 1 and findings[0].line == 5
    suppressed_src = src.replace(
        "time.sleep(0.1)",
        "time.sleep(0.1)  # sdklint: disable=no-blocking-sleep — poll a foreign pid",
    )
    findings, suppressed = _lint_fixture(
        tmp_path, suppressed_src, rule_id="no-blocking-sleep"
    )
    assert not findings and len(suppressed) == 1
    # testing/ harnesses are allowlisted wholesale
    findings, _ = _lint_fixture(
        tmp_path, src, rel="dcos_commons_tpu/testing/ticks.py",
        rule_id="no-blocking-sleep",
    )
    assert not findings
    # `from time import sleep` does not dodge the rule
    findings, _ = _lint_fixture(
        tmp_path,
        "from time import sleep\n\ndef f():\n    sleep(1)\n",
        rule_id="no-blocking-sleep",
    )
    assert len(findings) == 1


def test_rule_ledger_mutation(tmp_path):
    src = """
    class ReservationLedger:
        def evil(self, r):
            self._cache[r.reservation_id] = r

        def good(self, r):
            self._generation += 1
            self._cache[r.reservation_id] = r
    """
    findings, _ = _lint_fixture(tmp_path, src, rule_id="ledger-mutation")
    assert len(findings) == 1 and "evil" in findings[0].message
    # external reach-in is flagged anywhere, any class
    findings, _ = _lint_fixture(
        tmp_path,
        "def gc(ledger):\n    ledger._by_host.clear()\n",
        rule_id="ledger-mutation",
    )
    assert len(findings) == 1 and "reach" not in findings[0].message
    suppressed_src = src.replace(
        "self._cache[r.reservation_id] = r\n\n",
        "self._cache[r.reservation_id] = r  "
        "# sdklint: disable=ledger-mutation — rebuilt below\n\n",
    )
    findings, suppressed = _lint_fixture(
        tmp_path, suppressed_src, rule_id="ledger-mutation"
    )
    assert not findings and len(suppressed) == 1


def test_rule_ledger_mutation_covers_index_maintenance(tmp_path):
    """Fleet-scale extension: the inverted field indexes and per-view
    snapshot caches may never be written around the generation-bumping
    mutators — an index diverging from the ledger mis-routes every
    future placement silently."""
    # public inventory method mutating host state without a bump
    findings, _ = _lint_fixture(
        tmp_path,
        """
        class SliceInventory:
            def evil_drain(self, host_id):
                self._down.add(host_id)

            def good_drain(self, host_id):
                self._down.add(host_id)
                self._topology_gen += 1
                self._host_topo_gen[host_id] = self._topology_gen
        """,
        rule_id="ledger-mutation",
    )
    assert len(findings) == 1 and "evil_drain" in findings[0].message
    # external reach-in to the index/cache structures is banned
    # anywhere — even well-meaning "just patch the index" code
    for reach in (
        "def patch(inv, h):\n    inv._field_indexes['zone']['z'] = {h}\n",
        "def patch(inv, h):\n    inv._view_caches.clear()\n",
        "def patch(inv, h):\n    inv._ordinal_cache[h] = 0\n",
    ):
        findings, _ = _lint_fixture(
            tmp_path, reach, rule_id="ledger-mutation"
        )
        assert len(findings) == 1, reach


def test_rule_lock_discipline(tmp_path):
    src = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def incr(self):
            with self._lock:
                self.count += 1

        def reset(self):
            self.count = 0
    """
    findings, _ = _lint_fixture(tmp_path, src, rule_id="lock-discipline")
    assert len(findings) == 1 and "reset" in findings[0].message
    # the *_locked convention declares "caller holds the lock"
    convention_src = src.replace("def reset(self):", "def reset_locked(self):")
    findings, _ = _lint_fixture(
        tmp_path, convention_src, rule_id="lock-discipline"
    )
    assert not findings
    suppressed_src = src.replace(
        "self.count = 0\n",
        "self.count = 0  # sdklint: disable=lock-discipline — "
        "called pre-thread only\n",
        1,
    )
    # the first "self.count = 0" is __init__ (never flagged); suppress
    # the reset() write instead
    suppressed_src = src.replace(
        "def reset(self):\n            self.count = 0",
        "def reset(self):\n            self.count = 0  "
        "# sdklint: disable=lock-discipline — single-threaded test hook",
    )
    findings, suppressed = _lint_fixture(
        tmp_path, suppressed_src, rule_id="lock-discipline"
    )
    assert not findings and len(suppressed) == 1


def test_rule_no_gpus_resource(tmp_path):
    src = 'RESOURCES = {"cpus": 1, "gpus": 2}\n'
    findings, _ = _lint_fixture(tmp_path, src, rule_id="no-gpus-resource")
    assert len(findings) == 1
    findings, suppressed = _lint_fixture(
        tmp_path,
        src.rstrip() + "  # sdklint: disable=no-gpus-resource — legacy import shim\n",
        rule_id="no-gpus-resource",
    )
    assert not findings and len(suppressed) == 1
    # prose mentioning the word is fine; only the exact token trips
    findings, _ = _lint_fixture(
        tmp_path,
        '"""No gpus scalars anywhere — BASELINE."""\n',
        rule_id="no-gpus-resource",
    )
    assert not findings


def test_rule_swallowed_exception(tmp_path):
    src = """
    def f():
        try:
            risky()
        except Exception:
            pass
    """
    findings, _ = _lint_fixture(tmp_path, src, rule_id="swallowed-exception")
    assert len(findings) == 1
    # a handler that DOES something is fine
    handled = src.replace("pass", "LOG.exception('risky failed')")
    findings, _ = _lint_fixture(tmp_path, handled,
                                rule_id="swallowed-exception")
    assert not findings
    suppressed_src = src.replace(
        "except Exception:",
        "except Exception:  # sdklint: disable=swallowed-exception — "
        "broken listener must not break intake",
    )
    findings, suppressed = _lint_fixture(
        tmp_path, suppressed_src, rule_id="swallowed-exception"
    )
    assert not findings and len(suppressed) == 1


def test_rule_jit_tracer_cast(tmp_path):
    src = """
    import jax
    import numpy as np

    @jax.jit
    def step(x):
        scale = float(x.mean())
        return np.asarray(x) * scale
    """
    findings, _ = _lint_fixture(tmp_path, src, rule_id="jit-tracer-cast")
    assert len(findings) == 2
    # un-decorated host code may cast freely
    findings, _ = _lint_fixture(
        tmp_path,
        "def host(x):\n    return float(x)\n",
        rule_id="jit-tracer-cast",
    )
    assert not findings
    suppressed_src = src.replace(
        "scale = float(x.mean())",
        "scale = float(x.mean())  # sdklint: disable=jit-tracer-cast — "
        "static arg, never traced",
    ).replace("return np.asarray(x) * scale", "return x * scale")
    findings, suppressed = _lint_fixture(
        tmp_path, suppressed_src, rule_id="jit-tracer-cast"
    )
    assert not findings and len(suppressed) == 1


def test_rule_span_leak(tmp_path):
    src = """
    class Scheduler:
        def leaky(self, tracer):
            span = tracer.span("cycle")
            span.set_attr("k", "v")

        def discarded(self):
            self.tracer.span("evaluate")
    """
    findings, _ = _lint_fixture(tmp_path, src, rule_id="span-leak")
    assert len(findings) == 2
    # `with` closes on all paths
    ok = """
    def fine(tracer):
        with tracer.span("cycle") as span:
            span.set_attr("k", "v")

    def fine_deferred(tracer):
        span = tracer.span("cycle")
        with span:
            pass

    def fine_explicit(tracer):
        span = tracer.span("cycle")
        try:
            work()
        finally:
            span.end()

    def factory(tracer):
        # ownership transfers to the caller
        span = tracer.span("cycle")
        return span

    def events_are_exempt(tracer):
        tracer.event("status:TASK_RUNNING")
    """
    findings, _ = _lint_fixture(tmp_path, ok, rule_id="span-leak")
    assert not findings
    # non-tracer .span receivers are out of scope
    findings, _ = _lint_fixture(
        tmp_path,
        "def other(doc):\n    doc.span('highlight')\n",
        rule_id="span-leak",
    )
    assert not findings
    suppressed_src = src.replace(
        'span = tracer.span("cycle")',
        'span = tracer.span("cycle")  # sdklint: disable=span-leak — '
        "closed by the registry on shutdown",
    ).replace('self.tracer.span("evaluate")', "pass")
    findings, suppressed = _lint_fixture(
        tmp_path, suppressed_src, rule_id="span-leak"
    )
    assert not findings and len(suppressed) == 1


def test_rule_lease_gated_mutation(tmp_path):
    src = """
    class FrameworkRunner:
        def _store_options(self, payload):
            self._persister.set("/options", payload)

        def _wipe(self, backend):
            backend.recursive_delete("/svc")
            backend.apply([])
    """
    findings, _ = _lint_fixture(
        tmp_path, src, rel="dcos_commons_tpu/runtime/runner.py",
        rule_id="lease-gated-mutation",
    )
    assert len(findings) == 3
    # reads and non-persister receivers are out of scope
    ok = """
    class FrameworkRunner:
        def read_side(self):
            self._persister.get("/options")
            self._persister.get_children("/svc")
            self._stop.set()          # an Event, not a persister

        def through_the_store(self, options):
            OptionsStore(self._persister).store(options)
    """
    findings, _ = _lint_fixture(
        tmp_path, ok, rel="dcos_commons_tpu/runtime/runner.py",
        rule_id="lease-gated-mutation",
    )
    assert not findings
    # store modules, the fence itself, and non-scheduler paths are
    # exempt (raw mutations are their JOB)
    for exempt_rel in (
        "dcos_commons_tpu/multi/store.py",
        "dcos_commons_tpu/ha/election.py",
        "dcos_commons_tpu/state/state_store.py",
        "dcos_commons_tpu/storage/cache.py",
        "dcos_commons_tpu/testing/chaos.py",
    ):
        findings, _ = _lint_fixture(
            tmp_path, src, rel=exempt_rel,
            rule_id="lease-gated-mutation",
        )
        assert not findings, exempt_rel
    # a deliberate raw write carries an explaining suppression
    suppressed_src = src.replace(
        'self._persister.set("/options", payload)',
        'self._persister.set("/options", payload)  '
        "# sdklint: disable=lease-gated-mutation — pre-lease bootstrap",
    ).replace('backend.recursive_delete("/svc")\n', "").replace(
        "backend.apply([])", "pass"
    )
    findings, suppressed = _lint_fixture(
        tmp_path, suppressed_src,
        rel="dcos_commons_tpu/runtime/runner.py",
        rule_id="lease-gated-mutation",
    )
    assert not findings and len(suppressed) == 1


def test_rule_health_plan_only(tmp_path):
    """ISSUE 15's layering invariant: health-plane code (detectors,
    the action governor) may not mutate ledger/state-store/persister
    directly — actions ride factory-built plan steps and journaled
    scheduler verbs."""
    src = """
    class RogueDetector:
        def act(self, scheduler):
            scheduler.ledger.release("res-1")
            scheduler.state_store.clear_task("serve-2-server")
            scheduler.state_store.store_property("k", b"v")
            self._persister.set("/x", b"1")
    """
    findings, _ = _lint_fixture(
        tmp_path, src, rel="dcos_commons_tpu/health/actions.py",
        rule_id="health-plan-only",
    )
    assert len(findings) == 4
    # the allowed surface: journal appends, scheduler verbs, plan
    # synthesis, reads — and non-store receivers named like builtins
    ok = """
    class Governor:
        def act(self, scheduler):
            scheduler.journal.append("health", verb="scale-out")
            scheduler.set_pod_count("serve", 3, source="autoscale")
            scheduler.restart_pod("serve", 1, replace=True)
            scheduler.state_store.fetch_tasks()
            self._seen.add("h1")          # a set, not a persister
            self._wake.set()              # an Event, not a persister
    """
    findings, _ = _lint_fixture(
        tmp_path, ok, rel="dcos_commons_tpu/health/actions.py",
        rule_id="health-plan-only",
    )
    assert not findings
    # journal.py is exempt (it IS the audit surface and owns its
    # backend); non-health paths are out of scope
    for exempt_rel in (
        "dcos_commons_tpu/health/journal.py",
        "dcos_commons_tpu/decommission/factory.py",
    ):
        findings, _ = _lint_fixture(
            tmp_path, src, rel=exempt_rel, rule_id="health-plan-only",
        )
        assert not findings, exempt_rel
    suppressed_src = """
    class Governor:
        def act(self, scheduler):
            scheduler.ledger.release("res-1")  # sdklint: disable=health-plan-only — test-only fixture
    """
    findings, suppressed = _lint_fixture(
        tmp_path, suppressed_src,
        rel="dcos_commons_tpu/health/actions.py",
        rule_id="health-plan-only",
    )
    assert not findings and len(suppressed) == 1


def test_rule_metric_cardinality(tmp_path):
    src = """
    class S:
        def record(self, status, request_id):
            self.metrics.incr(f"task_status.{status.task_id}")
            self.metrics.gauge("lat." + request_id, lambda: 1.0)
            self.metrics.incr("req.%s" % request_id)
            self.metrics.incr("req.{}".format(request_id))
    """
    findings, _ = _lint_fixture(
        tmp_path, src, rule_id="metric-cardinality"
    )
    assert len(findings) == 4
    assert "task_id" in findings[0].message
    # bounded vocabularies and non-metric receivers are out of scope
    ok = """
    class S:
        def record(self, status, key, pid):
            self.metrics.incr(f"task_status.{status.state.value}")
            self.metrics.incr(f"ha.rehydrate.{key}")
            self.metrics.incr("operations.launch")
            self.queue.incr(f"depth.{status.task_id}")  # not a registry
            self.log.time(f"t.{pid}")                   # not a registry
    """
    findings, _ = _lint_fixture(
        tmp_path, ok, rule_id="metric-cardinality"
    )
    assert not findings
    # the documented waiver: suppression with the bound stated
    suppressed_src = """
    class S:
        def record(self, status, request_id):
            self.metrics.incr(f"task_status.{status.task_id}")  # sdklint: disable=metric-cardinality — bounded: test fixture
    """
    findings, suppressed = _lint_fixture(
        tmp_path, suppressed_src, rule_id="metric-cardinality"
    )
    assert not findings and len(suppressed) == 1
    # registered allowlist prefixes waive the check (the bound lives
    # at the registration site)
    import dcos_commons_tpu.analysis.rules as rules_mod

    original = rules_mod.METRIC_CARDINALITY_ALLOWLIST
    rules_mod.METRIC_CARDINALITY_ALLOWLIST = ("task_status.",)
    try:
        findings, _ = _lint_fixture(
            tmp_path, src, rule_id="metric-cardinality"
        )
        assert len(findings) == 3  # the task_status. call is waived
    finally:
        rules_mod.METRIC_CARDINALITY_ALLOWLIST = original


def test_rule_router_stats_staleness(tmp_path):
    """Router code reaching into raw stats dicts bypasses the
    telemetry staleness gate (ISSUE 12): subscripts and .get() on
    stats-named receivers are flagged in router/ — except inside
    telemetry.py, the gate itself."""
    src = """
    def pick(pods):
        for pod in pods:
            depth = pod.stats["queue_depth"]
            free = pod.last_stats.get("kv_pages_free", 0)
        return depth, free
    """
    findings, _ = _lint_fixture(
        tmp_path, src, rel="dcos_commons_tpu/router/core.py",
        rule_id="router-stats-staleness",
    )
    assert len(findings) == 2
    assert "staleness" in findings[0].message
    # the gate module itself is the one legitimate parser
    findings, _ = _lint_fixture(
        tmp_path, src, rel="dcos_commons_tpu/router/telemetry.py",
        rule_id="router-stats-staleness",
    )
    assert not findings
    # code OUTSIDE router/ is out of scope (the serve engine builds
    # its own stats dicts all day)
    findings, _ = _lint_fixture(
        tmp_path, src, rel="dcos_commons_tpu/serve/engine.py",
        rule_id="router-stats-staleness",
    )
    assert not findings
    # non-stats dicts and gauge METHOD calls stay clean
    ok = """
    def pick(router, body):
        rows = body["tokens"]
        snapshot = router.stats()
        return rows, snapshot
    """
    findings, _ = _lint_fixture(
        tmp_path, ok, rel="dcos_commons_tpu/router/core.py",
        rule_id="router-stats-staleness",
    )
    assert not findings
    # the documented waiver form
    suppressed_src = """
    def mirror(stats):
        return stats["t"]  # sdklint: disable=router-stats-staleness — writing our OWN snapshot, not a pod's
    """
    findings, suppressed = _lint_fixture(
        tmp_path, suppressed_src,
        rel="dcos_commons_tpu/router/core.py",
        rule_id="router-stats-staleness",
    )
    assert not findings and len(suppressed) == 1


def test_file_level_suppression(tmp_path):
    src = (
        "# sdklint: disable-file=no-blocking-sleep — tick harness\n"
        "import time\n"
        "def a():\n    time.sleep(1)\n"
        "def b():\n    time.sleep(2)\n"
    )
    findings, suppressed = _lint_fixture(
        tmp_path, src, rule_id="no-blocking-sleep"
    )
    assert not findings and len(suppressed) == 2


# -- baseline mechanics -----------------------------------------------


def test_baseline_absorbs_and_bounds(tmp_path):
    src = """
    import time

    def a():
        time.sleep(1)
    """
    path = tmp_path / "dcos_commons_tpu" / "legacy.py"
    path.parent.mkdir(parents=True)
    path.write_text(textwrap.dedent(src))
    result = lint_paths([str(path)], str(tmp_path))
    bl_path = str(tmp_path / ".sdklint-baseline.json")
    counts = baseline_mod.save_baseline(bl_path, result.findings)
    assert sum(counts.values()) == 1
    # baselined: the same debt passes the gate
    known = baseline_mod.load_baseline(bl_path)
    fresh, absorbed = baseline_mod.apply_baseline(result.findings, known)
    assert not fresh and len(absorbed) == 1
    # NEW debt of the same rule in the same file exceeds the budget
    path.write_text(textwrap.dedent(src) + "\n\ndef b():\n    time.sleep(2)\n")
    result = lint_paths([str(path)], str(tmp_path))
    fresh, absorbed = baseline_mod.apply_baseline(result.findings, known)
    assert len(fresh) == 1 and len(absorbed) == 1
    # baseline entries are line-number free (fingerprint = file::rule)
    assert all("::" in k and k.count(":") == 2 for k in known)


def test_baseline_file_is_committed_and_parseable():
    path = baseline_mod.baseline_path(REPO)
    assert os.path.exists(path), "commit .sdklint-baseline.json"
    with open(path) as f:
        doc = json.load(f)
    assert "entries" in doc


# -- spec analyzer fixtures -------------------------------------------


def _speccheck_fixture(tmp_path, svc_yaml, options=None):
    framework = tmp_path / "frameworks" / "fix"
    framework.mkdir(parents=True, exist_ok=True)
    (framework / "svc.yml").write_text(textwrap.dedent(svc_yaml))
    if options is not None:
        (framework / "options.json").write_text(json.dumps(options))
    return speccheck.analyze_all(str(tmp_path))


def test_speccheck_clean_spec_passes(tmp_path):
    findings = _speccheck_fixture(tmp_path, """
    name: clean
    pods:
      web:
        count: 2
        tasks:
          server:
            goal: RUNNING
            cmd: "serve"
            cpus: 1
            memory: 1024
    """)
    assert findings == []


def test_speccheck_validator_errors_surface(tmp_path):
    findings = _speccheck_fixture(tmp_path, """
    name: bad__name
    pods:
      web:
        count: 1
        tasks:
          server:
            goal: RUNNING
            cmd: "serve"
    """)
    assert any(f.rule == "spec-validators" and "__" in f.message
               for f in findings)


def test_speccheck_unsatisfiable_placement(tmp_path):
    base = """
    name: svc
    pods:
      trainer:
        count: 4
        gang: true
        placement: '{placement}'
        tpu:
          generation: v5e
          chips-per-host: 4
          topology: 4x4
        tasks:
          worker:
            goal: RUNNING
            cmd: "train"
    """
    # 4x4 topology at 4 chips/host = 4 hosts; count 4 can't fit 0/host
    findings = _speccheck_fixture(
        tmp_path, base.format(placement="max-per-host:0")
    )
    assert any(f.rule == "spec-placement" for f in findings)
    # generation pin contradicting the pod's own tpu block
    findings = _speccheck_fixture(
        tmp_path, base.format(placement="generation:v4")
    )
    assert any(f.rule == "spec-placement" and "v4" in f.message
               for f in findings)
    # a satisfiable constraint stays quiet
    findings = _speccheck_fixture(
        tmp_path, base.format(placement="max-per-host:1")
    )
    assert not [f for f in findings if f.rule == "spec-placement"]


def test_speccheck_port_conflicts(tmp_path):
    findings = _speccheck_fixture(tmp_path, """
    name: svc
    pods:
      web:
        count: 1
        tasks:
          a:
            goal: RUNNING
            cmd: "a"
            ports:
              http:
                port: 8080
          b:
            goal: RUNNING
            cmd: "b"
            ports:
              admin:
                port: 8080
    """)
    assert any(f.rule == "spec-ports" and "8080" in f.message
               for f in findings)
    # count > 1 with a fixed port and nothing keeping instances apart
    findings = _speccheck_fixture(tmp_path, """
    name: svc
    pods:
      web:
        count: 3
        tasks:
          a:
            goal: RUNNING
            cmd: "a"
            ports:
              http:
                port: 8080
    """)
    assert any(f.rule == "spec-ports" and "max-per-host" in f.message
               for f in findings)


def test_speccheck_plan_findings(tmp_path):
    findings = _speccheck_fixture(tmp_path, """
    name: svc
    pods:
      web:
        count: 2
        tasks:
          server:
            goal: RUNNING
            cmd: "serve"
    plans:
      deploy:
        phases:
          one:
            pod: nonexistent
          two:
            pod: web
            dependencies: [three]
          three:
            pod: web
            dependencies: [two]
          four:
            pod: web
            steps:
              - 7: [[server]]
              - 0: [[bogus]]
    """)
    rules = {f.rule for f in findings}
    assert rules == {"spec-plan"}
    text = "\n".join(f.message for f in findings)
    assert "nonexistent" in text
    assert "cycle" in text
    assert "out of range" in text
    assert "bogus" in text


def test_speccheck_resources_exceed_host(tmp_path):
    findings = _speccheck_fixture(tmp_path, """
    name: svc
    pods:
      web:
        count: 1
        tasks:
          server:
            goal: RUNNING
            cmd: "serve"
            cpus: 64
            memory: 262144
    """)
    assert any(f.rule == "spec-resources" and "cpus" in f.message
               for f in findings)


def test_speccheck_gpus_key_and_file_suppression(tmp_path):
    yaml = """
    name: svc
    pods:
      web:
        count: 1
        tasks:
          server:
            goal: RUNNING
            cmd: "serve"
            gpus: 2
    """
    findings = _speccheck_fixture(tmp_path, yaml)
    assert any(f.rule == "no-gpus-resource" for f in findings)
    suppressed = "# sdklint: disable-file=no-gpus-resource — negative example\n" + yaml
    findings = _speccheck_fixture(tmp_path, suppressed)
    assert not [f for f in findings if f.rule == "no-gpus-resource"]


def test_speccheck_bad_options_schema(tmp_path):
    findings = _speccheck_fixture(
        tmp_path,
        """
        name: svc
        pods:
          web:
            count: 1
            tasks:
              server:
                goal: RUNNING
                cmd: "serve"
        """,
        options={"properties": {"web": {"properties": {
            "count": {"type": "integer"}  # no default, not required
        }}}},
    )
    assert any(f.rule == "spec-options" for f in findings)


# -- lock-order checker -----------------------------------------------


def test_lockcheck_reports_inverse_order_cycle(tmp_path):
    lockcheck.install()
    try:
        lockcheck.reset()
        a = threading.Lock()
        b = threading.Lock()

        def order_ab():
            with a:
                with b:
                    pass

        def order_ba():
            with b:
                with a:
                    pass

        # run the two orderings SEQUENTIALLY: the graph records both
        # nestings without ever actually deadlocking
        t1 = threading.Thread(target=order_ab, daemon=True)
        t1.start(); t1.join(timeout=5)
        t2 = threading.Thread(target=order_ba, daemon=True)
        t2.start(); t2.join(timeout=5)
        rep = lockcheck.report()
        assert len(rep.cycles) == 1, rep.describe()
        assert len(rep.cycles[0]) == 2
        assert "DEADLOCK RISK" in rep.describe()
    finally:
        lockcheck.uninstall()
        lockcheck.reset()


def test_lockcheck_consistent_order_is_clean():
    lockcheck.install()
    try:
        lockcheck.reset()
        a = threading.Lock()
        b = threading.Lock()

        def nested():
            with a:
                with b:
                    pass

        for _ in range(3):
            t = threading.Thread(target=nested, daemon=True)
            t.start(); t.join(timeout=5)
        rep = lockcheck.report()
        assert rep.cycles == [], rep.describe()
        assert len(rep.edges) == 1
    finally:
        lockcheck.uninstall()
        lockcheck.reset()


def test_lockcheck_rlock_reentry_no_self_edge():
    lockcheck.install()
    try:
        lockcheck.reset()
        lock = threading.RLock()

        def reenter():
            with lock:
                with lock:
                    pass

        t = threading.Thread(target=reenter, daemon=True)
        t.start(); t.join(timeout=5)
        rep = lockcheck.report()
        assert rep.edges == {} and rep.cycles == []
    finally:
        lockcheck.uninstall()
        lockcheck.reset()


def test_lockcheck_watch_flags_cross_thread_unguarded_write():
    lockcheck.install()
    try:
        lockcheck.reset()
        guard = threading.Lock()

        class Shared:
            def __init__(self):
                self.value = 0

        shared = Shared()
        lockcheck.watch(shared)

        def locked_writer():
            with guard:
                shared.value = 1

        def unlocked_writer():
            shared.value = 2

        for target in (locked_writer, unlocked_writer):
            t = threading.Thread(target=target, daemon=True)
            t.start(); t.join(timeout=5)
        rep = lockcheck.report()
        assert any("Shared.value" in w for w in rep.unguarded_writes), \
            rep.describe()
    finally:
        lockcheck.uninstall()
        lockcheck.reset()


def test_lockcheck_uninstall_restores_factories():
    before = threading.Lock
    lockcheck.install()
    assert threading.Lock is not before
    # locks created while installed keep working after uninstall
    lock = threading.Lock()
    lockcheck.uninstall()
    assert threading.Lock is before
    with lock:
        assert lock.locked()
    assert not lock.locked()


def test_self_attr_writes_tuple_unpack_does_not_mutate_ast():
    """Regression: tuple-assignment expansion must not append into the
    live AST node — repeated passes (multiple rules walk one tree)
    would otherwise see duplicated targets and duplicate findings."""
    import ast as ast_mod

    from dcos_commons_tpu.analysis.rules import _self_attr_writes

    tree = ast_mod.parse("class C:\n    def m(self):\n        self.a, self.b = 1, 2\n")
    assign = tree.body[0].body[0].body[0]
    before = len(assign.targets)
    first = sorted(attr for attr, _ in _self_attr_writes(tree))
    second = sorted(attr for attr, _ in _self_attr_writes(tree))
    assert first == second == ["a", "b"]
    assert len(assign.targets) == before


def test_speccheck_strategy_conflicts_with_dependencies(tmp_path):
    findings = _speccheck_fixture(tmp_path, """
    name: svc
    pods:
      web:
        count: 1
        tasks:
          server:
            goal: RUNNING
            cmd: "serve"
    plans:
      deploy:
        strategy: serial
        phases:
          one:
            pod: web
          two:
            pod: web
            dependencies: [one]
    """)
    assert any(f.rule == "spec-plan" and "conflicts" in f.message
               for f in findings)


def test_speccheck_findings_anchor_to_declaring_line(tmp_path):
    """Pod/plan findings land on the declaring YAML line, so the
    on-the-line suppression contract holds for them too."""
    yaml = """
    name: svc
    pods:
      web:
        count: 3
        tasks:
          a:
            goal: RUNNING
            cmd: "a"
            ports:
              http:
                port: 8080
    """
    findings = _speccheck_fixture(tmp_path, yaml)
    ports = [f for f in findings if f.rule == "spec-ports"]
    assert ports and ports[0].line > 1
    # line-level suppression on the pod declaration silences it
    suppressed = yaml.replace(
        "  web:", "  web:  # sdklint: disable=spec-ports — host-net by design"
    )
    findings = _speccheck_fixture(tmp_path, suppressed)
    assert not [f for f in findings if f.rule == "spec-ports"]


def test_speccheck_options_json_escape_hatch(tmp_path):
    """options.json is JSON (no comments): a top-level
    x-sdklint-disable list suppresses framework-wide."""
    schema = {"properties": {"web": {"properties": {
        "count": {"type": "integer"}  # no default, not required
    }}}}
    findings = _speccheck_fixture(
        tmp_path,
        """
        name: svc
        pods:
          web:
            count: 1
            tasks:
              server:
                goal: RUNNING
                cmd: "serve"
        """,
        options=schema,
    )
    assert any(f.rule == "spec-options" for f in findings)
    schema["x-sdklint-disable"] = ["spec-options"]
    findings = _speccheck_fixture(
        tmp_path,
        """
        name: svc
        pods:
          web:
            count: 1
            tasks:
              server:
                goal: RUNNING
                cmd: "serve"
        """,
        options=schema,
    )
    assert not [f for f in findings if f.rule == "spec-options"]


def test_suppression_accepts_plain_hyphen_rationale(tmp_path):
    """Regression: 'disable=rule - reason' (ASCII hyphen, not em-dash)
    must suppress — the rationale separator grammar accepts '#', EOL,
    em-dash, '--', and ' - '."""
    src = (
        "import time\n\ndef f():\n"
        "    time.sleep(1)  # sdklint: disable=no-blocking-sleep - foreign pid\n"
    )
    findings, suppressed = _lint_fixture(
        tmp_path, src, rule_id="no-blocking-sleep"
    )
    assert not findings and len(suppressed) == 1


def test_lock_discipline_sees_except_handler_writes(tmp_path):
    """Regression: writes inside except-handler bodies (error-recovery
    paths) must not be invisible to the lock-discipline walker."""
    src = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._state = {}

        def incr(self):
            with self._lock:
                self._state["n"] = 1

        def recover(self):
            try:
                risky()
            except Exception:
                self._state = {}
    """
    findings, _ = _lint_fixture(tmp_path, src, rule_id="lock-discipline")
    assert len(findings) == 1 and "recover" in findings[0].message


def test_lockcheck_watch_guarded_write_does_not_mask_unguarded():
    """Regression: a thread that wrote once under the lock and once
    without must still be reported (AND across writes, not OR)."""
    lockcheck.install()
    try:
        lockcheck.reset()
        guard = threading.Lock()

        class Shared2:
            def __init__(self):
                self.value = 0

        shared = Shared2()
        lockcheck.watch(shared)

        def mixed_writer():
            with guard:
                shared.value = 1   # guarded...
            shared.value = 2       # ...then unguarded: taints thread

        def guarded_writer():
            with guard:
                shared.value = 3

        for target in (mixed_writer, guarded_writer):
            t = threading.Thread(target=target, daemon=True)
            t.start(); t.join(timeout=5)
        rep = lockcheck.report()
        assert any("Shared2.value" in w for w in rep.unguarded_writes), \
            rep.describe()
    finally:
        lockcheck.uninstall()
        lockcheck.reset()


# -- spmdcheck: the repo gate -----------------------------------------


def test_spmdcheck_repo_gate():
    """Zero non-baselined SPMD findings across the data-plane layers;
    the one in-tree suppression (serve_gang_worker's driver/follower
    split) is annotated as intentional."""
    result = spmdcheck.analyze_tree(REPO)
    known = baseline_mod.load_baseline(baseline_mod.baseline_path(REPO))
    fresh, _ = baseline_mod.apply_baseline(result.findings, known)
    assert not fresh, "\n".join(f.render() for f in fresh)
    assert result.files_checked >= 20


def test_spmd_rule_catalog_lists_every_rule():
    catalog = spmdcheck.spmd_rule_catalog()
    for rule in spmdcheck.all_spmd_rules():
        assert rule.id in catalog


# -- spmdcheck: per-rule fixtures (caught + suppressed) ---------------


def _spmd_fixture(source, rule_id, extra_files=()):
    """Run spmdcheck over one in-memory fixture module (plus optional
    companions for interprocedural cases); returns (findings,
    suppressed) filtered to rule_id."""
    files = [(
        "/fix/dcos_commons_tpu/parallel/mod.py",
        "dcos_commons_tpu/parallel/mod.py",
        textwrap.dedent(source),
    )]
    for i, src in enumerate(extra_files):
        files.append((
            f"/fix/dcos_commons_tpu/parallel/extra{i}.py",
            f"dcos_commons_tpu/parallel/extra{i}.py",
            textwrap.dedent(src),
        ))
    result = spmdcheck.analyze_paths(files)
    pick = lambda fs: [f for f in fs if f.rule == rule_id]  # noqa: E731
    return pick(result.findings), pick(result.suppressed)


def test_spmd_rule_host_branch():
    src = """
    import jax
    from jax import lax

    def f(x):
        if jax.process_index() == 0:
            return lax.psum(x, "dp")
        return x
    """
    findings, _ = _spmd_fixture(src, "spmd-host-branch")
    assert len(findings) == 1 and "psum" in findings[0].message
    suppressed_src = src.replace(
        "if jax.process_index() == 0:",
        "if jax.process_index() == 0:  "
        "# sdklint: disable=spmd-host-branch — leader-only barrier",
    )
    findings, suppressed = _spmd_fixture(suppressed_src, "spmd-host-branch")
    assert not findings and len(suppressed) == 1


def test_spmd_rule_host_branch_interprocedural():
    """The collective three calls away from the rank branch — the
    reason spmdcheck is whole-program, not per-file."""
    helper = """
    from jax import lax

    def sync_all(x):
        return lax.all_gather(x, "dp")
    """
    src = """
    from dcos_commons_tpu.parallel.extra0 import sync_all

    def f(x, contract):
        rank = contract["worker_id"]
        if rank != 0:
            return sync_all(x)
        return x
    """
    findings, _ = _spmd_fixture(src, "spmd-host-branch",
                                extra_files=[helper])
    assert len(findings) == 1 and "all_gather" in findings[0].message
    # without the collective in the callee, the same branch is clean
    findings, _ = _spmd_fixture(
        src, "spmd-host-branch",
        extra_files=[helper.replace(
            'lax.all_gather(x, "dp")', "x + 1"
        )],
    )
    assert not findings


def test_spmd_rule_traced_cond():
    src = """
    from jax import lax

    def f(x):
        idx = lax.axis_index("dp")
        if idx == 0:
            x = lax.psum(x, "dp")
        return x
    """
    findings, _ = _spmd_fixture(src, "spmd-traced-cond")
    assert len(findings) == 1
    # lax.cond spelling with a collective-bearing branch function
    cond_src = """
    from jax import lax

    def branch(x):
        return lax.psum(x, "dp")

    def f(x):
        idx = lax.axis_index("dp")
        return lax.cond(idx == 0, branch, lambda y: y, x)
    """
    findings, _ = _spmd_fixture(cond_src, "spmd-traced-cond")
    assert len(findings) == 1 and "cond" in findings[0].message
    # collective-free branches under a varying predicate are the
    # CORRECT pattern (pipeline_loss_fn's last-rank loss) — clean
    clean = cond_src.replace('lax.psum(x, "dp")', "x * 2")
    findings, _ = _spmd_fixture(clean, "spmd-traced-cond")
    assert not findings
    suppressed_src = src.replace(
        "if idx == 0:",
        "if idx == 0:  # sdklint: disable=spmd-traced-cond — uniform by construction",
    )
    findings, suppressed = _spmd_fixture(suppressed_src, "spmd-traced-cond")
    assert not findings and len(suppressed) == 1


def test_spmd_rule_unknown_axis():
    src = """
    from jax import lax
    from jax.sharding import Mesh

    def build(devices):
        return Mesh(devices, ("dp", "tp"))

    def f(x):
        return lax.psum(x, "model")
    """
    findings, _ = _spmd_fixture(src, "spmd-unknown-axis")
    assert len(findings) == 1 and "'model'" in findings[0].message
    # a declared axis is fine; dynamic axis args are not judged
    findings, _ = _spmd_fixture(
        src.replace('lax.psum(x, "model")', 'lax.psum(x, "tp")'),
        "spmd-unknown-axis",
    )
    assert not findings
    suppressed_src = src.replace(
        'return lax.psum(x, "model")',
        'return lax.psum(x, "model")  '
        "# sdklint: disable=spmd-unknown-axis — bound by the caller's mesh",
    )
    findings, suppressed = _spmd_fixture(suppressed_src, "spmd-unknown-axis")
    assert not findings and len(suppressed) == 1


def test_spmd_rule_unordered_iter():
    src = """
    from jax import lax

    def f(x, hosts):
        for h in set(hosts):
            x = lax.ppermute(x, "dp", [(0, 1)])
        return x
    """
    findings, _ = _spmd_fixture(src, "spmd-unordered-iter")
    assert len(findings) == 1
    # a permute table comprehended out of a set, fed to the collective
    perm_src = """
    from jax import lax

    def f(x, pairs):
        perm = [(a, b) for a, b in set(pairs)]
        return lax.ppermute(x, "dp", perm)
    """
    findings, _ = _spmd_fixture(perm_src, "spmd-unordered-iter")
    assert len(findings) == 1 and "perm" in findings[0].message
    # sorted() restores a cross-host-deterministic order — clean
    findings, _ = _spmd_fixture(
        src.replace("set(hosts)", "sorted(set(hosts))"),
        "spmd-unordered-iter",
    )
    assert not findings
    suppressed_src = src.replace(
        "for h in set(hosts):",
        "for h in set(hosts):  "
        "# sdklint: disable=spmd-unordered-iter — singleton set",
    )
    findings, suppressed = _spmd_fixture(
        suppressed_src, "spmd-unordered-iter"
    )
    assert not findings and len(suppressed) == 1


def test_spmd_rule_per_host_trip_count():
    src = """
    import jax
    from jax import lax

    def f(x):
        steps = len(jax.local_devices())
        for i in range(steps):
            x = lax.psum(x, "dp")
        return x
    """
    findings, _ = _spmd_fixture(src, "spmd-per-host-trip-count")
    assert len(findings) == 1
    # agreeing on the bound through a uniformizing collective cleanses
    agreed = """
    import jax
    from jax import lax
    from jax.experimental import multihost_utils

    def f(x):
        steps = len(jax.local_devices())
        agreed = multihost_utils.process_allgather(steps)
        steps = int(agreed[0])
        for i in range(steps):
            x = lax.psum(x, "dp")
        return x
    """
    findings, _ = _spmd_fixture(agreed, "spmd-per-host-trip-count")
    assert not findings
    # jit-built step functions count as mesh programs (GSPMD inserts
    # the collectives even when none are spelled out)
    jit_src = """
    import jax

    def f(x):
        start = len(jax.local_devices())
        step = jax.jit(lambda y: y + 1)
        for i in range(start):
            x = step(x)
        return x
    """
    findings, _ = _spmd_fixture(jit_src, "spmd-per-host-trip-count")
    assert len(findings) == 1
    suppressed_src = src.replace(
        "for i in range(steps):",
        "for i in range(steps):  "
        "# sdklint: disable=spmd-per-host-trip-count — single-host tool",
    )
    findings, suppressed = _spmd_fixture(
        suppressed_src, "spmd-per-host-trip-count"
    )
    assert not findings and len(suppressed) == 1


def test_spmd_module_level_driver_analyzed():
    """A worker script with its collective branch at TOP level (no
    main() wrapper) is the same divergence hazard — the module body is
    analyzed as a pseudo-function."""
    src = """
    import jax
    from jax import lax

    x = jax.numpy.ones(4)
    if jax.process_index() == 0:
        x = lax.psum(x, "dp")
    """
    findings, _ = _spmd_fixture(src, "spmd-host-branch")
    assert len(findings) == 1 and "<module>" in findings[0].message


def test_update_baseline_subset_retains_other_analyzer(tmp_path):
    """Regression: lint and spmd share the baseline file, so
    `--lint --update-baseline` (the command the baseline's own comment
    prescribes) must not erase triaged spmd entries it never
    recomputed — and vice versa."""
    pkg = tmp_path / "dcos_commons_tpu" / "parallel"
    pkg.mkdir(parents=True)
    # one lint finding (blocking sleep) + one spmd finding (host branch)
    (tmp_path / "dcos_commons_tpu" / "legacy.py").write_text(
        "import time\n\ndef poll():\n    time.sleep(1)\n"
    )
    (pkg / "driver.py").write_text(textwrap.dedent("""
        import jax
        from jax import lax

        def f(x):
            if jax.process_index() == 0:
                return lax.psum(x, "dp")
            return x
    """))
    root = str(tmp_path)
    rc = analysis_main(["--lint", "--spmd", "--update-baseline",
                        "--root", root])
    assert rc == 0
    both = baseline_mod.load_baseline(baseline_mod.baseline_path(root))
    assert any("spmd-host-branch" in k for k in both)
    assert any("no-blocking-sleep" in k for k in both)
    # subset update: lint alone must keep the spmd entry verbatim
    rc = analysis_main(["--lint", "--update-baseline", "--root", root])
    assert rc == 0
    after = baseline_mod.load_baseline(baseline_mod.baseline_path(root))
    assert after == both
    # and both passes still gate clean against the retained file
    rc = analysis_main(["--lint", "--spmd", "--root", root])
    assert rc == 0
    # modes that feed no baseline refuse to rewrite it
    rc = analysis_main(["--specs", "--update-baseline", "--root", root])
    assert baseline_mod.load_baseline(
        baseline_mod.baseline_path(root)
    ) == both


# -- plancheck: the repo gate -----------------------------------------


def test_plancheck_repo_gate():
    """The full-depth model-check of the REAL plan machinery: every
    built-in configuration fully explored (no truncation, so the
    livelock check is sound), >= 10,000 deduped states total, zero
    invariant violations."""
    summary = plancheck.check_all(max_states=120_000)
    assert summary.ok, summary.render()
    assert summary.states_explored >= 10_000, summary.render()
    for result in summary.results:
        assert not result.truncated, result.config
        assert result.livelock_checked, result.config
        assert result.complete_states > 0, result.config
    # the gang-recovery configuration (ISSUE 13) is part of the gate
    # and must ITSELF clear the 10k-state bar: the kill/unreserve/
    # replace choreography x old-process deaths x operator verbs is
    # where the split-brain and double-reservation interleavings live
    by_name = {r.config: r for r in summary.results}
    assert "gang-recovery" in by_name, sorted(by_name)
    assert by_name["gang-recovery"].states >= 10_000, summary.render()
    # the autoscale configuration (ISSUE 15) gates the closed
    # health->action loop's no-flap contract at the same depth: the
    # REAL decide()/remediation_allowed() x cooldown latches x
    # episode toggles x operator verbs, livelock-sound (asserted for
    # every config above), with 0 violations of
    # no-opposite-concurrent / cooldown-honored / no-remediation-storm
    assert "autoscale" in by_name, sorted(by_name)
    assert by_name["autoscale"].states >= 10_000, summary.render()
    # the migration configuration (ISSUE 16) gates the fenced cutover
    # protocol at the same depth: freeze/stream/cutover/release x
    # operator abort x pod deaths at every protocol state x operator
    # verbs, with 0 violations of no-double-serve / no-token-loss —
    # the exactly-once cutover contract bench_disagg asserts
    # empirically, certified over ALL interleavings here
    assert "migration" in by_name, sorted(by_name)
    assert by_name["migration"].states >= 10_000, summary.render()
    # the multislice-recovery configuration (ISSUE 20) gates the
    # whole-slice elastic choreography at the same depth: slice-drop
    # shrink (kill -> unreserve -> replace-shrunken) THEN regrow to
    # declared width (kill-shrunken -> unreserve-shrunken ->
    # replace-full) x old/shrunken worker deaths at every point x
    # the capacity-returns edge x operator verbs, livelock-sound,
    # with 0 violations of no-split-brain-multislice /
    # no-double-slice-reservation across all THREE incarnations
    assert "multislice-recovery" in by_name, sorted(by_name)
    assert by_name["multislice-recovery"].states >= 10_000, \
        summary.render()


def test_plancheck_catches_broken_cutover_protocol():
    """Seeded migration-protocol bugs: an abort handler that unfreezes
    the source after the destination activated forks the token stream
    (no-double-serve); a protocol that retires the source row on
    splice success instead of the activate ack discards the session's
    only copy when the activation never lands (no-token-loss).  Both
    caught with minimal traces."""
    result = plancheck.check_plan(
        lambda: plancheck._migration_plan(abort_after_cutover=True),
        config_name="seeded-late-abort", max_states=120_000,
        check_livelock=False,
    )
    fork = [v for v in result.violations
            if v.invariant == "no-double-serve"]
    assert fork, result.violations
    # BFS minimality: freeze -> stream -> cutover -> abort, no detour
    assert len(fork[0].trace) <= 5, fork[0].render()

    result = plancheck.check_plan(
        lambda: plancheck._migration_plan(release_before_activate=True),
        config_name="seeded-early-release", max_states=120_000,
        check_livelock=False,
    )
    loss = [v for v in result.violations
            if v.invariant == "no-token-loss"]
    assert loss, result.violations
    assert len(loss[0].trace) <= 5, loss[0].render()


def test_plancheck_catches_flapping_governor():
    """Seeded flap: a governor that skips the cooldown check re-arms
    a same-direction scale action while the cooldown latch from the
    previous terminal state is still set — caught by
    cooldown-honored with a minimal trace.  A governor that skips
    the single-flight check is caught too (remediation storm /
    opposite-direction concurrency)."""
    result = plancheck.check_plan(
        lambda: plancheck._autoscale_plan(honor_cooldown=False),
        config_name="seeded-flap", max_states=120_000,
        check_livelock=False,
    )
    flap = [v for v in result.violations
            if v.invariant == "cooldown-honored"]
    assert flap, result.violations
    # BFS minimality: breach -> arm -> complete -> settle -> re-arm
    # is a handful of events, not a wandering trace
    assert len(flap[0].trace) <= 8, flap[0].render()

    result = plancheck.check_plan(
        lambda: plancheck._autoscale_plan(single_flight=False),
        config_name="seeded-storm", max_states=120_000,
        check_livelock=False,
    )
    names = {v.invariant for v in result.violations}
    assert "no-remediation-storm" in names or \
        "no-opposite-concurrent" in names, result.violations


def test_plancheck_catches_regrow_without_kill():
    """Seeded bug: a regrow phase that relaunches the declared width
    WITHOUT first killing + unreserving the shrunken gang commits the
    full-width claims while the shrunken incarnation still holds the
    surviving slice — no-double-slice-reservation fires with a
    minimal trace (the shortest path is the whole shrink choreography
    plus capacity-returns plus one launch, nothing more)."""
    result = plancheck.check_plan(
        lambda: plancheck._multislice_recovery_plan(
            regrow_skips_kill=True
        ),
        config_name="seeded-regrow-no-kill", max_states=120_000,
        check_livelock=False,
    )
    overlap = [v for v in result.violations
               if v.invariant == "no-double-slice-reservation"]
    assert overlap, result.violations
    assert len(overlap[0].trace) <= 9, overlap[0].render()


def test_plancheck_catches_unordered_gang_recovery():
    """Seeded bug: a gang recovery phase whose strategy does NOT
    serialize kill -> unreserve -> replace lets the replacement gang
    launch while old processes live and old claims stand — both new
    invariants must fire with minimal traces."""
    from dcos_commons_tpu.plan.phase import Phase
    from dcos_commons_tpu.plan.plan import Plan
    from dcos_commons_tpu.plan.step import (
        ActionStep,
        DeploymentStep,
        PodInstanceRequirement,
    )
    from dcos_commons_tpu.plan.strategy import ParallelStrategy
    from dcos_commons_tpu.specification.specs import (
        GoalState,
        PodSpec,
        TaskSpec,
    )

    def broken():
        pod = PodSpec(
            type="trainer", count=2, gang=True,
            tasks=[TaskSpec(name="worker", goal=GoalState.RUNNING,
                            cmd="train")],
        )
        replace = DeploymentStep(
            "replace-trainer-gang",
            PodInstanceRequirement(pod=pod, instances=[0, 1]),
            backoff=plancheck.ModelBackoff(),
        )
        kill = ActionStep("kill-trainer-survivors", lambda s: False)
        unreserve = ActionStep(
            "unreserve-trainer-slice", lambda s: False
        )
        world = plancheck.GangRecoveryWorld(kill, unreserve, replace)
        kill._action = world.kill_survivors
        unreserve._action = world.unreserve_slice
        phase = Phase(
            "recover-trainer-gang", [kill, unreserve, replace],
            ParallelStrategy(),  # SEEDED BUG: no ordering
        )
        plan = Plan("recovery", [phase], ParallelStrategy())
        world.bind(plan)
        return plan, world

    result = plancheck.check_plan(
        broken, config_name="broken-gang", max_states=50_000,
        max_violations=6, check_livelock=False,
    )
    names = {v.invariant for v in result.violations}
    assert "no-split-brain-gang" in names, result.violations
    assert "no-double-reservation" in names, result.violations
    shortest = min(
        len(v.trace) for v in result.violations
        if v.invariant == "no-double-reservation"
    )
    assert shortest == 1  # launch(replace) alone exposes it


# -- plancheck: seeded bugs produce minimal traces --------------------


def _model_plan(steps, strategy):
    from dcos_commons_tpu.plan.phase import Phase
    from dcos_commons_tpu.plan.plan import Plan
    from dcos_commons_tpu.plan.strategy import SerialStrategy

    return Plan(
        "deploy", [Phase("phase", steps, strategy)], SerialStrategy()
    )


def test_plancheck_catches_broken_dependency_strategy():
    """Seeded bug: a DependencyStrategy that forgets to check deps.
    plancheck reports dependency-honored with a MINIMAL trace — one
    event is enough to expose stage-b running before stage-a."""
    from dcos_commons_tpu.plan.strategy import (
        DependencyStrategy,
        _eligible,
    )

    class BrokenDeps(DependencyStrategy):
        def _candidates(self, children, dirty_assets):
            return [c for c in children if _eligible(c, dirty_assets)]

    def factory():
        return _model_plan(
            [plancheck._step("stage-a", "da"),
             plancheck._step("stage-b", "db")],
            BrokenDeps({"stage-b": ["stage-a"]}),
        )

    result = plancheck.check_plan(
        factory, config_name="seeded-deps", max_states=30_000
    )
    violations = [
        v for v in result.violations if v.invariant == "dependency-honored"
    ]
    assert violations, result
    assert len(violations[0].trace) == 1, violations[0]
    assert violations[0].trace[0] == "launch(stage-a)"


def test_plancheck_catches_complete_regression():
    """Seeded bug: a step missing DeploymentStep's is_complete guard
    (step.py:251) — a reordered late FAILED yanks a finished step back
    to DELAYED.  The quotient probe detects the class is unsafe,
    disables the COMPLETE quotient, and the search reports
    no-silent-regression with a 3-event minimal trace."""
    from dcos_commons_tpu.common import TaskState, task_name_of
    from dcos_commons_tpu.plan.step import (
        DeploymentStep,
        PodInstanceRequirement,
    )
    from dcos_commons_tpu.plan.strategy import SerialStrategy

    class RegressingStep(DeploymentStep):
        def update(self, status):
            with self._lock:
                try:
                    name = task_name_of(status.task_id)
                except ValueError:
                    return
                if name not in self._expected:
                    return
                # BUG under test: no is_complete guard, no stale check
                self._task_states[name] = status.state
                if status.ready:
                    self._task_ready[name] = True
                if status.state is not TaskState.ERROR:
                    self._recompute(failed=status.state.is_failure)

    def factory():
        step = RegressingStep(
            "node-0",
            PodInstanceRequirement(
                pod=plancheck._pod("na"), instances=[0]
            ),
            backoff=plancheck.ModelBackoff(),
        )
        return _model_plan([step], SerialStrategy())

    result = plancheck.check_plan(
        factory, config_name="seeded-regress", max_states=30_000
    )
    assert not result.quotient  # the probe caught the unsafe class
    violations = [
        v for v in result.violations
        if v.invariant == "no-silent-regression"
    ]
    assert violations, result
    trace = violations[0].trace
    assert len(trace) == 3, violations[0].render()
    assert trace[0] == "launch(node-0)"
    assert "FAILED" in trace[-1] or "STALE" in trace[-1]


def test_plancheck_quotient_probe_passes_for_real_step():
    """The production DeploymentStep keeps its is_complete guard, so
    the probe enables the verified COMPLETE quotient."""
    result = plancheck.check_plan(
        plancheck._parallel_plan, config_name="probe",
        max_states=30_000, step_interrupts=True,
    )
    assert result.quotient
    assert result.ok, result.violations


def test_plancheck_stale_status_never_mutates():
    """A status from a dead launch is a no-op in every reachable
    state: no transition labeled STALE ever produced a new state (the
    checker's dedup would have recorded it otherwise).  Checked
    indirectly: exploring WITHOUT the stale event yields the same
    state count."""
    base = plancheck.check_plan(
        plancheck._parallel_plan, config_name="with-stale",
        max_states=30_000,
    )
    harness_events = plancheck.PlanHarness(
        plancheck._parallel_plan()
    ).events()
    assert any("STALE" in label for label, _ in harness_events)
    assert base.ok


# -- CLI: subcommands + --json ----------------------------------------


def test_cli_subcommand_spellings(capsys):
    """`spmd` and `plan` run as positional subcommands."""
    rc = analysis_main(["spmd", "--root", REPO])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "spmd:" in out and "lint:" not in out
    rc = analysis_main(["plan", "--plan-max-states", "800"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "plan:" in out and "states explored" in out


def test_cli_json_output(capsys):
    """--json emits one machine-readable document with per-analyzer
    findings and the plancheck.states_explored metric."""
    rc = analysis_main([
        "--all", "--json", "--root", REPO, "--plan-max-states", "800",
    ])
    out = capsys.readouterr().out
    doc = json.loads(out)
    assert rc == 0
    assert doc["exit_code"] == 0
    assert doc["lint"]["findings"] == []
    assert doc["spmd"]["findings"] == []
    assert doc["spmd"]["suppressed"] == 1  # the annotated driver split
    assert doc["specs"]["findings"] == []
    assert doc["plan"]["states_explored"] >= 800
    assert doc["plan"]["violations"] == []
    assert set(doc["plan"]["configs"]) == set(plancheck.BUILTIN_CONFIGS)
    # the shard document: findings gate PLUS the footprint/cost trend
    # keys bench tooling consumes
    assert doc["shard"]["findings"] == []
    footprint = doc["shard"]["footprint"]
    assert "frameworks/jax/svc.yml:trainer" in footprint
    trainer = footprint["frameworks/jax/svc.yml:trainer"]
    assert trainer["per_chip_mb"] > 0
    assert {"params", "grads", "opt", "activations"} <= set(
        trainer["sections_mb"]
    )
    assert trainer["mesh"] == {"dp": 4, "tp": 4}
    cost = doc["shard"]["cost"]["frameworks/jax/svc.yml:trainer"]
    assert cost["total_ring_us"] > 0
    for entry in cost["per_step"]:
        assert {"axis", "ring_us", "allgather_us", "recommend"} <= set(
            entry
        )
        assert entry["ring_mb_per_chip"] <= entry["allgather_mb_per_chip"]
    # the race document: findings gate PLUS the trend keys dashboards
    # watch — total cross-thread shared attrs and distinct thread roles
    assert doc["race"]["findings"] == []
    assert doc["race"]["shared_attrs"] >= 1
    assert doc["race"]["roles"] >= 2
    assert any(
        info["shared_attrs"] for info in doc["race"]["classes"].values()
    )
    # the config document: findings gate PLUS the flow-graph trend
    # keys — tracked vars, joined YAML-env→reader edges, per-rule
    # counters for every rule in the catalog
    assert doc["config"]["findings"] == []
    assert doc["config"]["env_vars"] >= 100
    assert doc["config"]["flows"] >= 30
    assert set(doc["config"]["per_rule"]) == {
        rule_id for rule_id, _ in configcheck.CONFIG_RULES
    }
    assert all(n == 0 for n in doc["config"]["per_rule"].values())
    # the dur document: findings gate PLUS the durability-surface
    # trend keys the chaos matrix and dashboards consume
    assert doc["dur"]["findings"] == []
    assert doc["dur"]["persistence_points"] > 50
    assert doc["dur"]["per_kind"]["wal"] >= 3
    assert doc["dur"]["per_kind"]["persister"] >= 10
    # per_rule counts fresh+suppressed: the six annotated in-tree
    # debts stay on the trend line even though the gate is clean
    assert sum(doc["dur"]["per_rule"].values()) == doc["dur"]["suppressed"]


def test_cli_json_reports_findings(tmp_path, capsys):
    """Findings surface in the JSON document and flip the exit code."""
    bad = tmp_path / "dcos_commons_tpu" / "parallel" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent("""
        import jax
        from jax import lax

        def f(x):
            if jax.process_index() == 0:
                return lax.psum(x, "dp")
            return x
    """))
    rc = analysis_main([
        "--spmd", "--json", "--root", str(tmp_path),
    ])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["exit_code"] == 1
    assert any(
        f["rule"] == "spmd-host-branch" for f in doc["spmd"]["findings"]
    )


# -- shardcheck: the repo gate ----------------------------------------


def test_shardcheck_repo_gate():
    """Every packaged jax YAML's sharding layout checks clean: meshes
    derive, every PartitionSpec axis divides its dim, and the per-chip
    footprint fits both the generation HBM and the declared memory."""
    result = shardcheck.analyze_all(REPO)
    known = baseline_mod.load_baseline(baseline_mod.baseline_path(REPO))
    fresh, _ = baseline_mod.apply_baseline(result.findings, known)
    assert not fresh, "\n".join(f.render() for f in fresh)
    assert result.files_checked >= 4
    # all four packaged workloads produced reports
    scripts = {r.script for r in result.reports}
    assert {"train_worker.py", "train_mnist.py", "serve_worker.py",
            "serve_gang_worker.py"} <= scripts


def test_shard_rule_catalog_lists_every_rule():
    catalog = shardcheck.shard_rule_catalog()
    for rule_id, _ in shardcheck.SHARD_RULES:
        assert rule_id in catalog


# -- shardcheck: per-rule fixtures (caught + suppressed) ---------------


_TRAINER_YAML = """
name: fix
pods:
  trainer:{pod_comment}
    count: {count}
    gang: true
    tpu:
      generation: v5e
      chips-per-host: {chips}
      topology: {topology}
    tasks:
      worker:
        goal: RUNNING
        cmd: "python frameworks/jax/train_worker.py"
        cpus: 4
        memory: {memory}
"""


def _shard_fixture(tmp_path, yaml, options=None, **kwargs):
    framework = tmp_path / "frameworks" / "fix"
    framework.mkdir(parents=True, exist_ok=True)
    (framework / "svc.yml").write_text(textwrap.dedent(yaml))
    if options is not None:
        (framework / "options.json").write_text(json.dumps(options))
    return shardcheck.analyze_all(str(tmp_path), **kwargs)


def _trainer_yaml(chips=4, topology="4x4", memory=8192, pod_comment=""):
    return _TRAINER_YAML.format(
        chips=chips, topology=topology, memory=memory, count=4,
        pod_comment=pod_comment,
    )


def test_shard_rule_divisibility(tmp_path):
    """topology 2x3 at 3 chips/host derives dp=2 x tp=3 — and tp=3
    does not divide the flagship's 512-wide head/ffn dims."""
    result = _shard_fixture(
        tmp_path, _trainer_yaml(chips=3, topology="2x3")
    )
    found = [f for f in result.findings if f.rule == "shard-divisibility"]
    assert found and "tp" in found[0].message
    assert found[0].line > 1  # anchored to the pod's declaring line
    suppressed = _shard_fixture(tmp_path, _trainer_yaml(
        chips=3, topology="2x3",
        pod_comment="  # sdklint: disable=shard-divisibility,"
        "shard-hbm-overcommit — negative fixture",
    ))
    assert not [f for f in suppressed.findings
                if f.rule == "shard-divisibility"]
    assert [f for f in suppressed.suppressed
            if f.rule == "shard-divisibility"]


def test_shard_rule_hbm_overcommit(tmp_path):
    """memory: 64 cannot hold the flagship's per-host state."""
    result = _shard_fixture(tmp_path, _trainer_yaml(memory=64))
    found = [f for f in result.findings
             if f.rule == "shard-hbm-overcommit"]
    assert found and "declared memory" in found[0].message
    # the generation-HBM leg: shrink the budget below the footprint
    result = _shard_fixture(tmp_path, _trainer_yaml(), hbm_mb=8)
    assert any(f.rule == "shard-hbm-overcommit" and "HBM" in f.message
               for f in result.findings)
    suppressed = _shard_fixture(tmp_path, _trainer_yaml(
        memory=64,
        pod_comment="  # sdklint: disable=shard-hbm-overcommit — fixture",
    ))
    assert not [f for f in suppressed.findings
                if f.rule == "shard-hbm-overcommit"]
    assert [f for f in suppressed.suppressed
            if f.rule == "shard-hbm-overcommit"]


def test_shard_rule_mesh_underivable(tmp_path):
    """3 chips/host cannot tile a 2x2 slice: derive() raises SpecError
    and the finding lands on the pod's line with the topology string."""
    result = _shard_fixture(
        tmp_path, _trainer_yaml(chips=3, topology="2x2")
    )
    found = [f for f in result.findings if f.rule == "shard-mesh"]
    assert found and "'2x2'" in found[0].message
    assert found[0].line > 1
    suppressed = _shard_fixture(tmp_path, _trainer_yaml(
        chips=3, topology="2x2",
        pod_comment="  # sdklint: disable=shard-mesh — fixture",
    ))
    assert not [f for f in suppressed.findings
                if f.rule == "shard-mesh"]


def test_shard_rule_mesh_idle_chips(tmp_path):
    """A pod reserving more chips than its workload's mesh spans is
    the svc_mnist.yml bug this analyzer caught in-tree (the options
    TPU_CHIPS_PER_HOST default leaking into a single-chip job)."""
    yaml = """
    name: fix
    pods:
      mnist:
        count: 1
        tpu:
          generation: v5e
          chips-per-host: 4
        tasks:
          train:
            goal: FINISH
            cmd: "python frameworks/jax/train_mnist.py"
            cpus: 2
            memory: 4096
    """
    result = _shard_fixture(tmp_path, yaml)
    found = [f for f in result.findings if f.rule == "shard-mesh"]
    assert found and "idle" in found[0].message
    assert "4 chip(s)" in found[0].message


def test_shard_rule_replicated_giant(tmp_path):
    """With the threshold below the flagship's weight size, the
    dp-replicated (fsdp=1) params trip the rule; the default 256 MB
    threshold keeps the small flagship quiet."""
    result = _shard_fixture(tmp_path, _trainer_yaml(), giant_mb=1.0)
    found = [f for f in result.findings
             if f.rule == "shard-replicated-giant"]
    assert found and "replicated" in found[0].message
    quiet = _shard_fixture(tmp_path, _trainer_yaml())
    assert not [f for f in quiet.findings
                if f.rule == "shard-replicated-giant"]
    suppressed = _shard_fixture(tmp_path, _trainer_yaml(
        pod_comment="  # sdklint: disable=shard-replicated-giant — dp"
        " replication is intentional at this size",
    ), giant_mb=1.0)
    assert not [f for f in suppressed.findings
                if f.rule == "shard-replicated-giant"]
    assert [f for f in suppressed.suppressed
            if f.rule == "shard-replicated-giant"]


def test_shard_rule_unknown_axis(tmp_path):
    """A profile whose rules name an axis no Mesh/MeshSpec declares is
    flagged — the extension point is the PROFILES registry, so the
    fixture registers a synthetic workload."""
    from dcos_commons_tpu.parallel.mesh import MeshSpec

    def fixture_profile(env, tpu, pod, task):
        leaf = shardcheck.AbstractLeaf(
            "params/w", (8, 8), 2, (("model",), ("dp",)), "params"
        )
        return shardcheck.Workload(
            script="fixture_worker.py", mesh=MeshSpec(dp=2),
            leaves=[leaf],
        )

    yaml = """
    name: fix
    pods:
      web:{pod_comment}
        count: 1
        tpu:
          generation: v5e
          chips-per-host: 2
        tasks:
          server:
            goal: RUNNING
            cmd: "python fixture_worker.py"
            cpus: 1
            memory: 1024
    """
    shardcheck.PROFILES["fixture_worker.py"] = fixture_profile
    try:
        result = _shard_fixture(tmp_path, yaml.format(pod_comment=""))
        found = [f for f in result.findings
                 if f.rule == "shard-unknown-axis"]
        assert found and "'model'" in found[0].message
        suppressed = _shard_fixture(tmp_path, yaml.format(
            pod_comment="  # sdklint: disable=shard-unknown-axis — fixture",
        ))
        assert not [f for f in suppressed.findings
                    if f.rule == "shard-unknown-axis"]
        assert [f for f in suppressed.suppressed
                if f.rule == "shard-unknown-axis"]
    finally:
        del shardcheck.PROFILES["fixture_worker.py"]


def test_shard_options_json_escape_hatch(tmp_path):
    """x-sdklint-disable in options.json silences shard rules
    framework-wide, like the other YAML analyzers."""
    result = _shard_fixture(
        tmp_path, _trainer_yaml(chips=3, topology="2x3"),
        options={"x-sdklint-disable": ["shard-divisibility",
                                       "shard-hbm-overcommit"]},
    )
    assert not [f for f in result.findings
                if f.rule == "shard-divisibility"]
    assert [f for f in result.suppressed
            if f.rule == "shard-divisibility"]


def test_shard_cli_subcommand_and_json(tmp_path, capsys):
    """`shard` runs as a positional subcommand; a seeded bad YAML
    surfaces in the --json document and flips the exit code."""
    rc = analysis_main(["shard", "--root", REPO])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "shard:" in out and "lint:" not in out
    framework = tmp_path / "frameworks" / "fix"
    framework.mkdir(parents=True)
    (framework / "svc.yml").write_text(textwrap.dedent(
        _trainer_yaml(chips=3, topology="2x3", memory=64)
    ))
    rc = analysis_main(["--shard", "--json", "--root", str(tmp_path)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["exit_code"] == 1
    rules = {f["rule"] for f in doc["shard"]["findings"]}
    assert "shard-divisibility" in rules
    assert "shard-hbm-overcommit" in rules
    # findings are line-anchored in the machine output too
    assert all(f["line"] > 1 for f in doc["shard"]["findings"])
    assert "footprint" in doc["shard"] and "cost" in doc["shard"]


def test_shard_baseline_ownership(tmp_path):
    """shard- baseline entries survive a `--lint --update-baseline`
    that never recomputed them, like the spmd entries do."""
    framework = tmp_path / "frameworks" / "fix"
    framework.mkdir(parents=True)
    (framework / "svc.yml").write_text(textwrap.dedent(
        _trainer_yaml(chips=3, topology="2x3")
    ))
    (tmp_path / "dcos_commons_tpu").mkdir()
    (tmp_path / "dcos_commons_tpu" / "legacy.py").write_text(
        "import time\n\ndef poll():\n    time.sleep(1)\n"
    )
    root = str(tmp_path)
    rc = analysis_main(["--lint", "--shard", "--update-baseline",
                        "--root", root])
    assert rc == 0
    both = baseline_mod.load_baseline(baseline_mod.baseline_path(root))
    assert any("shard-divisibility" in k for k in both)
    assert any("no-blocking-sleep" in k for k in both)
    rc = analysis_main(["--lint", "--update-baseline", "--root", root])
    assert rc == 0
    after = baseline_mod.load_baseline(baseline_mod.baseline_path(root))
    assert after == both
    rc = analysis_main(["--lint", "--shard", "--root", root])
    assert rc == 0


def test_shard_malformed_env_is_a_finding_not_a_crash(tmp_path):
    """A non-numeric env value the worker would int() must fail THAT
    pod with an anchored, suppressible finding — one broken framework
    cannot abort the whole analysis CLI."""
    yaml = """
    name: fix
    pods:
      trainer:
        count: 1
        gang: true
        tpu:
          generation: v5e
          chips-per-host: 4
          topology: 2x2
        tasks:
          worker:
            goal: RUNNING
            cmd: "python frameworks/jax/train_worker.py"
            cpus: 4
            memory: 8192
            env:
              VOCAB: "not-a-number"
    """
    result = _shard_fixture(tmp_path, yaml)
    found = [f for f in result.findings if f.rule == "shard-mesh"]
    assert found and "not-a-number" in found[0].message
    assert found[0].line > 1


# -- stepcompare: predicted-vs-measured step time (ISSUE 7) -----------


def test_stepcompare_gates_on_mean_vs_floor():
    """The gate statistic is the MEAN wall (total-conserving under the
    in-flight window's ready-to-ready billing); regression trips past
    floor * (1 + slack)."""
    records = [{"wall_s": 0.010, "blocked_s": 0.001}] * 20
    out = shardcheck.stepcompare(
        None, records, floor_us=9000.0, slack=0.25
    )
    assert out["steps"] == 19  # default skip=1 drops the compile step
    assert abs(out["measured_mean_us"] - 10000.0) < 1.0
    assert out["predicted_floor_us"] == 9000.0
    assert out["measured_p95_us"] == out["measured_p50_us"]
    assert out["blocked_p50_us"] is not None
    assert out["regression"] is False  # 1.11x < 1.25
    out = shardcheck.stepcompare(
        None, records, floor_us=7000.0, slack=0.25
    )
    assert out["regression"] is True  # 1.43x > 1.25


def test_stepcompare_wire_model_and_malformed_records():
    """The wire floor is the CHEAPER collective spelling PER AXIS
    (each collective runs ONE spelling, so the floor sums per-axis
    minima); records a killed worker truncated (non-numeric/missing
    wall_s) are skipped, not crashed on."""
    cost = {
        "per_step": [
            {"axis": "dp", "ring_us": 300.0, "allgather_us": 450.0},
            {"axis": "tp", "ring_us": 350.0, "allgather_us": 200.0},
        ],
        "total_ring_us": 650.0,
        "total_allgather_us": 650.0,
    }
    records = [{"wall_s": 0.0005}]
    out = shardcheck.stepcompare(cost, records, slack=0.25, skip=0)
    assert out["predicted_wire_us"] == 500.0
    assert out["predicted_wire_dcn_us"] == 0.0  # no dcn leg in this mesh
    assert out["regression"] is False
    out = shardcheck.stepcompare(
        cost, records + [{"wall_s": "garbage"}, {}, {"step": 3}],
        skip=0,
    )
    assert out["steps"] == 1


def test_stepcompare_skips_the_compile_record():
    """A cold worker's step 0 bills the jit compile — multi-second on
    one record.  The default skip keeps it out of the gate; skip=0
    shows what it would have done to the mean."""
    records = [{"wall_s": 6.0}] + [{"wall_s": 0.010}] * 9
    out = shardcheck.stepcompare(
        None, records, floor_us=10000.0, slack=0.5
    )
    assert out["steps"] == 9
    assert out["regression"] is False
    out = shardcheck.stepcompare(
        None, records, floor_us=10000.0, slack=0.5, skip=0
    )
    assert out["regression"] is True


def test_stepcompare_ungated_without_records_or_floor():
    """No records, or nothing to gate against -> regression None
    (never a false trip on a single chip with no calibration)."""
    out = shardcheck.stepcompare(None, [], floor_us=100.0)
    assert out["regression"] is None
    assert out["measured_mean_us"] is None
    out = shardcheck.stepcompare(None, [{"wall_s": 0.001}], skip=0)
    assert out["regression"] is None
    assert out["measured_mean_us"] is not None


def test_stepcompare_cli_steplog(tmp_path, capsys):
    """--steplog attaches a predicted-vs-measured comparison for every
    train workload to the shard JSON; a regression past --step-slack
    flips the exit code (the operator asked for the gate)."""
    steplog = tmp_path / "steplog.jsonl"
    steplog.write_text("\n".join(
        json.dumps({"step": i, "wall_s": 0.02, "blocked_s": 0.0})
        for i in range(8)
    ))
    rc = analysis_main([
        "shard", "--root", REPO, "--json",
        "--steplog", str(steplog), "--step-floor-us", "19000",
    ])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    comparisons = doc["shard"]["stepcompare"]
    assert comparisons, "no train workload produced a cost model"
    for comparison in comparisons.values():
        assert abs(comparison["measured_mean_us"] - 20000.0) < 1.0
        assert comparison["regression"] is False
    # a tight floor makes the same steplog a regression
    rc = analysis_main([
        "shard", "--root", REPO, "--json",
        "--steplog", str(steplog), "--step-floor-us", "1000",
        "--step-slack", "0.25",
    ])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["exit_code"] == 1
    assert any(
        c["regression"] is True
        for c in doc["shard"]["stepcompare"].values()
    )


# -- configcheck: the repo gate ---------------------------------------


def test_configcheck_repo_gate():
    """Zero non-baselined config-contract findings across the package
    — the config baseline ships EMPTY, so every env var the pipeline
    sets is read, every read is covered, and every deliberate default
    split carries an inline `# sdklint: disable=` rationale."""
    result = configcheck.analyze_all(REPO)
    known = baseline_mod.load_baseline(baseline_mod.baseline_path(REPO))
    fresh, _ = baseline_mod.apply_baseline(result.findings, known)
    assert not fresh, "\n".join(f.render() for f in fresh)
    assert not any("config-" in k for k in known), \
        "the config baseline must stay empty: fix or suppress instead"
    assert result.files_checked >= 100
    # the deliberate default splits (SERVE_BATCH dev fallback,
    # TPU_CHIPS_PER_HOST autodetect sentinel, mnist demo scale) are
    # suppressed in-tree, not invisible
    assert any(
        f.rule == "config-default-drift" for f in result.suppressed
    )
    # the flow graph actually joined YAML env to worker reads
    assert len(result.env_vars) >= 100
    assert len(result.flows) >= 30


def test_config_rule_catalog_lists_every_rule():
    catalog = configcheck.config_rule_catalog()
    for rule_id, _ in configcheck.CONFIG_RULES:
        assert rule_id in catalog


# -- configcheck: per-rule fixtures (caught + suppressed) --------------


_CONFIG_YAML = """
name: fix
pods:
  web:{pod_comment}
    count: 1
    tasks:
      server:
        goal: RUNNING
        cmd: "python frameworks/fix/worker.py"
        cpus: 1
        memory: 1024
        env:{env_block}
"""

_CONFIG_WORKER = """
import os


def main():
    steps = int(os.environ.get("STEPS", "5")){extra}
    return steps
"""


def _config_fixture(tmp_path, yaml=None, worker=None, options=None):
    framework = tmp_path / "frameworks" / "fix"
    framework.mkdir(parents=True, exist_ok=True)
    (framework / "svc.yml").write_text(textwrap.dedent(
        yaml if yaml is not None else _config_yaml()
    ))
    (framework / "worker.py").write_text(textwrap.dedent(
        worker if worker is not None else _CONFIG_WORKER.format(extra="")
    ))
    if options is not None:
        (framework / "options.json").write_text(json.dumps(options))
    return configcheck.analyze_all(str(tmp_path))


def _config_yaml(pod_comment="", env_block='\n          STEPS: "3"'):
    return _CONFIG_YAML.format(
        pod_comment=pod_comment, env_block=env_block
    )


def _config_options(**extra):
    props = {
        "steps": {
            "description": "Fixture steps",
            "type": "integer", "default": 5, "env": "STEPS",
        },
    }
    props.update(extra)
    return {"properties": {"fix": {"properties": props}}}


def test_config_rule_undeclared_read(tmp_path):
    """A required os.environ[...] read the task env never sets (and
    the launch path never injects) fails the pod at its declaring
    line."""
    worker = _CONFIG_WORKER.format(
        extra='\n    token = os.environ["FIXTURE_TOKEN"]'
    )
    result = _config_fixture(tmp_path, worker=worker)
    found = [f for f in result.findings
             if f.rule == "config-undeclared-read"]
    assert found and "FIXTURE_TOKEN" in found[0].message
    assert "worker.py" in found[0].message
    assert found[0].line > 1  # anchored to the pod's declaring line
    suppressed = _config_fixture(tmp_path, yaml=_config_yaml(
        pod_comment="  # sdklint: disable=config-undeclared-read"
        " — fixture",
    ), worker=worker)
    assert not [f for f in suppressed.findings
                if f.rule == "config-undeclared-read"]
    assert [f for f in suppressed.suppressed
            if f.rule == "config-undeclared-read"]
    # setting the var in the task env clears it
    clean = _config_fixture(tmp_path, yaml=_config_yaml(
        env_block='\n          STEPS: "3"'
        '\n          FIXTURE_TOKEN: "t"',
    ), worker=worker)
    assert not [f for f in clean.findings
                if f.rule == "config-undeclared-read"]


def test_config_rule_dead_var(tmp_path):
    """An env key nothing reads — no direct read, no contract-helper
    closure, no dynamic table, no template/cmd reference in the YAML
    itself — is dead operator surface, anchored at the key's line."""
    result = _config_fixture(tmp_path, yaml=_config_yaml(
        env_block='\n          STEPS: "3"\n          DEAD_KEY: "1"',
    ))
    found = [f for f in result.findings if f.rule == "config-dead-var"]
    assert found and "DEAD_KEY" in found[0].message
    assert not any("STEPS" in f.message for f in found)
    suppressed = _config_fixture(tmp_path, yaml=_config_yaml(
        env_block='\n          STEPS: "3"'
        '\n          # sdklint: disable=config-dead-var — fixture'
        '\n          DEAD_KEY: "1"',
    ))
    assert not [f for f in suppressed.findings
                if f.rule == "config-dead-var"]
    assert [f for f in suppressed.suppressed
            if f.rule == "config-dead-var"]


def test_config_dead_var_spares_shell_consumers(tmp_path):
    """A var the task's own cmd consumes ($VAR expansion — the
    helloworld SLEEP_DURATION shape) is alive without any Python
    reader."""
    yaml = """
    name: fix
    pods:
      web:
        count: 1
        tasks:
          server:
            goal: RUNNING
            cmd: "sleep $NAP_S && python frameworks/fix/worker.py"
            cpus: 1
            memory: 1024
            env:
              STEPS: "3"
              NAP_S: "10"
    """
    result = _config_fixture(tmp_path, yaml=yaml)
    assert not [f for f in result.findings
                if f.rule == "config-dead-var"]


def test_config_rule_type_mismatch(tmp_path):
    """An env value the read-site cast cannot parse crashes the
    worker at startup — caught at the key's line instead."""
    result = _config_fixture(tmp_path, yaml=_config_yaml(
        env_block='\n          STEPS: "not-a-number"',
    ))
    found = [f for f in result.findings
             if f.rule == "config-type-mismatch"]
    assert found and "int()" in found[0].message
    assert "worker.py" in found[0].message
    suppressed = _config_fixture(tmp_path, yaml=_config_yaml(
        env_block='\n          # sdklint: disable=config-type-mismatch'
        ' — fixture\n          STEPS: "not-a-number"',
    ))
    assert not [f for f in suppressed.findings
                if f.rule == "config-type-mismatch"]
    assert [f for f in suppressed.suppressed
            if f.rule == "config-type-mismatch"]


def test_config_rule_default_drift_code(tmp_path):
    """The microbatch bug class: the worker's in-code fallback and
    options.json disagree about the same knob, anchored at the READ
    site and suppressible there."""
    worker = """
    import os


    def main():
        return int(os.environ.get("STEPS", "7"))
    """
    result = _config_fixture(
        tmp_path, yaml=_config_yaml(env_block='\n          STEPS: "{{STEPS:-5}}"'),
        worker=worker, options=_config_options(),
    )
    found = [f for f in result.findings
             if f.rule == "config-default-drift"]
    assert found and "'7'" in found[0].message
    assert found[0].file == "frameworks/fix/worker.py"
    suppressed_worker = """
    import os


    def main():
        # sdklint: disable=config-default-drift — fixture
        return int(os.environ.get("STEPS", "7"))
    """
    suppressed = _config_fixture(
        tmp_path, yaml=_config_yaml(env_block='\n          STEPS: "{{STEPS:-5}}"'),
        worker=suppressed_worker, options=_config_options(),
    )
    assert not [f for f in suppressed.findings
                if f.rule == "config-default-drift"]
    assert [f for f in suppressed.suppressed
            if f.rule == "config-default-drift"]


def test_config_rule_default_drift_template(tmp_path):
    """The YAML-only leg: a template fallback that disagrees with the
    options default splits YAML-only deploys from rendered ones."""
    drifted = _config_yaml(env_block='\n          STEPS: "{{STEPS:-9}}"')
    result = _config_fixture(
        tmp_path, yaml=drifted, options=_config_options(),
    )
    found = [f for f in result.findings
             if f.rule == "config-default-drift"]
    assert found and "{{STEPS:-9}}" in found[0].message
    assert found[0].file == "frameworks/fix/svc.yml"
    suppressed = _config_fixture(tmp_path, yaml=_config_yaml(
        env_block='\n          # sdklint: disable=config-default-drift'
        ' — fixture\n          STEPS: "{{STEPS:-9}}"',
    ), options=_config_options())
    assert not [f for f in suppressed.findings
                if f.rule == "config-default-drift"]
    assert [f for f in suppressed.suppressed
            if f.rule == "config-default-drift"]
    # a matching template default is quiet
    clean = _config_fixture(tmp_path, yaml=_config_yaml(
        env_block='\n          STEPS: "{{STEPS:-5}}"',
    ), options=_config_options())
    assert not [f for f in clean.findings
                if f.rule == "config-default-drift"]


def test_config_rule_options_orphan(tmp_path):
    """An options.json knob no YAML template consumes is dead
    operator surface; JSON cannot carry comments, so the
    x-sdklint-disable escape hatch is the suppression plane."""
    orphan = {
        "description": "Renders nowhere",
        "type": "string", "default": "x", "env": "ORPHAN_KEY",
    }
    options = _config_options(orphan=orphan)
    result = _config_fixture(
        tmp_path, yaml=_config_yaml(env_block='\n          STEPS: "{{STEPS:-5}}"'),
        options=options,
    )
    found = [f for f in result.findings
             if f.rule == "config-options-orphan"]
    assert found and "ORPHAN_KEY" in found[0].message
    assert found[0].file == "frameworks/fix/options.json"
    options["x-sdklint-disable"] = ["config-options-orphan"]
    suppressed = _config_fixture(
        tmp_path, yaml=_config_yaml(env_block='\n          STEPS: "{{STEPS:-5}}"'),
        options=options,
    )
    assert not [f for f in suppressed.findings
                if f.rule == "config-options-orphan"]
    assert [f for f in suppressed.suppressed
            if f.rule == "config-options-orphan"]


def test_config_cli_subcommand_and_json(tmp_path, capsys):
    """`config` runs as a positional subcommand; a seeded drifting
    fixture surfaces in the --json document and flips the exit
    code."""
    rc = analysis_main(["config", "--root", REPO])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "config:" in out and "lint:" not in out
    _config_fixture(tmp_path, yaml=_config_yaml(
        env_block='\n          STEPS: "{{STEPS:-9}}"'
        '\n          DEAD_KEY: "1"',
    ), options=_config_options())
    rc = analysis_main(["--config", "--json", "--root", str(tmp_path)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["exit_code"] == 1
    rules = {f["rule"] for f in doc["config"]["findings"]}
    assert "config-default-drift" in rules
    assert "config-dead-var" in rules
    assert doc["config"]["per_rule"]["config-dead-var"] >= 1
    assert doc["config"]["env_vars"] >= 1
    assert all(f["line"] > 1 for f in doc["config"]["findings"])


def test_config_baseline_ownership(tmp_path):
    """config- baseline entries survive a `--lint --update-baseline`
    that never recomputed them, like the shard/spmd entries do."""
    _config_fixture(tmp_path, yaml=_config_yaml(
        env_block='\n          DEAD_KEY: "1"\n          STEPS: "3"',
    ))
    (tmp_path / "dcos_commons_tpu").mkdir(exist_ok=True)
    (tmp_path / "dcos_commons_tpu" / "legacy.py").write_text(
        "import time\n\ndef poll():\n    time.sleep(1)\n"
    )
    root = str(tmp_path)
    rc = analysis_main(["--lint", "--config", "--update-baseline",
                        "--root", root])
    assert rc == 0
    both = baseline_mod.load_baseline(baseline_mod.baseline_path(root))
    assert any("config-dead-var" in k for k in both)
    assert any("no-blocking-sleep" in k for k in both)
    rc = analysis_main(["--lint", "--update-baseline", "--root", root])
    assert rc == 0
    after = baseline_mod.load_baseline(baseline_mod.baseline_path(root))
    assert after == both
    rc = analysis_main(["--lint", "--config", "--root", root])
    assert rc == 0


def test_config_reference_doc_is_current():
    """docs/config-reference.md is generated; the committed copy must
    match what `analysis config --docs` would write today."""
    result = configcheck.analyze_all(REPO)
    expected = configcheck.render_config_reference(result)
    path = os.path.join(REPO, "docs", "config-reference.md")
    with open(path, "r", encoding="utf-8") as f:
        committed = f.read()
    assert committed == expected, (
        "docs/config-reference.md is stale — regenerate with "
        "`python -m dcos_commons_tpu.analysis config --docs`"
    )


# -- durcheck: the repo gate ------------------------------------------


def test_durcheck_repo_gate():
    """Zero non-baselined crash-consistency findings across the
    persistence layers — the dur baseline ships EMPTY, so every
    effect-before-WAL window, unfenced write, and fsync-less file
    persist in tree is either fixed or carries an inline
    `# durcheck: <rule>=<reason>` rationale."""
    result = durcheck.analyze_tree(REPO)
    known = baseline_mod.load_baseline(baseline_mod.baseline_path(REPO))
    fresh, _ = baseline_mod.apply_baseline(result.findings, known)
    assert not fresh, "\n".join(f.render() for f in fresh)
    assert not any("dur-" in k for k in known), \
        "the dur baseline must stay empty: fix or annotate instead"
    assert result.files_checked >= 50
    # the durability surface the chaos matrix auto-derives from
    assert len(result.persistence_points) > 50
    kinds = {p.kind for p in result.persistence_points}
    assert {"wal", "store", "property", "persister", "file"} <= kinds
    # the deliberate in-tree debts (recovery-covered kill before the
    # relaunch WAL, fence-injected raw persisters, telemetry mirrors)
    # are annotated, not invisible
    suppressed_rules = {f.rule for f in result.suppressed}
    assert {"dur-effect-before-wal", "dur-unfenced-write",
            "dur-file-discipline"} <= suppressed_rules


def test_dur_rule_catalog_lists_every_rule():
    catalog = durcheck.dur_rule_catalog()
    for rule in durcheck.all_dur_rules():
        assert rule.id in catalog


# -- durcheck: per-rule fixtures (caught + suppressed) ----------------


def _dur_fixture(files, rule_id):
    """Run durcheck over in-memory (rel, source) fixture pairs;
    returns (findings, suppressed) filtered to rule_id."""
    triples = [
        (f"/fix/{rel}", rel, textwrap.dedent(src))
        for rel, src in files
    ]
    result = durcheck.analyze_paths(triples)
    pick = lambda fs: [f for f in fs if f.rule == rule_id]  # noqa: E731
    return pick(result.findings), pick(result.suppressed)


def test_dur_rule_effect_before_wal():
    src = """
    class S:
        def process(self, ops):
            self.task_killer.kill("old-task")
            self.ledger.commit(ops)
    """
    files = [("dcos_commons_tpu/scheduler/mod.py", src)]
    findings, _ = _dur_fixture(files, "dur-effect-before-wal")
    assert len(findings) == 1 and "kill" in findings[0].message
    suppressed_src = src.replace(
        "self.ledger.commit(ops)",
        "# durcheck: dur-effect-before-wal=kill is recovery-covered\n"
        "            self.ledger.commit(ops)",
    )
    findings, suppressed = _dur_fixture(
        [("dcos_commons_tpu/scheduler/mod.py", suppressed_src)],
        "dur-effect-before-wal",
    )
    assert not findings and len(suppressed) == 1


def test_dur_effect_before_wal_is_path_sensitive():
    # an effect on ONE branch taints the join: a persist-free branch
    # never masks the ordering hazard (may-analysis)
    branchy = """
    class S:
        def process(self, ops, cond):
            if cond:
                self.task_killer.kill("old-task")
            self.ledger.commit(ops)
    """
    files = [("dcos_commons_tpu/scheduler/mod.py", branchy)]
    findings, _ = _dur_fixture(files, "dur-effect-before-wal")
    assert len(findings) == 1
    # ...but a branch that TERMINATES after the effect does not flow
    # to the join: kill-then-early-return is the fenced bail-out
    # pattern, not an ordering hazard
    terminated = branchy.replace(
        'self.task_killer.kill("old-task")',
        'self.task_killer.kill("old-task")\n                return',
    )
    files = [("dcos_commons_tpu/scheduler/mod.py", terminated)]
    findings, _ = _dur_fixture(files, "dur-effect-before-wal")
    assert not findings


def test_dur_effect_before_wal_interprocedural_effects():
    # the kill happens two calls away; the summary fixpoint carries
    # it to the caller, where the flow walk sees it precede the WAL
    src = """
    class S:
        def _evict(self, name):
            self._reap(name)

        def _reap(self, name):
            self.task_killer.kill(name)

        def process(self, ops):
            self._evict("old")
            self.ledger.commit(ops)
    """
    files = [("dcos_commons_tpu/scheduler/mod.py", src)]
    findings, _ = _dur_fixture(files, "dur-effect-before-wal")
    assert len(findings) == 1
    assert "commit" in findings[0].message  # flagged AT the WAL site


def test_dur_rule_replay_parity():
    src = """
    class S:
        def save(self, store):
            store.store_property("ghost-record", b"x")

        def load(self, store):
            return store.fetch_property("orphan-key")
    """
    files = [("dcos_commons_tpu/state/mod.py", src)]
    findings, _ = _dur_fixture(files, "dur-replay-parity")
    messages = " | ".join(f.message for f in findings)
    assert len(findings) == 2
    assert "ghost-record" in messages and "orphan-key" in messages
    # pairing the keys clears both directions
    paired = src.replace('"orphan-key"', '"ghost-record"')
    findings, _ = _dur_fixture(
        [("dcos_commons_tpu/state/mod.py", paired)], "dur-replay-parity"
    )
    assert not findings
    # suppression: annotated write-only key (e.g. exported for an
    # external consumer) is documented debt, not a finding
    suppressed_src = src.replace(
        'store.store_property("ghost-record", b"x")',
        "# durcheck: dur-replay-parity=read by the fleet dashboard\n"
        '            store.store_property("ghost-record", b"x")',
    ).replace('"orphan-key"', '"ghost-record"')
    findings, suppressed = _dur_fixture(
        [("dcos_commons_tpu/state/mod.py", suppressed_src)],
        "dur-replay-parity",
    )
    assert not findings


def test_dur_replay_parity_journal_kinds():
    # a kind-filtered query for a kind nothing appends is an orphan
    # reader (typo'd query kind) — the generic events() consumer only
    # satisfies the WRITE side
    src = """
    class S:
        def emit(self):
            self.journal.append("scale-up", count=3)

        def recent(self):
            return self.journal.events(kinds=("scale-upp",))
    """
    files = [("dcos_commons_tpu/health/mod.py", src)]
    findings, _ = _dur_fixture(files, "dur-replay-parity")
    assert len(findings) == 2  # typo'd filter + now-unread append
    assert any("scale-upp" in f.message for f in findings)
    fixed = src.replace('"scale-upp"', '"scale-up"')
    findings, _ = _dur_fixture(
        [("dcos_commons_tpu/health/mod.py", fixed)], "dur-replay-parity"
    )
    assert not findings


def test_dur_rule_unfenced_write():
    # a raw persister write OUTSIDE the lease-gated-mutation scope,
    # reachable from scheduler-path code over the call graph — the
    # site the single-file lint structurally cannot see
    helper = """
    class Backend:
        def __init__(self, persister):
            self._persister = persister

        def store(self, raw):
            self._persister.set("/journal", raw)
    """
    caller = """
    def run_cycle(backend):
        backend.store(b"x")
    """
    files = [
        ("dcos_commons_tpu/health/helper.py", helper),
        ("dcos_commons_tpu/scheduler/mod.py", caller),
    ]
    findings, _ = _dur_fixture(files, "dur-unfenced-write")
    assert len(findings) == 1
    assert findings[0].file == "dcos_commons_tpu/health/helper.py"
    # cross-reference: the same raw write INSIDE the lint's scope is
    # lease-gated-mutation's finding, never durcheck's — one site is
    # never double-reported
    files = [
        ("dcos_commons_tpu/scheduler/helper.py", helper),
        ("dcos_commons_tpu/scheduler/mod.py", caller),
    ]
    findings, _ = _dur_fixture(files, "dur-unfenced-write")
    assert not findings
    # ...and unreachable helpers are not findings: nothing scheduler-
    # path can execute them
    files = [("dcos_commons_tpu/health/helper.py", helper)]
    findings, _ = _dur_fixture(files, "dur-unfenced-write")
    assert not findings
    # suppression with rationale
    suppressed_src = helper.replace(
        'self._persister.set("/journal", raw)',
        "# durcheck: dur-unfenced-write=builder injects the fence\n"
        '            self._persister.set("/journal", raw)',
    )
    files = [
        ("dcos_commons_tpu/health/helper.py", suppressed_src),
        ("dcos_commons_tpu/scheduler/mod.py", caller),
    ]
    findings, suppressed = _dur_fixture(files, "dur-unfenced-write")
    assert not findings and len(suppressed) == 1


def test_dur_rule_nonatomic_pair():
    src = """
    class Store:
        def save(self, name):
            self._persister.set(self._task_path(name, "info"), b"a")
            self._persister.set(self._task_path(name, "status"), b"b")
    """
    files = [("dcos_commons_tpu/state/mod.py", src)]
    findings, _ = _dur_fixture(files, "dur-nonatomic-pair")
    assert len(findings) == 1 and "tear" in findings[0].message
    # a generation bump between the writes makes the pair observable-
    # safe (replayers reject the torn half)
    bumped = src.replace(
        'self._persister.set(self._task_path(name, "status"), b"b")',
        "self._bump_generation(name)\n"
        '            self._persister.set(self._task_path(name, "status"), b"b")',
    )
    findings, _ = _dur_fixture(
        [("dcos_commons_tpu/state/mod.py", bumped)], "dur-nonatomic-pair"
    )
    assert not findings
    # suppression
    suppressed_src = src.replace(
        'self._persister.set(self._task_path(name, "status"), b"b")',
        "# durcheck: dur-nonatomic-pair=status replay tolerates tears\n"
        '            self._persister.set(self._task_path(name, "status"), b"b")',
    )
    findings, suppressed = _dur_fixture(
        [("dcos_commons_tpu/state/mod.py", suppressed_src)],
        "dur-nonatomic-pair",
    )
    assert not findings and len(suppressed) == 1


def test_dur_rule_file_discipline():
    src = """
    import os

    def save(path, data):
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(data)
        os.replace(tmp, path)
    """
    files = [("dcos_commons_tpu/utils/mod.py", src)]
    findings, _ = _dur_fixture(files, "dur-file-discipline")
    assert len(findings) == 1 and "fsync" in findings[0].message
    fixed = src.replace(
        "f.write(data)",
        "f.write(data)\n"
        "            f.flush()\n"
        "            os.fsync(f.fileno())",
    )
    findings, _ = _dur_fixture(
        [("dcos_commons_tpu/utils/mod.py", fixed)], "dur-file-discipline"
    )
    assert not findings
    suppressed_src = src.replace(
        'with open(tmp, "w") as f:',
        "# durcheck: dur-file-discipline=telemetry mirror, loss ok\n"
        '        with open(tmp, "w") as f:',
    )
    findings, suppressed = _dur_fixture(
        [("dcos_commons_tpu/utils/mod.py", suppressed_src)],
        "dur-file-discipline",
    )
    assert not findings and len(suppressed) == 1


def test_dur_cli_subcommand_and_points(capsys):
    """`analysis dur` gates; `analysis dur --points` dumps the
    persistence-point map the chaos harness consumes."""
    rc = analysis_main(["dur", "--root", REPO])
    out = capsys.readouterr().out
    assert rc == 0 and "dur:" in out
    rc = analysis_main(["dur", "--points", "--root", REPO])
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out)
    points = doc["persistence_points"]
    assert len(points) > 50
    assert all(
        {"file", "line", "end_line", "kind", "function"} <= set(p)
        for p in points
    )
    assert doc["per_kind"]["wal"] >= 3


def test_dur_baseline_ownership(tmp_path):
    """`--dur --update-baseline` owns only dur- entries: debt triaged
    by other analyzers survives a dur-only rewrite verbatim."""
    pkg = tmp_path / "dcos_commons_tpu" / "utils"
    pkg.mkdir(parents=True)
    (pkg / "writer.py").write_text(textwrap.dedent("""
        import os

        def save(path, data):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(data)
            os.replace(tmp, path)
    """))
    root = str(tmp_path)
    rc = analysis_main(["--dur", "--update-baseline", "--root", root])
    assert rc == 0
    entries = baseline_mod.load_baseline(baseline_mod.baseline_path(root))
    assert any("dur-file-discipline" in k for k in entries)
    # the gate is clean against its own baseline
    assert analysis_main(["--dur", "--root", root]) == 0
