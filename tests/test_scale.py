"""Scale test: many services through the multi scheduler at once.

Reference: frameworks/helloworld/tests/scale/test_scale.py deploys N
service instances concurrently and watches them all complete; this is
the sim-speed analogue over a shared fleet, asserting completion,
isolation (every service's tasks land and no reservation collides)
and that the control plane's per-cycle cost stays sane as N grows.

test_scale_distributed_fleet_with_churn crosses real sockets: 32
agent daemon PROCESSES under one multi scheduler process, 40
services, daemon-kill churn — the fleet fan-out
(agent/remote.py concurrent poll) at fleet size.
"""

import os
import subprocess
import sys
import time

import pytest

from dcos_commons_tpu.common import TaskState, TaskStatus
from dcos_commons_tpu.multi import MultiServiceScheduler
from dcos_commons_tpu.offer.inventory import SliceInventory, TpuHost
from dcos_commons_tpu.scheduler import SchedulerConfig
from dcos_commons_tpu.specification.yaml_spec import from_yaml
from dcos_commons_tpu.storage import MemPersister
from dcos_commons_tpu.testing import FakeAgent

N_SERVICES = 24
PODS_PER_SERVICE = 2


def service_yaml(i: int) -> str:
    return f"""
name: svc-{i:03d}
pods:
  app:
    count: {PODS_PER_SERVICE}
    tasks:
      main:
        goal: RUNNING
        cmd: "serve-{i:03d}"
        cpus: 0.5
        memory: 256
"""


def ack_all_running(multi, agent):
    for info in agent.launched:
        if info.task_id in agent.active_task_ids():
            agent.send(TaskStatus(
                task_id=info.task_id, state=TaskState.RUNNING, ready=True
            ))


def test_scale_many_services_on_shared_fleet():
    hosts = [
        TpuHost(host_id=f"h{i:02d}", cpus=16.0, memory_mb=32768)
        for i in range(8)
    ]
    agent = FakeAgent()
    multi = MultiServiceScheduler(
        persister=MemPersister(),
        inventory=SliceInventory(hosts),
        agent=agent,
        scheduler_config=SchedulerConfig(
            backoff_enabled=False, revive_capacity=1_000_000
        ),
    )
    t0 = time.monotonic()
    for i in range(N_SERVICES):
        multi.add_service(from_yaml(service_yaml(i)))

    deadline = time.monotonic() + 60
    cycles = 0
    while time.monotonic() < deadline:
        multi.run_cycle()
        cycles += 1
        ack_all_running(multi, agent)
        if all(
            multi.get_service(f"svc-{i:03d}").deploy_manager.get_plan()
            .is_complete
            for i in range(N_SERVICES)
        ):
            break
    elapsed = time.monotonic() - t0

    for i in range(N_SERVICES):
        svc = multi.get_service(f"svc-{i:03d}")
        assert svc.deploy_manager.get_plan().is_complete, f"svc-{i:03d}"
        for p in range(PODS_PER_SERVICE):
            info = svc.state_store.fetch_task(f"app-{p}-main")
            assert info is not None
            assert f"serve-{i:03d}" in info.command
    # every launch is alive exactly once: no cross-service task kills
    assert len(agent.launched) == N_SERVICES * PODS_PER_SERVICE
    assert agent.kills == []
    # fleet-level accounting: total cpu claims fit the fleet
    total_cpus = sum(
        r.cpus
        for i in range(N_SERVICES)
        for r in multi.get_service(f"svc-{i:03d}").ledger.all()
    )
    assert total_cpus <= sum(h.cpus for h in hosts)
    assert elapsed < 60, f"scale deploy too slow: {elapsed:.1f}s"


def test_scale_uninstall_one_leaves_rest_running():
    """Scaled-down isolation check under load: removing one service
    kills only its own tasks (the ADVICE.md multi-kill regression at
    fleet scale)."""
    hosts = [
        TpuHost(host_id=f"h{i:02d}", cpus=16.0, memory_mb=32768)
        for i in range(4)
    ]
    agent = FakeAgent()
    multi = MultiServiceScheduler(
        persister=MemPersister(),
        inventory=SliceInventory(hosts),
        agent=agent,
        scheduler_config=SchedulerConfig(
            backoff_enabled=False, revive_capacity=1_000_000
        ),
    )
    n = 6
    for i in range(n):
        multi.add_service(from_yaml(service_yaml(i)))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        multi.run_cycle()
        ack_all_running(multi, agent)
        if all(
            multi.get_service(f"svc-{i:03d}").deploy_manager.get_plan()
            .is_complete
            for i in range(n)
        ):
            break
    victim_tasks = {
        multi.get_service("svc-000").state_store.fetch_task(
            f"app-{p}-main"
        ).task_id
        for p in range(PODS_PER_SERVICE)
    }
    multi.uninstall_service("svc-000")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        multi.run_cycle()
        if multi.get_service("svc-000") is None:
            break
    killed = set(agent.kills)
    assert victim_tasks <= killed
    survivor_ids = {
        multi.get_service(f"svc-{i:03d}").state_store.fetch_task(
            f"app-{p}-main"
        ).task_id
        for i in range(1, n)
        for p in range(PODS_PER_SERVICE)
    }
    assert not (survivor_ids & killed)


# -- distributed-plane scale: real daemons, real sockets --------------


@pytest.mark.slow
def test_scale_distributed_fleet_with_churn(tmp_path):
    """32 agent daemon processes under one serve --multi scheduler
    process, 40 services (80 tasks), then daemon-kill churn: the two
    dead hosts' tasks are replaced on survivors, every unaffected
    service keeps its task ids, and the per-cycle timer stays bounded
    (reference: helloworld/tests/scale/test_scale.py + the
    fleet fan-out in agent/remote.py:140-161)."""
    from dcos_commons_tpu.testing.integration import (
        AgentProcess,
        ServiceClient,
        reap_orphan_tasks,
        wait_for,
    )

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    n_daemons, n_services = 32, 40
    daemons = [
        AgentProcess(f"sh{i:02d}", str(tmp_path / f"agent-{i:02d}"), repo)
        for i in range(n_daemons)
    ]
    svc_paths = []
    for i in range(n_services):
        path = tmp_path / f"svc-{i:03d}.yml"
        # a REAL command — these run as processes inside the daemons
        path.write_text(service_yaml(i).replace(
            f'cmd: "serve-{i:03d}"',
            f'cmd: "echo serve-{i:03d} && sleep 600"',
        ))
        svc_paths.append(str(path))
    lines = ["hosts:"]
    for daemon in daemons:
        lines += [
            f"  - host_id: {daemon.host_id}",
            f"    agent_url: {daemon.url}",
            "    cpus: 8.0",
            "    memory_mb: 16384",
        ]
    topology = tmp_path / "topology.yml"
    topology.write_text("\n".join(lines) + "\n")
    announce = tmp_path / "announce"
    log = open(tmp_path / "scheduler.log", "ab")
    scheduler = subprocess.Popen(
        [
            sys.executable, "-m", "dcos_commons_tpu", "serve", "--multi",
            *svc_paths,
            "--topology", str(topology),
            "--port", "0",
            "--state-dir", str(tmp_path / "state"),
            "--sandbox-root", str(tmp_path / "sbx"),
            "--announce-file", str(announce),
        ],
        cwd=repo,
        env={
            **os.environ,
            "ENABLE_BACKOFF": "false",
            "PERMANENT_FAILURE_TIMEOUT_S": "1",
        },
        stdout=log,
        stderr=subprocess.STDOUT,
    )
    try:
        url = wait_for(
            lambda: (
                open(announce).read().strip()
                if os.path.exists(announce) else None
            ),
            30.0,
            what="multi scheduler announce",
        )
        client = ServiceClient(url)
        names = [f"svc-{i:03d}" for i in range(n_services)]

        def all_deployed():
            for name in names:
                plan = client.get(f"/v1/multi/{name}/v1/plans/deploy")
                if plan["status"] != "COMPLETE":
                    return None
            return True

        wait_for(all_deployed, 180.0, interval_s=1.0,
                 what=f"{n_services} services deployed over {n_daemons} daemons")

        def ids_of(name):
            infos = [
                info
                for p in range(PODS_PER_SERVICE)
                for info in client.get(
                    f"/v1/multi/{name}/v1/pod/app-{p}/info"
                )
            ]
            return {i["name"]: (i["task_id"], i["agent_id"])
                    for i in infos}

        before = {name: ids_of(name) for name in names}
        spread = {
            agent_id
            for svc in before.values()
            for _, agent_id in svc.values()
        }
        # first-fit packs 16 tasks/host (8 cpus / 0.5) -> >= 3 hosts
        assert len(spread) >= 3, f"fleet barely used: {sorted(spread)}"

        # churn: kill two daemons that actually carry tasks
        victim_hosts = sorted(spread)[:2]
        victims = set(victim_hosts)
        for daemon in daemons:
            if daemon.host_id in victims:
                daemon.kill()
        affected = {
            name for name, tasks in before.items()
            if any(agent_id in victims for _, agent_id in tasks.values())
        }
        assert affected, "churn hit no services — topology spread broken"

        def recovered():
            for name in affected:
                now = ids_of(name)
                for task, (old_id, old_agent) in before[name].items():
                    if old_agent not in victims:
                        continue
                    current = now.get(task)
                    if current is None or current[0] == old_id or \
                            current[1] in victims:
                        return None
            return True

        wait_for(recovered, 180.0, interval_s=1.0,
                 what="churned tasks replaced on surviving daemons")

        # no cross-service kills: unaffected services keep their ids
        for name in sorted(set(names) - affected):
            assert ids_of(name) == before[name], f"{name} was disturbed"

        # per-cycle cost stays bounded at fleet size (cycle.process
        # timer; generous CI bound — the point is not-seconds).  The
        # steady-state bound is asserted on p95: the MAX legitimately
        # carries remote-daemon timeout smear — the cycle that first
        # polls a freshly-killed daemon blocks up to the
        # RemoteAgentClient RPC timeout (5.0s), so max_s ~5.01s was
        # observed under contention without anything being slow.  max
        # gets its own bound of steady-state + one full RPC-timeout
        # window.
        slowest_p95, slowest_max = 0.0, 0.0
        for name in names[:4]:
            snap = client.get(f"/v1/multi/{name}/v1/metrics")
            slowest_p95 = max(
                slowest_p95, snap.get("cycle.process.p95_s", 0.0)
            )
            slowest_max = max(
                slowest_max, snap.get("cycle.process.max_s", 0.0)
            )
        assert 0.0 < slowest_p95 < 5.0, f"cycle.process.p95_s {slowest_p95}"
        assert slowest_max < 5.0 + 5.0, f"cycle.process.max_s {slowest_max}"
    finally:
        scheduler.terminate()
        try:
            scheduler.wait(timeout=15)
        except subprocess.TimeoutExpired:
            scheduler.kill()
        log.close()
        for daemon in daemons:
            daemon.stop()
        # stopped daemons leave their tasks running (durable-task
        # semantics): 48 sleep-600 supervisors must not pile up on
        # the CI host across runs
        reap_orphan_tasks(daemons)
