"""Security plane: secret materialization + TLS certificate issuance.

Reference: the X2 subsystem (dcos/) — SecretsClient.java fetching from
the DC/OS secrets service, CertificateAuthorityClient.java signing
per-task certs consumed by TLSEvaluationStage.java (214 LoC), gated by
the TLSRequiresServiceAccount validator.  TPU-first shape: secrets
resolve through a pluggable provider on the scheduler, certs come from
a CA the scheduler owns, and both land in task sandboxes as 0600 files
shipped over the launch channel (never via env logging or artifacts
URLs).

Trust model for the launch channel itself (security/auth.py): the
control plane authenticates every hop with a shared cluster bearer
token and can serve HTTPS from the same CA (``python -m
dcos_commons_tpu certs`` provisions both).  Without a token the plane
is **loopback/trusted-network only**: secrets and TLS keys transit the
scheduler->agent launch request, so 0.0.0.0 fleets MUST set
--auth-token-file everywhere and SHOULD add --tls-* so that channel is
encrypted end to end.  All entrypoints warn on non-loopback binds
without a token.
"""

from dcos_commons_tpu.security.secrets import (
    FileSecretsProvider,
    InMemorySecretsProvider,
    SecretNotFound,
    SecretsProvider,
)
from dcos_commons_tpu.security.tls import CertificateAuthority

__all__ = [
    "CertificateAuthority",
    "FileSecretsProvider",
    "InMemorySecretsProvider",
    "SecretNotFound",
    "SecretsProvider",
]
