"""MultiServiceScheduler: fan-out event loop over N services.

Reference: scheduler/multi/MultiServiceEventClient.java:48 (offer/
status fan-out, auto-uninstall and removal of finished clients) +
MultiServiceManager.java (add/remove/lookup) + MultiServiceRunner.
Each service gets namespaced stores inside the shared persister and
competes for the shared slice inventory through its own evaluator;
the reservation ledgers are namespaced too, so the inventory view
subtracts every service's claims (snapshots take a merged ledger).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional

from dcos_commons_tpu.agent.base import Agent
from dcos_commons_tpu.multi.discipline import AnyFootprintDiscipline
from dcos_commons_tpu.multi.store import ServiceStore
from dcos_commons_tpu.offer.inventory import SliceInventory
from dcos_commons_tpu.runtime.task_killer import TaskKiller
from dcos_commons_tpu.scheduler.builder import SchedulerBuilder
from dcos_commons_tpu.scheduler.config import SchedulerConfig
from dcos_commons_tpu.scheduler.scheduler import DefaultScheduler
from dcos_commons_tpu.specification.specs import ServiceSpec
from dcos_commons_tpu.state.framework_store import FrameworkStore
from dcos_commons_tpu.state.state_store import StateStore
from dcos_commons_tpu.storage import Persister
from dcos_commons_tpu.uninstall import UninstallScheduler

LOG = logging.getLogger(__name__)


class _ServiceAgentAdapter:
    """Per-service view of the shared agent.

    The shared agent's poll() drains its queue, so the multi scheduler
    polls ONCE per cycle and routes each status to the owning service
    (reference: MultiServiceEventClient.taskStatus fan-out,
    MultiServiceEventClient.java:169-290).  Launch/kill pass through.
    """

    def __init__(self, agent: Agent):
        self._agent = agent
        self._queue: List = []

    def launch(self, task_infos):
        self._agent.launch(task_infos)

    def launch_one(self, info, readiness=None, health=None, templates=None,
                   **kwargs):
        launch_one = getattr(self._agent, "launch_one", None)
        if launch_one is not None:
            launch_one(
                info, readiness=readiness, health=health,
                templates=templates, **kwargs,
            )
        else:
            self._agent.launch([info])

    def kill(self, task_id, grace_period_s=0.0):
        self._agent.kill(task_id, grace_period_s)

    def active_task_ids(self):
        return self._agent.active_task_ids()

    def poll(self):
        out = list(self._queue)
        self._queue.clear()
        return out

    def deliver(self, status) -> None:
        self._queue.append(status)

    # worker telemetry pass-through: each service's /v1/debug/trace,
    # /v1/debug/serving and health monitor read sandbox steplogs /
    # serving gauges through ITS agent handle — without forwarding,
    # multi mode (the production topology) was blind to both
    def steplog_of(self, task_name, agent_id=None):
        reader = getattr(self._agent, "steplog_of", None)
        if not callable(reader):
            return []
        return reader(task_name, agent_id=agent_id) if agent_id \
            else reader(task_name)

    def serving_stats_of(self, task_name, agent_id=None):
        reader = getattr(self._agent, "serving_stats_of", None)
        if not callable(reader):
            return {}
        return reader(task_name, agent_id=agent_id) if agent_id \
            else reader(task_name)

    def advertised_port_of(self, task_name, agent_id=None):
        # the /v1/endpoints `advertise: true` contract (ISSUE 12):
        # without this forward, multi mode would list the reserved
        # port even when the worker bound (and advertised) another
        reader = getattr(self._agent, "advertised_port_of", None)
        if not callable(reader):
            return None
        return reader(task_name, agent_id=agent_id) if agent_id \
            else reader(task_name)


class _MergedLedgerView:
    """Union view over every service's reservation ledger, handed to
    SliceInventory snapshot sync so one service's free-capacity view
    excludes every other service's claims.

    Implements the incremental-sync protocol (generation_token /
    changed_hosts_since): any service's commit/GC — or a service
    appearing/disappearing — changes the composite token, and the
    dirty set is the union of every member ledger's dirty set, so a
    10k-host fleet re-synthesizes only the hosts someone touched."""

    def __init__(self, multi: "MultiServiceScheduler"):
        self._multi = multi
        self._items_cache = None
        self._items_version = -1

    def _items(self):
        # memoized on the multi's service-set version: a full sync
        # pass calls host_generation once per host, and re-taking the
        # services lock + copy + sort per HOST would be O(hosts x
        # services) — the version counter keeps it one sort per
        # service add/remove/rebuild
        version = self._multi.services_version
        if self._items_cache is None or self._items_version != version:
            self._items_cache = sorted(self._multi.services().items())
            self._items_version = version
        return self._items_cache

    def reserved_on(self, host_id: str):
        out = []
        for _name, service in self._items():
            out.extend(service.ledger.reserved_on(host_id))
        return out

    def host_generation(self, host_id: str):
        """Composite per-host change token (legacy full-pass path):
        (service, ledger epoch, per-host generation) triples, compared
        only by equality — the epoch keeps a rebuilt service's rebased
        generations from aliasing a stale token."""
        return tuple(
            (
                name,
                getattr(service.ledger, "epoch", ""),
                service.ledger.host_generation(host_id),
            )
            for name, service in self._items()
        )

    def generation_token(self):
        """Composite whole-view token: each member ledger's own
        (epoch, generation) token — any commit/GC anywhere, a service
        set change, or a service REBUILD (fresh ledger object over
        the same tree) makes it compare unequal."""
        return tuple(
            (name, service.ledger.generation_token())
            for name, service in self._items()
        )

    def changed_hosts_since(self, token):
        if not isinstance(token, tuple):
            return None
        items = self._items()
        old = dict(token)
        if len(old) != len(token) or set(old) != {n for n, _ in items}:
            # a service appeared or disappeared: its claims (dis)appear
            # on hosts no member journal will report — all dirty
            return None
        out = set()
        for name, service in items:
            changed = service.ledger.changed_hosts_since(old[name])
            if changed is None:
                return None
            out |= changed
        return out


class MultiServiceScheduler:
    def __init__(
        self,
        persister: Persister,
        inventory: SliceInventory,
        agent: Agent,
        scheduler_config: Optional[SchedulerConfig] = None,
        discipline=None,
        builder_hook: Optional[Callable[[SchedulerBuilder], None]] = None,
        ha_state=None,
    ):
        # HA (dcos_commons_tpu/ha/): one election per PROCESS — the
        # shared (already lease-fenced) persister carries the fence;
        # the HAState handle is propagated onto every service scheduler
        # so each serves GET /v1/debug/ha
        self.ha_state = ha_state
        self.persister = persister
        self.inventory = inventory
        self.agent = agent
        self.config = scheduler_config or SchedulerConfig()
        self.discipline = discipline or AnyFootprintDiscipline()
        self.service_store = ServiceStore(persister)
        self.framework_store = FrameworkStore(persister)
        self._builder_hook = builder_hook
        self._services: Dict[str, object] = {}  # name -> scheduler
        # bumped on every service add/remove/rebuild; the merged
        # ledger view memoizes its sorted service list on it
        self._services_version = 0
        # merged orphan sweep goes through a TaskKiller so lost kill
        # requests are retried and acked like every other kill
        self.task_killer = TaskKiller(agent)
        # incremental orphan index (ISSUE 13 satellite, the PR 9
        # remainder): per-service expected-task-id sets cached on the
        # service's task-subtree generation stamp, so the per-cycle
        # sweep is O(services) stamp compares on a quiet fleet instead
        # of O(services x tasks) store scans.  The stamp is
        # epoch-qualified (StateStore.task_generation), so a service
        # REBUILD (upgrade, failover) re-bases under a fresh epoch and
        # can never alias a stale cached set.
        self._orphan_index: Dict[str, tuple] = {}
        # wedge detection (mirrors DefaultScheduler.run_forever): a
        # service failing this many consecutive cycles flags the whole
        # process fatal for supervised restart
        self.max_consecutive_failures = 5
        self._cycle_failures: Dict[str, int] = {}
        # per-service offer discipline (reference: suppress/revive,
        # framework/ReviveManager.java): a service whose plans hold no
        # pending/in-flight work after its cycle is SUPPRESSED —
        # skipped entirely by run_cycle — until a routed status or a
        # nudge() (HTTP mutation) revives it
        self._suppressed_services: set = set()
        self._fatal_error: Optional[str] = None
        self._lock = threading.RLock()
        self._stop = threading.Event()
        # event-driven wake (mirrors DefaultScheduler): service
        # add/remove and agent status arrival cut the fallback wait
        self._wake = threading.Event()
        add_listener = getattr(agent, "add_status_listener", None)
        if callable(add_listener):
            add_listener(self.nudge)
        # fleet-level event journal (health plane): admission
        # rejections target services that may not exist yet, so no
        # per-service store can own them — this one persists at a raw
        # tree path through the (fenced-in-HA) shared persister and is
        # served at GET /v1/multi/events
        from dcos_commons_tpu.health import EventJournal, PersisterBackend

        self.journal = EventJournal(
            PersisterBackend(persister),
            capacity=self.config.health_journal_capacity,
        ) if self.config.health_enabled else EventJournal(
            backend=None, capacity=0
        )
        # ONE merged view shared by every service's evaluator: the
        # shared inventory keys its snapshot cache on the view object,
        # so per-service view instances would clear it on every
        # service switch within a cycle
        self._merged_view = _MergedLedgerView(self)
        with self._lock:
            self._reload_locked()

    # -- add/remove/lookup (reference: MultiServiceManager) -----------

    def _reload_locked(self) -> None:
        """Restart resume: rebuild every persisted service, including
        those mid-uninstall."""
        for name in self.service_store.list_names():
            entry = self.service_store.fetch(name)
            if entry is None:
                continue
            spec = ServiceSpec.from_dict(entry["spec"])
            if entry.get("uninstalling"):
                self._services[name] = self._make_uninstaller(spec)
            else:
                self._services[name] = self._build(spec)
            self._services_version += 1

    def add_service(self, spec: ServiceSpec,
                    options: Optional[dict] = None) -> None:
        with self._lock:
            if spec.name in self._services:
                raise ValueError(f"service {spec.name!r} already exists")
            # build BEFORE persisting: a spec that cannot build must
            # not be stored, or _reload poisons every restart.  ONE
            # store: options must never be persisted separately from
            # the spec they rendered (a crash between two stores would
            # silently drop them).
            built = self._build(spec)
            self.service_store.store(
                spec.name, spec.to_dict(), options=options
            )
            self._services[spec.name] = built
            self._services_version += 1
            self._suppressed_services.discard(spec.name)
        self.journal.append("operator", verb="add-service",
                            service=spec.name)
        self.journal.flush()
        self.nudge()  # deploy work just became pending

    @property
    def artifact_base(self):
        return getattr(self, "_artifact_base", None)

    @artifact_base.setter
    def artifact_base(self, value) -> None:
        """Apply to every service, existing AND future: the runner can
        only learn the URL after the API server starts, which is after
        seeded/reloaded services were built."""
        self._artifact_base = value
        for name, svc in self.services().items():
            if hasattr(svc, "artifact_base"):
                svc.artifact_base = (
                    f"{value.rstrip('/')}/v1/multi/{name}" if value else None
                )

    def install_package(
        self, name: str, payload: bytes, upgrade: bool = False,
        options: Optional[dict] = None,
    ) -> None:
        """Install a framework package tarball (the Cosmos flow): the
        bundle is extracted into this scheduler's packages dir, its
        svc.yml loads with template paths anchored there, and the
        service joins the framework.

        ``upgrade=True`` pushes a NEW package version to a RUNNING
        service (reference: Cosmos `update --package-version`): the
        bundle replaces the package dir and the service rebuilds over
        its existing state — the config diff validates, a rejected
        diff keeps the old target (errors surface on the plan), and an
        accepted one rolls the update plan.

        Reference: Cosmos rendering a universe package into a running
        scheduler (tools/universe/ + marathon.json.mustache)."""
        import os as _os
        import re as _re

        from dcos_commons_tpu.specification.yaml_spec import from_yaml_file
        from dcos_commons_tpu.tools.packaging import extract_package

        import shutil as _shutil

        from dcos_commons_tpu.specification.specs import SpecError

        # the name comes straight off the URL: validate BEFORE it
        # touches a filesystem path ('..' would extract into state_dir)
        if not _re.fullmatch(r"[A-Za-z0-9][A-Za-z0-9._-]*", name) or \
                name in (".", ".."):
            raise SpecError(f"invalid service name {name!r}")
        # admission's mesh derivation imports jax lazily, and a COLD
        # import under the lock below would freeze every service's
        # cycles for seconds (run_cycle takes the same lock).  Peek at
        # the payload's svc.yml straight from the tar stream OUTSIDE
        # the lock (no throwaway extraction: a CPU-only deployment
        # would otherwise pay a full double-extract on EVERY install,
        # since its guard never becomes true) and warm the import
        # first; malformed payloads fail properly inside the locked
        # path.
        import sys as _sys

        if "dcos_commons_tpu.parallel.mesh" not in _sys.modules:
            try:
                import io as _io
                import tarfile as _tarfile

                svc_text = ""
                with _tarfile.open(
                    fileobj=_io.BytesIO(payload), mode="r:gz"
                ) as tar:
                    for member in tar.getmembers():
                        if _os.path.basename(member.name) == "svc.yml":
                            handle = tar.extractfile(member)
                            if handle is not None:
                                svc_text = handle.read().decode(
                                    "utf-8", errors="replace"
                                )
                            break
                from dcos_commons_tpu.multi.admission import _targets_jax

                # warm for ANY tpu: pod, not just recognizably
                # jax-targeting cmds: the peek reads UNRENDERED
                # YAML, and a templated cmd ("python {{SCRIPT}}")
                # would otherwise defeat it — mesh derivation only
                # runs for tpu pods, so this over-approximates
                # exactly the set that can need the import
                if _targets_jax(svc_text) or _re.search(
                    r"^\s*tpu\s*:", svc_text, _re.M
                ):
                    import dcos_commons_tpu.parallel.mesh  # noqa: F401
            except Exception:  # sdklint: disable=swallowed-exception — warm-up only; the locked path re-raises real failures with their findings
                pass
        # the whole exists-check -> extract -> commit -> register
        # sequence holds the lock: the API server is threaded, and two
        # concurrent PUTs for one name must not interleave their
        # filesystem commits (the loser would clobber the winner's
        # live templates before failing)
        with self._lock:
            existing = self._services.get(name)
            if isinstance(existing, UninstallScheduler):
                raise SpecError(f"service {name!r} is uninstalling")
            if existing is not None and not upgrade:
                raise SpecError(
                    f"service {name!r} already exists (pass upgrade=true "
                    "to push a new package version)"
                )
            if existing is None and upgrade:
                raise SpecError(f"no service {name!r} to upgrade")
            # stage the extraction: a rejected install must never
            # clobber a running service's templates (launches read them)
            packages_root = _os.path.join(self.config.state_dir, "packages")
            staging = _os.path.join(packages_root, f".staging-{name}")
            _shutil.rmtree(staging, ignore_errors=True)
            try:
                manifest = extract_package(payload, staging)
                # the Cosmos options plane: validate the operator's
                # options against the NEW package's options.json and
                # render them to env for the YAML interpolation.
                # Upgrades keep prior options and overlay new ones
                # (`dcos package update` semantics).
                from dcos_commons_tpu.tools.options import (
                    OptionsError,
                    load_schema,
                    merge_options,
                    prune_unknown,
                    render_options,
                )

                try:
                    schema = load_schema(staging)
                except OptionsError as e:
                    raise SpecError(
                        "options rejected: " + "; ".join(e.errors)
                    )
                prior_options = {}
                if existing is not None:
                    prior_entry = self.service_store.fetch(name) or {}
                    prior_options = prior_entry.get("options") or {}
                    # a new package version may DROP options: stored
                    # values for them must not brick every future
                    # upgrade (freshly-passed unknowns below still
                    # reject — there, unknown = typo)
                    prior_options, dropped = prune_unknown(
                        schema, prior_options
                    )
                    if dropped:
                        LOG.warning(
                            "%s: dropping stored options the new "
                            "package no longer defines: %s",
                            name, ", ".join(dropped),
                        )
                effective_options = merge_options(prior_options, options)
                try:
                    options_env = render_options(schema, effective_options)
                except OptionsError as e:
                    raise SpecError(
                        "options rejected: " + "; ".join(e.errors)
                    )
                render_env = {**_os.environ, **options_env}
                spec = from_yaml_file(
                    _os.path.join(staging, "svc.yml"), env=render_env
                )
                if spec.name != name:
                    raise SpecError(
                        f"package {manifest['name']!r} defines service "
                        f"{spec.name!r}, not {name!r}"
                    )
                # admission control on the rendered package spec: the
                # CI analyzers gate the dynamic path too.  Runs while
                # everything is still STAGED — a rejected package
                # leaves no trace on disk or in the store.
                from dcos_commons_tpu.multi.admission import (
                    AdmissionError,
                    check_rendered_spec,
                )

                with open(
                    _os.path.join(staging, "svc.yml"),
                    "r", encoding="utf-8",
                ) as f:
                    svc_lines = f.read().splitlines()
                findings = check_rendered_spec(
                    f"{name}/svc.yml", svc_lines, spec,
                    inventory=self.inventory,
                )
                if findings:
                    raise AdmissionError(findings)
                # VERSIONED final location: upgrades never delete the
                # dir a still-active (or kept-after-rejected-diff)
                # target config's templates live in — a rejected v2
                # must leave v1's templates untouched on disk
                import hashlib as _hashlib

                digest = _hashlib.sha256(payload).hexdigest()[:12]
                version = str(manifest.get("version", "0")).replace(
                    _os.sep, "_"
                )
                target = _os.path.join(
                    packages_root, name, f"{version}-{digest}"
                )
                _shutil.rmtree(target, ignore_errors=True)
                _os.makedirs(_os.path.dirname(target), exist_ok=True)
                _os.replace(staging, target)
            finally:
                _shutil.rmtree(staging, ignore_errors=True)
            # re-anchor template paths in the final location
            spec = from_yaml_file(
                _os.path.join(target, "svc.yml"), env=render_env
            )
            if existing is not None:
                # rebuild over the SAME namespace/state: the builder's
                # config-update pass validates the diff and selects
                # the update plan; the swapped-in scheduler resumes
                # running tasks instead of redeploying.  BUILD FIRST —
                # persisting a spec that cannot build would poison
                # every restart's _reload
                rebuilt = self._build(spec)
                self.service_store.store(
                    name, spec.to_dict(), options=effective_options
                )
                self._services[name] = rebuilt
                self._services_version += 1
                self._suppressed_services.discard(name)
                # prune superseded version dirs: repeated upgrades
                # otherwise grow state_dir without bound.  Keep the new
                # target plus every dir any STORED config still
                # references — a rejected-diff upgrade keeps the old
                # target config live, and relaunches read its templates
                # from disk (rejected v2/v3 must not orphan v1).
                import json as _json

                keep = {_os.path.basename(target)}
                marker = _re.escape(f"packages/{name}/") + r"([^/\"\\]+)"
                cfg_store = getattr(rebuilt, "config_store", None)
                if cfg_store is not None:
                    for cfg_id in cfg_store.list_ids():
                        data = cfg_store.fetch(cfg_id)
                        if data:
                            for m in _re.finditer(
                                marker, _json.dumps(data)
                            ):
                                keep.add(m.group(1))
                svc_root = _os.path.join(packages_root, name)
                for entry_name in _os.listdir(svc_root):
                    if entry_name in keep or entry_name.startswith("."):
                        continue
                    _shutil.rmtree(
                        _os.path.join(svc_root, entry_name),
                        ignore_errors=True,
                    )
            else:
                self.add_service(spec, options=effective_options)

    def uninstall_service(self, name: str) -> None:
        """Flip the service to teardown; it is dropped from the set
        once its uninstall plan completes (reference: uninstall flag +
        client removal, MultiServiceEventClient.java:169-290)."""
        with self._lock:
            service = self._services.get(name)
            if service is None:
                raise KeyError(name)
            if isinstance(service, UninstallScheduler):
                return
            entry = self.service_store.fetch(name)
            self.service_store.store(name, entry["spec"], uninstalling=True)
            self._services[name] = self._make_uninstaller(
                ServiceSpec.from_dict(entry["spec"])
            )
            self._services_version += 1
            self._suppressed_services.discard(name)
        self.journal.append("operator", verb="uninstall-service",
                            service=name)
        self.journal.flush()
        self.nudge()  # teardown work just became pending

    def get_service(self, name: str):
        with self._lock:
            return self._services.get(name)

    @property
    def services_version(self) -> int:
        """Monotonic counter of service add/remove/rebuild events
        (merged-view memoization key)."""
        return self._services_version

    def services(self) -> Dict[str, object]:
        with self._lock:
            return dict(self._services)

    def suppress_state(self, name: Optional[str] = None) -> Dict[str, object]:
        """Per-service offer-discipline state for /v1/debug/offers:
        which services are currently suppressed (skipped by
        run_cycle), optionally focused on one service.  Called from
        HTTP threads while run_cycle mutates the set — the C-level
        set() copy is atomic under the GIL, so sorting can never see
        a mid-mutation resize."""
        snapshot = set(self._suppressed_services)
        out: Dict[str, object] = {
            "suppressed_services": sorted(snapshot),
            "total_services": len(self._services),
        }
        if name is not None:
            out["service"] = name
            out["suppressed"] = name in snapshot
        return out

    def service_names(self) -> List[str]:
        with self._lock:
            return sorted(self._services)

    # -- construction -------------------------------------------------

    def _build(self, spec: ServiceSpec) -> DefaultScheduler:
        import dataclasses

        config = dataclasses.replace(
            self.config, service_namespace=spec.name, uninstall=False
        )
        builder = SchedulerBuilder(spec, config, self.persister)
        builder.set_inventory(self.inventory)
        builder.set_agent(_ServiceAgentAdapter(self.agent))
        if self._builder_hook is not None:
            self._builder_hook(builder)
        scheduler = builder.build()
        # served multi mode: agents pull config templates from the one
        # shared API server; per-service artifact paths route through
        # /v1/multi/<name>/v1/artifacts/...
        base = self.artifact_base
        scheduler.artifact_base = (
            f"{base.rstrip('/')}/v1/multi/{spec.name}" if base else None
        )
        # snapshots must subtract EVERY service's reservations, not
        # just this service's own namespaced ledger
        scheduler.evaluator.set_snapshot_view(self._merged_view)
        # the shared agent's task set spans every service: per-service
        # orphan sweeps would kill siblings' tasks, so the multi loop
        # runs ONE merged sweep instead (_kill_merged_orphans)
        scheduler.kill_orphaned_tasks = False
        # offer-discipline observability: every service's metrics
        # snapshot and /v1/debug/offers can show the fleet's suppress
        # state (len() on a set is atomic under the GIL)
        scheduler.metrics.gauge(
            "cycle.suppressed_services",
            lambda: float(len(self._suppressed_services)),
        )
        scheduler.offer_discipline = (
            lambda name=spec.name: self.suppress_state(name)
        )
        if self.ha_state is not None:
            # one process-wide election; every service serves it at
            # its own /v1/debug/ha and exports the ha.* gauges
            self.ha_state.attach(scheduler)
        return scheduler

    def _make_uninstaller(self, spec: ServiceSpec) -> UninstallScheduler:
        from dcos_commons_tpu.offer.ledger import ReservationLedger
        from dcos_commons_tpu.state.config_store import ConfigStore

        return UninstallScheduler(
            spec=spec,
            state_store=StateStore(self.persister, spec.name),
            ledger=ReservationLedger(self.persister, spec.name),
            inventory=self.inventory,
            agent=_ServiceAgentAdapter(self.agent),
            persister=self.persister,
            config_store=ConfigStore(self.persister, spec.name),
            framework_store=self.framework_store,
            namespace=spec.name,
            deregister=False,
        )

    # -- host lifecycle verbs (ISSUE 13) ------------------------------
    # fleet-level: the inventory is SHARED, so the mark lands once,
    # but preemption's task stamping fans out to every service that
    # has tasks on the host (routes: /v1/multi/hosts/<id>/<verb>)

    def drain_host(self, host_id: str, window_s: float = 0.0) -> bool:
        import time as _time

        with self._lock:
            if self.inventory.host(host_id) is None:
                raise KeyError(host_id)
            window_end = _time.time() + window_s if window_s > 0 else 0.0
            changed = self.inventory.set_maintenance(host_id, window_end)
        if changed:
            self.journal.append(
                "host", verb="drain", host=host_id, window_s=window_s,
                message=f"host {host_id} entering maintenance",
            )
            self.journal.flush()
        self.nudge()
        return changed

    def undrain_host(self, host_id: str) -> bool:
        with self._lock:
            if self.inventory.host(host_id) is None:
                raise KeyError(host_id)
            changed = self.inventory.clear_host_state(host_id)
        if changed:
            self.journal.append(
                "host", verb="up", host=host_id,
                message=f"host {host_id} back in service",
            )
            self.journal.flush()
        self.nudge()
        return changed

    def preempt_host(self, host_id: str) -> Dict[str, List[str]]:
        """Mark the host preempted once, then stamp every service's
        tasks on it (each service synthesizes its own LOST statuses
        and gang recovery).  The per-service calls run OUTSIDE the
        multi lock — they take each service's own lock, and holding
        both here would order-invert against run_cycle."""
        with self._lock:
            if self.inventory.host(host_id) is None:
                raise KeyError(host_id)
            self.inventory.set_preempted(host_id)
            services = dict(self._services)
        lost: Dict[str, List[str]] = {}
        for name, service in services.items():
            noter = getattr(service, "note_host_preempted", None)
            if callable(noter):
                touched = noter(host_id)
                if touched:
                    lost[name] = touched
        self.journal.append(
            "host", verb="preempt", host=host_id,
            tasks=sum(len(v) for v in lost.values()),
            message=f"host {host_id} preempted "
                    f"({sum(len(v) for v in lost.values())} task(s) "
                    f"across {len(lost)} service(s))",
        )
        self.journal.flush()
        self.nudge()
        return lost

    # -- the loop (reference: MultiServiceEventClient fan-out) --------

    def run_cycle(self) -> None:
        with self._lock:
            services = dict(self._services)
            revived = self._route_statuses(services)
            # offer discipline: a suppressed service is skipped
            # entirely — no status intake (it got none), no candidate
            # scan, no GC — unless a status arrival or nudge() revived
            # it.  take_nudge() is only CONSUMED here, so a nudge
            # racing the post-cycle suppress decision is never lost.
            runnable: Dict[str, object] = {}
            for name, service in services.items():
                if (
                    isinstance(service, DefaultScheduler)
                    and name in self._suppressed_services
                    and name not in revived
                    and not service.take_nudge()
                ):
                    continue
                runnable[name] = service
            growing = [
                name
                for name, s in runnable.items()
                if isinstance(s, DefaultScheduler) and self._is_growing(s)
            ]
            selected = self.discipline.select(growing)
            for name, service in runnable.items():
                try:
                    if isinstance(service, DefaultScheduler):
                        service.run_cycle(
                            allow_footprint_growth=(
                                name in selected or name not in growing
                            )
                        )
                        if service.work_pending():
                            self._suppressed_services.discard(name)
                        else:
                            self._suppressed_services.add(name)
                    else:
                        service.run_cycle()
                    self._cycle_failures[name] = 0
                except Exception as exc:
                    # a failed cycle must leave the service RUNNABLE:
                    # its revive trigger (nudge/status) was already
                    # consumed this cycle, so staying suppressed here
                    # would skip it forever — silently dropping the
                    # operator verb and making the wedge detection
                    # below unreachable
                    self._suppressed_services.discard(name)
                    failures = self._cycle_failures.get(name, 0) + 1
                    self._cycle_failures[name] = failures
                    LOG.exception(
                        "service %s cycle failed (%d consecutive)",
                        name, failures,
                    )
                    if failures >= self.max_consecutive_failures:
                        self._fatal_error = f"service {name}: {exc!r}"
                        LOG.critical(
                            "service %s wedged after %d consecutive cycle "
                            "failures; flagging fatal for supervised restart",
                            name, failures,
                        )
            self._kill_merged_orphans(services)
            self.task_killer.retry_pending()
            # drop services whose uninstall finished
            for name, service in services.items():
                if isinstance(service, UninstallScheduler) and \
                        service.is_complete:
                    self.service_store.remove(name)
                    del self._services[name]
                    self._services_version += 1
                    self._suppressed_services.discard(name)
                    LOG.info("service %s uninstalled and removed", name)

    def _expected_task_ids(self, services: Dict[str, object]) -> set:
        """Union of every service's stored task ids, served from the
        incremental orphan index: a service whose task-generation
        stamp is unchanged reuses its cached id set (one string
        compare), only mutated services pay the store scan.  Must be
        EXACTLY equivalent to the full scan — an over-approximation
        would shelter a real orphan, an under-approximation would
        kill a live task (equivalence-tested in test_multi_service)."""
        expected: set = set()
        for name, service in services.items():
            store = service.state_store
            gen = getattr(store, "task_generation", None)
            hit = self._orphan_index.get(name)
            if gen is not None and hit is not None and hit[0] == gen:
                ids = hit[1]
            else:
                ids = frozenset(
                    info.task_id for info in store.fetch_tasks()
                )
                if gen is not None:
                    # re-read the stamp AFTER the scan: a mutation
                    # racing the scan must invalidate, not be masked
                    # behind the pre-scan stamp
                    post = store.task_generation
                    if post == gen:
                        # racecheck: handoff=only the multi-loop thread (or a test driving run_cycle inline) reaches the orphan sweep; cycles never overlap
                        self._orphan_index[name] = (gen, ids)
            expected |= ids
        if len(self._orphan_index) > len(services):
            # drop removed/rebuilt-away services so the index cannot
            # grow without bound across add/uninstall churn
            self._orphan_index = {
                n: v for n, v in self._orphan_index.items()
                if n in services
            }
        return expected

    def _kill_merged_orphans(self, services: Dict[str, object]) -> None:
        """Kill agent tasks NO service's store owns (lost-kill safety
        net; the per-service sweep is disabled in multi mode because
        each service sees the shared agent's full task set)."""
        expected = self._expected_task_ids(services)
        for task_id in self.agent.active_task_ids() - expected:
            if task_id in self.task_killer.pending_ids():
                continue  # retry_pending re-issues until acked
            LOG.info("killing orphaned task %s (no owning service)", task_id)
            self.task_killer.kill(task_id)

    def _route_statuses(self, services: Dict[str, object]) -> set:
        """Poll the shared agent once and deliver each status to the
        service whose stored TaskInfo owns the task id; unroutable
        statuses go to every service (their stale guards drop them).
        Returns the names of services that received a delivery — a
        status arrival REVIVES a suppressed service (it must never
        miss work its own tasks caused)."""
        from dcos_commons_tpu.common import task_name_of

        revived: set = set()
        for status in self.agent.poll():
            self.task_killer.handle_status(status)
            try:
                task_name = task_name_of(status.task_id)
            except ValueError:
                LOG.warning("dropped unparseable task id %s", status.task_id)
                continue
            routed = False
            holders = []  # services holding a TaskInfo under this name
            for name, service in services.items():
                info = service.state_store.fetch_task(task_name)
                if info is None:
                    continue
                if info.task_id == status.task_id:
                    service.agent.deliver(status)
                    revived.add(name)
                    routed = True
                    break
                holders.append((name, service))
            if routed:
                continue
            # no exact id owner: deliver only to services that hold a
            # stored TaskInfo for the NAME (their stale-id guards drop
            # it); broadcasting to everyone would persist stray status
            # nodes in services that never owned the task, which can
            # later wedge their uninstall kill-all
            if holders:
                for name, service in holders:
                    service.agent.deliver(status)
                    revived.add(name)
            else:
                LOG.info(
                    "dropped status for unknown task %s", status.task_id
                )
        return revived

    @staticmethod
    def _is_growing(scheduler: DefaultScheduler) -> bool:
        """A service 'grows' while any plan that can take new
        reservations is incomplete."""
        for plan in scheduler.plans().values():
            if plan.name == "recovery":
                continue
            if not plan.is_complete and not plan.has_errors():
                return True
        return False

    def run_forever(
        self,
        interval_s: float = 0.5,
        max_consecutive_failures: int = 5,
    ) -> threading.Thread:
        """Same crash-to-restart contract as DefaultScheduler: stop the
        loop with ``fatal_error`` set once the outer cycle (or any one
        service, tracked in run_cycle) is permanently wedged."""
        def loop():
            failures = 0
            while not self._stop.is_set():
                self._wake.clear()
                try:
                    self.run_cycle()
                    failures = 0
                except Exception as exc:
                    failures += 1
                    LOG.exception(
                        "multi cycle failed (%d consecutive)", failures
                    )
                    if failures >= max_consecutive_failures:
                        with self._lock:
                            self._fatal_error = repr(exc)
                if self._fatal_error is not None:
                    LOG.critical(
                        "multi scheduler wedged (%s); stopping loop for "
                        "supervised restart", self._fatal_error,
                    )
                    self._stop.set()
                    break
                timeout = interval_s
                if self._work_in_flight():
                    timeout = min(interval_s, 0.05)
                self._wake.wait(timeout)

        thread = threading.Thread(target=loop, name="multi-loop", daemon=True)
        thread.start()
        return thread

    def nudge(self) -> None:
        """Wake run_forever for an immediate merged cycle (status
        arrival, service add/remove, HTTP mutation)."""
        self._wake.set()

    def _work_in_flight(self) -> bool:
        """True while any service's plan step awaits task statuses."""
        for service in self.services().values():
            managers = getattr(
                getattr(service, "coordinator", None), "plan_managers", []
            )
            if any(m.in_progress_assets() for m in managers):
                return True
        return False

    @property
    def fatal_error(self) -> Optional[str]:
        return self._fatal_error

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
