"""Weight-only int8 quantization for the serving path.

Decode at serving batch sizes is HBM-bound on STREAMING THE WEIGHTS:
every decode step reads the full layer stack once (~2 bytes/param in
bf16), so the tokens/s ceiling is ``HBM_bw / weight_bytes``.  Storing
the matmul weights as int8 with a per-output-channel scale halves the
bytes per step — the same lever the int8 KV cache applies to the
cache reads (models/decode.py), applied to the other, larger half of
decode's HBM traffic.

Representation: each big matmul leaf ``W`` in ``params["layers"]`` is
replaced by ``{"q": int8, "scale": f32}`` where ``scale`` is the
max-abs over W's CONTRACTION axis (axis -2 in every layer layout:
``x @ W`` contracts -2, so the scale rides the kept output axis and
folds in AFTER the matmul algebraically — ``x @ (q*s) == (x @ q) * s``
for a per-column s).  XLA fuses the dequantize (convert + multiply)
into the consuming dot's operand load: the bf16 weights are never
written back to HBM, only the int8 bytes stream.  Quantization error
is bounded per element by ``max|column| / 254`` (symmetric round to
127 steps) — tests/test_quantize.py pins the bound and the end-to-end
logit agreement.

Embeddings and norms stay native: norms are vectors (noise-critical,
byte-trivial) and the tied embedding is both a gather table and the
logit head (~2% of flagship weight bytes — not worth the head's
precision).  The MoE expert stacks quantize the same way (the router
stays f32: it is byte-trivial and decides argmax routing).

Reference analogue: none — the reference schedules services and has
no inference plane.  This belongs to the flagship workload the way
backup/restore plans belong to cassandra: the thing the framework
exists to run well.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

Params = Dict[str, Any]

# layer leaves eligible for weight-only quantization; everything else
# (norms, router, biases) stays native
_QUANT_LEAVES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_weight(w: jax.Array) -> Dict[str, jax.Array]:
    """Symmetric per-output-channel int8: scale over axis -2 (the
    contraction axis of ``x @ W``), so dequantization commutes with
    the matmul and the scale multiply runs on the small output."""
    scale = jnp.max(
        jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True
    ) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(
        jnp.round(w.astype(jnp.float32) / scale), -127, 127
    ).astype(jnp.int8)
    return {"q": q, "scale": scale}


def dequantize_weight(w: Any, dtype: Any) -> jax.Array:
    """Inverse of :func:`quantize_weight`; identity on plain arrays.

    Called at the USE SITE inside the per-layer scan body so the
    convert+multiply fuses into the consuming matmul — hoisting it
    out of the layer loop would materialize the full bf16 stack and
    give the bytes back."""
    if isinstance(w, dict) and "q" in w:
        return (w["q"].astype(jnp.float32) * w["scale"]).astype(dtype)
    return w


def quantize_params_int8(params: Params) -> Params:
    """Return a copy of the flagship param tree with the layer matmul
    weights stored int8 (``{"q", "scale"}`` leaves).

    The tree SHAPE is preserved (each quantized leaf keeps its leading
    n_layers axis, scan-compatible: ``lax.scan`` slices ``q`` and
    ``scale`` together), so decode/prefill/forward consume it
    unchanged — they route every weight read through
    :func:`dequantize_weight`."""
    layers = dict(params["layers"])
    for name in _QUANT_LEAVES:
        if name in layers:
            layers[name] = quantize_weight(layers[name])
    out = dict(params)
    out["layers"] = layers
    return out


# NOTE: "bytes of the quantized tree" is utils.param_bytes — it sums
# as-stored leaf bytes over any pytree, int8 + scale leaves included
# (bench.py's decode rooflines use it directly).
