"""CLI tests: main() against a live API server over loopback.

Reference: the Go CLI's verb surface (cli/commands/*.go) — here the
CLI process boundary is exercised too (python -m dcos_commons_tpu.cli
in a subprocess for one smoke case; the rest call main() in-process).
"""

import json
import subprocess
import sys

import pytest

from dcos_commons_tpu.cli.commands import main
from dcos_commons_tpu.http import ApiServer
from dcos_commons_tpu.testing import (
    AdvanceCycles,
    ExpectDeploymentComplete,
    SendTaskRunning,
    ServiceTestRunner,
)

YAML = """
name: cli-svc
pods:
  app:
    count: 1
    tasks:
      main:
        goal: RUNNING
        cmd: "serve"
        cpus: 0.1
        memory: 32
"""


@pytest.fixture()
def deployed():
    runner = ServiceTestRunner(YAML)
    runner.run([
        AdvanceCycles(1),
        SendTaskRunning("app-0-main"),
        ExpectDeploymentComplete(),
    ])
    server = ApiServer(runner.world.scheduler).start()
    yield runner, server
    server.stop()


def cli(server, *argv, expect_rc=0, capsys=None):
    rc = main(["--url", server.url, *argv])
    assert rc == expect_rc
    out = capsys.readouterr().out if capsys else ""
    try:
        return json.loads(out)
    except json.JSONDecodeError:
        return out.strip()


def test_plan_and_pod_sections(deployed, capsys):
    runner, server = deployed
    assert cli(server, "plan", "list", capsys=capsys) == ["deploy", "recovery"]
    plan = cli(server, "plan", "show", "deploy", capsys=capsys)
    assert plan["status"] == "COMPLETE"
    assert cli(server, "pod", "list", capsys=capsys) == ["app-0"]
    status = cli(server, "pod", "status", "app-0", capsys=capsys)
    assert status["tasks"][0]["status"] == "TASK_RUNNING"

    cli(server, "pod", "restart", "app-0", capsys=capsys)
    runner.run([AdvanceCycles(2), SendTaskRunning("app-0-main")])
    assert len(runner.agent.launches_of("app-0-main")) == 2


def test_config_state_endpoints_health(deployed, capsys):
    runner, server = deployed
    target = cli(server, "config", "target", capsys=capsys)
    assert target["name"] == "cli-svc"
    target_id = cli(server, "config", "target_id", capsys=capsys)
    assert target_id in cli(server, "config", "list", capsys=capsys)
    props = cli(server, "state", "properties", capsys=capsys)
    assert "deployment-completed" in props
    health = cli(server, "health", capsys=capsys)
    assert health["healthy"]
    metrics = cli(server, "metrics", capsys=capsys)
    assert metrics["operations.launch"] >= 1
    offers = cli(server, "debug", "offers", capsys=capsys)
    assert offers[-1]["passed"]


def test_plan_verbs(deployed, capsys):
    runner, server = deployed
    cli(server, "plan", "force-restart", "deploy", "app", "app-0:[main]",
        capsys=capsys)
    plan = cli(server, "plan", "show", "deploy", capsys=capsys)
    assert plan["status"] == "PENDING"
    cli(server, "plan", "force-complete", "deploy", "app", "app-0:[main]",
        capsys=capsys)
    plan = cli(server, "plan", "show", "deploy", capsys=capsys)
    assert plan["status"] == "COMPLETE"


def test_error_surfaces_as_exit_code(deployed, capsys):
    runner, server = deployed
    cli(server, "plan", "show", "nope", expect_rc=1, capsys=capsys)
    err = capsys.readouterr  # stderr captured alongside; rc checked above


def test_subprocess_smoke(deployed):
    runner, server = deployed
    result = subprocess.run(
        [sys.executable, "-m", "dcos_commons_tpu.cli",
         "--url", server.url, "plan", "list"],
        capture_output=True, text=True, timeout=30, cwd="/root/repo",
    )
    assert result.returncode == 0, result.stderr
    assert json.loads(result.stdout) == ["deploy", "recovery"]
