"""Multi-host gang training END TO END with real processes.

The flagship claim driven for real: a gang pod deploys over agent
daemon processes, each worker is a REAL ``frameworks/jax``
train_worker that rendezvouses via jax.distributed at the
scheduler-issued coordinator and trains a pjit mesh (CPU backend
here — same code path the TPU fleet runs); killing a daemon flips the
WHOLE gang to recovery (SURVEY hard-part 3: gang semantics the
reference never needed), and the replacement gang RESUMES from the
orbax-style checkpoint instead of step 0 (SURVEY 5.4: re-place +
restore is PERMANENT recovery's workload half).
"""

import os

import pytest

from dcos_commons_tpu.testing.integration import (
    AgentProcess,
    SchedulerProcess,
    reap_orphan_tasks,
    wait_for,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GANG_SVC = """
name: gangtrain
pods:
  trainer:
    count: 2
    gang: true
    tpu:
      generation: v5e
      chips-per-host: 1
      topology: 1x2
    tasks:
      worker:
        goal: RUNNING
        cmd: >-
          JAX_PLATFORMS=cpu REPO_ROOT={{REPO_ROOT}}
          CHECKPOINT_DIR={{CKPT_DIR}} DATA_DIR={{DATA_DIR}}
          VOCAB=128 D_MODEL=64 N_LAYERS=2 SEQ_LEN=64 TRAIN_STEPS=4000
          python {{REPO_ROOT}}/frameworks/jax/train_worker.py
        cpus: 1.0
        memory: 2048
"""


def _write_topology(path, agents):
    """One slice, a 2x2 host grid of 1-chip hosts: the 1x2 gang fits
    in either column, so losing one host leaves a full column free."""
    grids = [(0, 0), (0, 1), (1, 0), (1, 1)]
    lines = ["hosts:"]
    for agent, (gx, gy) in zip(agents, grids):
        lines += [
            f"  - host_id: {agent.host_id}",
            f"    agent_url: {agent.url}",
            "    hostname: 127.0.0.1",  # the dialable DCN address
            "    slice_id: s0",
            "    generation: v5e",
            f"    grid: [{gx}, {gy}]",
            "    chip_block: [1, 1]",
            "    cpus: 4.0",
            "    memory_mb: 8192",
        ]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def _worker_logs(agents):
    """task-name -> (host_id, stdout text) for every trainer sandbox."""
    out = {}
    for agent in agents:
        for idx in (0, 1):
            path = os.path.join(
                agent.workdir, "sandboxes", f"trainer-{idx}-worker", "stdout"
            )
            if os.path.exists(path):
                with open(path, errors="replace") as f:
                    out.setdefault(f"trainer-{idx}-worker", []).append(
                        (agent.host_id, f.read())
                    )
    return out


@pytest.mark.slow
def test_gang_trains_and_resumes_from_checkpoint_after_host_loss(tmp_path):
    import numpy as np

    from dcos_commons_tpu.data import write_token_shard

    agents = [
        AgentProcess(f"g{i}", str(tmp_path / f"agent-{i}"), REPO)
        for i in range(4)
    ]
    svc = tmp_path / "svc.yml"
    svc.write_text(GANG_SVC)
    topology = tmp_path / "topology.yml"
    _write_topology(str(topology), agents)
    ckpt_dir = tmp_path / "ckpt"
    # a REAL corpus: the gang trains from memmap shards (disjoint per
    # worker via the env contract), not synthetic tokens
    data_dir = tmp_path / "corpus"
    data_dir.mkdir()
    rng = np.random.default_rng(0)
    for i in range(4):
        write_token_shard(
            str(data_dir / f"shard-{i}.tokens"),
            rng.integers(0, 128, 8000),
        )
    scheduler = SchedulerProcess(
        str(svc), str(topology), str(tmp_path / "sched"),
        env={
            "ENABLE_BACKOFF": "false",
            "PERMANENT_FAILURE_TIMEOUT_S": "1",
            "REPO_ROOT": REPO,
            "CKPT_DIR": str(ckpt_dir),
            "DATA_DIR": str(data_dir),
        },
        repo_root=REPO,
    )
    try:
        client = scheduler.client()
        client.wait_for_completed_deployment(timeout_s=120)

        # both workers rendezvous (2-process Gloo mesh), load DISJOINT
        # corpus shards, and make real training steps; worker 0 writes
        # checkpoints every 20 steps
        def progressed():
            logs = _worker_logs(agents)
            loaded = sum(
                1 for entries in logs.values()
                for _, text in entries if "data: " in text
            )
            stepped = sum(
                1 for entries in logs.values()
                for _, text in entries if "step 20 " in text
            )
            return (loaded >= 2 and stepped >= 1) or None

        wait_for(progressed, 240.0, interval_s=2.0,
                 what="gang made 20+ real training steps")

        def checkpoint_past_20():
            if not ckpt_dir.exists():
                return None
            steps = [
                int(f[len("step_"):-len(".npz")])
                for f in os.listdir(ckpt_dir)
                if f.startswith("step_") and f.endswith(".npz")
            ]
            return max(steps) if steps and max(steps) >= 21 else None

        wait_for(checkpoint_past_20, 120.0, interval_s=2.0,
                 what="checkpoint at step >= 21 written")

        # find the daemon hosting worker 1 and kill it: ONE host loss
        # must flip the WHOLE gang to recovery
        infos = {
            i["name"]: i
            for idx in (0, 1)
            for i in client.get(f"/v1/pod/trainer-{idx}/info")
        }
        old_ids = {n: i["task_id"] for n, i in infos.items()}
        victim_host = infos["trainer-1-worker"]["agent_id"]
        victim = next(a for a in agents if a.host_id == victim_host)
        victim.kill()

        def gang_replaced():
            try:
                now = {
                    i["name"]: i
                    for idx in (0, 1)
                    for i in client.get(f"/v1/pod/trainer-{idx}/info")
                }
            except Exception:
                return None
            if set(now) != set(old_ids):
                return None
            # BOTH workers get new task ids (gang-atomic recovery),
            # and nothing lands on the dead host
            if any(now[n]["task_id"] == old_ids[n] for n in now):
                return None
            if any(i["agent_id"] == victim_host for i in now.values()):
                return None
            return now

        replaced = wait_for(gang_replaced, 180.0, interval_s=2.0,
                            what="whole gang replaced off the dead host")
        new_hosts = {i["agent_id"] for i in replaced.values()}
        old_hosts = {i["agent_id"] for i in infos.values()}
        assert victim_host not in new_hosts

        # the replacement gang RESUMES from the checkpoint: on a FRESH
        # host (one the original gang never touched, so its sandbox log
        # starts with the replacement) the first logged step must be
        # >= 40 — train_worker logs every 20th step, and a restored
        # start of >= 21 makes 40 the first loggable step; a
        # from-scratch run would log step 0 first
        fresh_hosts = new_hosts - old_hosts
        assert fresh_hosts, (
            f"replacement reused every original host: {new_hosts}"
        )

        def resumed():
            logs = _worker_logs(agents)
            for entries in logs.values():
                for host, text in entries:
                    if host not in fresh_hosts:
                        continue
                    first = next(
                        (ln for ln in text.splitlines()
                         if ln.startswith("step ") and " loss=" in ln),
                        None,
                    )
                    if first is not None:
                        step = int(first.split()[1])
                        assert step >= 40, (
                            f"replacement on {host} started at step "
                            f"{step} — did not resume from checkpoint"
                        )
                        return True
            return None

        wait_for(resumed, 240.0, interval_s=2.0,
                 what="replacement gang resumed from checkpoint")
    finally:
        scheduler.terminate()
        for agent in agents:
            agent.stop()
        reap_orphan_tasks(agents)
